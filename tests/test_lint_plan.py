"""Plan-lint tests over the golden bad-plan fixtures, plus the
regression pair for the round-5 alltoall admit/crash mismatch: the
hazard is (a) flagged by lint and (b) no longer reachable at runtime."""

import importlib.util
import json
import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.analysis import capabilities as caps
from spark_rapids_tpu.analysis.diagnostics import (RULE_CATALOG,
                                                   format_diagnostics)
from spark_rapids_tpu.analysis.plan_lint import (downgrade_hazards,
                                                 lint_plan,
                                                 lint_spark_plan)
from spark_rapids_tpu.config import RapidsConf

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "goldens", "lint")


def _fixtures():
    spec = importlib.util.spec_from_file_location(
        "lint_bad_plans", os.path.join(GOLDEN_DIR, "bad_plans.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return {k: getattr(mod, k) for k in dir(mod) if k.startswith("plan_")}


with open(os.path.join(GOLDEN_DIR, "expected_codes.json")) as f:
    EXPECTED = json.load(f)


def test_every_fixture_has_expectations_and_vice_versa():
    assert sorted(_fixtures()) == sorted(EXPECTED)


@pytest.mark.parametrize("name", sorted(EXPECTED))
def test_golden_bad_plan_flags_expected_codes(name):
    root, conf_map = _fixtures()[name]()
    diags = lint_plan(root, RapidsConf(conf_map))
    got = {d.code for d in diags}
    want = set(EXPECTED[name])
    assert want <= got, (name, format_diagnostics(diags))
    # a fixture built for one hazard must not drown it in others
    unexpected_errors = {d.code for d in diags
                         if d.is_error and d.code not in want}
    assert not unexpected_errors, (name, format_diagnostics(diags))


def test_rule_class_coverage_is_at_least_eight():
    """Acceptance: the golden fixtures exercise >= 8 distinct rule
    classes, including the ICI admit mismatch and driver-collect-size."""
    all_codes = set()
    fx = _fixtures()
    for name, want in EXPECTED.items():
        root, conf_map = fx[name]()
        all_codes |= {d.code for d in lint_plan(root, RapidsConf(conf_map))}
    assert len(all_codes) >= 8, all_codes
    assert "TPU-L001" in all_codes and "TPU-L004" in all_codes
    assert all_codes <= set(RULE_CATALOG), all_codes


def test_clean_plan_produces_no_diagnostics():
    from spark_rapids_tpu.exec import base as eb
    from spark_rapids_tpu.exec.basic import LocalScanExec, ProjectExec
    from spark_rapids_tpu.expr.core import AttributeReference
    scan = LocalScanExec(pa.table({"v": pa.array([1, 2],
                                                 type=pa.int64())}))
    scan.placement = eb.TPU
    proj = ProjectExec([AttributeReference("v")], scan)
    proj.placement = eb.TPU
    assert lint_plan(proj, RapidsConf({})) == []


def test_suppression_drops_codes():
    fx = _fixtures()
    root, conf_map = fx["plan_L002_ping_pong"]()
    # the host-island fixture trips both the node rule (L002) and the
    # flow-sensitive path rule (L012); suppressing both silences it
    conf_map = dict(conf_map,
                    **{"spark.rapids.tpu.lint.disable":
                       "TPU-L002,TPU-L012"})
    assert lint_plan(root, RapidsConf(conf_map)) == []


def test_downgrade_moves_hazard_subtree_to_host():
    from spark_rapids_tpu.exec import base as eb
    fx = _fixtures()
    root, conf_map = fx["plan_L003_host_expr_on_device"]()
    conf = RapidsConf(conf_map)
    diags = lint_plan(root, conf)
    fixed = downgrade_hazards(root, diags)
    assert fixed.placement == eb.CPU
    # the downgraded subtree is clean on re-lint
    assert not [d for d in lint_plan(fixed, conf) if d.is_error]


def test_downgrade_clears_broken_colocation():
    from spark_rapids_tpu.exec import base as eb
    fx = _fixtures()
    root, conf_map = fx["plan_L006_partition_contract"]()
    conf = RapidsConf(conf_map)
    fixed = downgrade_hazards(root, lint_plan(root, conf))
    assert fixed.placement == eb.CPU and not fixed.colocated
    assert not [d for d in lint_plan(fixed, conf) if d.is_error]


# ---------------------------------------------------------------------------
# capability table: the gate cross-check provably catches the round-5 bug
# ---------------------------------------------------------------------------

def test_registered_gates_are_no_weaker_than_kernels():
    assert caps.verify_gates() == []


def test_old_exchange_gate_would_be_flagged():
    """The pre-fix admission gate (exchange_supported alone guarding the
    allgather path) is exactly what TPU-L001/R004 exist to catch."""
    from spark_rapids_tpu.parallel.alltoall import exchange_supported
    bad = caps.gate_weaker_than_kernel(exchange_supported,
                                       caps.ALLGATHER_BATCH)
    import spark_rapids_tpu.types as t
    assert any(isinstance(dt, t.ArrayType) for dt in bad)
    assert any(isinstance(dt, t.MapType) for dt in bad)


# ---------------------------------------------------------------------------
# regression: ungrouped array/map aggregate over ICI (ADVICE round 5)
# ---------------------------------------------------------------------------

def test_distributed_aggregate_rejects_ungrouped_array_at_construction():
    """Construction (= planning time) must refuse what allgather_batch
    would raise NotImplementedError on mid-query."""
    from spark_rapids_tpu.expr.aggregates import (AggregateExpression,
                                                  CollectList)
    from spark_rapids_tpu.expr.core import AttributeReference
    from spark_rapids_tpu.parallel import DistributedAggregate
    import spark_rapids_tpu.types as t
    with pytest.raises(NotImplementedError, match="allgather|array/map"):
        DistributedAggregate(
            [], [AggregateExpression(CollectList(AttributeReference("v")))],
            ["v"], [t.LONG])


def test_distributed_aggregate_grouped_array_still_admitted():
    """The stricter predicate must not over-reject: GROUPED collect_list
    routes through exchange_by_pid, which carries arrays of flat
    elements fine."""
    from spark_rapids_tpu.expr.aggregates import (AggregateExpression,
                                                  CollectList)
    from spark_rapids_tpu.expr.core import AttributeReference
    from spark_rapids_tpu.parallel import DistributedAggregate
    import spark_rapids_tpu.types as t
    agg = DistributedAggregate(
        [AttributeReference("k")],
        [AggregateExpression(CollectList(AttributeReference("v")))],
        ["k", "v"], [t.LONG, t.LONG])
    assert agg.output_names[0] == "k"


def test_global_collect_list_over_ici_runs_on_host_path():
    """End to end: with transport=ici a global collect_list query no
    longer reaches allgather_batch's NotImplementedError — it executes
    (host fallback) and returns the right rows."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession
    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.shuffle.transport", "ici")
         .get_or_create())
    tb = pa.table({"v": pa.array([3, 1, 2], type=pa.int64())})
    df = s.create_dataframe(tb, num_partitions=2)
    out = df.agg(F.collect_list(col("v")).alias("vs")).collect()
    assert sorted(out.column("vs")[0].as_py()) == [1, 2, 3]
    # the hazardous fused ICI stage was refused at planning time
    names = []
    s.last_plan.foreach(lambda e: names.append(type(e).__name__))
    assert "IciAggregateExec" not in names


# ---------------------------------------------------------------------------
# pre-flight wiring (spark.rapids.tpu.lint.enabled)
# ---------------------------------------------------------------------------

def test_preflight_lint_records_diagnostics_and_query_still_works():
    from spark_rapids_tpu.api.session import TpuSession
    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.tpu.lint.enabled", True)
         .config("spark.rapids.sql.explain", "NONE")
         .get_or_create())
    tb = pa.table({"v": pa.array(range(10), type=pa.int64())})
    df = s.create_dataframe(tb)
    out = df.filter(df["v"] > 5).collect()
    assert out.num_rows == 4
    # a clean query records an empty diagnostic list, not stale state
    assert isinstance(getattr(s, "last_plan"), object)


# ---------------------------------------------------------------------------
# event-log front end (qualification surfacing)
# ---------------------------------------------------------------------------

def test_lint_spark_plan_speaks_rule_vocabulary():
    from spark_rapids_tpu.tools.eventlog import PlanNode
    plan = PlanNode(
        "HashAggregate",
        "HashAggregate(keys=[], functions=[collect_list(v)])",
        [PlanNode("Project", "Project [regexp_replace(s, 'a', 'b')]",
                  [PlanNode("Scan parquet", "FileScan parquet", [])])])
    codes = {d.code for d in lint_spark_plan(plan)}
    assert "TPU-L001" in codes and "TPU-L003" in codes
    # offline text analysis is never upgraded to a hard error
    assert all(not d.is_error for d in lint_spark_plan(plan))
