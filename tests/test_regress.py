"""Cross-run regression watchdog (obs/history.py + `tools regress`).

Anti-vacuity is the point: the differ must be SILENT on identical
replays and LOUD on each injected regression kind (fallback, crossing
bump) — a watchdog that never barks, or always barks, is dead weight."""

import copy
import json
import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.obs.history import (DETERMINISTIC_FIELDS,
                                          TIMING_FIELDS, HistoryDir,
                                          deterministic_drift,
                                          diff_fingerprints, diff_runs,
                                          distill_event_log)


def _fp(sql_id=0, **over):
    fp = {
        "version": 1,
        "sql_id": sql_id,
        "description": f"q{sql_id}",
        "failed": False,
        "plan_shape": ["AggExec", [["FilterExec", [["ScanExec", []]]]]],
        "operators": {"AggExec": {"rows": 97, "bytes": 800,
                                  "batches": 1},
                      "FilterExec": {"rows": 1900, "bytes": 16000,
                                     "batches": 2}},
        "fallback_ops": ["DeviceToHostExec"],
        "fetch_crossings": 3,
        "lint_rule_hits": [],
        "distinct_programs": 3,
        "miss_causes": {"new_program": 2, "shape_churn": 1},
        "replay_class": "order_stable",
        "wall_ms": 120,
        "operator_time_ns": 5_000_000,
        "peak_device_bytes": 1 << 20,
        "compile_seconds": 4.2,
        "estimate_rows_err": 0.12,
        "pad_waste_ratio": 0.31,
        "slo_burn_rate": 0.2,
        "tail_dominant_segment": {"default": "compute:AggExec"},
    }
    fp.update(over)
    return fp


def _run(*fps):
    return {"version": 1, "recorded_at": "x", "label": "",
            "queries": list(fps)}


# ---------------------------------------------------------------------------
# differ semantics
# ---------------------------------------------------------------------------

def test_identical_replays_report_zero_drift():
    assert diff_runs(_run(_fp()), _run(_fp())) == []


def test_timing_only_changes_never_fail_ci():
    new = _fp(wall_ms=9999, operator_time_ns=1,
              peak_device_bytes=123)
    # without a threshold: silence
    assert diff_runs(_run(_fp()), _run(new)) == []
    # with a threshold: reported, but NOT deterministic
    drifts = diff_runs(_run(_fp()), _run(new), wall_threshold_pct=10)
    assert [d.kind for d in drifts] == ["wall_regression"]
    assert deterministic_drift(drifts) == []


def test_injected_fallback_is_flagged():
    new = _fp()
    new["fallback_ops"] = sorted(new["fallback_ops"] +
                                 ["InjectedHostOnlyExec"])
    drifts = diff_runs(_run(_fp()), _run(new))
    assert any(d.kind == "new_fallback" and d.deterministic
               for d in drifts)
    assert "InjectedHostOnlyExec" in drifts[0].detail
    # a REMOVED fallback (improvement) is not drift
    assert diff_runs(_run(new), _run(_fp())) == []


def test_injected_crossing_bump_is_flagged():
    new = _fp(fetch_crossings=5)
    drifts = diff_runs(_run(_fp()), _run(new))
    assert [d.kind for d in drifts] == ["crossing_growth"]
    assert drifts[0].deterministic
    # fewer crossings (improvement) is not drift
    assert diff_runs(_run(new), _run(_fp())) == []


def test_injected_extra_recompile_is_flagged():
    """Anti-vacuity for the compile-observatory fields: one extra
    program build between replays is a deterministic regression (the
    exact failure mode shape canonicalization exists to prevent)."""
    new = _fp(distinct_programs=4,
              miss_causes={"new_program": 3, "shape_churn": 1})
    drifts = diff_runs(_run(_fp()), _run(new))
    assert any(d.kind == "recompile_drift" and d.deterministic
               for d in drifts)
    # FEWER programs (improvement) is not drift
    assert diff_runs(_run(new), _run(_fp())) == []


def test_injected_cause_shift_is_flagged():
    """Same build count, different cause mix: canonicalization quietly
    stopped collapsing a shape."""
    new = _fp(miss_causes={"new_program": 1, "shape_churn": 2})
    drifts = diff_runs(_run(_fp()), _run(new))
    assert [d.kind for d in drifts] == ["cause_shift"]
    assert drifts[0].deterministic
    assert "shape_churn" in drifts[0].detail


def test_compile_seconds_is_timing_class_only():
    new = _fp(compile_seconds=42.0)
    # no threshold: silence — compile time is in the timing class
    assert diff_runs(_run(_fp()), _run(new)) == []
    drifts = diff_runs(_run(_fp()), _run(new), wall_threshold_pct=50)
    assert any(d.kind == "compile_regression" and not d.deterministic
               for d in drifts)
    assert deterministic_drift(drifts) == []


def test_pre_observatory_fingerprints_never_false_trip():
    """A history spanning the v1->v2 upgrade must not flag the absence
    of compile fields as drift."""
    old = _fp()
    for f in ("distinct_programs", "miss_causes", "compile_seconds"):
        del old[f]
    assert diff_runs(_run(old), _run(_fp()),
                     wall_threshold_pct=10) == []


def test_operator_row_drift_and_plan_change():
    new = _fp()
    new["operators"]["AggExec"] = {"rows": 96, "bytes": 800,
                                   "batches": 1}
    drifts = diff_runs(_run(_fp()), _run(new))
    assert [d.kind for d in drifts] == ["operator_drift"]
    new2 = _fp(plan_shape=["SortExec", []])
    kinds = {d.kind for d in diff_runs(_run(_fp()), _run(new2))}
    assert "plan_change" in kinds


def test_lint_drift_and_corpus_change():
    new = _fp(lint_rule_hits=["TPU-L004"])
    assert [d.kind for d in diff_runs(_run(_fp()), _run(new))] == \
        ["lint_drift"]
    drifts = diff_runs(_run(_fp(0), _fp(1)), _run(_fp(0)))
    assert [d.kind for d in drifts] == ["query_removed"]


def test_deterministic_and_timing_fields_are_disjoint():
    assert not set(DETERMINISTIC_FIELDS) & set(TIMING_FIELDS)
    fp = _fp()
    for f in DETERMINISTIC_FIELDS + TIMING_FIELDS:
        assert f in fp, f


# ---------------------------------------------------------------------------
# append-only history
# ---------------------------------------------------------------------------

def test_history_dir_append_only_ordering(tmp_path):
    hist = HistoryDir(str(tmp_path / "h"))
    p1 = hist.record([_fp()], label="one")
    p2 = hist.record([_fp(), _fp(1)], label="two")
    assert hist.runs() == [p1, p2]
    assert os.path.exists(p1) and os.path.exists(p2)
    doc1, doc2 = hist.latest(2)
    assert doc1["label"] == "one" and len(doc2["queries"]) == 2
    # round trips through JSON exactly
    assert doc2["queries"][0] == _fp()


# ---------------------------------------------------------------------------
# end-to-end: real query -> event log -> fingerprint -> differ
# ---------------------------------------------------------------------------

@pytest.fixture()
def logged_run(tmp_path):
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession
    d = str(tmp_path / "evt")
    s = (TpuSession.builder()
         .config("spark.rapids.sql.enabled", True)
         .config("spark.rapids.tpu.singleChipFuse", "off")
         .config("spark.rapids.tpu.eventLog.dir", d)
         .get_or_create())
    tb = pa.table({
        "k": pa.array((np.arange(600) % 13).astype(np.int64)),
        "v": pa.array(np.arange(600, dtype=np.int64))})
    out = (s.create_dataframe(tb, num_partitions=2)
           .filter(col("v") > 9).group_by(col("k"))
           .agg(F.sum(col("v")).alias("sv")).collect())
    assert out.num_rows == 13
    logs = [f for f in os.listdir(d) if f.startswith("events_")]
    assert len(logs) == 1
    return os.path.join(d, logs[0])


def test_distilled_fingerprint_fields(logged_run):
    fps = distill_event_log(logged_run)
    assert len(fps) == 1
    fp = fps[0]
    assert not fp["failed"]
    # crossings were recorded (the sanctioned fetch path announced
    # itself) and the result fetch moved real rows
    assert fp["fetch_crossings"] >= 1
    ops = fp["operators"]
    assert any(v["rows"] > 0 for v in ops.values())
    assert fp["plan_shape"]
    assert isinstance(fp["fallback_ops"], list)
    assert fp["wall_ms"] >= 0
    json.dumps(fp)  # JSON-clean


def test_self_diff_of_real_run_is_silent(logged_run):
    fps = distill_event_log(logged_run)
    assert diff_runs(_run(*fps), _run(*copy.deepcopy(fps))) == []
    # ... and the injections still trip on the REAL fingerprint
    tampered = copy.deepcopy(fps)
    tampered[0]["fallback_ops"] = \
        sorted(tampered[0]["fallback_ops"] + ["InjectedExec"])
    tampered[0]["fetch_crossings"] += 2
    kinds = {d.kind for d in diff_runs(_run(*fps), _run(*tampered))}
    assert {"new_fallback", "crossing_growth"} <= kinds


# ---------------------------------------------------------------------------
# tools regress CLI
# ---------------------------------------------------------------------------

def test_tools_regress_cli_record_and_check(tmp_path, logged_run,
                                            capsys):
    from spark_rapids_tpu.tools.__main__ import main as tools_main
    hist = str(tmp_path / "hist")
    assert tools_main(["regress", "--history", hist, "--record",
                       logged_run]) == 0
    assert tools_main(["regress", "--history", hist, "--record",
                       logged_run, "--check"]) == 0
    out = capsys.readouterr().out
    assert "regress clean" in out
    # tamper the newest run on disk -> --check must fail
    h = HistoryDir(hist)
    newest = h.runs()[-1]
    doc = h.load(newest)
    doc["queries"][0]["fetch_crossings"] += 7
    with open(newest, "w") as f:
        json.dump(doc, f)
    assert tools_main(["regress", "--history", hist, "--check"]) == 1
    assert "crossing_growth" in capsys.readouterr().out


def test_tools_regress_cli_needs_two_runs(tmp_path, capsys):
    from spark_rapids_tpu.tools.__main__ import main as tools_main
    hist = str(tmp_path / "hist2")
    HistoryDir(hist).record([_fp()])
    assert tools_main(["regress", "--history", hist, "--check"]) == 2
    assert "need >= 2" in capsys.readouterr().err
