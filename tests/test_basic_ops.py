"""Project/filter/limit/union/range differential tests + expression
semantics against independent pandas/pyarrow oracles."""

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col, lit
from spark_rapids_tpu.testing.asserts import (
    assert_tpu_and_cpu_are_equal_collect, assert_tables_equal,
    with_tpu_session)
from spark_rapids_tpu.testing.data_gen import (
    BooleanGen, ByteGen, DoubleGen, FloatGen, IntegerGen, LongGen, ShortGen,
    StringGen, gen_df, gen_table)


def test_project_arithmetic():
    def q(spark):
        df = gen_df(spark, [("a", LongGen()), ("b", IntegerGen())],
                    length=512)
        return df.select(
            (col("a") + col("b")).alias("add"),
            (col("a") - col("b")).alias("sub"),
            (col("a") * col("b")).alias("mul"),
            (-col("a")).alias("neg"),
            F.abs(col("b")).alias("abs"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_division_semantics():
    def q(spark):
        df = gen_df(spark, [("a", LongGen()),
                            ("b", IntegerGen(lo=-3, hi=3))], length=512)
        return df.select(
            (col("a") / col("b")).alias("div"),
            (col("a") % col("b")).alias("mod"))
    assert_tpu_and_cpu_are_equal_collect(q, approximate_float=1e-12)


def test_filter_comparisons():
    def q(spark):
        df = gen_df(spark, [("a", IntegerGen()), ("b", IntegerGen())],
                    length=1024)
        return df.filter((col("a") > col("b")) | col("a").is_null())
    assert_tpu_and_cpu_are_equal_collect(q)


def test_filter_string_predicates():
    def q(spark):
        df = gen_df(spark, [("s", StringGen(max_len=6)), ("v", LongGen())],
                    length=1024)
        return df.filter(col("s") > lit("m")).select("s", "v")
    assert_tpu_and_cpu_are_equal_collect(q)


def test_conditional_exprs():
    def q(spark):
        df = gen_df(spark, [("a", IntegerGen()), ("b", IntegerGen())],
                    length=512)
        return df.select(
            F.when(col("a") > 0, col("a")).when(col("b") > 0, col("b"))
             .otherwise(lit(0)).alias("cw"),
            F.coalesce(col("a"), col("b"), lit(-1)).alias("co"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_math_functions():
    def q(spark):
        df = gen_df(spark, [("d", DoubleGen(no_nans=True))], length=512)
        return df.select(
            F.sqrt(F.abs(col("d"))).alias("sq"),
            F.floor(col("d")).alias("fl"),
            F.ceil(col("d")).alias("ce"),
            F.log(F.abs(col("d"))).alias("lg"),
            F.signum(col("d")).alias("sg"))
    assert_tpu_and_cpu_are_equal_collect(q, approximate_float=1e-9)


def test_casts():
    def q(spark):
        df = gen_df(spark, [("i", IntegerGen()), ("l", LongGen()),
                            ("d", DoubleGen()), ("b", BooleanGen())],
                    length=512)
        return df.select(
            col("i").cast("long").alias("i2l"),
            col("l").cast("int").alias("l2i"),
            col("d").cast("int").alias("d2i"),
            col("i").cast("double").alias("i2d"),
            col("b").cast("int").alias("b2i"),
            col("i").cast("string").alias("i2s"),
            col("b").cast("string").alias("b2s"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_string_roundtrip_cast():
    def q(spark):
        df = gen_df(spark, [("l", LongGen())], length=512)
        return df.select(col("l").cast("string").cast("long").alias("r"),
                         col("l"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q)
    # also verify against the source column (independent oracle)
    for row in tpu.to_pylist():
        assert row["r"] == row["l"]


def test_limit_and_union():
    def q(spark):
        df1 = gen_df(spark, [("a", IntegerGen())], length=100, seed=1)
        df2 = gen_df(spark, [("a", IntegerGen())], length=100, seed=2)
        return df1.union(df2).limit(150)
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q)
    assert cpu.num_rows == 150


def test_range():
    def q(spark):
        return spark.range(0, 1000, 3).select(
            (col("id") * 2).alias("x"))
    assert_tpu_and_cpu_are_equal_collect(q)


def test_three_valued_logic_vs_oracle():
    """AND/OR null semantics checked against explicit truth table."""
    tbl = pa.table({
        "a": pa.array([True, True, True, False, False, False, None, None,
                       None]),
        "b": pa.array([True, False, None, True, False, None, True, False,
                       None])})

    def q(spark):
        df = spark.create_dataframe(tbl)
        return df.select((col("a") & col("b")).alias("and_"),
                         (col("a") | col("b")).alias("or_"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    assert tpu.column("and_").to_pylist() == [
        True, False, None, False, False, False, None, False, None]
    assert tpu.column("or_").to_pylist() == [
        True, True, True, True, False, None, True, None, None]


def test_nan_comparison_semantics():
    """Spark: NaN = NaN is true; NaN greater than all doubles."""
    tbl = pa.table({"a": pa.array([float("nan"), 1.0, float("inf")]),
                    "b": pa.array([float("nan"), float("nan"), 1.0])})

    def q(spark):
        df = spark.create_dataframe(tbl)
        return df.select((col("a") == col("b")).alias("eq"),
                         (col("a") > col("b")).alias("gt"),
                         (col("a") < col("b")).alias("lt"))
    cpu, tpu = assert_tpu_and_cpu_are_equal_collect(q, ignore_order=False)
    assert tpu.column("eq").to_pylist() == [True, False, False]
    assert tpu.column("gt").to_pylist() == [False, False, True]
    assert tpu.column("lt").to_pylist() == [False, True, False]


def test_explain_shows_tpu_placement():
    def q(spark):
        df = spark.create_dataframe({"a": [1, 2, 3]})
        return df.filter(col("a") > 1)
    out = with_tpu_session(lambda s: (q(s).collect(), s.last_explain))
    _, explain = out
    assert "will run on TPU" in explain


def test_distinct_multi_partition_dedupes_globally():
    """distinct() over a multi-partition source must co-locate rows
    before deduplicating — per-partition-only dedup leaks duplicates
    across partitions (round-5 regression test)."""
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.api.session import TpuSession
    rng = np.random.default_rng(6)
    tb = pa.table({
        "k": pa.array(rng.integers(0, 9, 500).astype(np.int64)),
        "s": pa.array([f"g{int(i) % 5}" for i in rng.integers(0, 50, 500)]),
    })
    want = tb.group_by(["k", "s"]).aggregate([]).num_rows
    for enabled in (True, False):
        s = (TpuSession.builder()
             .config("spark.rapids.sql.enabled", enabled)
             .get_or_create())
        got = s.create_dataframe(tb, num_partitions=4).distinct().collect()
        assert got.num_rows == want, (enabled, got.num_rows, want)
