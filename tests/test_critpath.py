"""Critical-path extraction edge cases (obs/critpath.py) plus the
latency observatory's windows (obs/slo.py).

The extractor runs on the neutral ``span_dicts()`` schema, so most
tests here hand-build span trees with exact nanosecond intervals and
assert the partition property directly: segments must sum to the root
wall time (the sweep is an exact partition by construction — any
residual is an algorithm bug, which is precisely what the tolerance
gate exists to catch)."""

import json

import pyarrow as pa
import pytest

from spark_rapids_tpu.obs.critpath import (
    RECONCILE_TOLERANCE, SEG_COMPILE, SEG_FETCH_SERVE, SEG_FETCH_WIRE,
    SEG_OC_SPILL, SEG_OTHER, SEG_PLANNING, SEG_PREWARM, SEG_QUEUE_WAIT,
    SEG_SHUFFLE_WRITE, extract_critical_path, segment_of)
from spark_rapids_tpu.obs.slo import (LatencyObservatory, aggregate_tail,
                                      format_tail_report)

MS = 1_000_000  # ns


def mk(sid, parent, name, kind, t0_ms, dur_ms, status="ok", proc=None,
       **attrs):
    d = {"spanId": sid, "parentId": parent, "name": name, "kind": kind,
         "startNs": int(t0_ms * MS), "durNs": int(dur_ms * MS),
         "status": status, "tid": 1, "attrs": attrs}
    if proc:
        d["proc"] = proc
    return d


def total(res):
    return sum(res["segments"].values())


@pytest.fixture
def fresh_observatory():
    LatencyObservatory.reset_for_tests()
    yield
    LatencyObservatory.reset_for_tests()


# ---------------------------------------------------------------------------
# partition property
# ---------------------------------------------------------------------------

def test_taxonomy_partition_sums_to_wall():
    spans = [
        mk(1, None, "query", "query", 0, 100),
        mk(2, 1, "admission.wait", "span", 0, 10, bytes=1 << 20),
        mk(3, 1, "phase:plan", "phase", 10, 10),
        mk(4, 1, "phase:execute", "phase", 20, 75),
        mk(5, 4, "FilterExec.execute", "operator", 25, 35,
           op="FilterExec"),
    ]
    res = extract_critical_path(spans)
    segs = res["segments"]
    assert segs[SEG_QUEUE_WAIT] == pytest.approx(0.010)
    assert segs[SEG_PLANNING] == pytest.approx(0.010)
    assert segs["compute:FilterExec"] == pytest.approx(0.035)
    # root self-time (95..100) + execute self-time (20..25, 60..95)
    assert segs[SEG_OTHER] == pytest.approx(0.045)
    assert total(res) == pytest.approx(res["wall_s"], abs=1e-12)
    assert res["reconciled"]


def test_concurrent_partitions_do_not_double_book():
    # two per-partition execute spans overlap 10..90: naive duration
    # summing books 170ms into a 100ms window; the sweep assigns each
    # elementary slice to the covering span that ends last
    spans = [
        mk(1, None, "query", "query", 0, 100),
        mk(2, 1, "phase:execute", "phase", 0, 100),
        mk(3, 2, "AggExec.execute", "operator", 0, 90, op="AggExec"),
        mk(4, 2, "AggExec.execute", "operator", 10, 90, op="AggExec"),
    ]
    res = extract_critical_path(spans)
    assert res["segments"]["compute:AggExec"] == pytest.approx(0.100)
    assert total(res) == pytest.approx(0.100, abs=1e-12)
    assert res["reconciled"]


def test_failed_query_error_span_mid_tree_reconciles():
    # finalize() closes open spans on failure, so an error span still
    # carries a closed interval — the partition must not care
    spans = [
        mk(1, None, "query", "query", 0, 50, status="error"),
        mk(2, 1, "phase:execute", "phase", 10, 40, status="error"),
        mk(3, 2, "SortExec.execute", "operator", 10, 25, status="error",
           op="SortExec"),
    ]
    res = extract_critical_path(spans)
    assert res["segments"]["compute:SortExec"] == pytest.approx(0.025)
    assert total(res) == pytest.approx(res["wall_s"], abs=1e-12)
    assert res["reconciled"]


def test_zero_length_spans_and_events_are_ignored():
    spans = [
        mk(1, None, "query", "query", 0, 10),
        mk(2, 1, "phase:execute", "phase", 0, 0),      # zero-length
        mk(3, 1, "shuffle.remote_fetch", "event", 5, 0),
        mk(4, 1, "fetch.crossing", "event", 6, 0),
    ]
    res = extract_critical_path(spans)
    assert res["segments"] == {SEG_OTHER: pytest.approx(0.010)}
    assert res["reconciled"]


def test_remote_fetch_wire_vs_producer_serve_split():
    # grafted producer spans carry `proc`: their time is the
    # producer's serve, the fetch span's remaining self-time is wire
    spans = [
        mk(1, None, "query", "query", 0, 100),
        mk(2, 1, "phase:execute", "phase", 0, 100),
        mk(3, 2, "shuffle.fetch", "span", 10, 80, shuffle_id=1),
        mk(4, 3, "ShuffleWriteExec.execute", "operator", 30, 40,
           proc="executor-2", op="ShuffleWriteExec"),
    ]
    res = extract_critical_path(spans)
    assert res["segments"][SEG_FETCH_WIRE] == pytest.approx(0.040)
    assert res["segments"][SEG_FETCH_SERVE] == pytest.approx(0.040)
    assert total(res) == pytest.approx(0.100, abs=1e-12)
    assert res["reconciled"]


def test_jit_build_event_synthesizes_compile_interval():
    # jit.build is an instant event carrying total_s: the extractor
    # reconstructs [t - total_s, t] as a compile child so operator
    # self-time is not silently inflated by XLA builds
    spans = [
        mk(1, None, "query", "query", 0, 100),
        mk(2, 1, "ProjectExec.execute", "operator", 0, 100,
           op="ProjectExec"),
        mk(3, 2, "jit.build", "event", 50, 0, total_s=0.030,
           cause="new_program"),
    ]
    res = extract_critical_path(spans)
    assert res["segments"][SEG_COMPILE] == pytest.approx(0.030)
    assert res["segments"]["compute:ProjectExec"] == pytest.approx(0.070)
    assert res["reconciled"]


def test_prewarm_cause_classifies_separately():
    spans = [
        mk(1, None, "query", "query", 0, 50),
        mk(2, 1, "jit.build", "event", 40, 0, total_s=0.020,
           cause="prewarm"),
    ]
    res = extract_critical_path(spans)
    assert res["segments"][SEG_PREWARM] == pytest.approx(0.020)
    assert total(res) == pytest.approx(0.050, abs=1e-12)


def test_compile_interval_clips_to_parent():
    # a build longer than its parent's elapsed time must not book
    # negative self-time: the synthetic interval clips at the parent
    spans = [
        mk(1, None, "query", "query", 0, 20),
        mk(2, 1, "jit.build", "event", 10, 0, total_s=0.050),
    ]
    res = extract_critical_path(spans)
    assert res["segments"][SEG_COMPILE] == pytest.approx(0.010)
    assert total(res) == pytest.approx(0.020, abs=1e-12)
    assert res["reconciled"]


def test_empty_and_rootless_traces_are_benign():
    assert extract_critical_path([])["segments"] == {}
    res = extract_critical_path(
        [mk(1, None, "phase:plan", "phase", 0, 10)])
    assert res["segments"] == {} and res["reconciled"]


def test_segment_of_taxonomy():
    assert segment_of(mk(1, None, "oc.sort_run", "span", 0, 1)) == \
        SEG_OC_SPILL
    assert segment_of(mk(1, None, "shuffle.map_write", "span", 0, 1)) \
        == SEG_SHUFFLE_WRITE
    assert segment_of(mk(1, None, "replan", "replan", 0, 1)) == \
        SEG_PLANNING
    assert segment_of(mk(1, None, "bridge.execute_stage", "span", 0, 1)
                      ) == SEG_OTHER
    # proc wins over every name-based rule
    assert segment_of(mk(1, None, "phase:plan", "phase", 0, 1,
                         proc="exec-1")) == SEG_FETCH_SERVE


# ---------------------------------------------------------------------------
# observatory windows + tail aggregation
# ---------------------------------------------------------------------------

def test_burn_rate_window_and_reservoir(fresh_observatory):
    obs = LatencyObservatory.get().configure(target_ms=100,
                                             objective=0.9)
    for _ in range(18):
        obs.record("pool-1", 0.010, {"compute:FilterExec": 0.010})
    for _ in range(2):
        obs.record("pool-1", 0.500, {SEG_QUEUE_WAIT: 0.450,
                                     "compute:FilterExec": 0.050})
    rep = obs.slo_report()
    t = rep["tenants"]["pool-1"]
    assert t["total"] == 20 and t["good"] == 18
    # bad share 2/20 = 10%, error budget 10% -> burn exactly 1.0
    assert t["burn_rate"] == pytest.approx(1.0)
    assert t["dominant_tail_segment"] == SEG_QUEUE_WAIT
    tail = obs.tail_report()["tenants"]["pool-1"]
    assert tail["slowest"][0]["wall_ms"] == pytest.approx(500.0)
    assert tail["p99_mix"][SEG_QUEUE_WAIT] == pytest.approx(0.9)
    assert "queue_wait" in format_tail_report(obs.tail_report())


def test_failed_queries_are_always_bad(fresh_observatory):
    obs = LatencyObservatory.get().configure(target_ms=10_000,
                                             objective=0.5)
    obs.record("pool-0", 0.001, {SEG_OTHER: 0.001}, failed=True)
    rep = obs.slo_report()["tenants"]["pool-0"]
    assert rep["good"] == 0 and rep["total"] == 1
    assert rep["burn_rate"] == pytest.approx(2.0)


def test_client_cancel_excluded_from_burn_window(fresh_observatory):
    """A client cancel is the caller changing its mind, not the engine
    missing: it must stay OUT of the burn window entirely — counting it
    either way would let a cancel storm fake (or mask) real burn."""
    obs = LatencyObservatory.get().configure(target_ms=100,
                                             objective=0.9)
    for _ in range(9):
        obs.record("pool-1", 0.010, {"compute:FilterExec": 0.010})
    obs.record("pool-1", 0.500, {SEG_QUEUE_WAIT: 0.500})  # one real miss
    base = obs.slo_report()["tenants"]["pool-1"]
    assert base["window"] == 10
    assert base["burn_rate"] == pytest.approx(1.0)
    # a burst of client cancels — slow AND fast — moves nothing
    obs.record("pool-1", 5.0, {SEG_QUEUE_WAIT: 5.0},
               failed=True, cancelled=True)
    obs.record("pool-1", 0.001, {SEG_OTHER: 0.001},
               failed=True, cancelled=True)
    rep = obs.slo_report()["tenants"]["pool-1"]
    assert rep["total"] == 12       # still counted as traffic
    assert rep["window"] == 10      # ... but absent from the window
    assert rep["burn_rate"] == pytest.approx(1.0)


def test_deadline_miss_counts_bad_in_burn_window(fresh_observatory):
    """A blown deadline IS the latency failure the SLO exists to catch:
    it counts BAD in the window even when the measured wall is under
    target, and even though the request also carries the cancelled flag
    (deadline wins over the client-cancel exclusion)."""
    obs = LatencyObservatory.get().configure(target_ms=100,
                                             objective=0.9)
    for _ in range(9):
        obs.record("pool-1", 0.010, {"compute:FilterExec": 0.010})
    obs.record("pool-1", 0.005, {SEG_OTHER: 0.005},
               cancelled=True, deadline=True)  # wall < target, still bad
    rep = obs.slo_report()["tenants"]["pool-1"]
    assert rep["total"] == 10 and rep["good"] == 9
    assert rep["window"] == 10
    assert rep["burn_rate"] == pytest.approx(1.0)


def test_ledger_sink_appends_jsonl(fresh_observatory, tmp_path):
    path = tmp_path / "latency_ledger.jsonl"
    obs = LatencyObservatory.get().configure(target_ms=100,
                                             ledger_path=str(path))
    obs.record("pool-2", 0.042, {SEG_OTHER: 0.042}, label="AggExec")
    obs.record("pool-2", 0.300, {SEG_QUEUE_WAIT: 0.300})
    lines = [json.loads(x) for x in
             path.read_text().strip().splitlines()]
    assert len(lines) == 2
    assert lines[0]["tenant"] == "pool-2" and lines[0]["good"]
    assert not lines[1]["good"]
    from spark_rapids_tpu.tools.tail_report import (aggregate_records,
                                                    load_ledger)
    agg = aggregate_records(load_ledger(str(tmp_path)))
    assert agg["tenants"]["pool-2"]["dominant_tail_segment"] == \
        SEG_QUEUE_WAIT


def test_aggregate_tail_empty():
    assert aggregate_tail([]) is None


# ---------------------------------------------------------------------------
# end to end: a real traced query flows through all three sinks
# ---------------------------------------------------------------------------

def test_traced_query_triple_sinks(fresh_observatory, tmp_path):
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.obs.metrics import MetricsRegistry

    s = TpuSession.builder() \
        .config("spark.rapids.sql.enabled", True) \
        .config("spark.rapids.tpu.trace.enabled", True) \
        .config("spark.rapids.tpu.slo.targetMs", 60_000) \
        .config("spark.rapids.tpu.regress.historyDir", str(tmp_path)) \
        .get_or_create()
    df = s.create_dataframe(pa.table({"x": pa.array(range(256))}))
    df.group_by(col("x")).agg(F.count("*").alias("c")).collect()

    tracer = s.last_query_trace()
    assert tracer is not None
    root = [sp for sp in tracer.span_dicts() if sp["kind"] == "query"][0]
    cp = root["attrs"].get("critical_path")
    assert cp and root["attrs"]["critical_path_reconciled"]
    assert sum(cp.values()) == pytest.approx(
        root["durNs"] / 1e9, rel=RECONCILE_TOLERANCE, abs=1e-3)

    fam = [f for f in MetricsRegistry.get().families()
           if f.name == "tpu_latency_segment_seconds_total"]
    assert fam and fam[0].total() > 0

    obs = LatencyObservatory.get()
    rep = obs.slo_report()
    assert rep["enabled"] and rep["tenants"]["default"]["total"] >= 1
    ledger = tmp_path / "latency_ledger.jsonl"
    assert ledger.exists()
    rec = json.loads(ledger.read_text().strip().splitlines()[-1])
    assert rec["reconciled"] and rec["segments"]


# -- tail-mix shift across runs ---------------------------------------------


def test_tail_mix_shift_is_timing_class_and_threshold_gated():
    from spark_rapids_tpu.obs.history import diff_fingerprints
    base = {"sql_id": 0, "description": "q0",
            "tail_dominant_segment": {"pool-1": "compute:FilterExec"}}
    shifted = dict(base,
                   tail_dominant_segment={"pool-1": "queue_wait"})
    # no percentile checks asked for: silence
    assert not any(d.kind == "tail_mix_shift"
                   for d in diff_fingerprints(base, shifted))
    drifts = diff_fingerprints(base, shifted, wall_threshold_pct=10)
    d = next(d for d in drifts if d.kind == "tail_mix_shift")
    assert not d.deterministic
    assert "pool-1" in d.detail
    assert "compute:FilterExec" in d.detail and "queue_wait" in d.detail


def test_tail_mix_shift_needs_both_runs_to_carry_it():
    """A history spanning the latency-observatory upgrade (old runs
    have no tail_dominant_segment) must never false-trip."""
    from spark_rapids_tpu.obs.history import diff_fingerprints
    old = {"sql_id": 0, "description": "q0"}
    new = {"sql_id": 0, "description": "q0",
           "tail_dominant_segment": {"pool-1": "queue_wait"}}
    for a, b in ((old, new), (new, old)):
        assert not any(d.kind == "tail_mix_shift"
                       for d in diff_fingerprints(
                           a, b, wall_threshold_pct=10))
