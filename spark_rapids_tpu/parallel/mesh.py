"""Device-mesh management.

The TPU analog of the reference's device acquisition + peer topology
bootstrap (ref: GpuDeviceManager.scala:125 initializeGpuAndMemory picks
one GPU per executor; RapidsShuffleHeartbeatManager.scala:50 teaches
executors about each other so UCX endpoints can form).  On TPU the
topology is declarative: a `jax.sharding.Mesh` over the slice's chips,
with the `"data"` axis carrying SQL data parallelism.  XLA lays the
collectives onto ICI; multi-pod meshes extend the same axis over DCN.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"

# Hard deadline on first-touch device discovery.  jax.devices() on a
# multichip slice blocks on PJRT topology exchange: one unreachable
# chip/host and the call hangs FOREVER (the MULTICHIP rc=124 rounds —
# the whole benchmark died inside discovery with nothing in-repo
# noticing).  The deadline turns that hang into a counted, traced,
# cleanly-degradable failure.
DEFAULT_PROBE_TIMEOUT_S = 120.0


class DeviceDiscoveryTimeout(RuntimeError):
    """Device discovery exceeded its hard deadline (likely an
    unreachable chip or a dead accelerator tunnel)."""


def _probe_timeout_s() -> float:
    raw = os.environ.get("SPARK_RAPIDS_TPU_DEVICE_PROBE_TIMEOUT_S")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return DEFAULT_PROBE_TIMEOUT_S


def discover_devices(timeout_s: Optional[float] = None) -> List:
    """``jax.devices()`` under a hard deadline.

    On timeout the daemon probe thread is left behind (there is no safe
    way to interrupt a hung PJRT client), ``tpu_device_probe_failures_
    total`` increments, a tracer event is emitted, and
    ``DeviceDiscoveryTimeout`` raises so callers take their single-chip
    or skip fallback instead of hanging the process."""
    from ..obs import metrics as m
    from ..obs.tracer import trace_event
    timeout_s = _probe_timeout_s() if timeout_s is None else timeout_s
    result: List = []
    error: List[BaseException] = []

    def probe():
        try:
            result.extend(jax.devices())
        except BaseException as ex:  # noqa: BLE001 — report, not mask
            error.append(ex)

    t = threading.Thread(target=probe, daemon=True,
                         name="tpu-device-probe")
    t.start()
    t.join(timeout_s)
    fail = m.counter("tpu_device_probe_failures_total",
                     "device discovery timeouts / errors")
    ok = m.gauge("tpu_device_probe_ok",
                 "1 when the last device probe succeeded, else 0")
    if t.is_alive():
        fail.inc()
        ok.set(0)
        trace_event("mesh.probe_timeout", timeout_s=timeout_s)
        raise DeviceDiscoveryTimeout(
            f"device discovery exceeded {timeout_s:g}s (unreachable "
            f"chip or dead tunnel); set "
            f"SPARK_RAPIDS_TPU_DEVICE_PROBE_TIMEOUT_S to adjust")
    if error:
        fail.inc()
        ok.set(0)
        trace_event("mesh.probe_error", error=repr(error[0]))
        raise error[0]
    ok.set(1)
    return result


def device_count(timeout_s: Optional[float] = None,
                 default: int = 1) -> int:
    """Visible-device count with the discovery deadline applied; a
    timed-out or failed probe degrades to ``default`` (single-chip) so
    planning gates skip the multichip path instead of hanging."""
    try:
        return len(discover_devices(timeout_s))
    except Exception as ex:
        # deliberate degradation to single-chip — breadcrumb the
        # swallowed probe error so a dead tunnel is diagnosable from
        # the trace (tpufsan TPU-R011)
        from ..obs.tracer import trace_event
        trace_event("mesh.degrade_single_chip", error=repr(ex))
        return default


def build_mesh(n_devices: Optional[int] = None,
               axis_name: str = DATA_AXIS,
               devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D data-parallel mesh over the first ``n_devices`` chips."""
    devs = list(devices) if devices is not None else discover_devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), axis_names=(axis_name,))


def mesh_sharding(mesh: Mesh, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Row-sharded placement: leading axis split across the data axis."""
    return NamedSharding(mesh, PartitionSpec(axis_name))
