"""Device-mesh management.

The TPU analog of the reference's device acquisition + peer topology
bootstrap (ref: GpuDeviceManager.scala:125 initializeGpuAndMemory picks
one GPU per executor; RapidsShuffleHeartbeatManager.scala:50 teaches
executors about each other so UCX endpoints can form).  On TPU the
topology is declarative: a `jax.sharding.Mesh` over the slice's chips,
with the `"data"` axis carrying SQL data parallelism.  XLA lays the
collectives onto ICI; multi-pod meshes extend the same axis over DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"


def build_mesh(n_devices: Optional[int] = None,
               axis_name: str = DATA_AXIS,
               devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D data-parallel mesh over the first ``n_devices`` chips."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), axis_names=(axis_name,))


def mesh_sharding(mesh: Mesh, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Row-sharded placement: leading axis split across the data axis."""
    return NamedSharding(mesh, PartitionSpec(axis_name))
