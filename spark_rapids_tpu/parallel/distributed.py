"""SPMD distributed query steps over a device mesh.

The multi-chip execution mode: instead of the host-orchestrated
partition-iterator shuffle (shuffle/manager.py — the analog of the
reference's always-available Spark-shuffle path), a whole query stage
compiles into ONE `shard_map`-ped XLA program per schema: every device
runs the identical operator pipeline on its shard and rows move over ICI
with `all_to_all` (parallel/alltoall.py).  This is the structural
equivalent of the reference's accelerated UCX shuffle stage
(ref: RapidsShuffleInternalManagerBase.scala:74 caching writer keeping
batches on-device; shuffle-plugin/.../UCXShuffleTransport.scala), with
the XLA compiler playing the role of the transport state machines.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import pyarrow as pa

try:
    from jax import shard_map
except ImportError:
    # pre-0.6 jax ships shard_map under experimental with the replica
    # check named check_rep instead of check_vma; adapt the call shape
    # so the SPMD stages run on both API generations
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=True):
        if f is None:
            return lambda g: shard_map(g, mesh=mesh, in_specs=in_specs,
                                       out_specs=out_specs,
                                       check_vma=check_vma)
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)
from jax.sharding import Mesh, PartitionSpec as P

from .. import types as t
from ..columnar.device import (DEFAULT_ROW_BUCKETS, DeviceBatch,
                               batch_to_arrow, batch_to_device, bucket_for)
from ..expr.core import EvalContext
from ..shuffle.partitioning import HashPartitioning
from .alltoall import (allgather_batch, allgather_supported,
                       exchange_by_pid, exchange_supported)
from .mesh import DATA_AXIS, build_mesh


class _SchemaSource:
    """Placeholder child carrying only an output schema, so exec nodes can
    be built against shard inputs that exist only inside shard_map."""

    num_partitions = 1

    def __init__(self, names: Sequence[str], dtypes: Sequence[t.DataType]):
        self.output_names = list(names)
        self.output_types = list(dtypes)
        self.children = []

    def execute_partition(self, pid, ctx):  # pragma: no cover
        raise RuntimeError("schema-only node is never executed")


def stack_shards(tables: Sequence[pa.Table], capacity: Optional[int] = None):
    """Upload one Arrow table per device and stack them on a leading
    device axis (the host->mesh transfer; each shard then lives on its
    device under `jax.device_put` with a row sharding)."""
    n_rows = max(max((tb.num_rows for tb in tables), default=1), 1)
    cap = capacity or bucket_for(n_rows, DEFAULT_ROW_BUCKETS)
    batches = []
    for tb in tables:
        rbs = tb.combine_chunks().to_batches()
        rb = rbs[0] if rbs else pa.RecordBatch.from_pydict(
            {f.name: pa.array([], type=f.type) for f in tb.schema},
            schema=tb.schema)
        batches.append(batch_to_device(rb, capacity=cap))
    # equalize char capacities across shards so stacking is legal
    batches = _equalize_char_caps(batches)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0),
                                     *batches)
    return stacked


def _equalize_char_caps(batches: List[DeviceBatch]) -> List[DeviceBatch]:
    """Pad every shard's span child lanes (string chars, array/map
    element lanes) to the max across shards so stacking is legal."""
    from ..columnar.device import DeviceColumn
    if not batches:
        return batches

    def pad_lane(x, cap):
        cur = int(x.shape[0])
        if cur >= cap:
            return x
        return jnp.concatenate([x, jnp.zeros((cap - cur,), x.dtype)])

    def equalize(cols: List[DeviceColumn]) -> List[DeviceColumn]:
        dt = cols[0].dtype
        if isinstance(dt, (t.StringType, t.BinaryType)):
            cap = max(int(c.data.shape[0]) for c in cols)
            return [DeviceColumn(c.dtype, data=pad_lane(c.data, cap),
                                 validity=c.validity, offsets=c.offsets)
                    for c in cols]
        if isinstance(dt, (t.ArrayType, t.MapType)):
            child_cols = [equalize([c.children[i] for c in cols])
                          for i in range(len(cols[0].children))]
            caps = [max(int(lane.shape[0])
                        for lane in (ch.data for ch in group))
                    for group in child_cols]
            padded = []
            for group, cap in zip(child_cols, caps):
                padded.append([
                    DeviceColumn(ch.dtype, data=pad_lane(ch.data, cap),
                                 validity=None if ch.validity is None
                                 else pad_lane(ch.validity, cap),
                                 offsets=ch.offsets,
                                 data_hi=None if ch.data_hi is None
                                 else pad_lane(ch.data_hi, cap),
                                 children=ch.children)
                    for ch in group])
            return [DeviceColumn(c.dtype, validity=c.validity,
                                 offsets=c.offsets,
                                 children=tuple(padded[i][bi]
                                                for i in range(len(padded))))
                    for bi, c in enumerate(cols)]
        if isinstance(dt, t.StructType):
            child_cols = [equalize([c.children[i] for c in cols])
                          for i in range(len(cols[0].children))]
            return [DeviceColumn(c.dtype, validity=c.validity,
                                 children=tuple(child_cols[i][bi]
                                                for i in range(len(child_cols))))
                    for bi, c in enumerate(cols)]
        return list(cols)

    ncol = batches[0].num_cols
    per_col = [equalize([b.columns[ci] for b in batches])
               for ci in range(ncol)]
    return [DeviceBatch([per_col[ci][bi] for ci in range(ncol)],
                        b.num_rows, b.names)
            for bi, b in enumerate(batches)]


def unstack_shards(stacked: DeviceBatch) -> List[DeviceBatch]:
    n_dev = int(jax.tree_util.tree_leaves(stacked)[0].shape[0])
    return [jax.tree_util.tree_map(lambda x, i=i: x[i], stacked)
            for i in range(n_dev)]


def shards_to_table(stacked: DeviceBatch) -> pa.Table:
    tables = [pa.Table.from_batches([batch_to_arrow(b)])
              for b in unstack_shards(stacked)]
    return pa.concat_tables(tables)


class DistributedAggregate:
    """Distributed GROUP BY: local partial agg -> ICI all_to_all on key
    hash -> local final agg.  Compiles to one XLA program; every stage
    stays on device (the reference's partial/exchange/final pipeline,
    aggregate.scala:258-275 + GpuShuffleExchangeExec, fused end-to-end)."""

    def __init__(self, grouping, aggregates, in_names, in_types,
                 mesh: Optional[Mesh] = None, axis: str = DATA_AXIS):
        from ..exec.aggregate import TpuHashAggregateExec
        from ..expr.aggregates import FINAL, PARTIAL
        self.mesh = mesh or build_mesh()
        self.axis = axis
        self.n_dev = self.mesh.shape[axis]
        src = _SchemaSource(in_names, in_types)
        self.partial = TpuHashAggregateExec(list(grouping), list(aggregates),
                                            PARTIAL, src)
        self.final = TpuHashAggregateExec(list(grouping),
                                          self.partial.aggregates, FINAL,
                                          self.partial)
        reason = exchange_supported(self.partial.output_types)
        if reason is None and not self.partial.grouping:
            # the ungrouped path replicates partial buffers through
            # allgather_batch, whose dtype coverage is STRICTLY NARROWER
            # than the exchange kernel's (no array/map span layout) — a
            # global collect_list/collect_set must fail HERE, at
            # planning/construction time, so callers fall back to the
            # host path instead of crashing mid-query (ADVICE round 5,
            # analysis/capabilities.py verify_gates)
            reason = allgather_supported(self.partial.output_types)
        if reason:
            raise NotImplementedError(reason)
        k = len(list(grouping))
        # route on the SAME Spark-compatible murmur3+pmod rule the host
        # shuffle uses (shuffle/partitioning.py), so both paths agree on
        # key placement
        self._routing = HashPartitioning(
            [_attr(n, dt) for n, dt in zip(self.partial.output_names[:k],
                                           self.partial.output_types[:k])],
            self.n_dev).bind(self.partial.output_names,
                             self.partial.output_types)

    @property
    def output_names(self):
        return self.final.output_names

    @property
    def output_types(self):
        return self.final.output_types

    def _step(self, shard: DeviceBatch) -> DeviceBatch:
        # leading device axis arrives stripped of sharding but kept as a
        # size-1 axis; drop it
        b = jax.tree_util.tree_map(lambda x: x[0], shard)
        part = self.partial._update_batch(jnp, b)
        if self.partial.grouping:
            ctx = EvalContext(jnp, part)
            pids = self._routing.partition_ids(jnp, ctx, part)
            routed = exchange_by_pid(part, pids, self.n_dev, self.axis)
        else:
            # global aggregate: replicate partials, every device computes
            # the same final row (cheap; buffers are one row each)
            routed = allgather_batch(part, self.axis, self.n_dev)
        merged = self.final._merge_batch(jnp, routed)
        out = self.final._evaluate_batch(jnp, merged)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    @functools.cached_property
    def _jit_key(self):
        from ..exec.base import semantic_sig
        return ("DistributedAggregate", self.axis,
                tuple(d.id for d in self.mesh.devices.flat),
                self.partial._jit_key, self.final._jit_key,
                semantic_sig(self._routing))

    @property
    def _compiled(self):
        from ..exec.base import process_jit

        def make():
            return shard_map(self._step, mesh=self.mesh,
                             in_specs=P(self.axis), out_specs=P(self.axis),
                             check_vma=False)
        return process_jit(self._jit_key, make)

    def run(self, tables: Sequence[pa.Table]) -> pa.Table:
        """tables: one scan shard per device."""
        assert len(tables) == self.n_dev, \
            f"need {self.n_dev} shards, got {len(tables)}"
        stacked = stack_shards(tables)
        out = self._compiled(stacked)
        result = shards_to_table(out)
        if not self.partial.grouping and result.num_rows:
            # every device produced the same global row; keep one
            result = result.slice(0, 1)
        return result


class DistributedExchange:
    """A bare distributed repartition: rows move to `hash(keys) % n_dev`
    (the building block joins/sorts stage on; analog of
    GpuShuffleExchangeExec.doExecuteColumnar, execution/
    GpuShuffleExchangeExec.scala:223)."""

    def __init__(self, keys, in_names, in_types,
                 mesh: Optional[Mesh] = None, axis: str = DATA_AXIS):
        self.mesh = mesh or build_mesh()
        self.axis = axis
        self.n_dev = self.mesh.shape[axis]
        reason = exchange_supported(in_types)
        if reason:
            raise NotImplementedError(reason)
        self.in_names, self.in_types = list(in_names), list(in_types)
        self._routing = HashPartitioning(list(keys), self.n_dev).bind(
            self.in_names, self.in_types)

    def _step(self, shard):
        b = jax.tree_util.tree_map(lambda x: x[0], shard)
        ctx = EvalContext(jnp, b)
        pids = self._routing.partition_ids(jnp, ctx, b)
        out = exchange_by_pid(b, pids, self.n_dev, self.axis)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    @functools.cached_property
    def _jit_key(self):
        from ..exec.base import semantic_sig
        return ("DistributedExchange", self.axis,
                tuple(d.id for d in self.mesh.devices.flat),
                tuple(zip(self.in_names, map(repr, self.in_types))),
                semantic_sig(self._routing))

    @property
    def _compiled(self):
        from ..exec.base import process_jit

        def make():
            return shard_map(self._step, mesh=self.mesh,
                             in_specs=P(self.axis), out_specs=P(self.axis),
                             check_vma=False)
        return process_jit(self._jit_key, make)

    def run_stacked(self, stacked: DeviceBatch) -> DeviceBatch:
        return self._compiled(stacked)

    def run(self, tables: Sequence[pa.Table]) -> List[pa.Table]:
        assert len(tables) == self.n_dev
        out = self.run_stacked(stack_shards(tables))
        return [pa.Table.from_batches([batch_to_arrow(b)])
                for b in unstack_shards(out)]


class DistributedSort:
    """Distributed total-order sort in ONE SPMD program: per-shard splitter
    sampling -> all_gather of candidates -> route rows to their key range
    with all_to_all -> local multi-key sort.  Device ``i`` ends up holding
    globally-ordered range ``i`` (read shards in mesh order for the total
    order) — the ICI realization of the reference's range-partition +
    per-partition sort pipeline (ref GpuRangePartitioner.scala +
    GpuSortExec.scala), with the sample/boundary handshake that Spark does
    on the driver folded into the compiled program as collectives."""

    def __init__(self, orders, in_names, in_types,
                 mesh: Optional[Mesh] = None, axis: str = DATA_AXIS):
        from ..exec.sort import SortExec
        self.mesh = mesh or build_mesh()
        self.axis = axis
        self.n_dev = self.mesh.shape[axis]
        reason = exchange_supported(in_types)
        if reason:
            raise NotImplementedError(reason)
        self.in_names, self.in_types = list(in_names), list(in_types)
        src = _SchemaSource(in_names, in_types)
        self._sorter = SortExec(list(orders), src)

    output_names = property(lambda self: self.in_names)
    output_types = property(lambda self: self.in_types)

    def _first_key_word(self, b: DeviceBatch):
        """Order-consistent uint64 routing word of the FIRST sort key:
        the first VALUE word with null rows forced to the extreme their
        nulls_first placement demands.  Ties may span further key words,
        but equal routing words land on the same shard, so the local
        multi-key sort finishes the order."""
        from ..ops import segmented as seg
        ctx = EvalContext(jnp, b)
        live = ctx.row_mask()
        e, asc, nf = self._sorter._bound[0]
        v = e.eval(ctx)
        from ..expr.core import ColumnValue, make_column
        if not isinstance(v, ColumnValue):
            v = make_column(ctx, e.data_type(),
                            v.value if v.value is not None else 0,
                            None if v.value is not None else False)
        words = seg.key_words_for_column(jnp, v.col, live,
                                         for_grouping=False,
                                         nulls_first=nf, ascending=asc)
        # words[0] is the null indicator — routing on it would ship every
        # non-null row to one device.  Route on the value word instead,
        # with nulls pinned to the boundary shard their placement wants.
        valid = v.col.validity if v.col.validity is not None else \
            jnp.ones((b.capacity,), bool)
        null_route = jnp.uint64(0) if nf else \
            jnp.uint64(0xFFFFFFFFFFFFFFFF)
        value_w = words[1] if len(words) > 1 else \
            jnp.zeros((b.capacity,), jnp.uint64)
        return jnp.where(valid, value_w, null_route), live

    def _step(self, shard):
        n_dev = self.n_dev
        b = jax.tree_util.tree_map(lambda x: x[0], shard)
        w0, live = self._first_key_word(b)
        cap = b.capacity
        maxw = jnp.uint64(0xFFFFFFFFFFFFFFFF)
        sorted_w0 = jnp.sort(jnp.where(live, w0, maxw))
        n_live = jnp.sum(live.astype(jnp.int32))
        # local splitter candidates at the n_dev-quantiles
        q = (jnp.arange(1, n_dev, dtype=jnp.int32) * n_live) // n_dev
        cand = sorted_w0[jnp.clip(q, 0, cap - 1)]
        # every shard contributes candidates; global splitters are the
        # n_dev-quantiles of the gathered candidate set
        all_cand = jax.lax.all_gather(cand, self.axis, axis=0,
                                      tiled=True)          # [(n_dev-1)*n_dev]
        all_sorted = jnp.sort(all_cand)
        m = all_cand.shape[0]
        pick = (jnp.arange(1, n_dev, dtype=jnp.int32) * m) // n_dev
        splitters = all_sorted[jnp.clip(pick, 0, m - 1)]   # [n_dev-1]
        pid = jnp.searchsorted(splitters, w0, side="right").astype(jnp.int32)
        routed = exchange_by_pid(b, pid, n_dev, self.axis)
        out = self._sorter._sort_batch(jnp, routed)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    @functools.cached_property
    def _jit_key(self):
        from ..exec.base import semantic_sig
        return ("DistributedSort", self.axis,
                tuple(d.id for d in self.mesh.devices.flat),
                tuple(zip(self.in_names, map(repr, self.in_types))),
                semantic_sig(self._sorter._bound))

    @property
    def _compiled(self):
        from ..exec.base import process_jit

        def make():
            return shard_map(self._step, mesh=self.mesh,
                             in_specs=P(self.axis), out_specs=P(self.axis),
                             check_vma=False)
        return process_jit(self._jit_key, make)

    def run(self, tables: Sequence[pa.Table]) -> pa.Table:
        """tables: one shard per device; returns the totally-ordered
        concatenation (shard 0's range first)."""
        assert len(tables) == self.n_dev
        out = self._compiled(stack_shards(tables))
        return shards_to_table(out)


class DistributedHashJoin:
    """Shuffled hash join over the mesh: both sides are exchanged to
    ``hash(keys) % n_dev`` inside one SPMD count program (so matching keys
    co-locate, ref GpuShuffledHashJoinBase.scala), ONE host round trip
    reads the per-shard output sizes, then a second SPMD program gathers
    the join output at the bucketed static capacity — the multi-chip
    mirror of HashJoinExec's count/sync/expand pipeline."""

    SUPPORTED = ("inner", "left", "full", "left_semi", "left_anti")

    def __init__(self, left_keys, right_keys, how: str, condition,
                 lnames, ltypes, rnames, rtypes,
                 mesh: Optional[Mesh] = None, axis: str = DATA_AXIS):
        from ..exec.join import HashJoinExec
        if how not in self.SUPPORTED:
            # right joins arrive pre-flipped to left (plan_join)
            raise NotImplementedError(f"ici join how={how}")
        if condition is not None and how not in ("inner", "left"):
            # inner post-filters in-shard; left runs the conditional
            # expand+repair kernel — co-located keys make both locally
            # exact (ref GpuOverrides.scala:3352-3355)
            raise NotImplementedError("ici join residual condition only "
                                      "for inner/left joins")
        self.mesh = mesh or build_mesh()
        self.axis = axis
        self.n_dev = self.mesh.shape[axis]
        for tys in (ltypes, rtypes):
            reason = exchange_supported(tys)
            if reason:
                raise NotImplementedError(reason)
        self.how = how
        lsrc = _SchemaSource(lnames, ltypes)
        rsrc = _SchemaSource(rnames, rtypes)
        self._join = HashJoinExec(list(left_keys), list(right_keys), how,
                                  condition, lsrc, rsrc, colocated=True)
        self._l_routing = HashPartitioning(
            list(left_keys), self.n_dev).bind(lnames, ltypes)
        self._r_routing = HashPartitioning(
            list(right_keys), self.n_dev).bind(rnames, rtypes)

    output_names = property(lambda self: self._join.output_names)
    output_types = property(lambda self: self._join.output_types)

    def _exchange_side(self, b: DeviceBatch, routing) -> DeviceBatch:
        ctx = EvalContext(jnp, b)
        pids = routing.partition_ids(jnp, ctx, b)
        return exchange_by_pid(b, pids, self.n_dev, self.axis)

    def _count_step(self, lshard, rshard):
        lb = jax.tree_util.tree_map(lambda x: x[0], lshard)
        rb = jax.tree_util.tree_map(lambda x: x[0], rshard)
        lx = self._exchange_side(lb, self._l_routing)
        rx = self._exchange_side(rb, self._r_routing)
        if self.how in ("left_semi", "left_anti"):
            # no expansion: compact the probe side in-program, no sizing
            from ..exec.filter_common import compact
            order, lo, counts, sizes, matched = self._join._count(
                jnp, rx, lx)
            live = lx.row_mask()
            keep = (counts > 0) if self.how == "left_semi" else \
                (counts == 0)
            out = compact(jnp, lx, keep & live, self._join.output_names)
            return jax.tree_util.tree_map(lambda x: x[None], out)
        order, lo, counts, sizes, matched = self._join._count(jnp, rx, lx)
        add1 = lambda x: jax.tree_util.tree_map(  # noqa: E731
            lambda y: y[None], x)
        return (add1(lx), add1(rx), add1(order), add1(lo), add1(counts),
                sizes[None], matched[None])

    def _expand_step(self, lx, rx, order, lo, counts, out_cap: int,
                     pchar, bchar):
        strip = lambda x: jax.tree_util.tree_map(  # noqa: E731
            lambda y: y[0], x)
        if self._join._bound_condition is not None and self.how == "left":
            # conditional LEFT: co-located shards make the expand+repair
            # kernel (HashJoinExec._expand_left_cond) locally exact
            out = self._join._expand_left_cond(
                jnp, strip(rx), strip(lx), strip(order), strip(lo),
                strip(counts), out_cap, pchar, bchar)
            return jax.tree_util.tree_map(lambda x: x[None], out)
        out = self._join._expand(jnp, strip(rx), strip(lx), strip(order),
                                 strip(lo), strip(counts), out_cap,
                                 pchar, bchar)
        if self._join._bound_condition is not None and self.how == "inner":
            from ..exec.filter_common import apply_filter
            ctx = EvalContext(jnp, out)
            pred = self._join._bound_condition.eval(ctx)
            out = apply_filter(jnp, out, pred, self._join.output_names)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    @functools.cached_property
    def _jit_key(self):
        from ..exec.base import semantic_sig
        return ("DistributedHashJoin", self.axis, self.how,
                tuple(d.id for d in self.mesh.devices.flat),
                self._join._jit_key, semantic_sig(self._l_routing),
                semantic_sig(self._r_routing))

    def _compiled_count(self):
        from ..exec.base import process_jit

        def make():
            return shard_map(self._count_step, mesh=self.mesh,
                             in_specs=(P(self.axis), P(self.axis)),
                             out_specs=P(self.axis), check_vma=False)
        return process_jit(self._jit_key + ("count",), make)

    def _compiled_expand(self, out_cap: int, pchar, bchar):
        from ..exec.base import process_jit

        def make():
            def step(lx, rx, order, lo, counts):
                return self._expand_step(lx, rx, order, lo, counts,
                                         out_cap, pchar, bchar)
            return shard_map(step, mesh=self.mesh,
                             in_specs=(P(self.axis),) * 5,
                             out_specs=P(self.axis), check_vma=False)
        return process_jit(self._jit_key + ("expand", out_cap,
                                            tuple(pchar), tuple(bchar)),
                           make)

    def run(self, left_tables: Sequence[pa.Table],
            right_tables: Sequence[pa.Table]) -> pa.Table:
        assert len(left_tables) == self.n_dev
        assert len(right_tables) == self.n_dev
        return self.run_stacked(stack_shards(left_tables),
                                stack_shards(right_tables))

    def run_stacked(self, ls: DeviceBatch, rs: DeviceBatch) -> pa.Table:
        """Join pre-stacked per-device shards (the device-resident
        scan->mesh edge: rows arrive without host Arrow staging, ref
        RapidsShuffleInternalManagerBase.scala:74)."""
        import numpy as np
        from ..columnar.device import (DEFAULT_CHAR_BUCKETS,
                                       DEFAULT_ROW_BUCKETS, bucket_for)
        if self.how in ("left_semi", "left_anti"):
            return shards_to_table(self._compiled_count()(ls, rs))
        (lx, rx, order, lo, counts, sizes,
         matched) = self._compiled_count()(ls, rs)
        sz = np.asarray(sizes)                       # one round trip
        ncols_l = len(self._join.children[0].output_names)
        if int(sz[:, 0].max()) >= (1 << 31):
            raise RuntimeError(
                f"join expansion of {int(sz[:, 0].max())} rows per shard "
                f"exceeds the 2^31-1 per-batch capacity")
        out_cap = bucket_for(max(int(sz[:, 0].max()), 1),
                             DEFAULT_ROW_BUCKETS)
        pb = sz[:, 1:1 + ncols_l].max(axis=0)
        bb = sz[:, 1 + ncols_l:].max(axis=0)
        l_types = self._join.children[0].output_types
        r_types = self._join.children[1].output_types
        pchar = [bucket_for(max(int(x), 1), DEFAULT_CHAR_BUCKETS)
                 if isinstance(dt, (t.StringType, t.BinaryType)) else 0
                 for x, dt in zip(pb, l_types)]
        bchar = [bucket_for(max(int(x), 1), DEFAULT_CHAR_BUCKETS)
                 if isinstance(dt, (t.StringType, t.BinaryType)) else 0
                 for x, dt in zip(bb, r_types)]
        out = self._compiled_expand(out_cap, pchar, bchar)(
            lx, rx, order, lo, counts)
        result = shards_to_table(out)
        if self.how == "full":
            # keys are co-located per shard, so every build row's matches
            # are local — per-shard unmatched emission is globally exact
            unmatched = self._compiled_unmatched()(rx, matched)
            um = shards_to_table(unmatched)
            if um.num_rows:
                result = pa.concat_tables(
                    [result, um.cast(result.schema)])
        return result

    def _compiled_unmatched(self):
        from ..exec.base import process_jit

        def make():
            def step(rx, matched):
                rb = jax.tree_util.tree_map(lambda y: y[0], rx)
                m = matched[0]
                out = self._join._unmatched_build(jnp, rb, m)
                return jax.tree_util.tree_map(lambda y: y[None], out)
            return shard_map(step, mesh=self.mesh,
                             in_specs=(P(self.axis), P(self.axis)),
                             out_specs=P(self.axis), check_vma=False)
        return process_jit(self._jit_key + ("unmatched",), make)


def _attr(name: str, dtype: t.DataType):
    from ..expr.core import AttributeReference
    return AttributeReference(name, dtype)
