"""SPMD distributed query steps over a device mesh.

The multi-chip execution mode: instead of the host-orchestrated
partition-iterator shuffle (shuffle/manager.py — the analog of the
reference's always-available Spark-shuffle path), a whole query stage
compiles into ONE `shard_map`-ped XLA program per schema: every device
runs the identical operator pipeline on its shard and rows move over ICI
with `all_to_all` (parallel/alltoall.py).  This is the structural
equivalent of the reference's accelerated UCX shuffle stage
(ref: RapidsShuffleInternalManagerBase.scala:74 caching writer keeping
batches on-device; shuffle-plugin/.../UCXShuffleTransport.scala), with
the XLA compiler playing the role of the transport state machines.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import pyarrow as pa
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .. import types as t
from ..columnar.device import (DEFAULT_ROW_BUCKETS, DeviceBatch,
                               batch_to_arrow, batch_to_device, bucket_for)
from ..expr.core import EvalContext
from ..shuffle.partitioning import HashPartitioning
from .alltoall import allgather_batch, exchange_by_pid, exchange_supported
from .mesh import DATA_AXIS, build_mesh


class _SchemaSource:
    """Placeholder child carrying only an output schema, so exec nodes can
    be built against shard inputs that exist only inside shard_map."""

    num_partitions = 1

    def __init__(self, names: Sequence[str], dtypes: Sequence[t.DataType]):
        self.output_names = list(names)
        self.output_types = list(dtypes)
        self.children = []

    def execute_partition(self, pid, ctx):  # pragma: no cover
        raise RuntimeError("schema-only node is never executed")


def stack_shards(tables: Sequence[pa.Table], capacity: Optional[int] = None):
    """Upload one Arrow table per device and stack them on a leading
    device axis (the host->mesh transfer; each shard then lives on its
    device under `jax.device_put` with a row sharding)."""
    n_rows = max(max((tb.num_rows for tb in tables), default=1), 1)
    cap = capacity or bucket_for(n_rows, DEFAULT_ROW_BUCKETS)
    batches = []
    for tb in tables:
        rbs = tb.combine_chunks().to_batches()
        rb = rbs[0] if rbs else pa.RecordBatch.from_pydict(
            {f.name: pa.array([], type=f.type) for f in tb.schema},
            schema=tb.schema)
        batches.append(batch_to_device(rb, capacity=cap))
    # equalize char capacities across shards so stacking is legal
    batches = _equalize_char_caps(batches)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0),
                                     *batches)
    return stacked


def _equalize_char_caps(batches: List[DeviceBatch]) -> List[DeviceBatch]:
    from ..columnar.device import DeviceColumn
    if not batches:
        return batches
    ncol = batches[0].num_cols
    out = [list(b.columns) for b in batches]
    for ci in range(ncol):
        cols = [b.columns[ci] for b in batches]
        if not isinstance(cols[0].dtype, (t.StringType, t.BinaryType)):
            continue
        char_cap = max(int(c.data.shape[0]) for c in cols)
        for bi, c in enumerate(cols):
            cur = int(c.data.shape[0])
            if cur < char_cap:
                data = jnp.concatenate(
                    [c.data, jnp.zeros((char_cap - cur,), jnp.uint8)])
                out[bi][ci] = DeviceColumn(c.dtype, data=data,
                                           validity=c.validity,
                                           offsets=c.offsets)
    return [DeviceBatch(cols, b.num_rows, b.names)
            for cols, b in zip(out, batches)]


def unstack_shards(stacked: DeviceBatch) -> List[DeviceBatch]:
    n_dev = int(jax.tree_util.tree_leaves(stacked)[0].shape[0])
    return [jax.tree_util.tree_map(lambda x, i=i: x[i], stacked)
            for i in range(n_dev)]


def shards_to_table(stacked: DeviceBatch) -> pa.Table:
    tables = [pa.Table.from_batches([batch_to_arrow(b)])
              for b in unstack_shards(stacked)]
    return pa.concat_tables(tables)


class DistributedAggregate:
    """Distributed GROUP BY: local partial agg -> ICI all_to_all on key
    hash -> local final agg.  Compiles to one XLA program; every stage
    stays on device (the reference's partial/exchange/final pipeline,
    aggregate.scala:258-275 + GpuShuffleExchangeExec, fused end-to-end)."""

    def __init__(self, grouping, aggregates, in_names, in_types,
                 mesh: Optional[Mesh] = None, axis: str = DATA_AXIS):
        from ..exec.aggregate import TpuHashAggregateExec
        from ..expr.aggregates import FINAL, PARTIAL
        self.mesh = mesh or build_mesh()
        self.axis = axis
        self.n_dev = self.mesh.shape[axis]
        src = _SchemaSource(in_names, in_types)
        self.partial = TpuHashAggregateExec(list(grouping), list(aggregates),
                                            PARTIAL, src)
        self.final = TpuHashAggregateExec(list(grouping),
                                          self.partial.aggregates, FINAL,
                                          self.partial)
        reason = exchange_supported(self.partial.output_types)
        if reason:
            raise NotImplementedError(reason)
        k = len(list(grouping))
        # route on the SAME Spark-compatible murmur3+pmod rule the host
        # shuffle uses (shuffle/partitioning.py), so both paths agree on
        # key placement
        self._routing = HashPartitioning(
            [_attr(n, dt) for n, dt in zip(self.partial.output_names[:k],
                                           self.partial.output_types[:k])],
            self.n_dev).bind(self.partial.output_names,
                             self.partial.output_types)

    @property
    def output_names(self):
        return self.final.output_names

    @property
    def output_types(self):
        return self.final.output_types

    def _step(self, shard: DeviceBatch) -> DeviceBatch:
        # leading device axis arrives stripped of sharding but kept as a
        # size-1 axis; drop it
        b = jax.tree_util.tree_map(lambda x: x[0], shard)
        part = self.partial._update_batch(jnp, b)
        if self.partial.grouping:
            ctx = EvalContext(jnp, part)
            pids = self._routing.partition_ids(jnp, ctx, part)
            routed = exchange_by_pid(part, pids, self.n_dev, self.axis)
        else:
            # global aggregate: replicate partials, every device computes
            # the same final row (cheap; buffers are one row each)
            routed = allgather_batch(part, self.axis, self.n_dev)
        merged = self.final._merge_batch(jnp, routed)
        out = self.final._evaluate_batch(jnp, merged)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    @functools.cached_property
    def _jit_key(self):
        from ..exec.base import semantic_sig
        return ("DistributedAggregate", self.axis,
                tuple(d.id for d in self.mesh.devices.flat),
                self.partial._jit_key, self.final._jit_key,
                semantic_sig(self._routing))

    @property
    def _compiled(self):
        from ..exec.base import process_jit

        def make():
            return shard_map(self._step, mesh=self.mesh,
                             in_specs=P(self.axis), out_specs=P(self.axis),
                             check_vma=False)
        return process_jit(self._jit_key, make)

    def run(self, tables: Sequence[pa.Table]) -> pa.Table:
        """tables: one scan shard per device."""
        assert len(tables) == self.n_dev, \
            f"need {self.n_dev} shards, got {len(tables)}"
        stacked = stack_shards(tables)
        out = self._compiled(stacked)
        result = shards_to_table(out)
        if not self.partial.grouping and result.num_rows:
            # every device produced the same global row; keep one
            result = result.slice(0, 1)
        return result


class DistributedExchange:
    """A bare distributed repartition: rows move to `hash(keys) % n_dev`
    (the building block joins/sorts stage on; analog of
    GpuShuffleExchangeExec.doExecuteColumnar, execution/
    GpuShuffleExchangeExec.scala:223)."""

    def __init__(self, keys, in_names, in_types,
                 mesh: Optional[Mesh] = None, axis: str = DATA_AXIS):
        self.mesh = mesh or build_mesh()
        self.axis = axis
        self.n_dev = self.mesh.shape[axis]
        reason = exchange_supported(in_types)
        if reason:
            raise NotImplementedError(reason)
        self.in_names, self.in_types = list(in_names), list(in_types)
        self._routing = HashPartitioning(list(keys), self.n_dev).bind(
            self.in_names, self.in_types)

    def _step(self, shard):
        b = jax.tree_util.tree_map(lambda x: x[0], shard)
        ctx = EvalContext(jnp, b)
        pids = self._routing.partition_ids(jnp, ctx, b)
        out = exchange_by_pid(b, pids, self.n_dev, self.axis)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    @functools.cached_property
    def _jit_key(self):
        from ..exec.base import semantic_sig
        return ("DistributedExchange", self.axis,
                tuple(d.id for d in self.mesh.devices.flat),
                tuple(zip(self.in_names, map(repr, self.in_types))),
                semantic_sig(self._routing))

    @property
    def _compiled(self):
        from ..exec.base import process_jit

        def make():
            return shard_map(self._step, mesh=self.mesh,
                             in_specs=P(self.axis), out_specs=P(self.axis),
                             check_vma=False)
        return process_jit(self._jit_key, make)

    def run_stacked(self, stacked: DeviceBatch) -> DeviceBatch:
        return self._compiled(stacked)

    def run(self, tables: Sequence[pa.Table]) -> List[pa.Table]:
        assert len(tables) == self.n_dev
        out = self.run_stacked(stack_shards(tables))
        return [pa.Table.from_batches([batch_to_arrow(b)])
                for b in unstack_shards(out)]


def _attr(name: str, dtype: t.DataType):
    from ..expr.core import AttributeReference
    return AttributeReference(name, dtype)
