"""ICI all-to-all shuffle kernel.

The TPU-native replacement for the reference's accelerated shuffle data
path (ref: shuffle-plugin/.../UCX.scala:69 RDMA transport +
GpuPartitioning.scala:50-130 device-side slicing).  Where the reference
moves device buffers peer-to-peer over UCX, a TPU pod slice moves them
over ICI with a single XLA `all_to_all` collective issued inside
`shard_map` — the compiler schedules the transfers, no bounce buffers,
no handshake protocol.

Design (static shapes, one compile per schema):

  1. Each device stably sorts its rows by destination partition id and
     computes per-peer counts/starts — the on-device slicing step.
  2. Every column leaf is gathered into a ``[n_parts, slot]`` send tensor
     (slot = per-peer row budget; default = local capacity so no row can
     overflow).  Strings additionally pack their bytes into a
     ``[n_parts, char_slot]`` tensor via a vmapped searchsorted layout.
  3. One ``lax.all_to_all`` per leaf rides the ICI mesh axis.
  4. The receiver stably compacts valid rows to the front; strings are
     re-assembled into (offsets, chars) form.

Variable-width nested types (arrays/structs) fall back to the host
shuffle path, mirroring the reference's fallback to the stock Spark
shuffle when the accelerated transport cannot carry a batch
(ref: RapidsShuffleInternalManagerBase.scala:462 proxy fallback).
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn
from ..ops.scan import cumsum_fast


def exchange_supported(dtypes) -> Optional[str]:
    """Return a reason string if the ICI path cannot carry these columns.
    Structs of fixed-width fields and arrays/maps of fixed-width
    elements ride the exchange; deeper nesting (string/span elements,
    struct elements) stages via host."""
    def fixed(dt) -> bool:
        return not isinstance(dt, (t.StringType, t.BinaryType,
                                   t.ArrayType, t.MapType, t.StructType))

    def ok(dt) -> bool:
        if isinstance(dt, t.ArrayType):
            return fixed(dt.element_type)
        if isinstance(dt, t.MapType):
            return fixed(dt.key_type) and fixed(dt.value_type)
        if isinstance(dt, t.StructType):
            return all(ok(f.data_type) and
                       not isinstance(f.data_type,
                                      (t.StringType, t.BinaryType))
                       for f in dt.fields)
        return True

    for dt in dtypes:
        if not ok(dt):
            return f"nested type {dt.name} falls back to host shuffle"
    return None


def allgather_supported(dtypes) -> Optional[str]:
    """Return a reason string if ``allgather_batch`` cannot replicate
    these columns.  A strict subset of ``exchange_supported``: the
    gather path has no span receive layout for arrays/maps (they raise
    NotImplementedError at runtime), so any planning gate admitting the
    replicate/allgather branch must check THIS predicate, not just the
    exchange one (the round-5 admit/crash mismatch,
    analysis/capabilities.py ALLGATHER_BATCH)."""
    def ok(dt) -> bool:
        if isinstance(dt, (t.ArrayType, t.MapType)):
            return False
        if isinstance(dt, t.StructType):
            return all(ok(f.data_type) for f in dt.fields)
        return True

    for dt in dtypes:
        if not ok(dt):
            return (f"array/map type {dt.name} rides the host broadcast "
                    f"fallback (no allgather span layout)")
    return None


def _flat_child_lanes(col: DeviceColumn):
    """(lanes, rebuild) for an array/map column of FLAT children: the
    child-aligned 1-D lanes sharing the column's offsets, and a function
    rebuilding the column from exchanged lanes.  (None, None) when a
    child is itself a span/struct (host fallback)."""
    def flat_lanes(c: DeviceColumn):
        if c.offsets is not None or c.children:
            return None
        out = [c.data]
        out.append(c.validity if c.validity is not None else
                   jnp.ones((int(c.data.shape[0]),), bool))
        if c.data_hi is not None:
            out.append(c.data_hi)
        return out

    per_child = [flat_lanes(ch) for ch in col.children]
    if any(x is None for x in per_child):
        return None, None
    lanes = [lane for ls in per_child for lane in ls]

    def rebuild(out_lanes, out_offs, validity):
        it = iter(out_lanes)
        children = []
        for ch, ls in zip(col.children, per_child):
            data = next(it)
            valid = next(it)
            new = DeviceColumn(ch.dtype, data=data, validity=valid)
            if ch.data_hi is not None:
                new.data_hi = next(it)
            children.append(new)
        return DeviceColumn(col.dtype, validity=validity,
                            offsets=out_offs, children=tuple(children))
    return lanes, rebuild


def _counts_starts(pid_key, n_parts: int):
    """Per-destination row counts and exclusive starts after a stable sort."""
    one_hot = pid_key[None, :] == jnp.arange(n_parts, dtype=pid_key.dtype)[:, None]
    counts = jnp.sum(one_hot.astype(jnp.int32), axis=1)
    starts = cumsum_fast(jnp, counts) - counts
    return counts, starts


def _span_send(offs, lanes, src_row, send_valid, n_parts: int, slot: int):
    """Pack a span column's child lanes into fixed-shape send tensors.

    `lanes` are 1-D child-aligned arrays (chars for strings; element
    data/validity lanes for arrays and maps — every lane shares `offs`).
    Returns (list of packed [P, child_slot] tensors, len_send [P, slot])."""
    child_slot = int(lanes[0].shape[0])
    lengths = offs[1:] - offs[:-1]
    row_len = jnp.where(send_valid, lengths[src_row], 0).astype(jnp.int32)
    # per-peer exclusive child starts [P, slot+1]
    child_start = jnp.concatenate(
        [jnp.zeros((n_parts, 1), jnp.int32), cumsum_fast(jnp, row_len, axis=1)],
        axis=1)
    total_children = child_start[:, -1]
    c = jnp.arange(child_slot, dtype=jnp.int32)

    def per_peer_src(cs, srow, tot):
        j = jnp.clip(jnp.searchsorted(cs, c, side="right") - 1, 0, slot - 1)
        within = c - cs[j]
        src_c = offs[srow[j]] + within
        valid_c = c < tot
        return jnp.clip(src_c, 0, child_slot - 1), valid_c

    src_c, valid_c = jax.vmap(per_peer_src)(child_start, src_row,
                                            total_children)
    packed = [jnp.where(valid_c, lane[src_c],
                        jnp.zeros((), lane.dtype))
              for lane in lanes]
    return packed, row_len


def _string_send(col: DeviceColumn, src_row, send_valid, n_parts: int,
                 slot: int):
    """Pack a string column into fixed-shape send tensors.

    Returns (chars_send [P, char_slot], len_send [P, slot])."""
    packed, row_len = _span_send(col.offsets, [col.data], src_row,
                                 send_valid, n_parts, slot)
    return packed[0], row_len


def _span_receive_layout(recv_len, ord2, n_parts: int, slot: int,
                         child_slot: int):
    """Shared re-assembly coordinates for received span lanes: returns
    (out_offs, peer_index, src_child_index, live_child_mask) so every
    child lane of the column gathers through one layout computation."""
    flat_rows = n_parts * slot
    len_flat = recv_len.reshape(flat_rows)
    out_len = len_flat[ord2]
    out_offs = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), cumsum_fast(jnp, out_len)]).astype(jnp.int32)
    # per-source-peer exclusive child starts in the receive buffer
    recv_start = jnp.concatenate(
        [jnp.zeros((n_parts, 1), jnp.int32), cumsum_fast(jnp, recv_len, axis=1)],
        axis=1)
    out_child_cap = n_parts * child_slot
    c = jnp.arange(out_child_cap, dtype=jnp.int32)
    r = jnp.clip(jnp.searchsorted(out_offs, c, side="right") - 1,
                 0, flat_rows - 1)
    flat_src = ord2[r]
    p = flat_src // slot
    j = flat_src - p * slot
    src_c = jnp.clip(recv_start[p, j] + (c - out_offs[r]), 0,
                     child_slot - 1)
    live = c < out_offs[-1]
    return out_offs, p, src_c, live


def _string_receive(recv_chars, recv_len, ord2, n_parts: int, slot: int):
    """Re-assemble a received string column into (offsets, chars)."""
    char_slot = int(recv_chars.shape[1])
    out_offs, p, src_c, live = _span_receive_layout(
        recv_len, ord2, n_parts, slot, char_slot)
    out_chars = jnp.where(live, recv_chars[p, src_c], jnp.uint8(0))
    return out_chars, out_offs


def exchange_by_pid(batch: DeviceBatch, pids, n_parts: int, axis_name: str,
                    slot: Optional[int] = None,
                    on_overflow: str = "error"):
    """Redistribute rows so the device at mesh position ``p`` along
    ``axis_name`` receives every row with ``pids == p``.

    Must be called inside ``shard_map`` over a mesh with that axis (size
    ``n_parts``).  Returns a batch of capacity ``n_parts * slot``.

    The send tensors are ``[n_parts, slot]`` — ``n_parts`` times the
    per-peer budget — so ``slot`` is the exchange's memory knob.  With
    the default ``on_overflow='error'``, ``slot < capacity`` is refused
    up front: a skewed destination would silently drop rows.  With
    ``on_overflow='guard'`` a sub-capacity slot is admitted and the
    return becomes ``(batch, ok)`` where ``ok`` is this shard's
    device-side bool that NO destination overflowed its budget — the
    speculative-sizing pattern (exec/join.py's deferred guard): the
    caller checks every shard's guard after the fetch and re-runs with
    ``slot=capacity`` on a miss, paying hash-shard-balanced joins
    ~``slot/capacity`` of the full exchange footprint."""
    cap = batch.capacity
    guarded = on_overflow == "guard"
    if on_overflow not in ("error", "guard"):
        raise ValueError(f"on_overflow={on_overflow!r}: "
                         f"expected 'error' or 'guard'")
    if slot is not None and slot < cap and not guarded:
        # a per-peer budget below the local capacity can silently drop rows
        # when one destination receives more than `slot` of them; there is
        # no in-graph way to signal that, so refuse up front
        raise ValueError(
            f"slot={slot} < capacity={cap}: a skewed partition could "
            f"overflow the per-peer budget; use slot >= capacity "
            f"(or on_overflow='guard')")
    slot = slot or cap
    live = batch.row_mask()
    pid_key = jnp.where(live, pids.astype(jnp.int32), n_parts)
    order = jnp.argsort(pid_key, stable=True)
    counts, starts = _counts_starts(pid_key, n_parts)

    j = jnp.arange(slot, dtype=jnp.int32)
    send_pos = starts[:, None] + j[None, :]
    send_valid = j[None, :] < counts[:, None]                  # [P, slot]
    src_row = order[jnp.clip(send_pos, 0, cap - 1)]            # [P, slot]

    a2a = lambda x: jax.lax.all_to_all(  # noqa: E731
        x, axis_name, split_axis=0, concat_axis=0, tiled=True)

    recv_valid = a2a(send_valid)
    flat_rows = n_parts * slot
    valid_flat = recv_valid.reshape(flat_rows)
    ord2 = jnp.argsort(~valid_flat, stable=True)
    out_total = jnp.sum(valid_flat.astype(jnp.int32))
    out_live = jnp.arange(flat_rows, dtype=jnp.int32) < out_total

    def move(col: DeviceColumn) -> DeviceColumn:
        validity = col.validity if col.validity is not None else \
            jnp.ones((cap,), bool)
        v_send = validity[src_row] & send_valid
        recv_v = a2a(v_send).reshape(flat_rows)[ord2] & out_live
        if isinstance(col.dtype, (t.StringType, t.BinaryType)):
            chars_send, len_send = _string_send(col, src_row, send_valid,
                                                n_parts, slot)
            recv_chars = a2a(chars_send)
            recv_len = a2a(len_send)
            out_chars, out_offs = _string_receive(
                recv_chars, recv_len, ord2, n_parts, slot)
            return DeviceColumn(col.dtype, data=out_chars,
                                validity=recv_v, offsets=out_offs)
        if isinstance(col.dtype, t.StructType):
            # struct children are row-aligned: each field rides the same
            # permutation independently
            return DeviceColumn(col.dtype, validity=recv_v,
                                children=tuple(move(ch)
                                               for ch in col.children))
        if isinstance(col.dtype, (t.ArrayType, t.MapType)):
            # array/map of flat elements: every child lane shares the
            # offsets, so they ride one span layout (the string path
            # generalized — elements instead of bytes)
            lanes, rebuild = _flat_child_lanes(col)
            if lanes is None:
                raise NotImplementedError(
                    "nested span elements ride the host shuffle fallback")
            child_slot = int(lanes[0].shape[0])
            packed, row_len = _span_send(col.offsets, lanes, src_row,
                                         send_valid, n_parts, slot)
            recv_lanes = [a2a(x) for x in packed]
            recv_len = a2a(row_len)
            out_offs, p, src_c, live_c = _span_receive_layout(
                recv_len, ord2, n_parts, slot, child_slot)
            out_lanes = [jnp.where(live_c, rl[p, src_c],
                                   jnp.zeros((), rl.dtype))
                         for rl in recv_lanes]
            return rebuild(out_lanes, out_offs, recv_v)
        data_send = col.data[src_row]
        out_data = a2a(data_send).reshape(flat_rows)[ord2]
        out_data = jnp.where(out_live, out_data,
                             jnp.zeros_like(out_data))
        new_col = DeviceColumn(col.dtype, data=out_data, validity=recv_v)
        if col.data_hi is not None:
            hi = a2a(col.data_hi[src_row]).reshape(flat_rows)[ord2]
            new_col.data_hi = jnp.where(out_live, hi, jnp.zeros_like(hi))
        return new_col

    out = DeviceBatch([move(c) for c in batch.columns], out_total,
                      batch.names)
    if guarded:
        # no destination held more rows than its send budget (checked on
        # the send side, where the drop would happen)
        return out, jnp.all(counts <= jnp.int32(slot))
    return out


def allgather_batch(batch: DeviceBatch, axis_name: str,
                    n_parts: int) -> DeviceBatch:
    """Replicate every device's rows onto all devices (the ICI analog of
    the reference's broadcast exchange, ref GpuBroadcastExchangeExec.scala):
    each device ends up with the concatenation of all shards, valid rows
    compacted to the front."""
    cap = batch.capacity
    ag = lambda x: jax.lax.all_gather(x, axis_name, axis=0, tiled=True)  # noqa: E731
    live = batch.row_mask()
    flat_rows = n_parts * cap
    valid_flat = ag(live)
    ord2 = jnp.argsort(~valid_flat, stable=True)
    total = jnp.sum(valid_flat.astype(jnp.int32))
    out_live = jnp.arange(flat_rows, dtype=jnp.int32) < total

    def gather_col(col: DeviceColumn) -> DeviceColumn:
        validity = col.validity if col.validity is not None else \
            jnp.ones((cap,), bool)
        recv_v = ag(validity & live)[ord2] & out_live
        if isinstance(col.dtype, (t.StringType, t.BinaryType)):
            char_slot = int(col.data.shape[0])
            lengths = jnp.where(live, col.offsets[1:] - col.offsets[:-1], 0)
            recv_chars = ag(col.data).reshape(n_parts, char_slot)
            recv_len = ag(lengths).reshape(n_parts, cap)
            # source char starts inside each gathered shard = its own offsets
            out_chars, out_offs = _string_receive(
                recv_chars, recv_len, ord2, n_parts, cap)
            return DeviceColumn(col.dtype, data=out_chars,
                                validity=recv_v, offsets=out_offs)
        if isinstance(col.dtype, t.StructType):
            return DeviceColumn(col.dtype, validity=recv_v,
                                children=tuple(gather_col(ch)
                                               for ch in col.children))
        if isinstance(col.dtype, (t.ArrayType, t.MapType)):
            raise NotImplementedError(
                "array/map types ride the host broadcast fallback")
        out_data = ag(col.data)[ord2]
        out_data = jnp.where(out_live, out_data, jnp.zeros_like(out_data))
        new_col = DeviceColumn(col.dtype, data=out_data, validity=recv_v)
        if col.data_hi is not None:
            hi = ag(col.data_hi)[ord2]
            new_col.data_hi = jnp.where(out_live, hi, jnp.zeros_like(hi))
        return new_col

    return DeviceBatch([gather_col(c) for c in batch.columns], total,
                       batch.names)
