"""ICI-routed physical operators: plug the SPMD mesh stages into the
regular query path.

Ref: the reference substitutes its accelerated UCX shuffle under
`spark.rapids.shuffle.transport` (GpuShuffleEnv.isRapidsShuffleEnabled →
RapidsShuffleInternalManagerBase); here
`spark.rapids.shuffle.transport=ici` + a multi-chip mesh substitutes the
fused partial→all_to_all→final aggregate stage
(parallel/distributed.py) for the host-orchestrated
partial→exchange→final triple.  A post-conversion pass rewrites the plan
exactly where the reference's shuffle manager would take over the
exchange.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np
import pyarrow as pa

from .. import config as cfg
from ..exec.base import (NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, TPU,
                         Batch, Exec, MetricTimer, to_host_batch)
from ..columnar.interop import to_arrow_schema


class IciAggregateExec(Exec):
    """Fused distributed GROUP BY over the device mesh (replaces
    final ← exchange ← partial; one XLA program, rows ride ICI)."""

    placement = TPU

    def __init__(self, final_agg, mesh=None):
        from .mesh import build_mesh
        exchange = final_agg.children[0]
        partial = exchange.children[0]
        source = partial.children[0]
        super().__init__([source])
        self.final_agg = final_agg
        self.partial = partial
        self.mesh = mesh or build_mesh()
        from .distributed import DistributedAggregate
        self._dagg = DistributedAggregate(
            partial.grouping, partial.aggregates,
            source.output_names, source.output_types, mesh=self.mesh)

    @property
    def output_names(self):
        return self.final_agg.output_names

    @property
    def output_types(self):
        return self.final_agg.output_types

    @property
    def num_partitions(self):
        return 1

    def describe(self):
        n = self.mesh.shape[self._dagg.axis]
        return f"IciAggregate({n} chips, all_to_all)"

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        from ..columnar.device import batch_to_device
        source = self.children[0]
        n_dev = self._dagg.n_dev
        rbs = []
        for spid in range(source.num_partitions):
            for b in source.execute_partition(spid, ctx):
                rb = to_host_batch(b, source.output_names)
                if rb.num_rows:
                    rbs.append(rb)
        schema = to_arrow_schema(source.output_names, source.output_types)
        tbl = pa.Table.from_batches([rb.cast(schema) for rb in rbs],
                                    schema=schema) if rbs else \
            schema.empty_table()
        per = max(1, -(-tbl.num_rows // n_dev))
        shards = [tbl.slice(i * per, per) for i in range(n_dev)]
        with MetricTimer(self.metrics[OP_TIME]):
            out = self._dagg.run(shards)
        for rb in out.combine_chunks().to_batches():
            if rb.num_rows == 0:
                continue
            batch = batch_to_device(rb, xp=self.xp)
            self.metrics[NUM_OUTPUT_ROWS] += rb.num_rows
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            yield batch


def install_ici_stages(root: Exec, conf: cfg.RapidsConf) -> Exec:
    """Post-conversion rewrite: final←exchange←partial aggregate triples
    become one IciAggregateExec when the ICI transport is selected and a
    multi-chip mesh exists."""
    if conf.get(cfg.SHUFFLE_TRANSPORT) != "ici":
        return root
    import jax
    if len(jax.devices()) < 2:
        return root
    from ..exec.aggregate import TpuHashAggregateExec
    from ..expr.aggregates import FINAL, PARTIAL
    from ..shuffle.exchange import ShuffleExchangeExec
    from ..shuffle.partitioning import HashPartitioning
    from .alltoall import exchange_supported

    def rewrite(node: Exec) -> Exec:
        node = node.with_new_children([rewrite(c) for c in node.children])
        if not (isinstance(node, TpuHashAggregateExec) and
                node.mode == FINAL and node.grouping):
            return node
        ex = node.children[0]
        if not (isinstance(ex, ShuffleExchangeExec) and
                isinstance(ex.partitioning, HashPartitioning)):
            return node
        part = ex.children[0]
        if not (isinstance(part, TpuHashAggregateExec) and
                part.mode == PARTIAL and part.placement == TPU):
            return node
        source = part.children[0]
        if exchange_supported(part.output_types) or \
                exchange_supported(source.output_types):
            return node  # nested types ride the host shuffle
        try:
            return IciAggregateExec(node)
        except NotImplementedError:
            return node

    return rewrite(root)
