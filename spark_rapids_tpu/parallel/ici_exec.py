"""ICI-routed physical operators: plug the SPMD mesh stages into the
regular query path.

Ref: the reference substitutes its accelerated UCX shuffle under
`spark.rapids.shuffle.transport` (GpuShuffleEnv.isRapidsShuffleEnabled →
RapidsShuffleInternalManagerBase); here
`spark.rapids.shuffle.transport=ici` + a multi-chip mesh substitutes the
fused partial→all_to_all→final aggregate stage
(parallel/distributed.py) for the host-orchestrated
partial→exchange→final triple.  A post-conversion pass rewrites the plan
exactly where the reference's shuffle manager would take over the
exchange.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np
import pyarrow as pa

from .. import config as cfg
from ..exec.base import (NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, TPU,
                         Batch, Exec, MetricTimer, to_host_batch)
from ..columnar.interop import to_arrow_schema
from ..obs.tracer import trace_event


def _note_stage(op: str, path: str, chips: int) -> None:
    """One ICI stage ran: flight-recorder event + the continuous
    stacked-vs-host decision counter (a drift toward `host` is the
    ICI reshard quietly degrading — the watchdog's signal)."""
    trace_event("ici.stage", op=op, path=path, chips=chips)
    from ..obs import metrics as m
    m.counter("tpu_ici_stage_total",
              "fused mesh stages by operator and data path",
              ("op", "path")).labels(op=op, path=path).inc()


class IciAggregateExec(Exec):
    """Fused distributed GROUP BY over the device mesh (replaces
    final ← exchange ← partial; one XLA program, rows ride ICI)."""

    placement = TPU

    def __init__(self, final_agg, mesh=None):
        from .mesh import build_mesh
        exchange = final_agg.children[0]
        partial = exchange.children[0]
        source = partial.children[0]
        super().__init__([source])
        self.final_agg = final_agg
        self.partial = partial
        self.mesh = mesh or build_mesh()
        from .distributed import DistributedAggregate
        self._dagg = DistributedAggregate(
            partial.grouping, partial.aggregates,
            source.output_names, source.output_types, mesh=self.mesh)

    @property
    def output_names(self):
        return self.final_agg.output_names

    @property
    def output_types(self):
        return self.final_agg.output_types

    @property
    def num_partitions(self):
        return 1

    def describe(self):
        n = self.mesh.shape[self._dagg.axis]
        return f"IciAggregate({n} chips, all_to_all)"

    def determinism(self):
        # the fused stage realizes the host aggregate's semantics on
        # the mesh: same replay class as the operator it replaces
        return self.final_agg.determinism()

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        source = self.children[0]
        stacked = _gather_source_stacked(
            source, ctx, source.output_names, source.output_types,
            self._dagg.n_dev)
        if stacked is not None:
            _note_stage("aggregate", "stacked", self._dagg.n_dev)
            with MetricTimer(self.metrics[OP_TIME]):
                out = self._dagg._compiled(stacked)
            yield from _emit_stacked(self, out)
            return
        _note_stage("aggregate", "host", self._dagg.n_dev)
        tbl = _gather_source_table(source, ctx, source.output_names,
                                   source.output_types)
        shards = _shard_table(tbl, self._dagg.n_dev)
        with MetricTimer(self.metrics[OP_TIME]):
            out = self._dagg.run(shards)
        yield from _emit_table(self, out)


def _gather_source_table(source: Exec, ctx, names, dtypes) -> pa.Table:
    rbs = []
    for spid in range(source.num_partitions):
        for b in source.execute_partition(spid, ctx):
            rb = to_host_batch(b, names)
            if rb.num_rows:
                rbs.append(rb)
    schema = to_arrow_schema(names, dtypes)
    if not rbs:
        return schema.empty_table()
    return pa.Table.from_batches([rb.cast(schema) for rb in rbs],
                                 schema=schema)


def _stackable_schema(dtypes) -> bool:
    """Schemas the device-resident reshard can carry: fixed-width lanes,
    structs of them, and TOP-LEVEL strings/binaries (their offsets
    rebase per shard; arrays/maps and span-inside-struct still stage
    through host Arrow, matching exchange_supported's fallback)."""
    from .. import types as t

    def fixed(dt):
        return not isinstance(dt, (t.StringType, t.BinaryType,
                                   t.ArrayType, t.MapType, t.StructType))

    def flat(dt):
        if isinstance(dt, (t.StringType, t.BinaryType, t.ArrayType,
                           t.MapType)):
            return False
        if isinstance(dt, t.StructType):
            return all(flat(f.data_type) for f in dt.fields)
        return True

    def spannable(dt):
        if isinstance(dt, (t.StringType, t.BinaryType)):
            return True
        if isinstance(dt, t.ArrayType):
            return fixed(dt.element_type)
        if isinstance(dt, t.MapType):
            return fixed(dt.key_type) and fixed(dt.value_type)
        return False
    return all(flat(dt) or spannable(dt) for dt in dtypes)


def _gather_source_stacked(source: Exec, ctx, names, dtypes, n_dev: int):
    """Device-resident scan->mesh edge: collect the source's DEVICE
    batches, concatenate on device, and reshape every lane to
    (n_dev, shard_cap) with ONE jitted program — rows never stage
    through host Arrow (ref RapidsShuffleInternalManagerBase.scala:74:
    shuffle input stays device-resident end-to-end).  String/binary
    lanes rebase: each shard slices its char range at the source's char
    capacity (conservative static shape; a balanced shard holds ~1/n of
    the bytes) and rewrites offsets relative to its slice.  Returns the
    stacked DeviceBatch, or None for schemas the reshard cannot carry
    (arrays/maps — the host path remains)."""
    if not _stackable_schema(dtypes):
        return None
    import jax
    import jax.numpy as jnp
    from jax import lax
    from ..columnar.device import (DEFAULT_ROW_BUCKETS, DeviceBatch,
                                   DeviceColumn, batch_to_device,
                                   bucket_for)
    from ..exec.concat import concat_batches
    from ..exec.base import process_jit, schema_sig

    batches = []
    for spid in range(source.num_partitions):
        for b in source.execute_partition(spid, ctx):
            batches.append(b)
    batches = [b for b in batches if int(b.num_rows)]
    if not batches:
        schema = to_arrow_schema(names, dtypes)
        rb = pa.RecordBatch.from_pydict(
            {f.name: pa.array([], type=f.type) for f in schema},
            schema=schema)
        batches = [batch_to_device(rb)]
    merged = concat_batches(jnp, batches, names, dtypes) \
        if len(batches) > 1 else batches[0]
    total = int(merged.num_rows)
    # per-shard row budget rounds up to a power of two so distinct totals
    # share compiled reshard programs (static-shape discipline) while
    # shard imbalance stays bounded by 2x (the sparse row-bucket ladder
    # could idle most of the mesh)
    import math
    need_rows = max(1024, -(-total // n_dev))
    per = 1 << math.ceil(math.log2(need_rows))
    in_cap = merged.capacity
    char_caps = tuple(
        int((c.data if c.data is not None
             else c.children[0].data).shape[0])
        if c.offsets is not None else 0
        for c in merged.columns)

    def make():
        def reshard(b: DeviceBatch):
            need = n_dev * per

            def pad_to(x, size):
                if x.shape[0] >= size:
                    return x[:size]
                return jnp.pad(x, (0, size - x.shape[0]))

            cols = []
            for c, ccap in zip(b.columns, char_caps):
                if c.offsets is not None:
                    # offsets edge-extend so padding rows are empty spans
                    offs = c.offsets
                    if offs.shape[0] < need + 1:
                        offs = jnp.concatenate(
                            [offs, jnp.full((need + 1 - offs.shape[0],),
                                            offs[-1], offs.dtype)])
                    else:
                        offs = offs[:need + 1]
                    # every child-aligned lane (chars for strings,
                    # element lanes for arrays/maps) slices per shard at
                    # the source's child capacity; padding ensures the
                    # dynamic slice never clamps
                    if c.children:
                        from .alltoall import _flat_child_lanes
                        lanes, rebuild = _flat_child_lanes(c)
                    else:
                        lanes, rebuild = [c.data], None
                    padded = [jnp.concatenate(
                        [ln, jnp.zeros((ccap,), ln.dtype)])
                        for ln in lanes]
                    sh_off = []
                    sh_lanes = [[] for _ in lanes]
                    for i in range(n_dev):
                        o = offs[i * per:i * per + per + 1]
                        sh_off.append(o - o[0])
                        for li, ln in enumerate(padded):
                            sh_lanes[li].append(lax.dynamic_slice(
                                ln, (o[0],), (ccap,)))
                    validity = None if c.validity is None else \
                        pad_to(c.validity, need).reshape(n_dev, per)
                    stacked_lanes = [jnp.stack(g) for g in sh_lanes]
                    if rebuild is None:
                        cols.append(DeviceColumn(
                            c.dtype, data=stacked_lanes[0],
                            validity=validity,
                            offsets=jnp.stack(sh_off)))
                    else:
                        cols.append(rebuild(stacked_lanes,
                                            jnp.stack(sh_off), validity))
                else:
                    cols.append(jax.tree_util.tree_map(
                        lambda x: pad_to(x, need).reshape(n_dev, per), c))
            rows = jnp.clip(
                jnp.asarray(b.num_rows, jnp.int32)
                - jnp.arange(n_dev, dtype=jnp.int32) * np.int32(per),
                0, np.int32(per))
            return DeviceBatch(cols, rows, b.names)
        return reshard
    fn = process_jit(("ici_reshard", tuple(names),
                      tuple(repr(d) for d in dtypes), in_cap, n_dev, per,
                      char_caps),
                     make)
    return fn(merged)


def _emit_stacked(self, stacked) -> Iterator[Batch]:
    """Yield per-shard device batches (mesh order) without host staging."""
    import jax
    from .distributed import unstack_shards
    for b in unstack_shards(stacked):
        n = int(np.asarray(b.num_rows))
        if n == 0:
            continue
        out = Batch(b.columns, n, b.names)
        self.metrics[NUM_OUTPUT_ROWS] += n
        self.metrics[NUM_OUTPUT_BATCHES] += 1
        yield out


def _shard_table(tbl: pa.Table, n_dev: int):
    per = max(1, -(-tbl.num_rows // n_dev))
    return [tbl.slice(i * per, per) for i in range(n_dev)]


def _emit_table(self, tbl: pa.Table) -> Iterator[Batch]:
    from ..columnar.device import batch_to_device
    for rb in tbl.combine_chunks().to_batches():
        if rb.num_rows == 0:
            continue
        batch = batch_to_device(rb, xp=self.xp)
        self.metrics[NUM_OUTPUT_ROWS] += rb.num_rows
        self.metrics[NUM_OUTPUT_BATCHES] += 1
        yield batch


class IciSortExec(Exec):
    """Distributed total-order sort over the mesh (replaces
    sort ← range-exchange; splitter sampling + all_to_all routing +
    local sort compile into ONE SPMD program, ref GpuRangePartitioner +
    GpuSortExec)."""

    placement = TPU

    def __init__(self, sort_exec, mesh=None):
        from .mesh import build_mesh
        exchange = sort_exec.children[0]
        source = exchange.children[0]
        super().__init__([source])
        self.sort_exec = sort_exec
        self.mesh = mesh or build_mesh()
        from .distributed import DistributedSort
        self._dsort = DistributedSort(sort_exec.orders,
                                      source.output_names,
                                      source.output_types, mesh=self.mesh)

    output_names = property(lambda self: self.sort_exec.output_names)
    output_types = property(lambda self: self.sort_exec.output_types)
    num_partitions = property(lambda self: 1)

    def describe(self):
        n = self.mesh.shape[self._dsort.axis]
        return f"IciSort({n} chips, sample+all_to_all)"

    def determinism(self):
        return self.sort_exec.determinism()

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        source = self.children[0]
        stacked = _gather_source_stacked(
            source, ctx, source.output_names, source.output_types,
            self._dsort.n_dev)
        if stacked is not None:
            _note_stage("sort", "stacked", self._dsort.n_dev)
            # shard i holds globally-ordered range i: emit in mesh order
            with MetricTimer(self.metrics[OP_TIME]):
                out = self._dsort._compiled(stacked)
            yield from _emit_stacked(self, out)
            return
        _note_stage("sort", "host", self._dsort.n_dev)
        tbl = _gather_source_table(source, ctx, source.output_names,
                                   source.output_types)
        shards = _shard_table(tbl, self._dsort.n_dev)
        with MetricTimer(self.metrics[OP_TIME]):
            out = self._dsort.run(shards)
        yield from _emit_table(self, out)


class IciJoinExec(Exec):
    """Shuffled hash join over the mesh (replaces
    join ← {hash-exchange, hash-exchange}; both sides ride all_to_all
    inside the compiled stage, ref GpuShuffledHashJoinBase +
    UCXShuffleTransport)."""

    placement = TPU

    def __init__(self, join_exec, mesh=None):
        from .mesh import build_mesh
        lex, rex = join_exec.children
        lsrc, rsrc = lex.children[0], rex.children[0]
        super().__init__([lsrc, rsrc])
        self.join_exec = join_exec
        self.mesh = mesh or build_mesh()
        from .distributed import DistributedHashJoin
        self._djoin = DistributedHashJoin(
            [k for k in join_exec.left_keys],
            [k for k in join_exec.right_keys],
            join_exec.how, join_exec.condition,
            lsrc.output_names, lsrc.output_types,
            rsrc.output_names, rsrc.output_types, mesh=self.mesh)

    output_names = property(lambda self: self.join_exec.output_names)
    output_types = property(lambda self: self.join_exec.output_types)
    num_partitions = property(lambda self: 1)

    def describe(self):
        n = self.mesh.shape[self._djoin.axis]
        return f"IciJoin({self.join_exec.how}, {n} chips, all_to_all)"

    def determinism(self):
        return self.join_exec.determinism()

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        lsrc, rsrc = self.children
        n_dev = self._djoin.n_dev
        # device-resident edge first: both sides reshard on device and
        # the join consumes the stacked shards without host staging
        ls = _gather_source_stacked(lsrc, ctx, lsrc.output_names,
                                    lsrc.output_types, n_dev)
        rs = _gather_source_stacked(rsrc, ctx, rsrc.output_names,
                                    rsrc.output_types, n_dev) \
            if ls is not None else None
        if ls is not None and rs is not None:
            _note_stage("join", "stacked", n_dev)
            with MetricTimer(self.metrics[OP_TIME]):
                out = self._djoin.run_stacked(ls, rs)
            yield from _emit_table(self, out)
            return
        _note_stage("join", "host", n_dev)
        lt = _gather_source_table(lsrc, ctx, lsrc.output_names,
                                  lsrc.output_types)
        rt = _gather_source_table(rsrc, ctx, rsrc.output_names,
                                  rsrc.output_types)
        with MetricTimer(self.metrics[OP_TIME]):
            out = self._djoin.run(_shard_table(lt, n_dev),
                                  _shard_table(rt, n_dev))
        yield from _emit_table(self, out)


class IciExchangeExec(Exec):
    """A bare hash repartition routed over the mesh (replaces a
    ShuffleExchangeExec that no fused stage absorbed; the all_to_all
    analog of the reference transport serving EVERY shuffle,
    UCXShuffleTransport.scala).  Downstream operators read one shard per
    partition id."""

    placement = TPU

    def __init__(self, exchange, mesh=None):
        import threading
        from .mesh import build_mesh
        source = exchange.children[0]
        super().__init__([source])
        self.exchange = exchange
        self.mesh = mesh or build_mesh()
        from .distributed import DATA_AXIS as _axis
        if exchange.partitioning.num_partitions != \
                self.mesh.shape[_axis]:
            # pmod(mesh) would change the key->partition mapping the
            # user asked for (e.g. partitioned writes rely on it)
            raise NotImplementedError(
                f"repartition({exchange.partitioning.num_partitions}) "
                f"!= mesh size {self.mesh.shape[_axis]}: host exchange")
        from .distributed import DistributedExchange
        self._dex = DistributedExchange(
            list(exchange.partitioning.keys), source.output_names,
            source.output_types, mesh=self.mesh)
        self._memo = {}
        self._memo_lock = threading.Lock()

    def release_shuffle(self):
        """Drop the memoized shuffled dataset (the HBM analog of
        unregistering shuffle blocks; called by release_plan_shuffles)."""
        with self._memo_lock:
            self._memo.clear()

    output_names = property(lambda self: self.exchange.output_names)
    output_types = property(lambda self: self.exchange.output_types)
    num_partitions = property(
        lambda self: self.mesh.shape[self._dex.axis])

    def describe(self):
        return f"IciExchange({self.num_partitions} chips, all_to_all)"

    def determinism(self):
        from ..analysis.determinism import Determinism, ORDER_STABLE
        return Determinism(
            ORDER_STABLE, "all_to_all routing is content-determined; "
            "per-chip row multiset is invariant under arrival order")

    def memory_effects(self, child_states, conf):
        """Memoizes the whole shuffled dataset device-resident (raw, not
        spill-managed) until release_shuffle at query end — plus the
        all_to_all's send/recv staging while it runs."""
        from ..analysis.lifetime import (MemoryEffects,
                                         padded_partition_bytes)
        if not child_states:
            return None
        st = child_states[0]
        shards = max(self.num_partitions, 1)
        whole = padded_partition_bytes(
            st.replace(num_partitions=shards)) * shards
        return MemoryEffects(hold=2.0 * whole, retained=whole,
                             note="device shuffle memo")

    def _shards(self, ctx):
        key = ctx.uid
        with self._memo_lock:
            hit = self._memo.get(key)
            if hit is not None:
                return hit
            source = self.children[0]
            stacked = _gather_source_stacked(
                source, ctx, source.output_names, source.output_types,
                self._dex.n_dev)
            _note_stage("exchange",
                        "stacked" if stacked is not None else "host",
                        self._dex.n_dev)
            with MetricTimer(self.metrics[OP_TIME]):
                if stacked is not None:
                    out = self._dex.run_stacked(stacked)
                    from .distributed import unstack_shards
                    shards = unstack_shards(out)
                else:
                    tbl = _gather_source_table(source, ctx,
                                               source.output_names,
                                               source.output_types)
                    tables = self._dex.run(
                        _shard_table(tbl, self._dex.n_dev))
                    from ..columnar.device import batch_to_device
                    shards = []
                    for tb in tables:
                        rbs = tb.combine_chunks().to_batches()
                        shards.append(
                            batch_to_device(rbs[0], xp=self.xp) if rbs
                            else None)
            self._memo[key] = shards
            return shards

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        shard = self._shards(ctx)[pid]
        if shard is None:
            return
        n = int(np.asarray(shard.num_rows))
        if n == 0:
            return
        out = Batch(shard.columns, n, shard.names)
        self.metrics[NUM_OUTPUT_ROWS] += n
        self.metrics[NUM_OUTPUT_BATCHES] += 1
        yield out


def install_ici_stages(root: Exec, conf: cfg.RapidsConf) -> Exec:
    """Post-conversion rewrite: shuffle-bracketed stages become fused SPMD
    mesh stages when the ICI transport is selected and a multi-chip mesh
    exists — aggregate triples (IciAggregateExec), range-partitioned
    global sorts (IciSortExec), and co-partitioned hash joins
    (IciJoinExec).  The reference swaps its transport underneath every
    shuffle (UCXShuffleTransport serves aggregates, joins and sorts
    alike); this pass is the plan-level equivalent."""
    if conf.get(cfg.SHUFFLE_TRANSPORT) != "ici":
        return root
    # deadline-bounded discovery: a hung multichip topology exchange
    # (the MULTICHIP rc=124 shape) degrades to the single-chip path —
    # counted in tpu_device_probe_failures_total + a tracer event —
    # instead of hanging the planner
    from .mesh import device_count
    if device_count(default=1) < 2:
        return root
    from ..exec.aggregate import TpuHashAggregateExec
    from ..exec.join import HashJoinExec
    from ..exec.sort import SortExec
    from ..expr.aggregates import FINAL, PARTIAL
    from ..shuffle.exchange import ShuffleExchangeExec
    from ..shuffle.partitioning import HashPartitioning, RangePartitioning
    from .alltoall import exchange_supported

    def rewrite(node: Exec) -> Exec:
        node = node.with_new_children([rewrite(c) for c in node.children])
        # --- final <- hash-exchange <- partial aggregate ----------------
        if isinstance(node, TpuHashAggregateExec) and \
                node.mode == FINAL and node.grouping:
            ex = node.children[0]
            if isinstance(ex, ShuffleExchangeExec) and \
                    isinstance(ex.partitioning, HashPartitioning):
                part = ex.children[0]
                if isinstance(part, TpuHashAggregateExec) and \
                        part.mode == PARTIAL and part.placement == TPU:
                    source = part.children[0]
                    if not (exchange_supported(part.output_types) or
                            exchange_supported(source.output_types)):
                        try:
                            return IciAggregateExec(node)
                        except NotImplementedError:
                            pass
            return node
        # --- global sort <- range exchange ------------------------------
        if isinstance(node, SortExec) and node.is_global and \
                node.placement == TPU:
            ex = node.children[0]
            if isinstance(ex, ShuffleExchangeExec) and \
                    isinstance(ex.partitioning, RangePartitioning) and \
                    not exchange_supported(ex.output_types):
                try:
                    return IciSortExec(node)
                except NotImplementedError:
                    pass
            return node
        # --- colocated hash join <- two hash exchanges ------------------
        if isinstance(node, HashJoinExec) and node.colocated and \
                node.placement == TPU:
            lex, rex = node.children
            if all(isinstance(e, ShuffleExchangeExec) and
                   isinstance(e.partitioning, HashPartitioning)
                   for e in (lex, rex)) and \
                    not (exchange_supported(lex.output_types) or
                         exchange_supported(rex.output_types)):
                try:
                    return IciJoinExec(node)
                except NotImplementedError:
                    pass
            return node
        return node

    def wrap_exchanges(node: Exec) -> Exec:
        # second pass: any hash exchange the fused stages did not absorb
        # still rides ICI as a bare all_to_all repartition — the
        # transport serves EVERY shuffle, like the reference's
        # UCXShuffleTransport regardless of the operator above it
        node = node.with_new_children(
            [wrap_exchanges(c) for c in node.children])
        if isinstance(node, ShuffleExchangeExec) and \
                isinstance(node.partitioning, HashPartitioning) and \
                getattr(node.partitioning, "keys", None) and \
                not exchange_supported(node.output_types):
            try:
                return IciExchangeExec(node)
            except NotImplementedError:
                pass
        return node

    return wrap_exchanges(rewrite(root))
