"""Multi-chip SPMD execution: device meshes, ICI all-to-all shuffle,
distributed query stages (the TPU-native replacement for the reference's
UCX accelerated-shuffle plugin, shuffle-plugin/)."""

from .alltoall import allgather_batch, exchange_by_pid, exchange_supported
from .distributed import (DistributedAggregate, DistributedExchange,
                          shards_to_table, stack_shards, unstack_shards)
from .mesh import DATA_AXIS, build_mesh, mesh_sharding

__all__ = [
    "DATA_AXIS", "DistributedAggregate", "DistributedExchange",
    "allgather_batch", "build_mesh", "exchange_by_pid",
    "exchange_supported", "mesh_sharding", "shards_to_table",
    "stack_shards", "unstack_shards",
]
