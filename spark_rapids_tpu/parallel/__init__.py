"""Multi-chip SPMD execution: device meshes, ICI all-to-all shuffle,
distributed query stages (the TPU-native replacement for the reference's
UCX accelerated-shuffle plugin, shuffle-plugin/)."""

from .alltoall import (allgather_batch, allgather_supported,
                       exchange_by_pid, exchange_supported)
from .mesh import DATA_AXIS, build_mesh, mesh_sharding

try:
    from .distributed import (DistributedAggregate, DistributedExchange,
                              shards_to_table, stack_shards,
                              unstack_shards)
except ImportError:  # pragma: no cover
    # jax builds without the stable shard_map API cannot run the SPMD
    # stages; the admission gates and kernels above stay importable so
    # planning, lint, and the capability table keep working (queries
    # simply never take the ICI path on such builds)
    DistributedAggregate = DistributedExchange = None
    shards_to_table = stack_shards = unstack_shards = None

__all__ = [
    "DATA_AXIS", "DistributedAggregate", "DistributedExchange",
    "allgather_batch", "allgather_supported", "build_mesh",
    "exchange_by_pid", "exchange_supported", "mesh_sharding",
    "shards_to_table", "stack_shards", "unstack_shards",
]
