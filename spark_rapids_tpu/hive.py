"""Hive dialect support via the extension hook (ref
org/apache/spark/sql/hive/rapids/ + GpuHiveOverrides at
GpuOverrides.scala:53).

The reference accelerates two Hive surfaces: Hive UDF wrappers
(GpuHiveSimpleUDF/GpuHiveGenericUDF — JVM classes that cannot exist
here; our native/Python UDF paths are the equivalent capability) and
Hive-specific expressions.  This module provides the Hive hash — the
expression Hive bucketing and Hive-style DISTRIBUTE BY rely on — and
registers it through plan.extensions the way GpuHiveOverrides
self-registers when Hive is on the classpath.
"""

from __future__ import annotations

import numpy as np

from . import types as t
from .expr.core import (EvalContext, Expression, data_of, evaluator,
                        make_column, validity_of)


class HiveHash(Expression):
    """Hive's bucketing hash (int): for ints the value itself, for
    booleans 1/0, combined per-column as 31*h + col_hash — the ObjectsHashAggregate-compatible rule
    (ref HiveHash in the reference's hive overrides)."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def data_type(self):
        return t.INT

    def sql(self):
        return f"hive_hash({', '.join(c.sql() for c in self.children)})"


@evaluator(HiveHash)
def _eval_hive_hash(e: HiveHash, ctx: EvalContext):
    xp = ctx.xp
    h = xp.zeros((ctx.capacity,), dtype=np.int32)
    for c in e.children:
        v = c.eval(ctx)
        d = data_of(v, ctx)
        dt = c.data_type()
        if isinstance(dt, t.BooleanType):
            ch = d.astype(np.int32)
        elif isinstance(dt, (t.LongType, t.TimestampType)):
            x = d.astype(np.int64)
            ch = (x ^ ((x >> 32) & 0xFFFFFFFF)).astype(np.int32)
        elif isinstance(dt, t.DoubleType):
            x = d.astype(np.float64).view(np.int64) if xp is np else \
                xp.asarray(d, dtype=xp.float64).view(xp.int64)
            ch = (x ^ ((x >> 32) & 0xFFFFFFFF)).astype(np.int32)
        elif isinstance(dt, t.FloatType):
            # floatToIntBits, not value truncation
            ch = (d.astype(np.float32).view(np.int32) if xp is np else
                  xp.asarray(d, dtype=xp.float32).view(xp.int32))
        elif t.is_integral(dt) or isinstance(dt, t.DateType):
            ch = d.astype(np.int32)
        else:
            raise NotImplementedError(
                f"hive_hash over {dt.name} is not supported")
        valid = validity_of(v, ctx)
        if valid is not None:
            ch = xp.where(valid, ch, xp.zeros_like(ch))
        h = (h * np.int32(31) + ch).astype(np.int32)
    return make_column(ctx, t.INT, h, None)


def _register() -> None:
    from .plan.overrides import expr_rule
    from .types import T
    expr_rule(HiveHash, T.INT, "Hive bucketing hash")


def enable_hive_support() -> None:
    """Opt in to the Hive dialect rules (the analog of the reference
    finding Hive on the classpath)."""
    from .plan.extensions import register_override_provider
    register_override_provider(_register)
