"""Hive dialect support via the extension hook (ref
org/apache/spark/sql/hive/rapids/ + GpuHiveOverrides at
GpuOverrides.scala:53).

The reference accelerates two Hive surfaces: Hive UDF wrappers
(GpuHiveSimpleUDF/GpuHiveGenericUDF — JVM classes that cannot exist
here; our native/Python UDF paths are the equivalent capability) and
Hive-specific expressions.  This module provides the Hive hash — the
expression Hive bucketing and Hive-style DISTRIBUTE BY rely on — and
registers it through plan.extensions the way GpuHiveOverrides
self-registers when Hive is on the classpath.
"""

from __future__ import annotations

import numpy as np

from . import types as t
from .expr.core import (EvalContext, Expression, data_of, evaluator,
                        make_column, validity_of)


class HiveHash(Expression):
    """Hive's bucketing hash (int): for ints the value itself, for
    booleans 1/0, combined per-column as 31*h + col_hash — the ObjectsHashAggregate-compatible rule
    (ref HiveHash in the reference's hive overrides)."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def data_type(self):
        return t.INT

    def sql(self):
        return f"hive_hash({', '.join(c.sql() for c in self.children)})"


@evaluator(HiveHash)
def _eval_hive_hash(e: HiveHash, ctx: EvalContext):
    xp = ctx.xp
    h = xp.zeros((ctx.capacity,), dtype=np.int32)
    for c in e.children:
        v = c.eval(ctx)
        d = data_of(v, ctx)
        dt = c.data_type()
        if isinstance(dt, t.BooleanType):
            ch = d.astype(np.int32)
        elif isinstance(dt, (t.LongType, t.TimestampType)):
            x = d.astype(np.int64)
            ch = (x ^ ((x >> 32) & 0xFFFFFFFF)).astype(np.int32)
        elif isinstance(dt, t.DoubleType):
            x = d.astype(np.float64).view(np.int64) if xp is np else \
                xp.asarray(d, dtype=xp.float64).view(xp.int64)
            ch = (x ^ ((x >> 32) & 0xFFFFFFFF)).astype(np.int32)
        elif isinstance(dt, t.FloatType):
            # floatToIntBits, not value truncation
            ch = (d.astype(np.float32).view(np.int32) if xp is np else
                  xp.asarray(d, dtype=xp.float32).view(xp.int32))
        elif t.is_integral(dt) or isinstance(dt, t.DateType):
            ch = d.astype(np.int32)
        else:
            raise NotImplementedError(
                f"hive_hash over {dt.name} is not supported")
        valid = validity_of(v, ctx)
        if valid is not None:
            ch = xp.where(valid, ch, xp.zeros_like(ch))
        h = (h * np.int32(31) + ch).astype(np.int32)
    return make_column(ctx, t.INT, h, None)


def _register() -> None:
    from .plan.overrides import expr_rule
    from .types import T
    expr_rule(HiveHash, T.INT, "Hive bucketing hash")


def enable_hive_support() -> None:
    """Opt in to the Hive dialect rules (the analog of the reference
    finding Hive on the classpath): the HiveHash expression rule plus
    the Hive text-table read helper on the session class."""
    from .plan.extensions import register_override_provider
    register_override_provider(_register)
    from .api.session import TpuSession
    HiveTextRelation.attach(TpuSession)


# ---------------------------------------------------------------------------
# Hive text tables (LazySimpleSerDe): the file-format surface the
# reference accelerates in org/apache/spark/sql/hive/rapids
# (GpuHiveTableScanExec for reads, GpuHiveFileFormat for writes).
# Hive's default text layout: fields separated by \x01, rows by \n,
# NULL spelled \N, no header.
# ---------------------------------------------------------------------------

HIVE_FIELD_DELIM = "\x01"
HIVE_NULL = r"\N"


def expand_hive_paths(path: str):
    """Hive-layout file expansion: a literal file path reads as-is (no
    glob interpretation); a directory walks recursively, skipping any
    path COMPONENT that starts with '_' or '.' (_temporary/, _SUCCESS,
    hidden files) and taking every remaining file regardless of
    extension — Hive data files are extension-less (000000_0,
    part-00000)."""
    import os
    if not os.path.isdir(path):
        return [path]
    out = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if not d.startswith(("_", ".")))
        for f in sorted(files):
            if not f.startswith(("_", ".")):
                out.append(os.path.join(root, f))
    return out


def hive_text_read_options(names, want_schema):
    """The LazySimpleSerDe read-option triple shared by the standalone
    reader and the hivetext scan exec (one definition, no drift)."""
    import pyarrow.csv as pacsv
    ropts = pacsv.ReadOptions(column_names=list(names))
    popts = pacsv.ParseOptions(delimiter=HIVE_FIELD_DELIM,
                               quote_char=False, escape_char=False)
    copts = pacsv.ConvertOptions(null_values=[HIVE_NULL],
                                 strings_can_be_null=True,
                                 quoted_strings_can_be_null=False,
                                 column_types={f.name: f.type
                                               for f in want_schema})
    return ropts, popts, copts


def read_hive_text(path: str, names, dtypes):
    """Read a Hive text file/directory into an Arrow table with the given
    schema (ref GpuHiveTableScanExec's LazySimpleSerDe subset: default
    delimiters, no escaping/quoting — the same restrictions the
    reference's isSupportedType checks enforce).  Directories expand
    recursively (partitioned table layout); marker files skip."""
    import pyarrow as pa
    import pyarrow.csv as pacsv
    from .columnar.interop import to_arrow_schema
    want = to_arrow_schema(list(names), list(dtypes))
    paths = expand_hive_paths(path)
    if not paths:
        # empty Hive table/partition (e.g. only _SUCCESS markers)
        return want.empty_table()
    ropts, popts, copts = hive_text_read_options(names, want)
    tables = [pacsv.read_csv(p, read_options=ropts, parse_options=popts,
                             convert_options=copts) for p in paths]
    out = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
    return out.cast(want)


def write_hive_text(table, path: str) -> None:
    """Write an Arrow table in Hive text layout (ref GpuHiveFileFormat:
    delimited write with \\N nulls, no header)."""
    import pyarrow.csv as pacsv
    wopts = pacsv.WriteOptions(include_header=False,
                               delimiter=HIVE_FIELD_DELIM,
                               quoting_style="none")
    # pyarrow has no null-spelling option on write: substitute via fill
    import pyarrow as pa
    import pyarrow.compute as pc
    cols = []
    for i in range(table.num_columns):
        c = table.column(i)
        if c.null_count:
            c = pc.fill_null(c.cast(pa.string()), HIVE_NULL)
        cols.append(c)
    # positional table rebuild: duplicate column names must survive
    pacsv.write_csv(pa.table(cols, names=list(table.column_names)), path,
                    write_options=wopts)


class HiveTextRelation:
    """Session-level helper registered by enable_hive_support():
    session.read_hive_text(path, names, dtypes) -> DataFrame backed by
    the regular scan exec (fmt="hivetext"), so Hive tables get the same
    reader strategies, HBM pin cache, and batch chunking as parquet/csv
    scans (the scan-exec modeling of GpuHiveTableScanExec)."""

    @staticmethod
    def attach(session_cls) -> None:
        def read_hive_text_m(self, path, names, dtypes):
            from .api.dataframe import DataFrame
            from .plan.logical import FileRelation
            files = expand_hive_paths(path)
            return DataFrame(
                FileRelation("hivetext", files, list(names), list(dtypes)),
                self)
        session_cls.read_hive_text = read_hive_text_m
