"""Differential-test assertions.

Re-design of the reference's primary correctness net
(ref: integration_tests/src/main/python/asserts.py:434
assert_gpu_and_cpu_are_equal_collect, :14-60 recursive value compare,
:357 assert_gpu_fallback_collect): run the same query on the CPU engine
(spark.rapids.sql.enabled=false) and the TPU engine, deep-compare results
with float tolerance; fallback assertions capture the executed plan and
check an operator actually stayed on CPU.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

import pyarrow as pa

from ..api.session import TpuSession

_TPU_CONF = {"spark.rapids.sql.enabled": True}
_CPU_CONF = {"spark.rapids.sql.enabled": False}


def _mk(conf: Dict) -> TpuSession:
    b = TpuSession.builder()
    for k, v in conf.items():
        b.config(k, v)
    return b.get_or_create()


def with_cpu_session(fn: Callable[[TpuSession], object],
                     conf: Optional[Dict] = None):
    c = dict(conf or {})
    c.update(_CPU_CONF)
    return fn(_mk(c))


def with_tpu_session(fn: Callable[[TpuSession], object],
                     conf: Optional[Dict] = None):
    c = dict(conf or {})
    c.update(_TPU_CONF)
    return fn(_mk(c))


def _val_equal(a, b, approx: float) -> bool:
    if a is None and b is None:
        return True
    if a is None or b is None:
        return False
    if isinstance(a, float) or isinstance(b, float):
        fa, fb = float(a), float(b)
        if math.isnan(fa) and math.isnan(fb):
            return True
        if math.isinf(fa) or math.isinf(fb):
            return fa == fb
        if approx > 0:
            denom = max(abs(fa), abs(fb), 1e-12)
            return abs(fa - fb) <= approx * denom or abs(fa - fb) < 1e-11
        return fa == fb
    if isinstance(a, dict) and isinstance(b, dict):
        return (set(a) == set(b)
                and all(_val_equal(a[k], b[k], approx) for k in a))
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return (len(a) == len(b)
                and all(_val_equal(x, y, approx) for x, y in zip(a, b)))
    return a == b


def _sort_key(row):
    def k(v):
        if v is None:
            return (0, "")
        if isinstance(v, bool):
            return (1, str(int(v)))
        if isinstance(v, (int, float)):
            if isinstance(v, float) and math.isnan(v):
                return (3, "nan")
            return (2, f"{float(v):+040.12e}")
        if isinstance(v, (list, tuple, dict)):
            return (4, str(v))
        return (4, str(v))
    return tuple(k(v) for v in row)


def assert_tables_equal(cpu: pa.Table, tpu: pa.Table,
                        ignore_order: bool = True,
                        approximate_float: float = 0.0):
    assert cpu.schema.names == tpu.schema.names, \
        f"schema mismatch: {cpu.schema.names} vs {tpu.schema.names}"
    crows = [tuple(r.values()) for r in cpu.to_pylist()]
    trows = [tuple(r.values()) for r in tpu.to_pylist()]
    assert len(crows) == len(trows), \
        f"row count: cpu={len(crows)} tpu={len(trows)}"
    if ignore_order:
        crows = sorted(crows, key=_sort_key)
        trows = sorted(trows, key=_sort_key)
    for i, (cr, tr) in enumerate(zip(crows, trows)):
        if not _val_equal(list(cr), list(tr), approximate_float):
            raise AssertionError(
                f"row {i} differs:\n  cpu: {cr}\n  tpu: {tr}")


def assert_tpu_and_cpu_are_equal_collect(
        df_fn: Callable[[TpuSession], "object"],
        conf: Optional[Dict] = None,
        ignore_order: bool = True,
        approximate_float: float = 0.0):
    """Run the query builder against both engines and compare results
    (ref asserts.py:434)."""
    cpu = with_cpu_session(lambda s: df_fn(s).collect(), conf)
    tpu = with_tpu_session(lambda s: df_fn(s).collect(), conf)
    assert_tables_equal(cpu, tpu, ignore_order, approximate_float)
    return cpu, tpu


def assert_tpu_fallback_collect(
        df_fn: Callable[[TpuSession], "object"],
        cpu_exec_name: str,
        conf: Optional[Dict] = None,
        ignore_order: bool = True,
        approximate_float: float = 0.0):
    """Verify the op stayed on CPU *and* results match
    (ref asserts.py:357 + ExecutionPlanCaptureCallback)."""
    cpu = with_cpu_session(lambda s: df_fn(s).collect(), conf)

    c = dict(conf or {})
    c.update(_TPU_CONF)
    session = _mk(c)
    tpu = df_fn(session).collect()
    plan = session.last_plan
    found = []
    plan.foreach(lambda e: found.append(type(e).__name__))
    from ..exec.base import CPU as _CPU
    cpu_placed = []
    plan.foreach(lambda e: cpu_placed.append(type(e).__name__)
                 if e.placement == _CPU else None)
    assert any(cpu_exec_name in n for n in cpu_placed), \
        (f"expected {cpu_exec_name} to fall back to CPU; CPU-placed: "
         f"{cpu_placed}; all: {found}")
    assert_tables_equal(cpu, tpu, ignore_order, approximate_float)


def assert_tpu_and_cpu_error(df_fn, conf, error_message: str):
    """Both engines must raise with the message (ref asserts.py:495)."""
    for runner in (with_cpu_session, with_tpu_session):
        try:
            runner(lambda s: df_fn(s).collect(), conf)
            raise AssertionError(
                f"expected error '{error_message}' but query succeeded")
        except AssertionError:
            raise
        except Exception as ex:
            assert error_message in str(ex), \
                f"expected '{error_message}' in '{ex}'"
