"""Typed fuzz data generators.

Re-design of the reference's generator library
(ref: integration_tests/src/main/python/data_gen.py:30-987): typed
generators with weighted special cases (nulls, NaN, +/-Inf, min/max,
empty strings), nested array/struct generation, deterministic seeding.
"""

from __future__ import annotations

import datetime
import decimal as pydec
import random
import string
from typing import List, Optional, Sequence, Tuple

import pyarrow as pa

from .. import types as t
from ..columnar.interop import to_arrow_type


class DataGen:
    def __init__(self, dtype: t.DataType, nullable: bool = True,
                 null_prob: float = 0.1):
        self.dtype = dtype
        self.nullable = nullable
        self.null_prob = null_prob
        self._specials: List = []
        self._special_prob = 0.05

    def with_special_case(self, value, weight: float = 1.0):
        self._specials.append(value)
        return self

    def _gen_value(self, rng: random.Random):
        raise NotImplementedError

    def gen(self, rng: random.Random):
        if self.nullable and rng.random() < self.null_prob:
            return None
        if self._specials and rng.random() < self._special_prob * \
                len(self._specials):
            return rng.choice(self._specials)
        return self._gen_value(rng)


class BooleanGen(DataGen):
    def __init__(self, **kw):
        super().__init__(t.BOOLEAN, **kw)

    def _gen_value(self, rng):
        return rng.random() < 0.5


class _IntGen(DataGen):
    LO, HI = 0, 0

    def __init__(self, dtype, lo=None, hi=None, **kw):
        super().__init__(dtype, **kw)
        self.lo = self.LO if lo is None else lo
        self.hi = self.HI if hi is None else hi
        self.with_special_case(self.LO).with_special_case(self.HI)
        self.with_special_case(0)

    def _gen_value(self, rng):
        return rng.randint(self.lo, self.hi)


class ByteGen(_IntGen):
    LO, HI = -128, 127

    def __init__(self, **kw):
        super().__init__(t.BYTE, **kw)


class ShortGen(_IntGen):
    LO, HI = -32768, 32767

    def __init__(self, **kw):
        super().__init__(t.SHORT, **kw)


class IntegerGen(_IntGen):
    LO, HI = -(2**31), 2**31 - 1

    def __init__(self, **kw):
        super().__init__(t.INT, **kw)


class LongGen(_IntGen):
    LO, HI = -(2**63), 2**63 - 1

    def __init__(self, **kw):
        super().__init__(t.LONG, **kw)


class FloatGen(DataGen):
    def __init__(self, dtype=t.FLOAT, no_nans: bool = False, **kw):
        super().__init__(dtype, **kw)
        if not no_nans:
            self.with_special_case(float("nan"))
        self.with_special_case(float("inf"))
        self.with_special_case(float("-inf"))
        self.with_special_case(0.0).with_special_case(-0.0)

    def _gen_value(self, rng):
        choice = rng.random()
        if choice < 0.3:
            return rng.uniform(-1000, 1000)
        if choice < 0.6:
            return rng.uniform(-1, 1)
        return rng.uniform(-1e30, 1e30)


class DoubleGen(FloatGen):
    def __init__(self, **kw):
        super().__init__(t.DOUBLE, **kw)


class StringGen(DataGen):
    """Strings from an alphabet OR sampled from a regex pattern — the
    reference generates pattern strings with sre_yield
    (ref data_gen.py:153 `StringGen(pattern)`); here a sampler walks
    Python's own sre parse tree, so any stdlib-`re` pattern works.
    Special cases cover empty and UTF-8 multibyte edges by default."""

    def __init__(self, pattern: Optional[str] = None,
                 alphabet: str = string.ascii_letters + string.digits +
                 " _-", max_len: int = 20, **kw):
        super().__init__(t.STRING, **kw)
        self.alphabet = alphabet
        self.max_len = max_len
        self._parsed = None
        if pattern is not None:
            import re
            parser = getattr(re, "_parser", None)
            if parser is None:  # pragma: no cover - pre-3.11 stdlib
                import sre_parse as parser
            self._parsed = parser.parse(pattern)
        self.with_special_case("")
        self.with_special_case("\u00e9\u4e2d\U0001F600")  # 2/3/4-byte UTF-8

    def _gen_value(self, rng):
        if self._parsed is not None:
            return _sample_sre(self._parsed, rng)
        n = rng.randint(0, self.max_len)
        return "".join(rng.choice(self.alphabet) for _ in range(n))


_SRE_CATEGORIES = {
    "category_digit": string.digits,
    "category_not_digit": string.ascii_letters + "_ ",
    "category_word": string.ascii_letters + string.digits + "_",
    "category_not_word": " .,;-",
    "category_space": " \t",
    "category_not_space": string.ascii_letters + string.digits,
}
_MAX_REPEAT_SAMPLE = 8


def _sample_sre(parsed, rng: random.Random) -> str:
    """Generate one string matching a parsed stdlib-re pattern (the
    constructs the reference's test patterns use: literals, sets,
    ranges, categories, branches, groups, repeats, dot, anchors)."""
    out = []
    for op, arg in parsed:
        name = str(op).lower().split(".")[-1]
        if name == "literal":
            out.append(chr(arg))
        elif name == "not_literal":
            c = rng.choice(string.ascii_letters + string.digits)
            out.append(c if ord(c) != arg else "x")
        elif name == "any":
            out.append(rng.choice(string.ascii_letters + string.digits +
                                  " _-"))
        elif name == "in":
            out.append(_sample_in(arg, rng))
        elif name == "branch":
            _, branches = arg
            out.append(_sample_sre(rng.choice(branches), rng))
        elif name == "subpattern":
            out.append(_sample_sre(arg[3], rng))
        elif name in ("max_repeat", "min_repeat"):
            lo, hi, sub = arg
            hi = min(hi, lo + _MAX_REPEAT_SAMPLE)
            for _ in range(rng.randint(lo, hi)):
                out.append(_sample_sre(sub, rng))
        elif name == "at":
            pass  # anchors generate nothing
        elif name == "category":
            out.append(rng.choice(_SRE_CATEGORIES[
                str(arg).lower().split(".")[-1]]))
        else:
            raise ValueError(f"regex construct {name!r} not supported "
                             f"by the pattern sampler")
    return "".join(out)


def _sample_in(items, rng: random.Random) -> str:
    negated = any(str(op).lower().endswith("negate") for op, _ in items)
    if negated:
        member = set()
        for op, arg in items:
            name = str(op).lower().split(".")[-1]
            if name == "literal":
                member.add(chr(arg))
            elif name == "range":
                member |= {chr(c) for c in range(arg[0], arg[1] + 1)}
            elif name == "category":
                member |= set(_SRE_CATEGORIES[
                    str(arg).lower().split(".")[-1]])
        pool = [c for c in (string.ascii_letters + string.digits + " _-")
                if c not in member]
        return rng.choice(pool or ["x"])
    choices = []
    for op, arg in items:
        name = str(op).lower().split(".")[-1]
        if name == "literal":
            choices.append(chr(arg))
        elif name == "range":
            lo, hi = arg
            choices.append(chr(rng.randint(lo, hi)))
        elif name == "category":
            choices.append(rng.choice(_SRE_CATEGORIES[
                str(arg).lower().split(".")[-1]]))
    return rng.choice(choices) if choices else "x"


class DecimalGen(DataGen):
    def __init__(self, precision: int = 10, scale: int = 2, **kw):
        super().__init__(t.DecimalType(precision, scale), **kw)
        self.precision, self.scale = precision, scale

    def _gen_value(self, rng):
        unscaled = rng.randint(-(10**self.precision) + 1,
                               10**self.precision - 1)
        return pydec.Decimal(unscaled).scaleb(-self.scale)


class DateGen(DataGen):
    def __init__(self, **kw):
        super().__init__(t.DATE, **kw)
        self.with_special_case(datetime.date(1970, 1, 1))
        self.with_special_case(datetime.date(1582, 10, 15))

    def _gen_value(self, rng):
        return datetime.date(1970, 1, 1) + \
            datetime.timedelta(days=rng.randint(-30000, 30000))


class TimestampGen(DataGen):
    def __init__(self, **kw):
        super().__init__(t.TIMESTAMP, **kw)

    def _gen_value(self, rng):
        base = datetime.datetime(1970, 1, 1,
                                 tzinfo=datetime.timezone.utc)
        return base + datetime.timedelta(
            seconds=rng.randint(-(2**40) // 1000, (2**40) // 1000),
            microseconds=rng.randint(0, 999999))


class ArrayGen(DataGen):
    def __init__(self, child: DataGen, max_len: int = 5, **kw):
        super().__init__(t.ArrayType(child.dtype), **kw)
        self.child = child
        self.max_len = max_len

    def _gen_value(self, rng):
        return [self.child.gen(rng)
                for _ in range(rng.randint(0, self.max_len))]


class StructGen(DataGen):
    def __init__(self, fields: Sequence[Tuple[str, DataGen]], **kw):
        super().__init__(
            t.StructType([t.StructField(n, g.dtype) for n, g in fields]), **kw)
        self.fields = list(fields)

    def _gen_value(self, rng):
        return {n: g.gen(rng) for n, g in self.fields}


def nested_gen(rng_or_seed=0, max_depth: int = 3,
               leaf_gens: Optional[List[DataGen]] = None,
               depth_weight: float = 0.5) -> DataGen:
    """Randomly composed nested generator with weighted depth: at each
    level the chance of nesting deeper decays by `depth_weight` — the
    reference's weighted-choice nested map/struct depth control
    (ref data_gen.py nested gen construction)."""
    rng = rng_or_seed if isinstance(rng_or_seed, random.Random) \
        else random.Random(rng_or_seed)
    leaves = leaf_gens or [IntegerGen(), LongGen(), DoubleGen(),
                           StringGen(), BooleanGen()]

    def build(depth: int) -> DataGen:
        if depth >= max_depth or rng.random() > depth_weight ** depth:
            return rng.choice(leaves)
        kind = rng.choice(["array", "struct"])
        if kind == "array":
            return ArrayGen(build(depth + 1))
        n = rng.randint(1, 3)
        return StructGen([(f"f{i}", build(depth + 1))
                          for i in range(n)])

    return build(0)


# standard generator sets (mirrors data_gen.py's canonical lists)
int_gens = [ByteGen(), ShortGen(), IntegerGen(), LongGen()]
numeric_gens = int_gens + [FloatGen(), DoubleGen()]
all_basic_gens = numeric_gens + [BooleanGen(), StringGen()]


def gen_table(columns: Sequence[Tuple[str, DataGen]], length: int = 2048,
              seed: int = 0) -> pa.Table:
    rng = random.Random(seed)
    arrays = {}
    for name, g in columns:
        vals = [g.gen(rng) for _ in range(length)]
        arrays[name] = pa.array(vals, type=to_arrow_type(g.dtype))
    return pa.table(arrays)


def gen_df(session, columns, length: int = 2048, seed: int = 0,
           num_partitions: int = 1):
    return session.create_dataframe(gen_table(columns, length, seed),
                                    num_partitions=num_partitions)


def two_col_df(session, a: DataGen, b: DataGen, length=2048, seed=0):
    return gen_df(session, [("a", a), ("b", b)], length, seed)
