"""TPU task admission semaphore.

Ref: GpuSemaphore.scala:27-170 — bounds how many concurrent tasks may hold
device memory at once (spark.rapids.sql.concurrentGpuTasks); a task
acquires before its first device operation and releases at completion.
Re-entrant per task, like the reference's per-task bookkeeping.

The permit ledger is a mutex + condition variable rather than a raw
``threading.Semaphore``: the re-entrancy check and the permit grab happen
under ONE lock (two threads sharing a task id can no longer both miss the
holders table and double-acquire, leaking a permit), and a stray
release for a task that holds nothing is a no-op instead of inflating
the permit count past ``max_concurrent``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class TpuSemaphore:
    _instance: Optional["TpuSemaphore"] = None
    _lock = threading.Lock()

    def __init__(self, max_concurrent: int):
        if max_concurrent < 1:
            raise ValueError(f"max_concurrent must be >= 1, "
                             f"got {max_concurrent}")
        self.max_concurrent = max_concurrent
        self._cv = threading.Condition()
        self._permits = max_concurrent
        self._holders: Dict[int, int] = {}  # task_id -> re-entry depth

    @classmethod
    def initialize(cls, max_concurrent: int) -> "TpuSemaphore":
        with cls._lock:
            if cls._instance is None or \
                    cls._instance.max_concurrent != max_concurrent:
                cls._instance = TpuSemaphore(max_concurrent)
            return cls._instance

    @classmethod
    def get(cls) -> "TpuSemaphore":
        with cls._lock:
            if cls._instance is None:
                # before plugin init the configured width is still
                # knowable — fabricating max_concurrent=1 here silently
                # serialized every task on this path
                import warnings

                from .. import config as cfg
                width = cfg.RapidsConf({}).get(cfg.CONCURRENT_TPU_TASKS)
                warnings.warn(
                    f"TpuSemaphore.get() before plugin initialization; "
                    f"using the {cfg.CONCURRENT_TPU_TASKS.key} default "
                    f"({width}) — TpuSemaphore.initialize() at plugin "
                    f"startup is the supported path", RuntimeWarning,
                    stacklevel=2)
                cls._instance = TpuSemaphore(width)
            return cls._instance

    def acquire_if_necessary(self, task_id: int,
                             timeout: Optional[float] = None) -> bool:
        """Blocks until the task holds the semaphore (re-entrant)."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cv:
            held = self._holders.get(task_id)
            if held:
                self._holders[task_id] = held + 1
                return True
            while self._permits <= 0:
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining)
            self._permits -= 1
            self._holders[task_id] = 1
            return True

    def release_if_necessary(self, task_id: int) -> None:
        with self._cv:
            depth = self._holders.get(task_id)
            if depth is None:
                return  # double-release: permits stay untouched
            if depth > 1:
                self._holders[task_id] = depth - 1
                return
            del self._holders[task_id]
            self._permits += 1
            self._cv.notify()

    @property
    def holders(self) -> int:
        with self._cv:
            return len(self._holders)
