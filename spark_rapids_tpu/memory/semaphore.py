"""TPU task admission semaphore.

Ref: GpuSemaphore.scala:27-170 — bounds how many concurrent tasks may hold
device memory at once (spark.rapids.sql.concurrentGpuTasks); a task
acquires before its first device operation and releases at completion.
Re-entrant per task, like the reference's per-task bookkeeping.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class TpuSemaphore:
    _instance: Optional["TpuSemaphore"] = None
    _lock = threading.Lock()

    def __init__(self, max_concurrent: int):
        self.max_concurrent = max_concurrent
        self._sem = threading.Semaphore(max_concurrent)
        self._holders: Dict[int, int] = {}
        self._holders_lock = threading.Lock()

    @classmethod
    def initialize(cls, max_concurrent: int) -> "TpuSemaphore":
        with cls._lock:
            if cls._instance is None or \
                    cls._instance.max_concurrent != max_concurrent:
                cls._instance = TpuSemaphore(max_concurrent)
            return cls._instance

    @classmethod
    def get(cls) -> "TpuSemaphore":
        with cls._lock:
            if cls._instance is None:
                cls._instance = TpuSemaphore(1)
            return cls._instance

    def acquire_if_necessary(self, task_id: int,
                             timeout: Optional[float] = None) -> bool:
        """Blocks until the task holds the semaphore (re-entrant)."""
        with self._holders_lock:
            if task_id in self._holders:
                self._holders[task_id] += 1
                return True
        ok = self._sem.acquire(timeout=timeout) if timeout is not None \
            else self._sem.acquire()
        if ok:
            with self._holders_lock:
                self._holders[task_id] = 1
        return ok

    def release_if_necessary(self, task_id: int) -> None:
        with self._holders_lock:
            n = self._holders.get(task_id)
            if n is None:
                return
            if n > 1:
                self._holders[task_id] = n - 1
                return
            del self._holders[task_id]
        self._sem.release()

    @property
    def holders(self) -> int:
        with self._holders_lock:
            return len(self._holders)
