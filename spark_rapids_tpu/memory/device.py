"""Device manager: TPU acquisition + memory bookkeeping + semaphore init.

Ref: GpuDeviceManager.scala:125 initializeGpuAndMemory / :216 initializeRmm.
The RMM pool's TPU analog is an HBM budget tracked against the PJRT
device's memory stats; allocation visibility for spill decisions comes
from the batch registry (memory/spill.py) rather than allocator callbacks
(XLA owns the real allocator — SURVEY hard-part #5).
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

from .. import config as cfg


class DeviceManager:
    _instance: Optional["DeviceManager"] = None
    _lock = threading.Lock()

    def __init__(self, conf: cfg.RapidsConf):
        self.conf = conf
        self.device = None
        self.hbm_limit = 0
        self.hbm_reserve = conf.get(cfg.HBM_RESERVE)
        devs = jax.devices()
        if devs:
            self.device = devs[0]
            stats = {}
            try:
                stats = self.device.memory_stats() or {}
            except Exception:
                stats = {}
            total = stats.get("bytes_limit", 16 * (1 << 30))
            frac = conf.get(cfg.HBM_POOL_FRACTION)
            self.hbm_limit = int(total * frac) - self.hbm_reserve

    @classmethod
    def initialize(cls, conf: cfg.RapidsConf) -> "DeviceManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DeviceManager(conf)
            return cls._instance

    @classmethod
    def get(cls) -> Optional["DeviceManager"]:
        return cls._instance

    def memory_in_use(self) -> int:
        try:
            stats = self.device.memory_stats() or {}
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0
