"""Device manager: TPU acquisition + memory bookkeeping + semaphore init.

Ref: GpuDeviceManager.scala:125 initializeGpuAndMemory / :216 initializeRmm.
The RMM pool's TPU analog is an HBM budget tracked against the PJRT
device's memory stats; allocation visibility for spill decisions comes
from the batch registry (memory/spill.py) rather than allocator callbacks
(XLA owns the real allocator — SURVEY hard-part #5).
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

from .. import config as cfg


class DeviceManager:
    _instance: Optional["DeviceManager"] = None
    _lock = threading.Lock()

    def __init__(self, conf: cfg.RapidsConf):
        self.conf = conf
        self.device = None
        self.hbm_limit = 0
        self.hbm_reserve = conf.get(cfg.HBM_RESERVE)
        devs = jax.devices()
        if devs:
            self.device = devs[0]
            total = self._device_capacity(conf)
            frac = conf.get(cfg.HBM_POOL_FRACTION)
            self.hbm_limit = int(total * frac) - self.hbm_reserve

    # per-generation HBM capacities (public TPU specs); used only when the
    # PJRT runtime reports no memory_stats for the device
    _KNOWN_HBM = (
        ("v5 lite", 16 * (1 << 30)), ("v5e", 16 * (1 << 30)),
        ("v5p", 95 * (1 << 30)), ("v6", 32 * (1 << 30)),
        ("v4", 32 * (1 << 30)), ("v3", 16 * (1 << 30)),
        ("v2", 8 * (1 << 30)),
    )

    def _device_capacity(self, conf: cfg.RapidsConf) -> int:
        """Resolve real device memory: explicit conf > PJRT memory_stats >
        device-kind table > host RAM (CPU backend).  An unrecognized
        accelerator with no stats raises instead of silently assuming a
        capacity the spill budget would then be fiction against."""
        override = conf.get(cfg.HBM_LIMIT_OVERRIDE)
        if override:
            return int(override)
        try:
            stats = self.device.memory_stats() or {}
        except Exception:
            stats = {}
        if stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
        kind = (getattr(self.device, "device_kind", "") or "").lower()
        platform = getattr(self.device, "platform", "")
        for marker, cap in self._KNOWN_HBM:
            if marker in kind:
                return cap
        if platform == "cpu" or kind == "cpu":
            import os
            return os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
        from ..plugin import PluginInitError
        raise PluginInitError(
            f"cannot determine memory capacity of device {kind!r} "
            f"(platform {platform!r}): PJRT reports no memory_stats; set "
            f"{cfg.HBM_LIMIT_OVERRIDE.key} explicitly")

    @classmethod
    def initialize(cls, conf: cfg.RapidsConf) -> "DeviceManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DeviceManager(conf)
            return cls._instance

    @classmethod
    def get(cls) -> Optional["DeviceManager"]:
        return cls._instance

    def memory_in_use(self) -> int:
        try:
            stats = self.device.memory_stats() or {}
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0
