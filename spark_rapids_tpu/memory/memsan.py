"""tmsan runtime side: a shadow ledger over the spill catalog and the
host staging arena.

The static lifetime pass (analysis/lifetime.py) predicts buffer-protocol
violations and peak HBM from declared operator effects; this module is
the differential oracle that keeps those declarations honest — the role
analysis/oracle.py plays for the plan typechecker, applied to memory.

Opt-in via ``spark.rapids.tpu.memsan.enabled``: ``memory/spill.py`` and
``native/arena.py`` emit one event per lifecycle transition
(alloc/register/pin/spill/unspill/materialize/close/evict) into the
installed ledger, which

  * asserts every transition against the SAME ``LIFECYCLE`` relation the
    static pass evaluates (a use-after-close or double-spill raises
    ``LifecycleViolation`` at the exact call site, with the owning
    exec);
  * attributes every buffer to the Exec whose execute_partition frame
    acquired it (stack walk, only paid while the ledger is installed);
  * tracks live/peak device bytes so a query's measured peak can be
    checked against the static TPU-L014 bound
    (``devtools/run_lint.py --memsan`` replays the golden corpus doing
    exactly that);
  * extends ``SpillCatalog.leak_report()`` with exec provenance and
    gives the session a post-query ``assert_clean()`` — the
    Arm.scala-style leak check with the analyzer's vocabulary.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..analysis.lifetime import (ALLOC, ALLOCATED, CLOSE, CLOSED,
                                 DEVICE_RESIDENT, EVICT, MATERIALIZE, PIN,
                                 REGISTER, SPILL, UNBORN, UNSPILL,
                                 lifecycle_next)


class LifecycleViolation(RuntimeError):
    """A real buffer event broke the ownership state machine."""


class LedgerEntry:
    __slots__ = ("handle_id", "kind", "state", "device_bytes", "owner",
                 "history")

    def __init__(self, handle_id: str, kind: str, owner: str):
        self.handle_id = handle_id
        self.kind = kind              # "spillable" | "pinned" | "arena"
        self.state = UNBORN
        self.device_bytes = 0         # currently device-resident bytes
        self.owner = owner
        self.history: List[str] = []


def _owning_exec() -> str:
    """Attribute the current call to the nearest enclosing Exec frame
    (its execute path acquired the buffer); falls back to the first
    in-package caller outside memory/."""
    import sys
    from ..exec.base import Exec
    f = sys._getframe(2)
    fallback = ""
    while f is not None:
        self_ = f.f_locals.get("self")
        if isinstance(self_, Exec):
            return type(self_).__name__
        fn = f.f_code.co_filename
        if not fallback and "spark_rapids_tpu" in fn and \
                "/memory/" not in fn.replace("\\", "/"):
            fallback = f"{fn.rsplit('spark_rapids_tpu', 1)[-1].lstrip('/')}" \
                       f":{f.f_lineno}"
        f = f.f_back
    return fallback or "(unknown)"


class ShadowLedger:
    """Event sink + lifecycle asserter + peak accountant."""

    def __init__(self, strict: bool = True):
        self.strict = strict
        self._lock = threading.RLock()
        self.entries: Dict[str, LedgerEntry] = {}
        self.device_live = 0
        self.peak_device_bytes = 0
        self.violations: List[str] = []
        self.arena_high_water = 0

    # -- event core ---------------------------------------------------------
    def record(self, handle_id: str, event: str, device_delta: int = 0,
               kind: str = "spillable") -> None:
        with self._lock:
            entry = self.entries.get(handle_id)
            if entry is None:
                if event != ALLOC:
                    # a buffer born before the ledger was installed:
                    # nothing provable about its lifecycle — ignore
                    return
                entry = LedgerEntry(handle_id, kind, _owning_exec())
                self.entries[handle_id] = entry
            if event == ALLOC and entry.state == CLOSED and \
                    entry.history and entry.history[-1] == EVICT:
                # re-admission of an evicted pin-cache entry: the
                # deterministic pin handle id reuses the slot, so this
                # ALLOC starts a NEW lifecycle (eviction is the
                # catalog's doing, not the owner's — unlike an explicit
                # close, after which alloc stays illegal)
                entry.state = UNBORN
            nxt = lifecycle_next(entry.state, event)
            entry.history.append(event)
            if nxt is None:
                msg = (f"buffer {handle_id[:8]} (owner {entry.owner}): "
                       f"illegal {event} in state {entry.state} "
                       f"[history: {' -> '.join(entry.history)}]")
                self.violations.append(msg)
                if self.strict:
                    raise LifecycleViolation(msg)
                return
            entry.state = nxt
            if device_delta:
                entry.device_bytes += device_delta
                self.device_live += device_delta
                if self.device_live > self.peak_device_bytes:
                    self.peak_device_bytes = self.device_live

    # -- spill.py hook surface ----------------------------------------------
    def on_alloc(self, handle_id: str, nbytes: int,
                 kind: str = "spillable") -> None:
        self.record(handle_id, ALLOC, device_delta=nbytes, kind=kind)

    def on_register(self, handle_id: str) -> None:
        self.record(handle_id, REGISTER)

    def on_pin(self, handle_id: str, nbytes: int) -> None:
        # pin-cache entries are born and pinned in one step
        self.record(handle_id, ALLOC, device_delta=nbytes, kind="pinned")
        self.record(handle_id, PIN)

    def on_spill(self, handle_id: str, freed_device: int) -> None:
        self.record(handle_id, SPILL, device_delta=-freed_device)

    def on_unspill(self, handle_id: str, nbytes: int) -> None:
        self.record(handle_id, UNSPILL, device_delta=nbytes)

    def on_materialize(self, handle_id: str) -> None:
        self.record(handle_id, MATERIALIZE)

    def on_close(self, handle_id: str) -> None:
        with self._lock:
            entry = self.entries.get(handle_id)
            freed = entry.device_bytes if entry is not None and \
                entry.state in DEVICE_RESIDENT else 0
        self.record(handle_id, CLOSE, device_delta=-freed)

    def on_evict(self, handle_id: str) -> None:
        with self._lock:
            entry = self.entries.get(handle_id)
            freed = entry.device_bytes if entry is not None else 0
        self.record(handle_id, EVICT, device_delta=-freed)

    # -- arena hook surface --------------------------------------------------
    def on_arena_alloc(self, arena_id: str, size: int,
                       closed: bool) -> None:
        with self._lock:
            if closed:
                msg = f"arena {arena_id[:8]}: alloc after close"
                self.violations.append(msg)
                if self.strict:
                    raise LifecycleViolation(msg)
            self.arena_high_water = max(self.arena_high_water, size)

    # -- reports -------------------------------------------------------------
    def owner_of(self, handle_id: str) -> Optional[str]:
        entry = self.entries.get(handle_id)
        return entry.owner if entry is not None else None

    def live_entries(self, ignore_pinned: bool = True) -> List[LedgerEntry]:
        with self._lock:
            return [e for e in self.entries.values()
                    if e.state not in (CLOSED, UNBORN)
                    and not (ignore_pinned and e.kind == "pinned")]

    def assert_clean(self, ignore_pinned: bool = True) -> None:
        """Post-query check: every tracked buffer reached CLOSED (pinned
        cache entries are sanctioned residents — evictable under
        pressure — and excluded by default) and no violation was
        swallowed in non-strict mode."""
        leaks = self.live_entries(ignore_pinned)
        problems = list(self.violations)
        for e in leaks:
            problems.append(
                f"leaked buffer {e.handle_id[:8]}: owner {e.owner}, "
                f"state {e.state}, ~{max(e.device_bytes >> 10, 1)} KiB "
                f"device [history: {' -> '.join(e.history)}] (TPU-L015)")
        if problems:
            raise LifecycleViolation(
                f"shadow ledger dirty after query "
                f"({len(problems)} problem(s)):\n" + "\n".join(problems))


# ---------------------------------------------------------------------------
# installation (what spill.py/arena.py consult)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[ShadowLedger] = None
_TLS = threading.local()


def install(strict: bool = True) -> ShadowLedger:
    global _ACTIVE
    _ACTIVE = ShadowLedger(strict=strict)
    return _ACTIVE


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def install_local(strict: bool = True) -> ShadowLedger:
    """Thread-local install for concurrent serving (api/pool.py): each
    pool query audits ITS OWN buffers — a per-query assert_clean must
    not see co-running queries' live entries as leaks.  Single-session
    flows keep the process-global slot, where helper threads (scan
    prefetch, shuffle fetch) also report."""
    _TLS.ledger = ShadowLedger(strict=strict)
    return _TLS.ledger


def uninstall_local() -> None:
    _TLS.ledger = None


def active_ledger() -> Optional[ShadowLedger]:
    led = getattr(_TLS, "ledger", None)
    return led if led is not None else _ACTIVE


class installed:
    """Context manager: ``with memsan.installed() as ledger: ...``"""

    def __init__(self, strict: bool = True):
        self.strict = strict
        self.ledger: Optional[ShadowLedger] = None

    def __enter__(self) -> ShadowLedger:
        self.ledger = install(strict=self.strict)
        return self.ledger

    def __exit__(self, *exc):
        uninstall()
