"""Tiered spill framework: DEVICE -> HOST -> DISK.

Ref: RapidsBuffer.scala:53 (StorageTier), RapidsBufferCatalog.scala:156
(registry + tier wiring), RapidsBufferStore.synchronousSpill:146,
DeviceMemoryEventHandler.scala (Rmm OOM callback), SpillPriorities.scala,
SpillableColumnarBatch.scala.

TPU redesign (SURVEY hard-part #5): XLA owns the allocator, so there is no
RMM-style OOM callback.  Instead the framework tracks every *registered*
batch's device footprint in this catalog and reacts two ways:
  * proactively — `maybe_spill()` demotes lowest-priority buffers when the
    registered device bytes exceed the HBM budget;
  * reactively — `with_retry_spill(fn)` catches XLA RESOURCE_EXHAUSTED,
    spills synchronously, and retries, the analog of the reference's
    retry-on-OOM allocation loop.
Host tier holds serialized batches in RAM up to its own budget, then
overflows to local disk (RapidsDiskStore analog).
"""

from __future__ import annotations

import os
import tempfile
import threading
import uuid
from enum import Enum
from typing import Callable, Dict, List, Optional

import jax
import numpy as np

from ..columnar.device import DeviceBatch


def _ledger():
    """The installed tmsan shadow ledger, or None (the common case —
    the sanitizer is opt-in via spark.rapids.tpu.memsan.enabled and
    every hook below is a no-op without it)."""
    from . import memsan
    return memsan.active_ledger()


def _timeline():
    """The HBM observatory's occupancy timeline, or None when disabled
    (spark.rapids.tpu.hbm.timeline.enabled) — same no-op discipline as
    the shadow-ledger hooks."""
    from ..obs import memprof
    return memprof.active_timeline()


def _trace_event(name: str, **attrs) -> None:
    """Flight-recorder hook: tier moves are exactly what a post-mortem
    wants on the timeline (no-op without an installed tracer)."""
    from ..obs import tracer
    tr = tracer.active_tracer()
    if tr is not None:
        tr.event(name, **attrs)


def _metrics():
    """Continuous-metrics families for the spill subsystem (obs/metrics
    creation is idempotent; increments are no-ops when disabled)."""
    from ..obs import metrics as m
    return (
        m.counter("tpu_spill_registered_batches_total",
                  "spillable batches registered in the catalog"),
        m.counter("tpu_spill_registered_bytes_total",
                  "device bytes entering the spill catalog"),
        m.counter("tpu_spill_bytes_total",
                  "bytes demoted per destination tier", ("tier",)),
        m.counter("tpu_spill_pinned_evictions_total",
                  "pinned scan-cache entries evicted under pressure"),
        m.gauge("tpu_spill_device_bytes",
                "registered device-resident bytes (incl. pinned)"),
        m.gauge("tpu_spill_host_bytes",
                "serialized bytes held in the HOST tier"),
        m.counter("tpu_spill_raw_bytes_total",
                  "uncompressed serialized-body bytes entering each "
                  "tier (pre-codec)", ("tier",)),
        m.counter("tpu_spill_serialized_bytes_total",
                  "post-codec bytes actually stored per tier — vs the "
                  "raw counter this is the codec's effect on host "
                  "retention and disk I/O", ("tier",)),
    )


class StorageTier(Enum):
    DEVICE = 0
    HOST = 1
    DISK = 2


class SpillPriority:
    """Lower value spills first (ref SpillPriorities.scala)."""
    INPUT = -10
    SHUFFLE = 0
    ACTIVE = 100


def batch_device_bytes(batch: DeviceBatch) -> int:
    total = 0
    for leaf in jax.tree_util.tree_leaves(batch):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


class SpillableBatch:
    """A batch that can move down the storage tiers and come back
    (ref SpillableColumnarBatch.scala:29-230).  Supports `with` blocks —
    the Arm.scala withResource discipline: the reference leans on RAII +
    refcount asserts to catch leaks; here the context manager plus the
    catalog's debug leak tracker play that role."""

    def __init__(self, batch: DeviceBatch, catalog: "SpillCatalog",
                 priority: int = SpillPriority.ACTIVE):
        self.id = uuid.uuid4().hex
        self.catalog = catalog
        self.priority = priority
        self.tier = StorageTier.DEVICE
        self._batch: Optional[DeviceBatch] = batch
        self._host_bytes: Optional[bytes] = None
        self._disk_path: Optional[str] = None
        self.closed = False
        self.device_bytes = batch_device_bytes(batch)
        # num_rows may be a traced device scalar; resolving it here would
        # force a sync per registered batch — defer to first read
        self._num_rows = batch.num_rows
        led = _ledger()
        if led is not None:
            led.on_alloc(self.id, self.device_bytes)
        tl = _timeline()
        if tl is not None:
            from ..obs import memprof
            bclass = memprof.SHUFFLE_BLOCK \
                if priority == SpillPriority.SHUFFLE \
                else memprof.WORKING_SET
            tl.on_alloc(self.id, self.device_bytes, bclass)

    @property
    def num_rows(self) -> int:
        import numpy as _np
        if not isinstance(self._num_rows, int):
            self._num_rows = int(_np.asarray(self._num_rows))
        return self._num_rows

    # -- tier moves ---------------------------------------------------------
    def spill_to_host(self):
        if self.tier != StorageTier.DEVICE:
            return 0
        from .meta import serialize_batch_with_sizes
        self._host_bytes, raw_len, enc_len = \
            serialize_batch_with_sizes(self._batch)
        self._raw_body_len = raw_len
        self._batch = None
        self.tier = StorageTier.HOST
        led = _ledger()
        if led is not None:
            led.on_spill(self.id, self.device_bytes)
        tl = _timeline()
        if tl is not None:
            tl.on_spill(self.id, self.device_bytes)
        _trace_event("spill.host", bytes=self.device_bytes,
                     buffer=self.id[:8])
        mm = _metrics()
        mm[2].labels(tier="host").inc(self.device_bytes)
        mm[6].labels(tier="host").inc(raw_len)
        mm[7].labels(tier="host").inc(enc_len)
        return self.device_bytes

    def spill_to_disk(self):
        if self.tier == StorageTier.DEVICE:
            self.spill_to_host()
        if self.tier != StorageTier.HOST:
            return 0
        path = os.path.join(self.catalog.spill_dir, f"spill-{self.id}.bin")
        with open(path, "wb") as f:
            f.write(self._host_bytes)
        freed = len(self._host_bytes)
        self._disk_path = path
        self._host_bytes = None
        self.tier = StorageTier.DISK
        led = _ledger()
        if led is not None:
            led.on_spill(self.id, 0)  # host tier -> disk: no HBM delta
        _trace_event("spill.disk", bytes=freed, buffer=self.id[:8])
        mm = _metrics()
        mm[2].labels(tier="disk").inc(freed)
        mm[6].labels(tier="disk").inc(
            getattr(self, "_raw_body_len", freed))
        mm[7].labels(tier="disk").inc(freed)
        return freed

    def get_batch(self, xp) -> DeviceBatch:
        """Materialize (unspilling if needed)."""
        led = _ledger()
        if led is not None:
            led.on_materialize(self.id)
        if self.closed:
            raise RuntimeError(
                f"SpillableBatch {self.id[:8]} materialized after close "
                f"(use-after-close — the hazard TPU-L013 predicts)")
        if self.tier == StorageTier.DEVICE:
            b = self._batch
            if xp is not np:
                return b
            return b
        from .meta import deserialize_batch
        if self.tier == StorageTier.HOST:
            data = self._host_bytes
        else:
            with open(self._disk_path, "rb") as f:
                data = f.read()
        batch = deserialize_batch(data, xp=xp)
        if self.catalog.unspill_enabled and xp is not np:
            self._batch = batch
            self._host_bytes = None
            if self._disk_path:
                try:
                    os.unlink(self._disk_path)
                except OSError:
                    pass
                self._disk_path = None
            self.tier = StorageTier.DEVICE
            if led is not None:
                led.on_unspill(self.id, self.device_bytes)
            tl = _timeline()
            if tl is not None:
                tl.on_unspill(self.id, self.device_bytes)
            _trace_event("spill.unspill", bytes=self.device_bytes,
                         buffer=self.id[:8])
            self.catalog.note_unspill(self)
        return batch

    def host_size(self) -> int:
        return len(self._host_bytes) if self._host_bytes else 0

    def close(self):
        if self.closed:
            return  # idempotent, like file.close()
        led = _ledger()
        if led is not None:
            led.on_close(self.id)
        tl = _timeline()
        if tl is not None:
            tl.on_close(self.id)
        self.closed = True
        self.catalog.unregister(self)
        self._batch = None
        self._host_bytes = None
        if self._disk_path:
            try:
                os.unlink(self._disk_path)
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _pin_handle_id(owner, key, oid: Optional[int] = None) -> str:
    """Stable ledger handle id for one pin-cache entry (pin and evict
    must name the same buffer)."""
    return f"pin-{oid if oid is not None else id(owner)}-{hash(key):x}"


class SpillCatalog:
    """Registry + tier orchestration (ref RapidsBufferCatalog)."""

    _instance: Optional["SpillCatalog"] = None
    _lock = threading.Lock()

    def __init__(self, device_budget: int = 8 << 30,
                 host_budget: int = 1 << 30,
                 spill_dir: Optional[str] = None,
                 unspill_enabled: bool = False):
        self.device_budget = device_budget
        self.host_budget = host_budget
        self.spill_dir = spill_dir or tempfile.mkdtemp(
            prefix="spark_rapids_tpu_spill_")
        os.makedirs(self.spill_dir, exist_ok=True)
        self.unspill_enabled = unspill_enabled
        self._buffers: Dict[str, SpillableBatch] = {}
        # pinned device residents (scan pin caches): (owner_dict, key) ->
        # nbytes.  Counted against the budget and evicted FIRST under
        # pressure by dropping the owner's entry — they re-materialize
        # from host Arrow, so eviction is the cheapest possible "spill"
        # (the reference treats cached shuffle batches the same way:
        # device-resident but reclaimable, RapidsDeviceMemoryStore)
        self._pinned: Dict[tuple, int] = {}
        self._pin_owners: Dict[tuple, Dict] = {}
        self._reg_lock = threading.RLock()
        self.spilled_to_host_bytes = 0
        self.spilled_to_disk_bytes = 0
        self.pinned_evicted_bytes = 0
        # debug leak tracking (ref spark.rapids.memory.gpu.debug,
        # RapidsConf.scala:307 + Arm.scala's leak discipline): record
        # where every live buffer was registered
        self.debug = False
        self._created_at: Dict[str, str] = {}

    @classmethod
    def get(cls) -> "SpillCatalog":
        with cls._lock:
            if cls._instance is None:
                cls._instance = SpillCatalog()
            return cls._instance

    @classmethod
    def init_from_conf(cls, conf) -> "SpillCatalog":
        from .. import config as cfg
        from .device import DeviceManager
        dm = DeviceManager.get()
        device_budget = conf.get(cfg.SPILL_DEVICE_BUDGET)
        if device_budget is None:
            device_budget = dm.hbm_limit if dm and dm.hbm_limit > 0 \
                else 8 << 30
        with cls._lock:
            cls._instance = SpillCatalog(
                device_budget=device_budget,
                host_budget=conf.get(cfg.HOST_SPILL_STORAGE_SIZE),
                spill_dir=conf.get(cfg.SPILL_DIRS).split(",")[0],
                unspill_enabled=conf.get(cfg.UNSPILL_ENABLED))
            return cls._instance

    # -- registration -------------------------------------------------------
    def register(self, batch: DeviceBatch,
                 priority: int = SpillPriority.ACTIVE) -> SpillableBatch:
        sb = SpillableBatch(batch, self, priority)
        led = _ledger()
        if led is not None:
            led.on_register(sb.id)
        with self._reg_lock:
            self._buffers[sb.id] = sb
            if self.debug:
                import traceback
                self._created_at[sb.id] = "".join(
                    traceback.format_stack(limit=8)[:-1])
        mm = _metrics()
        mm[0].inc()
        mm[1].inc(sb.device_bytes)
        self.maybe_spill()
        self._update_gauges()
        return sb

    def unregister(self, sb: SpillableBatch):
        with self._reg_lock:
            self._buffers.pop(sb.id, None)
            self._created_at.pop(sb.id, None)
        self._update_gauges()

    def _update_gauges(self) -> None:
        from ..obs import metrics as m
        if not m.enabled():
            return  # the O(buffers) sums below are not free
        mm = _metrics()
        mm[4].set(self.device_bytes_registered())
        mm[5].set(self.host_bytes_registered())

    def leak_report(self) -> List[tuple]:
        """(id, tier, bytes, provenance) for every still-open buffer —
        the debug-mode leak check (Arm.scala analog).  Provenance is the
        creation stack under spark.rapids.memory.tpu.debug; with the
        tmsan shadow ledger installed it is prefixed with the OWNING
        EXEC the ledger attributed the allocation to."""
        led = _ledger()
        with self._reg_lock:
            out = []
            for b in self._buffers.values():
                prov = self._created_at.get(
                    b.id, "(enable debug for stacks)")
                owner = led.owner_of(b.id) if led is not None else None
                if owner:
                    prov = f"owner={owner}\n{prov}"
                out.append((b.id, b.tier.name, b.device_bytes, prov))
            return out

    # -- pinned scan batches -------------------------------------------------
    def register_pinned(self, owner: Dict, key, batch_list) -> None:
        """Account a pin-cache entry (owner[key] = batches) against the
        device budget and make it evictable."""
        nbytes = sum(batch_device_bytes(b) for b in batch_list)
        led = _ledger()
        if led is not None:
            led.on_pin(_pin_handle_id(owner, key), nbytes)
        tl = _timeline()
        if tl is not None:
            tl.on_pin(_pin_handle_id(owner, key), nbytes)
        with self._reg_lock:
            self._pinned[(id(owner), key)] = nbytes
            self._pin_owners[(id(owner), key)] = owner
        _metrics()[1].inc(nbytes)
        self.maybe_spill()
        self._update_gauges()

    def pinned_bytes(self) -> int:
        with self._reg_lock:
            return sum(self._pinned.values())

    def _evict_pinned(self, target_free: int) -> int:
        freed = 0
        led = _ledger()
        tl = _timeline()
        with self._reg_lock:
            for (oid, key), nbytes in list(self._pinned.items()):
                if freed >= target_free:
                    break
                owner = self._pin_owners.get((oid, key))
                if owner is not None:
                    owner.pop(key, None)
                if led is not None:
                    led.on_evict(_pin_handle_id(owner, key, oid))
                if tl is not None:
                    tl.on_evict(_pin_handle_id(owner, key, oid))
                self._pinned.pop((oid, key), None)
                self._pin_owners.pop((oid, key), None)
                freed += nbytes
                self.pinned_evicted_bytes += nbytes
                _trace_event("spill.evict_pinned", bytes=nbytes)
                _metrics()[3].inc()
        return freed

    def note_unspill(self, sb: SpillableBatch):
        self.maybe_spill()

    # -- accounting ---------------------------------------------------------
    def device_bytes_registered(self) -> int:
        with self._reg_lock:
            return sum(b.device_bytes for b in self._buffers.values()
                       if b.tier == StorageTier.DEVICE) + \
                sum(self._pinned.values())

    def host_bytes_registered(self) -> int:
        with self._reg_lock:
            return sum(b.host_size() for b in self._buffers.values()
                       if b.tier == StorageTier.HOST)

    # -- spilling -----------------------------------------------------------
    def synchronous_spill(self, target_free: int) -> int:
        """Demote device buffers (lowest priority first) until
        `target_free` bytes are released (ref synchronousSpill)."""
        # pinned scan batches go first: dropping them frees real HBM at
        # zero serialization cost (they rebuild from host Arrow on miss)
        freed = self._evict_pinned(target_free)
        with self._reg_lock:
            candidates = sorted(
                (b for b in self._buffers.values()
                 if b.tier == StorageTier.DEVICE),
                key=lambda b: b.priority)
            for b in candidates:
                if freed >= target_free:
                    break
                freed += b.spill_to_host()
                self.spilled_to_host_bytes += b.host_size()
            self._enforce_host_budget()
        self._update_gauges()
        return freed

    def _enforce_host_budget(self):
        used = sum(b.host_size() for b in self._buffers.values()
                   if b.tier == StorageTier.HOST)
        if used <= self.host_budget:
            return
        candidates = sorted(
            (b for b in self._buffers.values()
             if b.tier == StorageTier.HOST),
            key=lambda b: b.priority)
        for b in candidates:
            if used <= self.host_budget:
                break
            sz = b.host_size()
            self.spilled_to_disk_bytes += sz
            b.spill_to_disk()
            used -= sz

    def maybe_spill(self):
        over = self.device_bytes_registered() - self.device_budget
        if over > 0:
            self.synchronous_spill(over)


def is_oom_error(ex: Exception) -> bool:
    s = str(ex)
    return "RESOURCE_EXHAUSTED" in s or "Out of memory" in s or \
        "OOM" in s


def with_retry_spill(fn: Callable, catalog: Optional[SpillCatalog] = None,
                     attempts: int = 3):
    """Run a device computation; on XLA OOM, spill registered buffers and
    retry (the DeviceMemoryEventHandler analog)."""
    catalog = catalog or SpillCatalog.get()
    last = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as ex:  # XlaRuntimeError etc.
            if not is_oom_error(ex):
                raise
            last = ex
            freed = catalog.synchronous_spill(catalog.device_budget)
            if freed == 0 and i > 0:
                break
    raise last
