"""Byte-weighted TPU admission control for multi-tenant serving.

The count-based ``TpuSemaphore`` bounds HOW MANY tasks touch the device;
it knows nothing about bytes, so two queries whose peaks sum past HBM
can still co-run.  This controller closes that gap: at plan time each
query presents its tmsan static peak-device-bytes bound (the TPU-L014
machinery in analysis/lifetime.py) as an admission ticket, and tickets
co-run only while their bounds sum to at most
``spark.rapids.tpu.serve.hbmAdmissionBudgetBytes``.

Contract (the serving invariants the stress tests assert):

  * **Never OOM by construction** — admitted bounds never sum past the
    budget, and the bound is conservative per query.
  * **FIFO, never deadlock** — waiters queue in arrival order; a ticket
    that cannot fit within ``serve.admissionTimeoutMs`` fails with the
    typed ``AdmissionTimeout`` (backpressure the caller can act on),
    never a silent hang.  A ticket larger than the whole budget waits
    its timeout like any other — budget=1 byte must time out, not
    vacuously pass.
  * **Release on failure** — ``release()`` is idempotent and sits in
    the session's ``finally``; a failed query can never strand bytes.

Oversized-but-repairable plans are re-planned by the session through
``try_outofcore_repair`` (smaller ``oc_budget``) before admission, so a
giant sort/aggregate shrinks its ticket instead of hogging the budget.
After the map side of a shuffle materializes, the exchange-boundary
re-planner (analysis/replan.py) may ``reprice()`` a live ticket to the
measured bound — truthful accounting that backpressures FUTURE admits
without ever stalling the already-running query.

Every ``tpu_admission_*`` counter and queue gauge carries a ``tenant``
label (the pool-session id by default) so per-tenant consumption is
visible; cardinality is bounded by the registry's per-family series cap,
past which tenants collapse into the ``_overflow`` series.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

DEFAULT_TENANT = "default"

_TENANT_LABELS = ("tenant",)


class AdmissionTimeout(RuntimeError):
    """The admission ticket could not be granted within the timeout."""


class AdmissionTicket:
    """One admitted query's reservation against the byte budget."""

    __slots__ = ("nbytes", "label", "tenant", "repaired", "queue_wait_s",
                 "released")

    def __init__(self, nbytes: int, label: str, tenant: str,
                 repaired: bool, queue_wait_s: float):
        self.nbytes = nbytes
        self.label = label
        self.tenant = tenant
        self.repaired = repaired
        self.queue_wait_s = queue_wait_s
        self.released = False


def _metrics():
    from ..obs import metrics as m
    return m


def _timeline():
    """The HBM observatory's occupancy timeline (None when disabled).
    Ticket grant/reprice/release feed the per-tenant *reserved* series —
    the other half of the "who holds what" answer next to residency."""
    from ..obs import memprof
    return memprof.active_timeline()


class AdmissionController:
    """Process-wide FIFO byte-budget gate (None until configured: the
    single-tenant path pays nothing)."""

    _instance: Optional["AdmissionController"] = None
    _ilock = threading.Lock()

    def __init__(self, budget_bytes: int, timeout_s: float):
        if budget_bytes < 1:
            raise ValueError(f"admission budget must be >= 1 byte, "
                             f"got {budget_bytes}")
        self.budget_bytes = int(budget_bytes)
        self.timeout_s = float(timeout_s)
        self._cv = threading.Condition()
        self._in_flight = 0
        self._queue: deque = deque()  # waiter tokens, arrival order
        self.max_in_flight_seen = 0
        # per-tenant views of the two aggregates above (pruned at zero
        # so a burst of one-shot tenants cannot grow these unboundedly;
        # the metric families bound their own cardinality separately)
        self._queued_by_tenant: Dict[str, int] = {}
        self._inflight_by_tenant: Dict[str, int] = {}

    # -- process-wide configuration ------------------------------------------
    @classmethod
    def configure(cls, budget_bytes: Optional[int],
                  timeout_s: float) -> Optional["AdmissionController"]:
        """Install (budget set) or clear (budget None) the controller;
        idempotent for unchanged values so pooled sessions sharing one
        conf re-init without disturbing in-flight accounting."""
        with cls._ilock:
            if budget_bytes is None:
                cls._instance = None
                return None
            inst = cls._instance
            if inst is not None and \
                    inst.budget_bytes == int(budget_bytes) and \
                    inst.timeout_s == float(timeout_s):
                return inst
            cls._instance = AdmissionController(budget_bytes, timeout_s)
            # csan lock witness: each configure() builds a fresh _cv;
            # deferred registration is lock-safe (we hold _ilock here)
            from ..obs import lockwitness
            lockwitness.maybe_register(
                "memory.admission.AdmissionController._cv",
                cls._instance, "_cv")
            return cls._instance

    @classmethod
    def get(cls) -> Optional["AdmissionController"]:
        with cls._ilock:
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._ilock:
            cls._instance = None

    # -- admission ------------------------------------------------------------
    def _counter(self, name: str, doc: str, tenant: str):
        return _metrics().counter(name, doc,
                                  labelnames=_TENANT_LABELS) \
            .labels(tenant=tenant)

    def _publish_gauges(self) -> None:
        m = _metrics()
        qd = m.gauge("tpu_admission_queue_depth",
                     "queries waiting in the FIFO admission queue",
                     labelnames=_TENANT_LABELS)
        bif = m.gauge("tpu_admission_bytes_in_flight",
                      "sum of admitted tickets' static peak-HBM bounds",
                      labelnames=_TENANT_LABELS)
        # drained tenants publish a final 0 and leave the dict; their
        # metric series stay behind at 0, which is what a scrape wants
        for t in list(self._queued_by_tenant):
            qd.labels(tenant=t).set(self._queued_by_tenant[t])
            if not self._queued_by_tenant[t]:
                del self._queued_by_tenant[t]
        for t in list(self._inflight_by_tenant):
            bif.labels(tenant=t).set(self._inflight_by_tenant[t])
            if not self._inflight_by_tenant[t]:
                del self._inflight_by_tenant[t]

    def _tenant_add(self, book: Dict[str, int], tenant: str,
                    delta: int) -> None:
        book[tenant] = book.get(tenant, 0) + delta

    def admit(self, nbytes: int, label: str = "",
              timeout_s: Optional[float] = None,
              repaired: bool = False,
              tenant: str = DEFAULT_TENANT) -> AdmissionTicket:
        """Block until ``nbytes`` fits in the budget (FIFO order) and
        reserve it; raises ``AdmissionTimeout`` past the deadline."""
        nbytes = max(int(nbytes), 0)
        tenant = tenant or DEFAULT_TENANT
        timeout = self.timeout_s if timeout_s is None else timeout_s
        deadline = time.monotonic() + timeout
        t0 = time.monotonic()
        token = object()
        queued = False
        # cooperative cancel checkpoint: a queued query's cancel token
        # registers this controller's cv as a waker, so cancel() /
        # deadline expiry wakes the waiter immediately; the shared
        # finally below removes the token from the FIFO and notifies
        # the survivors — cancel-while-queued cannot strand the queue
        from ..obs import progress as prog
        from ..obs.progress import (TpuQueryCancelled,
                                    TpuQueryDeadlineExceeded)
        ctok = prog.current_token()
        # queue time becomes a real span under the query root (admit()
        # runs between phase:plan and phase:execute, so the thread's
        # span stack is empty and the span parents to the root): the
        # Perfetto timeline shows the wait and critical-path extraction
        # books it as queue_wait instead of inferring it
        from ..obs.tracer import trace_span
        with trace_span("admission.wait", bytes=nbytes,
                        tenant=tenant) as span:
            if ctok is not None:
                ctok.add_waker(self._cv)
            try:
                with self._cv:
                    self._queue.append(token)
                    span.set(queue_depth_at_enqueue=len(self._queue) - 1)
                    self._tenant_add(self._queued_by_tenant, tenant, 1)
                    try:
                        while self._queue[0] is not token or \
                                self._in_flight + nbytes > \
                                self.budget_bytes:
                            if not queued:
                                queued = True
                                self._counter(
                                    "tpu_admission_queued_total",
                                    "tickets that had to wait before "
                                    "admission", tenant).inc()
                            self._publish_gauges()
                            if ctok is not None:
                                if ctok.cancelled:
                                    raise TpuQueryCancelled(
                                        ctok.describe("queue-wait"),
                                        query_id=ctok.query_id,
                                        checkpoint="queue-wait",
                                        cause=ctok.cause)
                                if ctok.deadline_exceeded:
                                    raise TpuQueryDeadlineExceeded(
                                        ctok.describe("queue-wait"),
                                        query_id=ctok.query_id,
                                        checkpoint="queue-wait")
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                self._counter(
                                    "tpu_admission_timeouts_total",
                                    "tickets that hit "
                                    "serve.admissionTimeoutMs without "
                                    "fitting in the budget",
                                    tenant).inc()
                                raise AdmissionTimeout(
                                    f"admission ticket "
                                    f"{label or '(query)'} "
                                    f"({nbytes} bytes) timed out after "
                                    f"{timeout:g}s: budget "
                                    f"{self.budget_bytes} bytes, "
                                    f"{self._in_flight} in flight, "
                                    f"{len(self._queue) - 1} "
                                    f"ahead/behind in queue")
                            if ctok is not None:
                                dl = ctok.deadline_remaining_s()
                                if dl is not None:
                                    remaining = min(remaining,
                                                    max(dl, 0.0) + 0.01)
                            self._cv.wait(remaining)
                        self._in_flight += nbytes
                        self._tenant_add(self._inflight_by_tenant,
                                         tenant, nbytes)
                        if self._in_flight > self.max_in_flight_seen:
                            self.max_in_flight_seen = self._in_flight
                    finally:
                        self._queue.remove(token)
                        self._tenant_add(self._queued_by_tenant, tenant,
                                         -1)
                        self._publish_gauges()
                        # head departure (admitted, timed out OR
                        # cancelled) can unblock the next waiter
                        self._cv.notify_all()
            finally:
                if ctok is not None:
                    ctok.remove_waker(self._cv)
        wait_s = time.monotonic() - t0
        self._counter("tpu_admission_admitted_total",
                      "tickets granted a byte reservation",
                      tenant).inc()
        if repaired:
            self._counter("tpu_admission_repaired_total",
                          "oversized tickets admitted after out-of-core "
                          "re-planning shrank their bound",
                          tenant).inc()
        _metrics().histogram(
            "tpu_admission_queue_wait_seconds",
            "time from admit() to reservation").observe(wait_s)
        tl = _timeline()
        if tl is not None:
            tl.note_ticket(tenant, nbytes)
        return AdmissionTicket(nbytes, label, tenant, repaired, wait_s)

    def reprice(self, ticket: AdmissionTicket, new_nbytes: int) -> int:
        """Adjust a LIVE ticket's reservation to ``new_nbytes`` — the
        exchange-boundary re-planner calls this once the map stage's
        measured partition sizes sharpen (or inflate) the static bound.
        Never blocks: the query already holds the device, so when the
        new bound overshoots the budget the honest move is truthful
        accounting (future admits queue behind it), not a mid-flight
        stall.  Mutating ``ticket.nbytes`` in place keeps the
        release-once invariant intact — ``release()`` subtracts
        whatever the ticket says it holds.  Returns the signed byte
        delta applied (0 for a released ticket or an unchanged bound).
        """
        new = max(int(new_nbytes), 0)
        with self._cv:
            if ticket.released:
                return 0
            delta = new - ticket.nbytes
            if delta == 0:
                return 0
            self._in_flight += delta
            ticket.nbytes = new
            self._tenant_add(self._inflight_by_tenant, ticket.tenant,
                             delta)
            if self._in_flight > self.max_in_flight_seen:
                self.max_in_flight_seen = self._in_flight
            self._publish_gauges()
            # a shrink can unblock the next waiter
            self._cv.notify_all()
        self._counter("tpu_admission_repriced_total",
                      "live tickets re-priced by the exchange-boundary "
                      "re-planner", ticket.tenant).inc()
        tl = _timeline()
        if tl is not None:
            tl.note_ticket(ticket.tenant, delta)
        return delta

    def release(self, ticket: AdmissionTicket) -> None:
        """Return the ticket's bytes (idempotent: the session's finally
        may race a failure path that already released)."""
        with self._cv:
            if ticket.released:
                return
            ticket.released = True
            self._in_flight -= ticket.nbytes
            self._tenant_add(self._inflight_by_tenant, ticket.tenant,
                             -ticket.nbytes)
            self._publish_gauges()
            self._cv.notify_all()
        tl = _timeline()
        if tl is not None:
            tl.note_ticket(ticket.tenant, -ticket.nbytes)

    # -- introspection ---------------------------------------------------------
    def hbm_holders(self) -> dict:
        """The HBM observatory's occupancy split — "who holds what",
        the signal queue/reprice policy (and item 5's preemption) acts
        on.  Each tenant row carries resident bytes split into pinned /
        demotable (spillable-now) / closed-pending, plus the admission
        reservation tracked from this controller's own ticket stream.
        Returns a disabled-shaped report when the timeline is off."""
        from ..obs.memprof import MemoryTimeline
        return MemoryTimeline.get().report()

    @property
    def bytes_in_flight(self) -> int:
        with self._cv:
            return self._in_flight

    @property
    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)
