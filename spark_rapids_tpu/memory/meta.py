"""Table metadata + batch serialization for spill and shuffle transport.

Ref: MetaUtils.scala (FlatBuffers TableMeta describing packed tables) and
GpuColumnarBatchSerializer.scala (the serialized fallback path).

The wire format here is Arrow IPC for column payloads plus a fixed little-
endian header (magic, version, lengths) — language-neutral like the
reference's FlatBuffers schemas, with pyarrow doing the zero-copy body
encoding.  Compression plugs in via the native codec layer
(spark_rapids_tpu/native, ref TableCompressionCodec.scala)."""

from __future__ import annotations

import io
import struct
from typing import Optional, Tuple

import numpy as np
import pyarrow as pa

from ..columnar.device import DeviceBatch, batch_to_arrow, batch_to_device

MAGIC = b"TPUB"
VERSION = 1

_HEADER = struct.Struct("<4sHHqq")  # magic, version, codec, n_rows, body_len

CODEC_NONE = 0
CODEC_LZ4 = 1
CODEC_ZSTD = 2

CODEC_BY_NAME = {"none": CODEC_NONE, "lz4": CODEC_LZ4, "zstd": CODEC_ZSTD}

_default_codec = CODEC_NONE


def set_default_codec(name: str) -> None:
    """Process-wide payload codec, set from
    spark.rapids.shuffle.compression.codec at session init (ref
    TableCompressionCodec.getCodec)."""
    global _default_codec
    _default_codec = CODEC_BY_NAME[name]


def default_codec() -> int:
    return _default_codec


def serialize_batch(batch: DeviceBatch,
                    codec: Optional[int] = None) -> bytes:
    """Device/host batch -> self-describing bytes."""
    if codec is None:
        codec = _default_codec
    rb = batch_to_arrow(batch)
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    body = sink.getvalue()
    if codec == CODEC_LZ4:
        from ..native import codec as ncodec
        body = ncodec.lz4_compress(body)
    elif codec == CODEC_ZSTD:
        from ..native import codec as ncodec
        body = ncodec.zstd_compress(body)
    head = _HEADER.pack(MAGIC, VERSION, codec, int(batch.num_rows),
                        len(body))
    # spill/shuffle payloads stage through the shared pinned arena when
    # one is configured (spark.rapids.memory.pinnedPool.size): one
    # page-aligned native buffer instead of per-call heap churn, and
    # the arena's utilization gauges see every serialized batch
    from ..native.arena import stage_bytes
    return stage_bytes(head + body)


def deserialize_batch(data: bytes, xp=np) -> DeviceBatch:
    magic, version, codec, n_rows, body_len = _HEADER.unpack_from(data, 0)
    assert magic == MAGIC and version == VERSION, "bad batch header"
    body = data[_HEADER.size:_HEADER.size + body_len]
    if codec == CODEC_LZ4:
        from ..native import codec as ncodec
        body = ncodec.lz4_decompress(body)
    elif codec == CODEC_ZSTD:
        from ..native import codec as ncodec
        body = ncodec.zstd_decompress(body)
    with pa.ipc.open_stream(io.BytesIO(body)) as r:
        rbs = list(r)
    if not rbs:
        raise ValueError("empty batch stream")
    return batch_to_device(rbs[0], xp=xp)


class TableMeta:
    """Lightweight descriptor advertised before transfer (ref
    MetaUtils.buildTableMeta): row count + serialized size + schema id."""

    __slots__ = ("num_rows", "num_bytes", "schema_fingerprint")

    def __init__(self, num_rows: int, num_bytes: int,
                 schema_fingerprint: int):
        self.num_rows = num_rows
        self.num_bytes = num_bytes
        self.schema_fingerprint = schema_fingerprint

    _S = struct.Struct("<qqQ")

    def pack(self) -> bytes:
        return self._S.pack(self.num_rows, self.num_bytes,
                            self.schema_fingerprint)

    @classmethod
    def unpack(cls, data: bytes) -> "TableMeta":
        return cls(*cls._S.unpack_from(data, 0))

    @classmethod
    def of(cls, batch: DeviceBatch, payload: bytes) -> "TableMeta":
        import zlib
        names = ",".join(batch.names).encode()
        types = ",".join(d.name for d in batch.dtypes).encode()
        fp = zlib.crc32(names + b"|" + types)
        return cls(int(batch.num_rows), len(payload), fp)
