"""Table metadata + batch serialization for spill and shuffle transport.

Ref: MetaUtils.scala (FlatBuffers TableMeta describing packed tables) and
GpuColumnarBatchSerializer.scala (the serialized fallback path).

The wire format here is Arrow IPC for column payloads plus a fixed little-
endian header (magic, version, lengths) — language-neutral like the
reference's FlatBuffers schemas, with pyarrow doing the zero-copy body
encoding.  Compression plugs in via the native codec layer
(spark_rapids_tpu/native, ref TableCompressionCodec.scala)."""

from __future__ import annotations

import io
import struct
import time
from typing import Optional, Tuple

import numpy as np
import pyarrow as pa

from ..columnar.device import DeviceBatch, batch_to_arrow, batch_to_device

MAGIC = b"TPUB"
VERSION = 1

_HEADER = struct.Struct("<4sHHqq")  # magic, version, codec, n_rows, body_len

CODEC_NONE = 0
CODEC_LZ4 = 1
CODEC_ZSTD = 2

CODEC_BY_NAME = {"none": CODEC_NONE, "lz4": CODEC_LZ4, "zstd": CODEC_ZSTD}
CODEC_NAMES = {v: k for k, v in CODEC_BY_NAME.items()}

_default_codec = CODEC_NONE


class TpuCorruptPayloadError(ValueError):
    """A serialized batch failed to decode: bad magic/version, a body
    shorter than its declared length, or codec-level corruption.  Typed
    (never a bare assert) so shuffle transport and disk-spill reads can
    surface data corruption distinctly from programming errors."""


def set_default_codec(name: str) -> None:
    """Process-wide payload codec, set from
    spark.rapids.shuffle.compression.codec at session init (ref
    TableCompressionCodec.getCodec)."""
    global _default_codec
    _default_codec = CODEC_BY_NAME[name]


def default_codec() -> int:
    return _default_codec


def serialize_batch(batch: DeviceBatch,
                    codec: Optional[int] = None) -> bytes:
    """Device/host batch -> self-describing bytes."""
    return serialize_batch_with_sizes(batch, codec)[0]


def serialize_batch_with_sizes(batch: DeviceBatch,
                               codec: Optional[int] = None,
                               timings: Optional[dict] = None
                               ) -> Tuple[bytes, int, int]:
    """serialize_batch plus the (raw, encoded) body sizes, so callers
    (shuffle server, spill tiers) can account compression per payload
    without re-measuring.  Every serialized byte is metered into
    tpu_shuffle_{raw,compressed}_bytes_total{codec} here — the single
    choke point both shuffle transport and spill stage through.

    ``timings`` (when given) receives ``serialize_ns``/``compress_ns``
    so the shuffle server can attribute its serve histogram to the
    arrow-encode vs codec halves without a second clock around this
    call."""
    if codec is None:
        codec = _default_codec
    t0 = time.perf_counter_ns()
    rb = batch_to_arrow(batch)
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    body = sink.getvalue()
    raw_len = len(body)
    t1 = time.perf_counter_ns()
    if codec == CODEC_LZ4:
        from ..native import codec as ncodec
        body = ncodec.lz4_compress(body)
    elif codec == CODEC_ZSTD:
        from ..native import codec as ncodec
        body = ncodec.zstd_compress(body)
    if timings is not None:
        timings["serialize_ns"] = t1 - t0
        timings["compress_ns"] = time.perf_counter_ns() - t1
    head = _HEADER.pack(MAGIC, VERSION, codec, int(batch.num_rows),
                        len(body))
    from ..obs import metrics as m
    if m.enabled():
        name = CODEC_NAMES.get(codec, str(codec))
        m.counter("tpu_shuffle_raw_bytes_total",
                  "uncompressed payload bytes staged for shuffle/spill",
                  ("codec",)).labels(codec=name).inc(raw_len)
        m.counter("tpu_shuffle_compressed_bytes_total",
                  "encoded payload bytes after the codec (equals raw "
                  "for codec=none)",
                  ("codec",)).labels(codec=name).inc(len(body))
    # spill/shuffle payloads stage through the shared pinned arena when
    # one is configured (spark.rapids.memory.pinnedPool.size): one
    # page-aligned native buffer instead of per-call heap churn, and
    # the arena's utilization gauges see every serialized batch
    from ..native.arena import stage_bytes
    return stage_bytes(head + body), raw_len, len(body)


def deserialize_batch(data: bytes, xp=np) -> DeviceBatch:
    from ..native.codec import CodecCorruptionError
    if len(data) < _HEADER.size:
        raise TpuCorruptPayloadError(
            f"payload too short for header: {len(data)} bytes < "
            f"{_HEADER.size}")
    magic, version, codec, n_rows, body_len = _HEADER.unpack_from(data, 0)
    if magic != MAGIC or version != VERSION:
        raise TpuCorruptPayloadError(
            f"bad batch header: magic={magic!r} version={version}")
    body = data[_HEADER.size:_HEADER.size + body_len]
    if len(body) < body_len:
        raise TpuCorruptPayloadError(
            f"truncated payload body: header declares {body_len} bytes, "
            f"{len(body)} present")
    try:
        if codec == CODEC_LZ4:
            from ..native import codec as ncodec
            body = ncodec.lz4_decompress(body)
        elif codec == CODEC_ZSTD:
            from ..native import codec as ncodec
            body = ncodec.zstd_decompress(body)
        elif codec != CODEC_NONE:
            raise TpuCorruptPayloadError(
                f"unknown codec id {codec} in batch header")
        with pa.ipc.open_stream(io.BytesIO(body)) as r:
            rbs = list(r)
    except CodecCorruptionError as ex:
        raise TpuCorruptPayloadError(f"codec frame corrupt: {ex}") from ex
    except pa.ArrowInvalid as ex:
        raise TpuCorruptPayloadError(f"arrow body corrupt: {ex}") from ex
    if not rbs:
        raise TpuCorruptPayloadError("empty batch stream")
    return batch_to_device(rbs[0], xp=xp)


class TableMeta:
    """Lightweight descriptor advertised before transfer (ref
    MetaUtils.buildTableMeta): row count + serialized size + schema id +
    the block's u64 content digest (0 when digests are disabled or the
    writer recorded none — verification is skipped, never guessed)."""

    __slots__ = ("num_rows", "num_bytes", "schema_fingerprint",
                 "content_digest")

    def __init__(self, num_rows: int, num_bytes: int,
                 schema_fingerprint: int, content_digest: int = 0):
        self.num_rows = num_rows
        self.num_bytes = num_bytes
        self.schema_fingerprint = schema_fingerprint
        self.content_digest = content_digest

    _S = struct.Struct("<qqQQ")

    def pack(self) -> bytes:
        return self._S.pack(self.num_rows, self.num_bytes,
                            self.schema_fingerprint,
                            self.content_digest)

    @classmethod
    def unpack(cls, data: bytes) -> "TableMeta":
        return cls(*cls._S.unpack_from(data, 0))

    @classmethod
    def of(cls, batch: DeviceBatch, payload: bytes,
           content_digest: int = 0) -> "TableMeta":
        return cls(int(batch.num_rows), len(payload),
                   schema_fingerprint(batch.names, batch.dtypes),
                   content_digest)

    @classmethod
    def of_stats(cls, num_rows: int, num_bytes: int,
                 fingerprint: int, content_digest: int = 0) -> "TableMeta":
        """Meta from catalog-tracked stats — the O(1) path the block
        server uses instead of materializing and serializing payloads
        (num_bytes is the catalog's retained-size hint, not an exact
        serialized length; content_digest is the digest the catalog
        cached at map-write time, never computed here)."""
        return cls(int(num_rows), int(num_bytes), fingerprint,
                   content_digest)


def schema_fingerprint(names, dtypes) -> int:
    import zlib
    n = ",".join(names).encode()
    t = ",".join(d.name for d in dtypes).encode()
    return zlib.crc32(n + b"|" + t)
