"""Plugin bootstrap: driver/executor lifecycle.

Ref: sql-plugin/.../Plugin.scala — `RapidsDriverPlugin` (config fixup,
shuffle heartbeat registry, plan-capture test callback RPC at :264-386)
and `RapidsExecutorPlugin` (:166-238: cudf version handshake, GPU+RMM
init, semaphore init, heartbeat registration, hard `System.exit(1)` on
init failure so the cluster manager reschedules the executor).

The TPU build keeps the same two-phase shape: a driver-side plugin that
owns cluster-wide state (heartbeat registry, config fixup, capture
callback) and an executor-side plugin that initializes this process's
device runtime (device manager, HBM budget/spill catalog, task
semaphore, shuffle endpoint, shim selection) and applies the same
fail-fast contract.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional

from . import config as cfg

log = logging.getLogger("spark_rapids_tpu.plugin")


def _host_cpu_fingerprint() -> str:
    """Identify the host machine instance for the compilation-cache key.

    CPU feature flags alone are NOT enough: two VM instances can report
    identical cpuinfo flags while their pCPUs differ in ways XLA:CPU's
    AOT executables bake in — loading a stale instance's entry then
    SIGILLs/SEGVs inside the cache read (observed: a suite run crashing
    in get_executable_and_time on an entry a previous instance wrote).
    Scoping by machine-id/boot-id keeps the cache warm for the whole
    life of an instance (what repeated queries and CI runs need) while
    making cross-instance AOT reuse — the only unsafe case — a miss."""
    import hashlib
    import platform

    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
                if line.startswith("model name") and not flags:
                    flags = line.split(":", 1)[1].strip()
    except OSError:
        flags = platform.processor()
    instance = ""
    for p in ("/etc/machine-id", "/proc/sys/kernel/random/boot_id"):
        try:
            with open(p) as f:
                instance = f.read().strip()
            if instance:
                break
        except OSError:
            continue
    return platform.machine() + "|" + \
        hashlib.sha1(f"{flags}|{instance}".encode()).hexdigest()[:12]


class PluginInitError(RuntimeError):
    """Executor init failure.  The reference calls System.exit(1)
    (Plugin.scala:196-203); embedded in-process we raise instead and let
    the host decide, unless spark.rapids.tpu.hardExitOnInitFailure."""


def fixup_configs(conf_map: dict) -> dict:
    """Force settings the plugin needs, like the reference forcing
    `spark.sql.extensions` + serializer checks
    (RapidsPluginUtils.fixupConfigs, Plugin.scala:77-112)."""
    out = dict(conf_map)
    exts = out.get("spark.sql.extensions", "")
    ours = "com.nvidia.spark.rapids.tpu.SQLExecPlugin"
    if ours not in exts:
        out["spark.sql.extensions"] = f"{exts},{ours}".strip(",")
    # columnar serializer must stay compatible with device batches
    out.setdefault("spark.rapids.shuffle.transport",
                   cfg.RapidsConf(out).get(cfg.SHUFFLE_TRANSPORT))
    return out


# ---------------------------------------------------------------------------
# Plan-capture callback (ref ExecutionPlanCaptureCallback Plugin.scala:264)
# ---------------------------------------------------------------------------

class ExecutionPlanCaptureCallback:
    """Captures executed plans for fallback assertions in tests."""

    _capture = False
    _plans: List = []
    _lock = threading.Lock()

    @classmethod
    def start_capture(cls):
        with cls._lock:
            cls._capture = True
            cls._plans = []

    @classmethod
    def on_plan(cls, plan) -> None:
        with cls._lock:
            if cls._capture:
                cls._plans.append(plan)

    @classmethod
    def get_resulting_plans(cls) -> List:
        with cls._lock:
            cls._capture = False
            return list(cls._plans)

    @classmethod
    def assert_contains(cls, plan, exec_name: str) -> bool:
        found = []
        plan.foreach(lambda e: found.append(e)
                     if type(e).__name__ == exec_name else None)
        return bool(found)


class TpuDriverPlugin:
    """Driver-side lifecycle (ref RapidsDriverPlugin, Plugin.scala:129)."""

    def __init__(self, conf_map: Optional[dict] = None):
        self.conf_map = fixup_configs(conf_map or {})
        self.conf = cfg.RapidsConf(self.conf_map)
        self.heartbeat_manager = None
        self.fleet_aggregator = None

    def init(self) -> dict:
        from .shuffle.heartbeat import HeartbeatManager
        if self.conf.get(cfg.SHUFFLE_MANAGER_ENABLED):
            timeout = self.conf.get(cfg.SHUFFLE_HEARTBEAT_TIMEOUT_MS) / 1000
            self.heartbeat_manager = HeartbeatManager(timeout_s=timeout)
            if self.conf.get(cfg.FLEET_AGGREGATOR_ENABLED):
                # the driver is where cluster-rollup series and the
                # fleet verdict live: the aggregator walks THIS
                # registry's peers at every /metrics//healthz read
                from .obs.fleet import FleetAggregator, install_aggregator
                self.fleet_aggregator = install_aggregator(FleetAggregator(
                    self.heartbeat_manager,
                    max_peers=self.conf.get(cfg.FLEET_SCRAPE_MAX_PEERS),
                    timeout_s=self.conf.get(
                        cfg.FLEET_SCRAPE_TIMEOUT_MS) / 1000.0))
        log.info("TPU driver plugin initialized")
        return self.conf_map  # the fixed-up configs Spark distributes

    def receive(self, message):
        """Driver RPC dispatch (ref Plugin.scala:132-144): executors
        register / heartbeat through the plugin channel."""
        kind = message.get("kind")
        if self.heartbeat_manager is None:
            return {"ok": False, "error": "accelerated shuffle disabled"}
        if kind == "register":
            peers = self.heartbeat_manager.register_executor(
                message["executor_id"], message.get("host", ""),
                message.get("port", 0),
                obs_port=message.get("obs_port", 0))
            return {"ok": True, "peers": [p.__dict__ for p in peers]}
        if kind == "heartbeat":
            peers = self.heartbeat_manager.executor_heartbeat(
                message["executor_id"])
            return {"ok": True, "peers": [p.__dict__ for p in peers]}
        return {"ok": False, "error": f"unknown message {kind!r}"}

    def shutdown(self):
        if self.fleet_aggregator is not None:
            from .obs.fleet import install_aggregator
            install_aggregator(None)
            self.fleet_aggregator = None
        self.heartbeat_manager = None


class TpuExecutorPlugin:
    """Executor-side lifecycle (ref RapidsExecutorPlugin,
    Plugin.scala:166-238)."""

    def __init__(self, conf_map: Optional[dict] = None,
                 driver: Optional[TpuDriverPlugin] = None,
                 executor_id: str = "0"):
        self.conf = cfg.RapidsConf(conf_map or {})
        self.driver = driver
        self.executor_id = executor_id
        self.device_manager = None
        self.semaphore = None
        self.spill_catalog = None
        self.shuffle_server = None

    # -- version handshake (ref checkCudfVersion Plugin.scala:206) ----------
    @staticmethod
    def check_runtime_versions() -> List[str]:
        problems = []
        import jax
        import pyarrow
        jv = tuple(int(x) for x in jax.__version__.split(".")[:2])
        if jv < (0, 4):
            problems.append(f"jax {jax.__version__} is too old (need 0.4+)")
        pv = tuple(int(x) for x in pyarrow.__version__.split(".")[:1])
        if pv < (8,):
            problems.append(
                f"pyarrow {pyarrow.__version__} is too old (need 8+)")
        return problems

    def init(self):
        try:
            problems = self.check_runtime_versions()
            if problems:
                raise PluginInitError("; ".join(problems))
            self._init_compilation_cache()
            from .memory.device import DeviceManager
            from .memory.meta import set_default_codec
            from .memory.semaphore import TpuSemaphore
            from .memory.spill import SpillCatalog
            from .shims import ShimLoader
            self.shim = ShimLoader.get_shim(
                self.conf.raw("spark.rapids.tpu.sparkVersion", "3.2.0"))
            set_default_codec(self.conf.get(cfg.SHUFFLE_COMPRESSION_CODEC))
            self.device_manager = DeviceManager.initialize(self.conf)
            self.semaphore = TpuSemaphore.initialize(
                self.conf.get(cfg.CONCURRENT_TPU_TASKS))
            # byte-weighted admission (serve.hbmAdmissionBudgetBytes):
            # configured alongside the count semaphore so both gates
            # share one lifecycle; unset budget clears the controller
            # (single-tenant sessions must not inherit a previous
            # serving session's budget)
            from .memory.admission import AdmissionController
            AdmissionController.configure(
                self.conf.get(cfg.SERVE_ADMISSION_BUDGET),
                self.conf.get(cfg.SERVE_ADMISSION_TIMEOUT_MS) / 1000.0)
            self.spill_catalog = SpillCatalog.init_from_conf(self.conf)
            # HBM observatory: (re)configure the occupancy timeline
            # with the freshly-sized device budget, so its watermark
            # fraction and tpu_hbm_budget_bytes gauge are truthful even
            # when the plugin is bootstrapped outside a TpuSession
            from .obs.memprof import MemoryTimeline
            MemoryTimeline.configure(
                enabled=self.conf.get(cfg.HBM_TIMELINE_ENABLED),
                max_samples=self.conf.get(cfg.HBM_TIMELINE_MAX_SAMPLES),
                budget_bytes=self.spill_catalog.device_budget)
            pinned = self.conf.get(cfg.PINNED_POOL_SIZE)
            if pinned and pinned > 0:
                from .native.arena import configure_shared_arena
                configure_shared_arena(pinned)
            # block-server endpoint: starts next to the health HTTP
            # server when transport=tcp OR shuffle.server.enabled —
            # peers fetch this process's catalog blocks from it
            srv_on = self.conf.get(cfg.SHUFFLE_MANAGER_ENABLED) and (
                self.conf.get(cfg.SHUFFLE_TRANSPORT) == "tcp"
                or self.conf.get(cfg.SHUFFLE_SERVER_ENABLED))
            if srv_on:
                from .shuffle.transport import ShuffleServer
                self.shuffle_server = ShuffleServer(
                    port=self.conf.get(cfg.SHUFFLE_SERVER_PORT)).start()
            # the location registry learns this process's identity so
            # reduce-side reads can split local (zero-copy catalog)
            # from remote (fetched) blocks
            from .shuffle.registry import BlockLocationRegistry
            reg = BlockLocationRegistry.get()
            reg.set_local(self.executor_id, "127.0.0.1",
                          getattr(self.shuffle_server, "port", 0) or 0)
            # fleet endpoint: when metrics.port is configured this
            # executor serves /metrics//healthz//spans and advertises
            # the bound port at registration so the driver's aggregator
            # can scrape it and consumers can pull serve spans
            obs_port = 0
            mport = self.conf.get(cfg.METRICS_PORT)
            if mport is not None:
                from .obs.health import ensure_server
                obs_port = ensure_server(mport).port
            if self.shuffle_server is not None:
                self.shuffle_server.executor_id = self.executor_id
                self.shuffle_server.obs_port = obs_port
            if self.driver is not None:
                self.driver.receive({
                    "kind": "register", "executor_id": self.executor_id,
                    "host": "localhost",
                    "port": getattr(self.shuffle_server, "port", 0),
                    "obs_port": obs_port})
                if self.driver.heartbeat_manager is not None:
                    reg.attach_heartbeat(self.driver.heartbeat_manager)
            log.info("TPU executor plugin initialized (executor %s)",
                     self.executor_id)
        except Exception as ex:
            log.error("executor plugin init failed: %s", ex)
            raw = self.conf.raw("spark.rapids.tpu.hardExitOnInitFailure")
            if raw is not None and cfg._to_bool(raw):
                import os
                os._exit(1)  # the reference's System.exit(1) contract
            raise

    def _init_compilation_cache(self):
        """Persistent XLA compilation cache: re-planned queries re-trace
        but skip compilation (each collect builds fresh exec instances, so
        without this every repeated query pays a full XLA compile — the
        analog of the reference's one-time CUDA kernel load)."""
        import os
        if not self.conf.get(cfg.COMPILATION_CACHE_ENABLED):
            return
        if os.environ.get("SPARK_RAPIDS_TPU_DISABLE_COMPILE_CACHE"):
            # escape hatch for environments running many engine
            # processes against one cache dir concurrently: XLA:CPU AOT
            # loads from a dir under concurrent write have been observed
            # to segfault inside the cache read (tests/conftest.py sets
            # this — the hermetic suite relies on the in-process jit
            # table, and must never crash on a cache race)
            return
        # the explicit per-deployment key wins; the legacy key is the
        # default location (ROADMAP item 1: the cheapest first bite of
        # cross-session compile reuse is jax's own disk cache)
        cache_dir = os.path.expanduser(
            self.conf.get(cfg.JIT_PERSISTENT_CACHE_DIR)
            or self.conf.get(cfg.COMPILATION_CACHE_DIR))
        try:
            import hashlib
            import jax
            # scope by platform + XLA flags + host CPU features: AOT
            # executables compiled under one CPU-feature set must not
            # load under another (XLA warns about possible SIGILL on
            # mismatch), so a cache dir shared across heterogeneous
            # hosts or a migrated home dir must miss, not crash
            fp = hashlib.sha1(
                f"{jax.__version__}|{jax.default_backend()}|"
                f"{os.environ.get('XLA_FLAGS', '')}|"
                f"{_host_cpu_fingerprint()}".encode()).hexdigest()[:12]
            cache_dir = os.path.join(cache_dir, fp)
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            # count disk hits/misses so the observatory can tell whether
            # the persistent cache actually absorbs backend compiles
            from .obs.compileprof import install_persistent_cache_metrics
            install_persistent_cache_metrics()
        except Exception as ex:  # cache is an optimization, never fatal
            log.warning("compilation cache unavailable: %s", ex)

    def shutdown(self):
        if self.shuffle_server is not None:
            self.shuffle_server.stop()
            self.shuffle_server = None
