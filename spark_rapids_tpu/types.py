"""Data types and the TypeSig capability algebra.

TPU-native re-design of the reference's type-compatibility system
(ref: sql-plugin/.../TypeChecks.scala:169 `TypeSig`, :711 `TypeChecks`).
A `TypeSig` describes the set of types an operator / expression parameter
supports in a given context; tagging produces human-readable reasons used
by the plan-rewrite engine to decide TPU vs CPU placement, and it also
drives the generated `docs/supported_ops.md`.

Physical mapping notes (TPU-first):
  - integral/floating types map 1:1 onto jnp dtypes,
  - DECIMAL(p<=18) is an int64-backed fixed-point tensor (DECIMAL_64),
  - DECIMAL(p<=38) is a (hi:int64, lo:uint64) pair of tensors (DECIMAL_128),
  - STRING/BINARY are (offsets:int32[n+1], data:uint8[cap]) tensor pairs,
  - DATE is int32 days since epoch, TIMESTAMP int64 micros since epoch (UTC),
  - ARRAY adds an offsets tensor over its child; STRUCT is a named tuple of
    child columns; MAP is ARRAY<STRUCT<key,value>>.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# DataType hierarchy (mirrors Spark SQL's type lattice; independent impl)
# ---------------------------------------------------------------------------

class DataType:
    """Base class for SQL data types."""

    #: simple name used in signatures / docs
    name: str = "data"

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self))

    def __repr__(self):
        return self.name

    @property
    def default_size(self) -> int:
        return 8

    def simple_string(self) -> str:
        return self.name


class NullType(DataType):
    name = "null"

    @property
    def default_size(self):
        return 1


class BooleanType(DataType):
    name = "boolean"

    @property
    def default_size(self):
        return 1


class NumericType(DataType):
    pass


class IntegralType(NumericType):
    pass


class ByteType(IntegralType):
    name = "tinyint"

    @property
    def default_size(self):
        return 1


class ShortType(IntegralType):
    name = "smallint"

    @property
    def default_size(self):
        return 2


class IntegerType(IntegralType):
    name = "int"

    @property
    def default_size(self):
        return 4


class LongType(IntegralType):
    name = "bigint"

    @property
    def default_size(self):
        return 8


class FractionalType(NumericType):
    pass


class FloatType(FractionalType):
    name = "float"

    @property
    def default_size(self):
        return 4


class DoubleType(FractionalType):
    name = "double"

    @property
    def default_size(self):
        return 8


class StringType(DataType):
    name = "string"

    @property
    def default_size(self):
        return 20


class BinaryType(DataType):
    name = "binary"

    @property
    def default_size(self):
        return 100


class DateType(DataType):
    name = "date"

    @property
    def default_size(self):
        return 4


class TimestampType(DataType):
    name = "timestamp"

    @property
    def default_size(self):
        return 8


class CalendarIntervalType(DataType):
    name = "interval"


MAX_DECIMAL64_PRECISION = 18
MAX_DECIMAL128_PRECISION = 38


class DecimalType(FractionalType):
    """Fixed-point decimal.  p <= 18 backed by int64 on device (DECIMAL_64),
    p <= 38 by an int64-pair encoding (DECIMAL_128)."""

    def __init__(self, precision: int = 10, scale: int = 0):
        if precision < 1 or precision > MAX_DECIMAL128_PRECISION:
            raise ValueError(f"decimal precision {precision} out of range")
        if scale > precision:
            raise ValueError(f"decimal scale {scale} > precision {precision}")
        self.precision = precision
        self.scale = scale
        self.name = f"decimal({precision},{scale})"

    def __eq__(self, other):
        return (isinstance(other, DecimalType)
                and other.precision == self.precision
                and other.scale == self.scale)

    def __hash__(self):
        return hash(("decimal", self.precision, self.scale))

    @property
    def is64(self) -> bool:
        return self.precision <= MAX_DECIMAL64_PRECISION

    @property
    def default_size(self):
        return 8 if self.is64 else 16


class ArrayType(DataType):
    def __init__(self, element_type: DataType, contains_null: bool = True):
        self.element_type = element_type
        self.contains_null = contains_null
        self.name = f"array<{element_type.name}>"

    def __eq__(self, other):
        return (isinstance(other, ArrayType)
                and other.element_type == self.element_type)

    def __hash__(self):
        return hash(("array", self.element_type))


@dataclass(frozen=True)
class StructField:
    name: str
    data_type: DataType
    nullable: bool = True


class StructType(DataType):
    def __init__(self, fields: Iterable[StructField]):
        self.fields: Tuple[StructField, ...] = tuple(fields)
        self.name = "struct<" + ",".join(
            f"{f.name}:{f.data_type.name}" for f in self.fields) + ">"

    def __eq__(self, other):
        return isinstance(other, StructType) and other.fields == self.fields

    def __hash__(self):
        return hash(("struct", self.fields))

    def field_index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]


class MapType(DataType):
    def __init__(self, key_type: DataType, value_type: DataType,
                 value_contains_null: bool = True):
        self.key_type = key_type
        self.value_type = value_type
        self.value_contains_null = value_contains_null
        self.name = f"map<{key_type.name},{value_type.name}>"

    def __eq__(self, other):
        return (isinstance(other, MapType) and other.key_type == self.key_type
                and other.value_type == self.value_type)

    def __hash__(self):
        return hash(("map", self.key_type, self.value_type))


# singletons
NULL = NullType()
BOOLEAN = BooleanType()
BYTE = ByteType()
SHORT = ShortType()
INT = IntegerType()
LONG = LongType()
FLOAT = FloatType()
DOUBLE = DoubleType()
STRING = StringType()
BINARY = BinaryType()
DATE = DateType()
TIMESTAMP = TimestampType()
CALENDAR = CalendarIntervalType()

_INTEGRAL = (ByteType, ShortType, IntegerType, LongType)


def is_integral(dt: DataType) -> bool:
    return isinstance(dt, _INTEGRAL)


def is_numeric(dt: DataType) -> bool:
    return isinstance(dt, NumericType)


def is_floating(dt: DataType) -> bool:
    return isinstance(dt, (FloatType, DoubleType))


# numpy dtype mapping for the host representation
_NP_DTYPES = {
    BooleanType: np.bool_,
    ByteType: np.int8,
    ShortType: np.int16,
    IntegerType: np.int32,
    LongType: np.int64,
    FloatType: np.float32,
    DoubleType: np.float64,
    DateType: np.int32,
    TimestampType: np.int64,
}


def to_np_dtype(dt: DataType):
    """Physical numpy dtype of the primary buffer for a flat type."""
    if isinstance(dt, DecimalType):
        return np.int64
    t = _NP_DTYPES.get(type(dt))
    if t is None:
        raise TypeError(f"no flat numpy dtype for {dt}")
    return t


def from_np_dtype(npdt) -> DataType:
    npdt = np.dtype(npdt)
    table = {
        np.dtype(np.bool_): BOOLEAN,
        np.dtype(np.int8): BYTE,
        np.dtype(np.int16): SHORT,
        np.dtype(np.int32): INT,
        np.dtype(np.int64): LONG,
        np.dtype(np.float32): FLOAT,
        np.dtype(np.float64): DOUBLE,
    }
    if npdt in table:
        return table[npdt]
    if npdt.kind in ("U", "S", "O"):
        return STRING
    if npdt.kind == "M":
        return TIMESTAMP
    raise TypeError(f"unsupported numpy dtype {npdt}")


# ---------------------------------------------------------------------------
# TypeEnum + TypeSig algebra  (ref TypeChecks.scala:169)
# ---------------------------------------------------------------------------

class TypeEnum(enum.Flag):
    NONE = 0
    BOOLEAN = enum.auto()
    BYTE = enum.auto()
    SHORT = enum.auto()
    INT = enum.auto()
    LONG = enum.auto()
    FLOAT = enum.auto()
    DOUBLE = enum.auto()
    DATE = enum.auto()
    TIMESTAMP = enum.auto()
    STRING = enum.auto()
    DECIMAL_64 = enum.auto()
    DECIMAL_128 = enum.auto()
    NULL = enum.auto()
    BINARY = enum.auto()
    CALENDAR = enum.auto()
    ARRAY = enum.auto()
    MAP = enum.auto()
    STRUCT = enum.auto()
    UDT = enum.auto()


def _type_enum_of(dt: DataType) -> TypeEnum:
    if isinstance(dt, BooleanType):
        return TypeEnum.BOOLEAN
    if isinstance(dt, ByteType):
        return TypeEnum.BYTE
    if isinstance(dt, ShortType):
        return TypeEnum.SHORT
    if isinstance(dt, IntegerType):
        return TypeEnum.INT
    if isinstance(dt, LongType):
        return TypeEnum.LONG
    if isinstance(dt, FloatType):
        return TypeEnum.FLOAT
    if isinstance(dt, DoubleType):
        return TypeEnum.DOUBLE
    if isinstance(dt, DateType):
        return TypeEnum.DATE
    if isinstance(dt, TimestampType):
        return TypeEnum.TIMESTAMP
    if isinstance(dt, StringType):
        return TypeEnum.STRING
    if isinstance(dt, DecimalType):
        return TypeEnum.DECIMAL_64 if dt.is64 else TypeEnum.DECIMAL_128
    if isinstance(dt, NullType):
        return TypeEnum.NULL
    if isinstance(dt, BinaryType):
        return TypeEnum.BINARY
    if isinstance(dt, CalendarIntervalType):
        return TypeEnum.CALENDAR
    if isinstance(dt, ArrayType):
        return TypeEnum.ARRAY
    if isinstance(dt, MapType):
        return TypeEnum.MAP
    if isinstance(dt, StructType):
        return TypeEnum.STRUCT
    return TypeEnum.UDT


class TypeSig:
    """A set of types an op supports, with separate nested-child capability
    and per-type doc notes.  Immutable; combine with ``+``/``-``.

    Ref: TypeChecks.scala:169.
    """

    __slots__ = ("initial", "nested_sig", "lit_only", "notes", "max_decimal_precision")

    def __init__(self, initial: TypeEnum = TypeEnum.NONE,
                 nested_sig: TypeEnum = TypeEnum.NONE,
                 lit_only: TypeEnum = TypeEnum.NONE,
                 notes: Optional[Dict[TypeEnum, str]] = None,
                 max_decimal_precision: int = MAX_DECIMAL64_PRECISION):
        self.initial = initial
        self.nested_sig = nested_sig
        self.lit_only = lit_only
        self.notes = dict(notes or {})
        self.max_decimal_precision = max_decimal_precision

    # -- building -----------------------------------------------------------
    def __add__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(self.initial | other.initial,
                       self.nested_sig | other.nested_sig,
                       self.lit_only | other.lit_only,
                       {**self.notes, **other.notes},
                       max(self.max_decimal_precision, other.max_decimal_precision))

    def __sub__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(self.initial & ~other.initial,
                       self.nested_sig & ~other.nested_sig,
                       self.lit_only,
                       self.notes,
                       self.max_decimal_precision)

    def nested(self, sub: Optional["TypeSig"] = None) -> "TypeSig":
        """Allow nested children of the given sig (default: same as top)."""
        sub_enum = (sub.initial if sub is not None else self.initial)
        return TypeSig(self.initial, self.nested_sig | sub_enum,
                       self.lit_only, self.notes, self.max_decimal_precision)

    def with_ps_note(self, te: TypeEnum, note: str) -> "TypeSig":
        notes = dict(self.notes)
        notes[te] = note
        return TypeSig(self.initial, self.nested_sig, self.lit_only, notes,
                       self.max_decimal_precision)

    def with_lit_only(self, te: TypeEnum) -> "TypeSig":
        return TypeSig(self.initial, self.nested_sig, self.lit_only | te,
                       self.notes, self.max_decimal_precision)

    # -- checking -----------------------------------------------------------
    def _is_supported(self, dt: DataType, allowed: TypeEnum, depth: int) -> bool:
        te = _type_enum_of(dt)
        if te == TypeEnum.DECIMAL_64 or te == TypeEnum.DECIMAL_128:
            dec_ok = (TypeEnum.DECIMAL_64 | TypeEnum.DECIMAL_128) & allowed
            if not (te & allowed):
                return False
            assert isinstance(dt, DecimalType)
            if dt.precision > self.max_decimal_precision:
                return False
            return bool(dec_ok)
        if not (te & allowed):
            return False
        child_allowed = self.nested_sig
        if isinstance(dt, ArrayType):
            return self._is_supported(dt.element_type, child_allowed, depth + 1)
        if isinstance(dt, MapType):
            return (self._is_supported(dt.key_type, child_allowed, depth + 1)
                    and self._is_supported(dt.value_type, child_allowed, depth + 1))
        if isinstance(dt, StructType):
            return all(self._is_supported(f.data_type, child_allowed, depth + 1)
                       for f in dt.fields)
        return True

    def is_supported(self, dt: DataType) -> bool:
        return self._is_supported(dt, self.initial, 0)

    def reasons_not_supported(self, dt: DataType) -> List[str]:
        """Human-readable reasons why ``dt`` is not supported (empty == ok)."""
        if self.is_supported(dt):
            return []
        te = _type_enum_of(dt)
        if not (te & self.initial):
            return [f"{dt.name} is not supported"]
        if isinstance(dt, DecimalType) and dt.precision > self.max_decimal_precision:
            return [f"{dt.name} precision exceeds max supported "
                    f"({self.max_decimal_precision})"]
        if isinstance(dt, ArrayType):
            return [f"array child: {r}"
                    for r in TypeSig(self.nested_sig, self.nested_sig,
                                     max_decimal_precision=self.max_decimal_precision)
                    .reasons_not_supported(dt.element_type)]
        if isinstance(dt, MapType):
            child = TypeSig(self.nested_sig, self.nested_sig,
                            max_decimal_precision=self.max_decimal_precision)
            out = [f"map key: {r}" for r in child.reasons_not_supported(dt.key_type)]
            out += [f"map value: {r}" for r in child.reasons_not_supported(dt.value_type)]
            return out
        if isinstance(dt, StructType):
            child = TypeSig(self.nested_sig, self.nested_sig,
                            max_decimal_precision=self.max_decimal_precision)
            out = []
            for f in dt.fields:
                out += [f"struct field {f.name}: {r}"
                        for r in child.reasons_not_supported(f.data_type)]
            return out
        return [f"{dt.name} is not supported"]

    def described(self) -> str:
        if self.initial == TypeEnum.NONE:
            return "none"
        return ", ".join(t.name for t in TypeEnum if t != TypeEnum.NONE
                         and (t & self.initial))


def _sig(*types: TypeEnum) -> TypeSig:
    v = TypeEnum.NONE
    for t in types:
        v |= t
    return TypeSig(v)


class TpuTypeSigs:
    """Standard signatures (ref TypeChecks.scala companion object constants)."""
    none = TypeSig()
    BOOLEAN = _sig(TypeEnum.BOOLEAN)
    BYTE = _sig(TypeEnum.BYTE)
    SHORT = _sig(TypeEnum.SHORT)
    INT = _sig(TypeEnum.INT)
    LONG = _sig(TypeEnum.LONG)
    FLOAT = _sig(TypeEnum.FLOAT)
    DOUBLE = _sig(TypeEnum.DOUBLE)
    DATE = _sig(TypeEnum.DATE)
    TIMESTAMP = _sig(TypeEnum.TIMESTAMP)
    STRING = _sig(TypeEnum.STRING)
    NULL = _sig(TypeEnum.NULL)
    BINARY = _sig(TypeEnum.BINARY)
    CALENDAR = _sig(TypeEnum.CALENDAR)
    DECIMAL_64 = TypeSig(TypeEnum.DECIMAL_64)
    DECIMAL_128 = TypeSig(TypeEnum.DECIMAL_64 | TypeEnum.DECIMAL_128,
                          max_decimal_precision=MAX_DECIMAL128_PRECISION)
    ARRAY = _sig(TypeEnum.ARRAY)
    MAP = _sig(TypeEnum.MAP)
    STRUCT = _sig(TypeEnum.STRUCT)

    integral = BYTE + SHORT + INT + LONG
    gpu_numeric = integral + FLOAT + DOUBLE + DECIMAL_128
    numeric = gpu_numeric
    # expression kernels operate on the decimal low word only, so general
    # expressions are gated to 64-bit decimals (the reference is
    # decimal64-only, RapidsConf.scala:565); aggregation buffers may be
    # 128-bit (exact segment_sum128)
    numeric64 = integral + FLOAT + DOUBLE + DECIMAL_64
    comparable = numeric + BOOLEAN + DATE + TIMESTAMP + STRING + NULL
    common_scalar = (numeric + BOOLEAN + DATE + TIMESTAMP + STRING + NULL)
    orderable = common_scalar
    all_types = (common_scalar + BINARY + CALENDAR + ARRAY + MAP + STRUCT)


# convenience alias used across the codebase
T = TpuTypeSigs
