"""Typed configuration system.

TPU-native analog of the reference's config machinery
(ref: sql-plugin/.../RapidsConf.scala:116-296 builder machinery,
:301-1275 key definitions).  Every entry is typed, documented, validated,
and defaulted; `generate_docs()` renders docs/configs.md from the registry,
exactly as the reference generates its docs from code.

Keys keep the `spark.rapids.` prefix so existing reference configuration
carries over; TPU-specific keys live under `spark.rapids.tpu.` / `.memory.tpu.`.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Generic, List, Optional, Sequence, TypeVar

V = TypeVar("V")

_REGISTERED: Dict[str, "ConfEntry"] = {}


def _to_bool(s: Any) -> bool:
    if isinstance(s, bool):
        return s
    s = str(s).strip().lower()
    if s in ("true", "1", "yes"):
        return True
    if s in ("false", "0", "no"):
        return False
    raise ValueError(f"cannot convert {s!r} to bool")


def _to_bytes(s: Any) -> int:
    """Parse a byte size like '512m', '1g', '16384'."""
    if isinstance(s, int):
        return s
    s = str(s).strip().lower()
    units = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40, "b": 1}
    if s and s[-1] in units:
        return int(float(s[:-1]) * units[s[-1]])
    return int(s)


class ConfEntry(Generic[V]):
    """One typed config key (ref RapidsConf.scala:116 `ConfEntry`)."""

    def __init__(self, key: str, converter: Callable[[Any], V], doc: str,
                 default: Optional[V], is_internal: bool = False,
                 validator: Optional[Callable[[V], Optional[str]]] = None):
        self.key = key
        self.converter = converter
        self.doc = doc
        self.default = default
        self.is_internal = is_internal
        self.validator = validator
        if key in _REGISTERED:
            raise ValueError(f"duplicate conf key {key}")
        _REGISTERED[key] = self

    def get(self, conf: Dict[str, Any]) -> V:
        raw = conf.get(self.key, None)
        if raw is None:
            return self.default  # type: ignore[return-value]
        v = self.converter(raw)
        if self.validator is not None:
            err = self.validator(v)
            if err:
                raise ValueError(f"{self.key}: {err}")
        return v

    def help(self) -> str:
        return f"{self.key} (default={self.default}): {self.doc}"


class ConfBuilder(Generic[V]):
    """Fluent builder (ref RapidsConf.scala:153 `TypedConfBuilder`)."""

    def __init__(self, key: str, converter: Callable[[Any], V]):
        self._key = key
        self._converter = converter
        self._doc = ""
        self._internal = False
        self._validator: Optional[Callable[[V], Optional[str]]] = None

    def doc(self, text: str) -> "ConfBuilder[V]":
        self._doc = " ".join(text.split())
        return self

    def internal(self) -> "ConfBuilder[V]":
        self._internal = True
        return self

    def check_values(self, allowed: Sequence[V]) -> "ConfBuilder[V]":
        allowed = list(allowed)

        def v(x):
            return None if x in allowed else f"must be one of {allowed}, got {x}"
        self._validator = v
        return self

    def check(self, fn: Callable[[V], bool], msg: str) -> "ConfBuilder[V]":
        def v(x):
            return None if fn(x) else msg
        self._validator = v
        return self

    def create_with_default(self, default: V) -> ConfEntry[V]:
        return ConfEntry(self._key, self._converter, self._doc, default,
                         self._internal, self._validator)

    def create_optional(self) -> ConfEntry[Optional[V]]:
        return ConfEntry(self._key, self._converter, self._doc, None,
                         self._internal, self._validator)


def conf(key: str) -> "_Typed":
    return _Typed(key)


class _Typed:
    def __init__(self, key: str):
        self.key = key

    def boolean(self) -> ConfBuilder[bool]:
        return ConfBuilder(self.key, _to_bool)

    def integer(self) -> ConfBuilder[int]:
        return ConfBuilder(self.key, int)

    def double(self) -> ConfBuilder[float]:
        return ConfBuilder(self.key, float)

    def string(self) -> ConfBuilder[str]:
        return ConfBuilder(self.key, str)

    def bytes(self) -> ConfBuilder[int]:
        return ConfBuilder(self.key, _to_bytes)


# ---------------------------------------------------------------------------
# Key definitions (subset mirrors RapidsConf.scala:301-1275; grows with features)
# ---------------------------------------------------------------------------

SQL_ENABLED = conf("spark.rapids.sql.enabled").boolean() \
    .doc("Enable or disable TPU acceleration of SQL plans entirely.") \
    .create_with_default(True)

BACKEND = conf("spark.rapids.backend").string() \
    .doc("Accelerator backend. This framework provides 'tpu'.") \
    .check_values(["tpu", "cpu"]) \
    .create_with_default("tpu")

EXPLAIN = conf("spark.rapids.sql.explain").string() \
    .doc("Explain why parts of a query were or were not placed on the TPU: "
         "NONE, ALL, or NOT_ON_GPU (only report operators that stayed on CPU).") \
    .check_values(["NONE", "ALL", "NOT_ON_GPU"]) \
    .create_with_default("NOT_ON_GPU")

INCOMPATIBLE_OPS = conf("spark.rapids.sql.incompatibleOps.enabled").boolean() \
    .doc("Enable operators that produce results that differ from Spark in "
         "corner cases (e.g. float ordering of NaN, string upper/lower beyond "
         "ASCII).") \
    .create_with_default(False)

ANSI_ENABLED = conf("spark.rapids.sql.ansi.enabled").boolean() \
    .doc("ANSI-mode overflow/invalid-cast error semantics.") \
    .create_with_default(False)

BATCH_SIZE_BYTES = conf("spark.rapids.sql.batchSizeBytes").bytes() \
    .doc("Target size in bytes of output batches for TPU operators "
         "(ref RapidsConf.scala:437 GPU_BATCH_SIZE_BYTES).") \
    .check(lambda v: v > 0, "must be positive") \
    .create_with_default(512 * 1024 * 1024)

MAX_READER_BATCH_SIZE_ROWS = conf("spark.rapids.sql.reader.batchSizeRows").integer() \
    .doc("Soft cap on rows per batch produced by file readers.") \
    .create_with_default(2147483647)

MAX_READER_BATCH_SIZE_BYTES = conf("spark.rapids.sql.reader.batchSizeBytes").bytes() \
    .doc("Soft cap on bytes per batch produced by file readers.") \
    .create_with_default(2147483647)

DECIMAL_TYPE_ENABLED = conf("spark.rapids.sql.decimalType.enabled").boolean() \
    .doc("Enable decimal type acceleration (int64-backed fixed point; "
         "ref RapidsConf.scala:565).") \
    .create_with_default(True)

REPLACE_SORT_MERGE_JOIN = conf("spark.rapids.sql.replaceSortMergeJoin.enabled").boolean() \
    .doc("Replace sort-merge joins with TPU hash joins "
         "(ref RapidsConf.scala:572).") \
    .create_with_default(True)

AUTO_BROADCAST_JOIN_THRESHOLD = conf(
    "spark.rapids.sql.autoBroadcastJoinThreshold").bytes() \
    .doc("Broadcast the build side of a join when its estimated size is at "
         "most this many bytes (mirrors spark.sql.autoBroadcastJoinThreshold; "
         "-1 disables broadcast joins).") \
    .create_with_default(10 * 1024 * 1024)

STABLE_SORT = conf("spark.rapids.sql.stableSort.enabled").boolean() \
    .doc("Force stable sort (ref RapidsConf.scala:478).") \
    .create_with_default(False)

HAS_NANS = conf("spark.rapids.sql.hasNans").boolean() \
    .doc("Assume floating point data may contain NaN (affects agg/join on floats).") \
    .create_with_default(True)

VARIABLE_FLOAT_AGG = conf("spark.rapids.sql.variableFloatAgg.enabled").boolean() \
    .doc("Allow float/double aggregations whose result can vary with "
         "evaluation order (TPU parallel reductions reorder).") \
    .create_with_default(True)

CONCURRENT_TPU_TASKS = conf("spark.rapids.sql.concurrentGpuTasks").integer() \
    .doc("Number of concurrent tasks admitted to the TPU per executor "
         "(ref RapidsConf.scala:424; name kept for compatibility).") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_with_default(2)

# --- memory ---------------------------------------------------------------

HBM_POOL_FRACTION = conf("spark.rapids.memory.tpu.allocFraction").double() \
    .doc("Fraction of HBM to reserve for the framework's arena at startup.") \
    .check(lambda v: 0.0 < v <= 1.0, "must be in (0,1]") \
    .create_with_default(0.9)

HBM_RESERVE = conf("spark.rapids.memory.tpu.reserve").bytes() \
    .doc("Bytes of HBM left un-pooled for XLA scratch space.") \
    .create_with_default(1 << 30)

HBM_LIMIT_OVERRIDE = conf("spark.rapids.memory.tpu.limitBytes").bytes() \
    .doc("Explicit HBM capacity override for hosts whose PJRT runtime "
         "does not report memory_stats().  When unset, capacity comes "
         "from memory_stats, then a device-kind table, then (CPU backend "
         "only) host RAM; an unrecognized accelerator with no stats "
         "fails startup rather than guessing.") \
    .create_optional()

HOST_SPILL_STORAGE_SIZE = conf("spark.rapids.memory.host.spillStorageSize").bytes() \
    .doc("Host-memory spill tier capacity before overflow to disk.") \
    .create_with_default(1 << 30)

PINNED_POOL_SIZE = conf("spark.rapids.memory.pinnedPool.size").bytes() \
    .doc("Size of the native host staging arena used for device transfers.") \
    .create_with_default(0)

SPILL_DIRS = conf("spark.rapids.memory.spill.dirs").string() \
    .doc("Comma-separated local dirs for the DISK spill tier.") \
    .create_with_default("/tmp/spark_rapids_tpu_spill")

SPILL_DEVICE_BUDGET = conf("spark.rapids.memory.tpu.spillBudgetBytes").bytes() \
    .doc("Override the registered-batch device budget that triggers "
         "proactive spill (default: the HBM arena size).").internal() \
    .create_optional()

MEMORY_DEBUG = conf("spark.rapids.memory.tpu.debug").boolean() \
    .doc("Track the creation stack of every registered spillable buffer "
         "and fail queries that leak unclosed buffers (ref "
         "spark.rapids.memory.gpu.debug RapidsConf.scala:307 + the "
         "Arm.scala RAII discipline).  Diagnostics only.") \
    .create_with_default(False)

UNSPILL_ENABLED = conf("spark.rapids.memory.tpu.unspill.enabled").boolean() \
    .doc("Move spilled buffers back to device memory when touched again.") \
    .create_with_default(False)

# --- shuffle --------------------------------------------------------------

SHUFFLE_MANAGER_ENABLED = conf("spark.rapids.shuffle.enabled").boolean() \
    .doc("Use the accelerated shuffle that caches batches in device/host "
         "memory and exchanges over ICI/DCN instead of row serialization.") \
    .create_with_default(True)

SHUFFLE_TRANSPORT = conf("spark.rapids.shuffle.transport").string() \
    .doc("Accelerated shuffle transport: 'ici' (mesh collectives inside a "
         "pod slice), 'tcp' (host sockets across pods), 'none' (serialized "
         "base shuffle).  Opt-in like the reference's RapidsShuffleManager "
         "(rapids-shuffle.md setup).") \
    .check_values(["ici", "tcp", "none"]) \
    .create_with_default("none")

SINGLE_CHIP_FUSE = conf("spark.rapids.tpu.singleChipFuse").string() \
    .doc("Collapse multi-partition exchange stages into one fused program "
         "when the process drives a single chip: partial->exchange->final "
         "aggregates, co-partitioned shuffled joins, range-partitioned "
         "global sorts and hash-partitioned windows all absorb their "
         "exchanges (an N-partition exchange otherwise runs N per-"
         "partition programs SERIALLY on one chip, paying N program "
         "floors for parallelism that does not exist).  'auto' = when "
         "exactly one device is visible; 'on' / 'off' force it.  The "
         "multi-chip analog is the ICI transport "
         "(spark.rapids.shuffle.transport=ici).") \
    .check_values(["auto", "on", "off"]) \
    .create_with_default("auto")

SORT_COMPILE_LEAN = conf("spark.rapids.tpu.sort.compileLean").string() \
    .doc("Sort-kernel structure tradeoff.  'off' (throughput): payload "
         "lanes ride the sort as extra lax.sort operands — fastest warm, "
         "but a cache-cold novel shape pays minutes of XLA compile at "
         "1M rows.  'on' (compile-lean): every sort lowers as iterated "
         "2-operand (uint64, iota) passes plus payload gathers — an "
         "order of magnitude cheaper to compile, ~20ms/lane slower "
         "warm.  'auto' picks lean exactly when the persistent compile "
         "cache is cold (fresh deployments' first queries) and "
         "throughput kernels once it is warm.") \
    .check_values(["auto", "on", "off"]) \
    .create_with_default("auto")

JOIN_SPECULATIVE_SIZING = conf(
    "spark.rapids.tpu.join.speculativeSizing").boolean() \
    .doc("Fuse a hash join's count and expand phases into ONE program by "
         "guessing the output capacity (the probe side's capacity — exact "
         "whenever no probe row matches more than one build row).  The "
         "guess is validated by a deferred device guard that rides the "
         "result fetch, so the common case pays ZERO sizing round trips; "
         "a miss re-executes the query with exact sizing.  Flat (non-"
         "string) schemas and inner/left joins only.") \
    .create_with_default(True)

HOST_ASSISTED_COLLECT = conf(
    "spark.rapids.sql.collect.hostAssisted").boolean() \
    .doc("When a collect's plan is a global sort (over optional filters/"
         "column pruning) of a host-resident in-memory table, fetch only "
         "the device-computed row-index lane and apply `take` on the "
         "host copy — a permutation's bytes already sit on the host, so "
         "only ~4 bytes/row cross the interconnect instead of the whole "
         "row.  Results below 64Ki rows keep the direct fetch path.") \
    .create_with_default(True)

HOST_ASSISTED_WRITE = conf("spark.rapids.sql.write.hostAssisted").boolean() \
    .doc("When a write's plan is only row filtering/column pruning over a "
         "source whose bytes already live on the host (in-memory tables, "
         "file scans), fetch just the boolean keep-mask from the device "
         "(bit-packed) and apply it to the host copy, instead of pulling "
         "the full filtered payload back across the interconnect.") \
    .create_with_default(True)

PYTHON_WORKER_ENABLED = conf("spark.rapids.sql.python.worker.enabled").boolean() \
    .doc("Run Python/pandas UDFs in out-of-process Arrow-IPC workers "
         "(crash containment + no GIL/heap contention with the engine, "
         "ref GpuArrowEvalPythonExec + python/rapids/worker.py).  UDFs "
         "that cannot be pickled fall back to in-process evaluation.") \
    .create_with_default(True)

CONCURRENT_PYTHON_WORKERS = conf(
    "spark.rapids.python.concurrentPythonWorkers").integer() \
    .doc("Maximum live Python UDF worker processes "
         "(ref PythonWorkerSemaphore).") \
    .create_with_default(2)

SCAN_PIN_DEVICE = conf("spark.rapids.sql.localScan.pinDeviceBatches").boolean() \
    .doc("Keep uploaded device batches of in-memory scans pinned in HBM "
         "across collects, so repeated queries over the same DataFrame "
         "never re-upload (the analog of the reference's caching shuffle "
         "writer keeping batches device-resident).") \
    .create_with_default(True)

FILESCAN_PIN_DEVICE = conf("spark.rapids.sql.fileScan.pinDeviceBatches") \
    .boolean() \
    .doc("Keep decoded+uploaded file-scan batches pinned in HBM keyed by "
         "(path, size, mtime, schema, filters, decode options); a "
         "changed file changes "
         "the key.  Evicted first under memory pressure.") \
    .create_with_default(True)

SHUFFLE_COMPRESSION_CODEC = conf("spark.rapids.shuffle.compression.codec").string() \
    .doc("Codec for shuffle payloads: none, lz4, zstd (native codec library).") \
    .check_values(["none", "lz4", "zstd"]) \
    .create_with_default("none")

SHUFFLE_PARTITIONING_MAX_PARTS = conf(
    "spark.rapids.shuffle.partitioning.maxCpuBatchedParts").integer() \
    .doc("Above this partition count, slicing happens on host not device.") \
    .create_with_default(32768)

SHUFFLE_HEARTBEAT_INTERVAL_MS = conf("spark.rapids.shuffle.heartbeat.intervalMs").integer() \
    .doc("Executor->driver shuffle heartbeat interval "
         "(ref RapidsShuffleHeartbeatManager).") \
    .create_with_default(5000)

SHUFFLE_HEARTBEAT_TIMEOUT_MS = conf("spark.rapids.shuffle.heartbeat.timeoutMs").integer() \
    .doc("Peer considered dead after missing heartbeats for this long.") \
    .create_with_default(30000)

SHUFFLE_FETCH_MAX_IN_FLIGHT = conf(
    "spark.rapids.tpu.shuffle.fetch.maxInFlight").integer() \
    .doc("Bounded in-flight window of the async block fetcher: how many "
         "fetched-but-unconsumed blocks may be buffered while the "
         "consumer joins the previous block (fetch/compute overlap, "
         "ref BufferReceiveState windows).  Bounds reduce-side host "
         "memory at window x block size.") \
    .create_with_default(4)

SHUFFLE_FETCH_TIMEOUT_MS = conf(
    "spark.rapids.tpu.shuffle.fetch.timeoutMs").integer() \
    .doc("Per-block timeout of the async fetcher.  Liveness normally "
         "fails faster via heartbeat expiry "
         "(spark.rapids.shuffle.heartbeat.timeoutMs); this is the "
         "backstop for a live-but-stuck peer.") \
    .create_with_default(30000)

SHUFFLE_SLICE_VIEWS = conf(
    "spark.rapids.tpu.shuffle.sliceViews").boolean() \
    .doc("Map-output slicing strategy.  On: each map batch is sorted by "
         "target partition once and registered as ONE spillable block; "
         "per-reduce-partition blocks are row-range views sliced lazily "
         "at first read — the write path stages each batch's bytes once "
         "instead of once per reduce partition.  Off: eager per-"
         "partition gather copies at write time (the pre-slice-view "
         "behavior).") \
    .create_with_default(True)

SHUFFLE_SERVER_ENABLED = conf(
    "spark.rapids.tpu.shuffle.server.enabled").boolean() \
    .doc("Start the shuffle block-server endpoint at executor plugin "
         "init, next to the health HTTP server, so peers can fetch this "
         "process's catalog blocks over TCP.  Implied by "
         "spark.rapids.shuffle.transport=tcp; set explicitly to serve "
         "blocks while keeping another transport for writes.") \
    .create_with_default(False)

SHUFFLE_SERVER_PORT = conf(
    "spark.rapids.tpu.shuffle.server.port").integer() \
    .doc("TCP port of the shuffle block server (0 = ephemeral; the "
         "bound port is what heartbeat registration advertises to "
         "peers).") \
    .create_with_default(0)

SHUFFLE_LOCALITY_ENABLED = conf(
    "spark.rapids.tpu.shuffle.locality.enabled").boolean() \
    .doc("Consult the BlockLocationRegistry on reduce-side reads: "
         "blocks owned by this process stay zero-copy catalog reads "
         "(never crossing the wire), blocks registered to remote "
         "endpoints stream through the async fetcher.  Off: reads "
         "serve only the local catalog (the pre-registry behavior).") \
    .create_with_default(True)

SHUFFLE_FETCH_MAX_RETRIES = conf(
    "spark.rapids.tpu.shuffle.fetch.maxRetries").integer() \
    .doc("Additional fetch attempts after the first failure of a "
         "remote reduce-side read, each against the next live replica "
         "of the owning endpoint group (heartbeat liveness picks the "
         "candidates).  Exhausting the budget fails the stage with a "
         "typed error carrying provenance — never a silent hang.") \
    .create_with_default(2)

# --- io -------------------------------------------------------------------

PARQUET_ENABLED = conf("spark.rapids.sql.format.parquet.enabled").boolean() \
    .doc("Enable TPU parquet scan/write.").create_with_default(True)

PARQUET_READER_TYPE = conf("spark.rapids.sql.format.parquet.reader.type").string() \
    .doc("PERFILE, COALESCING, or MULTITHREADED (ref RapidsConf.scala:706).") \
    .check_values(["PERFILE", "COALESCING", "MULTITHREADED", "AUTO"]) \
    .create_with_default("AUTO")

PARQUET_MULTITHREAD_READ_NUM_THREADS = conf(
    "spark.rapids.sql.format.parquet.multiThreadedRead.numThreads").integer() \
    .doc("Thread pool size for the MULTITHREADED cloud reader.") \
    .create_with_default(20)

ORC_ENABLED = conf("spark.rapids.sql.format.orc.enabled").boolean() \
    .doc("Enable TPU ORC scan/write.").create_with_default(True)

CSV_ENABLED = conf("spark.rapids.sql.format.csv.enabled").boolean() \
    .doc("Enable TPU CSV scan.").create_with_default(True)

# --- udf ------------------------------------------------------------------

UDF_COMPILER_ENABLED = conf("spark.rapids.sql.udfCompiler.enabled").boolean() \
    .doc("Compile Python lambda UDFs to the expression IR via bytecode "
         "analysis (ref RapidsConf.scala:520).") \
    .create_with_default(False)

ARROW_MAX_RECORDS_PER_BATCH = \
    conf("spark.rapids.sql.python.arrowMaxRecordsPerBatch").integer() \
    .doc("Max rows handed to a Python/pandas UDF at once (ref "
         "GpuArrowEvalPythonExec rebatching / Spark "
         "spark.sql.execution.arrow.maxRecordsPerBatch).") \
    .check(lambda v: v > 0, "must be positive") \
    .create_with_default(10000)

# --- adaptive execution ---------------------------------------------------

ADAPTIVE_ENABLED = conf("spark.sql.adaptive.enabled").boolean() \
    .doc("Adaptive query execution: re-shape shuffle reads from "
         "materialized map-output statistics (coalesce small partitions, "
         "split skewed ones; ref GpuCustomShuffleReaderExec).") \
    .create_with_default(True)

ADVISORY_PARTITION_SIZE = conf(
    "spark.sql.adaptive.advisoryPartitionSizeInBytes").bytes() \
    .doc("Target size for coalesced shuffle partitions.") \
    .create_with_default(64 << 20)

SKEW_JOIN_ENABLED = conf("spark.sql.adaptive.skewJoin.enabled").boolean() \
    .doc("Split skewed probe-side join partitions and replicate the build "
         "side (ref OptimizeSkewedJoin).") \
    .create_with_default(True)

SKEW_JOIN_FACTOR = conf(
    "spark.sql.adaptive.skewJoin.skewedPartitionFactor").double() \
    .doc("A partition is skewed when larger than this factor times the "
         "median partition size (and the threshold below).") \
    .create_with_default(5.0)

SKEW_JOIN_THRESHOLD = conf(
    "spark.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes").bytes() \
    .doc("Minimum size for a partition to be considered skewed.") \
    .create_with_default(256 << 20)

# --- optimizer ------------------------------------------------------------

OPTIMIZER_ENABLED = conf("spark.rapids.sql.optimizer.enabled").boolean() \
    .doc("Enable the cost-based second pass that can move subtrees back to "
         "CPU (ref CostBasedOptimizer.scala).") \
    .create_with_default(False)

OPTIMIZER_EXPLAIN = conf("spark.rapids.sql.optimizer.explain").string() \
    .doc("NONE or ALL: log CBO decisions.") \
    .check_values(["NONE", "ALL"]).create_with_default("NONE")

# --- metrics / test hooks -------------------------------------------------

COMPILATION_CACHE_ENABLED = conf(
    "spark.rapids.tpu.compilationCache.enabled").boolean() \
    .doc("Persist XLA executables across queries and sessions so "
         "re-planned queries skip compilation (keyed by platform + XLA "
         "flags fingerprint).") \
    .create_with_default(True)

COMPILATION_CACHE_DIR = conf("spark.rapids.tpu.compilationCache.dir") \
    .string() \
    .doc("Directory for the persistent XLA compilation cache.") \
    .create_with_default("~/.cache/spark_rapids_tpu_xla")

JIT_PERSISTENT_CACHE_DIR = conf("spark.rapids.tpu.jit.persistentCacheDir") \
    .string() \
    .doc("Explicit directory for JAX's built-in persistent compilation "
         "cache (jax_compilation_cache_dir), wired at session init.  "
         "Overrides spark.rapids.tpu.compilationCache.dir when set; the "
         "platform/XLA-flags/host fingerprint subdirectory scoping "
         "still applies.  Disk hits and misses are counted as "
         "tpu_jit_persistent_cache_{hits,misses}_total.") \
    .create_optional()

JIT_THRASH_WARN_RATIO = conf("spark.rapids.tpu.jit.cacheThrashWarnRatio") \
    .double() \
    .doc("Warn when the process JIT cache thrashes: refault rate "
         "(eviction_refault rebuilds / LRU evictions) above this ratio "
         "logs a warning suggesting a larger "
         "SPARK_RAPIDS_TPU_JIT_CACHE_MAX.") \
    .check(lambda v: 0.0 < v <= 1.0, "must be in (0,1]") \
    .create_with_default(0.5)

COMPILE_OBSERVATORY_ENABLED = conf(
    "spark.rapids.tpu.compile.observatory.enabled").boolean() \
    .doc("Attribute, classify and persist every XLA program build at "
         "the process_jit seam (obs/compileprof.py): split trace-vs-"
         "compile timing, miss-cause classification (new_program / "
         "shape_churn / dtype_churn / eviction_refault), the "
         "tpu_jit_* metrics family, enriched jit.build spans and the "
         "cross-session compile ledger `tools compile-report` reads.") \
    .create_with_default(True)

COMPILE_LEDGER_DIR = conf("spark.rapids.tpu.compile.ledgerDir") \
    .string() \
    .doc("Directory for the cross-session compile ledger "
         "(compile_ledger.jsonl, appended by the compile observatory "
         "and aggregated by `tools compile-report`).  Defaults to "
         "spark.rapids.tpu.regress.historyDir when that is set; unset "
         "both and builds are still traced/metered but not persisted.") \
    .create_optional()

JIT_PREWARM_ENABLED = conf("spark.rapids.tpu.jit.prewarm.enabled") \
    .boolean() \
    .doc("Replay the costliest program recipes from the compile ledger "
         "at session init (the warm-start tier of the program cache): "
         "each recipe recompiles through the persistent disk cache and "
         "stages a dispatch-ready program, so repeated sessions run "
         "their first queries with zero query-time builds.  Requires a "
         "compile ledger dir; recipes live under its programs/ "
         "subdirectory.  tpu_jit_prewarm_{hits,seconds}_total measure "
         "the payoff.") \
    .create_with_default(True)

JIT_PREWARM_TOP_K = conf("spark.rapids.tpu.jit.prewarm.topK").integer() \
    .doc("How many ledger programs (ranked by cumulative compile "
         "seconds) to replay at session init.") \
    .check(lambda v: v >= 0, "must be >= 0") \
    .create_with_default(32)

JIT_PREWARM_BACKGROUND = conf(
    "spark.rapids.tpu.jit.prewarm.background").boolean() \
    .doc("Run the session-init prewarm on a daemon thread instead of "
         "blocking startup.  Queries racing the thread simply "
         "cold-build; the default is synchronous so a freshly opened "
         "session is deterministically warm.") \
    .create_with_default(False)

PROFILE_TRACE_ANNOTATIONS = conf(
    "spark.rapids.sql.profile.traceAnnotations").boolean() \
    .doc("Wrap timed operator work in jax.profiler TraceAnnotation ranges "
         "so device kernels correlate with operators in the TensorBoard "
         "trace viewer (the NVTX-range analog, ref NvtxWithMetrics).") \
    .create_with_default(False)

METRICS_LEVEL = conf("spark.rapids.sql.metrics.level").string() \
    .doc("ESSENTIAL, MODERATE, or DEBUG (ref GpuExec.scala:32-45).") \
    .check_values(["ESSENTIAL", "MODERATE", "DEBUG"]) \
    .create_with_default("MODERATE")

TEST_ENABLED = conf("spark.rapids.sql.test.enabled").boolean() \
    .doc("Test mode: fail if an op unexpectedly stays on CPU "
         "(ref RapidsConf.scala:937).").internal() \
    .create_with_default(False)

TEST_ALLOWED_NON_TPU = conf("spark.rapids.sql.test.allowedNonGpu").string() \
    .doc("Comma-separated exec names allowed on CPU in test mode.").internal() \
    .create_with_default("")

# --- tpu platform ---------------------------------------------------------

TPU_BATCH_CAPACITY_BUCKETS = conf("spark.rapids.tpu.batchCapacityBuckets").string() \
    .doc("Comma-separated row-capacity buckets batches are padded to so XLA "
         "compiles once per (schema, bucket) instead of once per row count.") \
    .create_with_default("1024,8192,65536,262144,1048576,4194304")

TPU_STRING_DATA_BUCKETS = conf("spark.rapids.tpu.stringDataBuckets").string() \
    .doc("Byte-capacity buckets for the string data buffer.") \
    .create_with_default("16384,131072,1048576,8388608,67108864,268435456")

# --- static analysis (tpulint) --------------------------------------------

LINT_ENABLED = conf("spark.rapids.tpu.lint.enabled").boolean() \
    .doc("Opt-in pre-flight plan lint: before execution the converted "
         "plan is checked against the TPU-Lxxx rule catalog "
         "(docs/static-analysis.md) and hazardous subtrees are "
         "downgraded to the host engine instead of crashing mid-query.") \
    .create_with_default(False)

LINT_INFER = conf("spark.rapids.tpu.lint.infer").boolean() \
    .doc("Run the plan lint in flow-sensitive mode: the abstract "
         "interpreter (analysis/interp.py) propagates schema/residency/"
         "partitioning/size states through the plan, upgrading "
         "TPU-L002/L006/L007 from syntactic to flow-sensitive and "
         "adding the boundary rules TPU-L009..L012.  A failed "
         "interpretation degrades to the syntactic rules.") \
    .create_with_default(True)

LINT_DISABLE = conf("spark.rapids.tpu.lint.disable").string() \
    .doc("Comma-separated diagnostic codes (e.g. TPU-L005) to suppress "
         "in the plan lint.") \
    .create_with_default("")

LINT_MAX_DRIVER_COLLECT = conf(
    "spark.rapids.tpu.lint.maxDriverCollectBytes").bytes() \
    .doc("Plan lint threshold (TPU-L004): a broadcast/build side whose "
         "estimated size exceeds this is flagged as a driver-side "
         "whole-build collect hazard.") \
    .check(lambda v: v > 0, "must be positive") \
    .create_with_default(512 * 1024 * 1024)

LINT_MAX_PROGRAMS = conf(
    "spark.rapids.tpu.lint.maxCompiledPrograms").integer() \
    .doc("Plan lint threshold (TPU-L005): warn when a plan spans more "
         "distinct compiled-program shapes than this (JIT residency "
         "cache churn).  Default is half the process JIT cache budget.") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_with_default(96)

# --- concurrency sanitizer (tpucsan) --------------------------------------

CSAN_ENABLED = conf("spark.rapids.tpu.csan.enabled").boolean() \
    .doc("Opt-in runtime lock witness (obs/lockwitness.py): the "
         "engine's registered locks are wrapped so every per-thread "
         "acquisition chain is recorded and checked against the static "
         "lock-order relation from the tpucsan pass "
         "(analysis/concurrency.py, TPU-R008..R010).  The witness "
         "report fails on an acquisition edge the static graph cannot "
         "explain (unmodeled edge) or on an observed lock-order cycle, "
         "and exports tpu_lock_contention_total / tpu_lock_wait_seconds "
         "for the witnessed locks.  Diagnostics only — adds per-acquire "
         "bookkeeping.") \
    .create_with_default(False)

# --- memory sanitizer (tmsan) ---------------------------------------------

MEMSAN_ENABLED = conf("spark.rapids.tpu.memsan.enabled").boolean() \
    .doc("Opt-in runtime shadow ledger over the spill catalog and "
         "staging arena: every alloc/register/pin/spill/unspill/close "
         "is recorded with owning-exec attribution and asserted "
         "against the buffer-lifecycle state machine "
         "(analysis/lifetime.py); after each query the session fails "
         "on a dirty ledger (leaked or mis-tiered buffers).  The "
         "runtime oracle for the static TPU-L013..L015 rules.  "
         "Diagnostics only — adds per-event bookkeeping.") \
    .create_with_default(False)

MEMSAN_HBM_BUDGET = conf("spark.rapids.tpu.memsan.hbmBudgetBytes").bytes() \
    .doc("Device-memory budget the static peak bound (TPU-L014) and "
         "the shadow ledger's peak check are evaluated against.  "
         "Default: the spill catalog's device budget "
         "(spark.rapids.memory.tpu.spillBudgetBytes or the HBM arena "
         "size).") \
    .create_optional()

# --- determinism sanitizer (tpudsan) --------------------------------------

DSAN_ENABLED = conf("spark.rapids.tpu.dsan.enabled").boolean() \
    .doc("Run the determinism / replay-safety pass "
         "(analysis/determinism.py) as part of the plan lint: every "
         "operator's declared replay class (bit_exact > order_stable > "
         "order_dependent > nondeterministic) is composed bottom-up and "
         "a subtree feeding an exchange or cacheable fragment whose "
         "class is weaker than order_stable raises TPU-L016 "
         "(repairable by forcing the aggregate's canonical keyed "
         "merge).  The permuted-replay oracle "
         "(devtools/run_lint.py --dsan) keeps the declarations "
         "honest.") \
    .create_with_default(True)

DSAN_DIGEST_ENABLED = conf("spark.rapids.tpu.dsan.digest.enabled") \
    .boolean() \
    .doc("Record a content digest (blake2b-64 over the Arrow-canonical "
         "live rows) for every shuffle block at map-write time, carry "
         "it in the block metadata wire frame, and verify it on every "
         "remote read — a mismatch fails typed "
         "(TpuShuffleDigestError) and counts "
         "tpu_shuffle_digest_mismatch_total.  This is the "
         "recovered-block correctness check lineage-based recompute "
         "relies on (a replayed map task must reproduce the block it "
         "replaces bit-for-bit).") \
    .create_with_default(True)

# --- program-efficiency sanitizer (tpuxsan) -------------------------------

XSAN_ENABLED = conf("spark.rapids.tpu.xsan.enabled").boolean() \
    .doc("Run the compiled-program efficiency pass (analysis/hloaudit.py) "
         "as part of the plan lint: per-subtree padding-waste accounting "
         "against the capacity buckets (TPU-L018, repairable by "
         "speculative re-bucketing through the pre-flight downgrade "
         "machinery) and the fusion-break roofline check (TPU-L020).  "
         "The StableHLO ledger audit (TPU-L019 host transfers, analytic "
         "cost-model cross-validation) rides the compile observatory's "
         "persisted programs (devtools/run_lint.py --hlo).") \
    .create_with_default(True)

XSAN_PAD_WASTE_MAX = conf("spark.rapids.tpu.xsan.padWasteMax").double() \
    .doc("TPU-L018 threshold: flag a subtree whose padding-waste ratio "
         "(1 - live rows / capacity bucket) exceeds this AND whose "
         "wasted bytes exceed xsan.padWasteMinBytes.  Capacity buckets "
         "are ~8x apart, so ratios up to ~0.87 are the normal cost of "
         "shape-stable compilation; above this the launch is mostly "
         "padding.") \
    .check(lambda v: 0.0 < v <= 1.0, "must be in (0, 1]") \
    .create_with_default(0.95)

XSAN_PAD_WASTE_MIN_BYTES = conf(
    "spark.rapids.tpu.xsan.padWasteMinBytes").bytes() \
    .doc("TPU-L018 floor: subtrees wasting fewer padded bytes than this "
         "per launch are never flagged, whatever their ratio — tiny "
         "batches on the smallest bucket are not worth re-bucketing.") \
    .create_with_default(1024 * 1024)

XSAN_HLO_DIR = conf("spark.rapids.tpu.xsan.hloDir").string() \
    .doc("Directory the compile observatory persists lowered StableHLO "
         "text into (blake2-keyed, per-program dedupe, 2 MB cap).  "
         "Default: an hlo/ subdir of the compile ledger dir "
         "(spark.rapids.tpu.compile.ledgerDir / regress.historyDir); "
         "no ledger dir means no persistence.") \
    .create_optional()

XSAN_COST_TOLERANCE = conf("spark.rapids.tpu.xsan.costTolerance") \
    .double() \
    .doc("Cross-validation tolerance between the analytic cost model "
         "(analysis/hlocost.py roofline) and XLA's own cost_analysis() "
         "bytes-accessed: the ratio analytic/XLA must land in "
         "[1/tol, tol].  The model is an order-of-magnitude roofline "
         "(it catches unit errors, missing operands and capacity/live "
         "confusion, not instruction scheduling); drift past the "
         "tolerance on the golden corpus fails the --hlo gate itself "
         "(anti-vacuity: a lying model is a gate failure).") \
    .check(lambda v: v >= 1.0, "must be >= 1.0") \
    .create_with_default(8.0)

XSAN_BROADCAST_BYTES_MAX = conf(
    "spark.rapids.tpu.xsan.broadcastBytesMax").bytes() \
    .doc("StableHLO audit bound: a materialized broadcast_in_dim "
         "intermediate larger than this inside one compiled program is "
         "reported as a fusion hazard (the broadcast should stay fused "
         "into its consumer, not hit HBM).") \
    .create_with_default(16 * 1024 * 1024)

# --- observability (flight recorder) --------------------------------------

TRACE_ENABLED = conf("spark.rapids.tpu.trace.enabled").boolean() \
    .doc("Record a per-query span tree (session phases, per-operator "
         "per-partition execute spans, spill/shuffle/ICI/bridge events) "
         "in the in-process flight recorder.  Low overhead by design: "
         "the hot path never syncs — deferred device scalars resolve in "
         "one crossing at query end.  Read back via "
         "session.last_query_trace() (Chrome-trace/text exporters) and "
         "the `tools trace` CLI.  Implied by eventLog.dir.") \
    .create_with_default(False)

TRACE_MAX_SPANS = conf("spark.rapids.tpu.trace.maxSpans").integer() \
    .doc("Bound on recorded spans per query; past it new spans are "
         "dropped and counted (a runaway query degrades the trace, "
         "never the engine).") \
    .check(lambda v: v >= 64, "must be >= 64") \
    .create_with_default(65536)

EVENT_LOG_DIR = conf("spark.rapids.tpu.eventLog.dir").string() \
    .doc("When set, the session appends each query to a JSON-lines "
         "event log (events_<appId>) in the SparkListener schema "
         "tools/eventlog.py parses — `tools profile` / `tools qualify` "
         "then work on this engine's own runs.  The emitted plan embeds "
         "per-operator metric values and predicted-vs-actual rows/bytes "
         "(`tools profile --accuracy`).  Failed queries flush too, as "
         "JobFailed.  Enables tracing for the logged queries.") \
    .create_optional()

# --- continuous metrics / health / regression watchdog --------------------

METRICS_ENABLED = conf("spark.rapids.tpu.metrics.enabled").boolean() \
    .doc("Feed the process-wide metrics registry (obs/metrics.py): "
         "counters/gauges/histograms from the spill catalog, staging "
         "arena, shuffle, ICI, bridge, fetch path and session query "
         "lifecycle.  Cheap by design (one locked integer add per "
         "event, <2% on the benchmark suite — bench.py "
         "--metrics-overhead guards it); read back via "
         "session.metrics_snapshot(), the Prometheus endpoint "
         "(metrics.port) or obs.health.render_prometheus().") \
    .create_with_default(True)

METRICS_PORT = conf("spark.rapids.tpu.metrics.port").integer() \
    .doc("When set, serve GET /metrics (Prometheus text format) and "
         "GET /healthz (JSON health snapshot derived from arena "
         "exhaustion, memsan ledger, heartbeat misses and device-probe "
         "liveness) on this localhost port via a stdlib HTTP daemon "
         "thread.  0 binds an ephemeral port (tests).  Unset: no "
         "endpoint (the default — exposition is opt-in, collection is "
         "not).") \
    .create_optional()

# --- fleet observatory (cross-process tracing + peer aggregation) ----------

FLEET_PROPAGATION_ENABLED = conf(
    "spark.rapids.tpu.fleet.propagation.enabled").boolean() \
    .doc("Thread the active query's (trace_id, span_id, tenant) context "
         "through the shuffle wire protocol (the v2 frame-header "
         "extension) so block servers record their serve/serialize/"
         "compress spans under the requesting fetch span, and pull "
         "those spans back over the producer's /spans endpoint after "
         "each remote fetch.  Pre-v2 peers degrade silently to "
         "uncorrelated v1 traffic; a failed pull closes the fetch span "
         "with a spans_lost annotation (counted in "
         "tpu_trace_remote_spans_lost_total), never a hang.") \
    .create_with_default(True)

FLEET_SPANS_MAX_TRACES = conf(
    "spark.rapids.tpu.fleet.spans.maxTraces").integer() \
    .doc("Bound on distinct trace buckets the producer-side "
         "RemoteSpanStore holds awaiting /spans pulls; past it the "
         "oldest trace is evicted (an abandoned consumer must not pin "
         "producer memory).") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_with_default(64)

FLEET_SPANS_MAX_PER_TRACE = conf(
    "spark.rapids.tpu.fleet.spans.maxPerTrace").integer() \
    .doc("Bound on buffered serve spans per trace in the producer-side "
         "RemoteSpanStore; past it new spans are dropped and counted "
         "in tpu_trace_remote_spans_dropped_total.") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_with_default(512)

FLEET_AGGREGATOR_ENABLED = conf(
    "spark.rapids.tpu.fleet.aggregator.enabled").boolean() \
    .doc("On the driver, walk the heartbeat peer registry and scrape "
         "each live peer's /metrics + /healthz into cluster-rollup "
         "series (tpu_fleet_rollup{peer,name}, tpu_fleet_peer_up) and "
         "a fleet health verdict (any dead, unreachable or unhealthy "
         "peer degrades /healthz).  Requires executors to advertise an "
         "obs port at registration.") \
    .create_with_default(True)

FLEET_SCRAPE_MAX_PEERS = conf(
    "spark.rapids.tpu.fleet.scrape.maxPeers").integer() \
    .doc("Cardinality cap on the aggregator's peer label: at most this "
         "many peers are scraped per round; excess live peers are "
         "counted in tpu_fleet_peers_skipped_total instead of labeled "
         "(the registry's own series cap backstops it).") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_with_default(16)

FLEET_SCRAPE_TIMEOUT_MS = conf(
    "spark.rapids.tpu.fleet.scrape.timeoutMs").integer() \
    .doc("Per-peer HTTP timeout for aggregator scrapes and post-fetch "
         "/spans pulls.  A pull that exceeds it counts the fetch's "
         "producer spans as lost rather than stalling the read path.") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_with_default(2000)

REGRESS_HISTORY_DIR = conf("spark.rapids.tpu.regress.historyDir") \
    .string() \
    .doc("Append-only directory of per-run query fingerprints for the "
         "cross-run regression watchdog (obs/history.py): `tools "
         "regress --record` distills self-emitted event logs into it "
         "and `tools regress --check` / `bench.py --check` diff the "
         "two most recent runs, failing on deterministic drift (new "
         "fallbacks, fetch-crossing growth, operator row drift).") \
    .create_optional()

# --- multi-tenant serving (admission control + session pool) --------------

SERVE_ADMISSION_BUDGET = conf(
    "spark.rapids.tpu.serve.hbmAdmissionBudgetBytes").bytes() \
    .doc("Byte-weighted admission budget for concurrent serving: each "
         "query presents its tmsan static peak-device-bytes bound "
         "(TPU-L014, analysis/lifetime.py) as its ticket at plan time, "
         "and tickets co-run only while their bounds sum to at most "
         "this.  Oversized-but-repairable plans (sort / aggregate "
         "merge) are re-planned through the out-of-core repair path "
         "with a smaller oc_budget first; the rest queue FIFO until "
         "serve.admissionTimeoutMs, then fail with the typed "
         "AdmissionTimeout.  Unset disables admission control (the "
         "single-tenant default: only the count-based "
         "concurrentGpuTasks semaphore gates the device).") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_optional()

SERVE_ADMISSION_TIMEOUT_MS = conf(
    "spark.rapids.tpu.serve.admissionTimeoutMs").integer() \
    .doc("How long a query may wait in the FIFO admission queue for "
         "its byte ticket before failing with AdmissionTimeout — "
         "typed backpressure a serving tier can retry or shed, never "
         "a silent hang (and never an OOM from admitting anyway).") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_with_default(30_000)

SERVE_POOL_SIZE = conf("spark.rapids.tpu.serve.poolSize").integer() \
    .doc("Logical sessions a SessionPool (api/pool.py) multiplexes "
         "over the ONE process-wide runtime (device manager, spill "
         "catalog, shuffle manager, metrics registry, compile "
         "observatory).  Each borrowed session binds to the borrowing "
         "thread with per-query tracer and memsan-ledger isolation; "
         "size it to the offered concurrency, not the chip count.") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_with_default(4)

SLO_TARGET_MS = conf("spark.rapids.tpu.slo.targetMs").integer() \
    .doc("Per-request latency objective for the latency observatory "
         "(obs/slo.py): a traced query counts GOOD when it completes "
         "within this many milliseconds; failed queries are always "
         "BAD.  Feeds the per-tenant tpu_slo_{good,total,burn_rate} "
         "gauges, the sustained-burn /healthz rule and "
         "SessionPool.slo_report().  Unset disables SLO accounting — "
         "critical-path extraction (obs/critpath.py) still runs for "
         "every traced query.") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_optional()

SLO_OBJECTIVE = conf("spark.rapids.tpu.slo.objective").double() \
    .doc("Fraction of requests that must meet slo.targetMs.  The "
         "windowed burn rate is (bad share) / (1 - objective): burn "
         "1.0 spends error budget exactly as fast as the objective "
         "allows, and sustained burn > 1 across two health snapshots "
         "degrades /healthz naming the burning tenant.") \
    .check(lambda v: 0.0 < v < 1.0, "must be in (0, 1)") \
    .create_with_default(0.99)

# --- feedback-directed planning (estimator observatory) -------------------

FEEDBACK_ENABLED = conf("spark.rapids.tpu.feedback.enabled").boolean() \
    .doc("Close the predict->execute loop: blend the estimator "
         "ledger's recorded per-(exec kind, input signature) actuals "
         "into plan/cost.estimate_rows, and re-plan the reduce side of "
         "a shuffle at the exchange boundary from the catalog's "
         "measured partition_stats (switch join strategy, force the "
         "out-of-core repair, re-price the admission ticket) before it "
         "launches.  Observation RECORDING is always on (the "
         "EstimatorLedger grades the CBO regardless); this key gates "
         "whether the recorded signal feeds back into planning.  Off "
         "by default: feedback makes plans depend on execution "
         "history.") \
    .create_with_default(False)

FEEDBACK_BLEND_FLOOR = conf("spark.rapids.tpu.feedback.blendFloor") \
    .double() \
    .doc("Minimum confidence weight given to a recorded actual when a "
         "matching (exec kind, input signature) exists in the "
         "estimator ledger: estimate = w*recorded + (1-w)*static with "
         "w clamped to [blendFloor, blendCap] by observation count "
         "(w grows as n/(n+1)).") \
    .check(lambda v: 0.0 <= v <= 1.0, "must be in [0, 1]") \
    .create_with_default(0.25)

FEEDBACK_BLEND_CAP = conf("spark.rapids.tpu.feedback.blendCap") \
    .double() \
    .doc("Maximum confidence weight a recorded actual can earn: even a "
         "heavily observed signature keeps (1-blendCap) of the static "
         "model, so a workload shift can still pull the estimate back "
         "before the ledger re-learns it.") \
    .check(lambda v: 0.0 <= v <= 1.0, "must be in [0, 1]") \
    .create_with_default(0.9)

FEEDBACK_MIN_OBSERVATIONS = conf(
    "spark.rapids.tpu.feedback.minObservations").integer() \
    .doc("Observations a (exec kind, input signature) needs in the "
         "estimator ledger before its recorded mean is blended into "
         "estimate_rows.  1 means a single prior run of the same "
         "query shape already sharpens the next plan.") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_with_default(1)

FEEDBACK_REPLAN_FACTOR = conf(
    "spark.rapids.tpu.feedback.replan.misestimateFactor").double() \
    .doc("How far the measured map-stage output may diverge from the "
         "planner's prediction (ratio, either direction) before the "
         "exchange-boundary re-plan switches the reduce-side join off "
         "speculative sizing (analysis/replan.py).  Ticket re-pricing "
         "and out-of-core repair decisions fire on any material bound "
         "change regardless of this factor.") \
    .check(lambda v: v > 1.0, "must be > 1") \
    .create_with_default(4.0)

# --- HBM observatory (obs/memprof.py) -------------------------------------

HBM_TIMELINE_ENABLED = conf(
    "spark.rapids.tpu.hbm.timeline.enabled").boolean() \
    .doc("Maintain the tenant-attributed device-memory occupancy "
         "timeline (obs/memprof.py): every spill-catalog, staging-"
         "arena, broadcast-retention and admission-ticket event books "
         "a per-(tenant, buffer class) byte delta, exported as "
         "Perfetto counter tracks in the Chrome trace and as the "
         "tpu_hbm_* metric families.  session.hbm_report() and the "
         "admission controller's hbm_holders() read it.  Cheap: one "
         "dict update per lifecycle event, bounded sample ring.") \
    .create_with_default(True)

HBM_TIMELINE_MAX_SAMPLES = conf(
    "spark.rapids.tpu.hbm.timeline.maxSamples").integer() \
    .doc("Bound on the occupancy timeline's in-memory sample ring; "
         "past it the oldest samples drop (the live per-tenant books "
         "stay exact — only the replayable history window is bounded). "
         "The post-mortem bundle and trace counter tracks read this "
         "window.") \
    .check(lambda v: v >= 64, "must be >= 64") \
    .create_with_default(4096)

HBM_POSTMORTEM_ENABLED = conf(
    "spark.rapids.tpu.hbm.postmortem.enabled").boolean() \
    .doc("Failure black box: on query failure, dirty memsan ledger or "
         "admission timeout, dump a bounded post-mortem bundle (trace, "
         "metrics snapshot, memory-timeline window, plan, interp/tmsan "
         "states, estimator grades, effective config) under "
         "<postmortem.dir>/postmortems/, rendered by `tools "
         "postmortem`.  Needs hbm.postmortem.dir or "
         "regress.historyDir to be set.") \
    .create_with_default(True)

HBM_POSTMORTEM_DIR = conf(
    "spark.rapids.tpu.hbm.postmortem.dir").string() \
    .doc("Directory whose postmortems/ subdir receives failure "
         "bundles.  Unset: falls back to regress.historyDir, and when "
         "neither is set the black box is inert.") \
    .create_optional()

HBM_POSTMORTEM_MAX_BUNDLES = conf(
    "spark.rapids.tpu.hbm.postmortem.maxBundles").integer() \
    .doc("Retention cap on the postmortems/ directory: past it the "
         "oldest bundles are deleted after each dump, so a crash-"
         "looping workload cannot fill the disk with black boxes.") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_with_default(16)

# --- progress observatory (obs/progress.py) -------------------------------

PROGRESS_ENABLED = conf("spark.rapids.tpu.progress.enabled").boolean() \
    .doc("Maintain the live in-flight query view (obs/progress.py): "
         "phase, per-operator partitions done/total, rows-so-far vs "
         "the estimator's predicted rows, a confidence-blended ETA, "
         "and the cooperative cancel/deadline token the partition-"
         "boundary, admission-wait and shuffle-fetch checkpoints "
         "consult.  Served by GET /queries and `tools top`.  Cheap: "
         "per-batch dict updates, no device crossings.  Off, "
         "session.cancel() and deadline_ms have nothing to act on and "
         "report/raise accordingly.") \
    .create_with_default(True)

PROGRESS_MAX_QUERIES = conf(
    "spark.rapids.tpu.progress.maxQueries").integer() \
    .doc("Bound on the live view's in-flight registry: past it the "
         "oldest entry is evicted (a registration leaked by a crashed "
         "query must not grow the view forever).  Size to the offered "
         "concurrency; the finished ring is bounded separately.") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_with_default(64)

PROGRESS_DEADLINE_MS = conf(
    "spark.rapids.tpu.progress.deadlineMs").integer() \
    .doc("Default per-query deadline: queries that run past it raise "
         "the typed TpuQueryDeadlineExceeded at the next cooperative "
         "checkpoint (partition boundary, admission queue wait, "
         "shuffle fetch loop).  An explicit "
         "TpuSession.execute(deadline_ms=...) overrides it per call.  "
         "Unset: no deadline unless the caller passes one.  Deadline "
         "failures count BAD against the tenant's SLO burn window; "
         "client cancels do not.") \
    .check(lambda v: v >= 1, "must be >= 1") \
    .create_optional()

WATCHDOG_STALL_SECONDS = conf(
    "spark.rapids.tpu.watchdog.stallSeconds").double() \
    .doc("Stuck-query watchdog threshold: an in-flight query with no "
         "progress event (no phase change, operator open/close or "
         "batch) for this long is flagged stalled — /healthz degrades "
         "naming the query and its deepest open operator span, and "
         "one stall record lands in the failure black box.  The scan "
         "is poll-driven (health snapshots, GET /queries); 0 disables "
         "it.") \
    .check(lambda v: v >= 0.0, "must be >= 0") \
    .create_with_default(30.0)

WATCHDOG_AUTO_CANCEL_SECONDS = conf(
    "spark.rapids.tpu.watchdog.autoCancelSeconds").double() \
    .doc("Hard stall deadline: a query stalled this long is cancelled "
         "by the watchdog (cause=watchdog in tpu_cancellations_total) "
         "at the next scan, unwinding through the same typed "
         "cooperative-cancel path a client cancel uses.  Unset: the "
         "watchdog only flags, never cancels.") \
    .check(lambda v: v > 0.0, "must be > 0") \
    .create_optional()

# Environment variables the engine reads directly (escape hatches that
# must exist before config parsing, e.g. cache sizing at import time).
# The repo lint (TPU-R002) fails on any SPARK_RAPIDS_* env read not
# listed here: env knobs are config surface and get declared like keys.
DECLARED_ENV_KEYS = (
    # process JIT residency budget, read at exec/base.py import
    "SPARK_RAPIDS_TPU_JIT_CACHE_MAX",
    # disable the persistent XLA compile cache (plugin.py startup)
    "SPARK_RAPIDS_TPU_DISABLE_COMPILE_CACHE",
    # hard deadline (seconds) on TPU device discovery before the
    # single-chip/skip fallback (parallel/mesh.py; the MULTICHIP rc=124
    # hang guard) — read before any conf exists
    "SPARK_RAPIDS_TPU_DEVICE_PROBE_TIMEOUT_S",
    # seed for shuffle/digest.py's process-wide digest switch: lets
    # session-less subprocesses (serve_map, the --dist bench child)
    # honor spark.rapids.tpu.dsan.digest.enabled without a conf object
    "SPARK_RAPIDS_TPU_DSAN_DIGEST",
)


class RapidsConf:
    """Snapshot of a config map with typed accessors
    (ref RapidsConf.scala class)."""

    def __init__(self, conf_map: Optional[Dict[str, Any]] = None):
        self._map = dict(conf_map or {})

    def get(self, entry: ConfEntry[V]) -> V:
        return entry.get(self._map)

    def raw(self, key: str, default: Any = None) -> Any:
        return self._map.get(key, default)

    def set(self, key: str, value: Any) -> "RapidsConf":
        m = dict(self._map)
        m[key] = value
        return RapidsConf(m)

    def is_op_enabled(self, kind: str, name: str, default: bool = True) -> bool:
        """Auto-derived per-op enable keys, e.g.
        spark.rapids.sql.exec.TpuSortExec (ref GpuOverrides.scala:145-150)."""
        raw = self._map.get(f"spark.rapids.sql.{kind}.{name}")
        return default if raw is None else _to_bool(raw)

    # convenient named properties used widely
    @property
    def sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED)

    @property
    def batch_size_bytes(self) -> int:
        return self.get(BATCH_SIZE_BYTES)

    @property
    def explain(self) -> str:
        return self.get(EXPLAIN)

    @property
    def ansi_enabled(self) -> bool:
        return self.get(ANSI_ENABLED)

    @property
    def arrow_max_records_per_batch(self) -> int:
        return self.get(ARROW_MAX_RECORDS_PER_BATCH)

    @property
    def udf_compiler_enabled(self) -> bool:
        return self.get(UDF_COMPILER_ENABLED)

    @property
    def capacity_buckets(self) -> List[int]:
        return sorted(int(x) for x in
                      self.get(TPU_BATCH_CAPACITY_BUCKETS).split(","))

    @property
    def string_data_buckets(self) -> List[int]:
        return sorted(int(x) for x in
                      self.get(TPU_STRING_DATA_BUCKETS).split(","))


def all_entries() -> List[ConfEntry]:
    return [e for _, e in sorted(_REGISTERED.items())]


def generate_docs() -> str:
    """Render docs/configs.md from the registry
    (ref RapidsConf.scala doc printer)."""
    lines = ["# Configuration", "",
             "Generated from `spark_rapids_tpu/config.py` — do not edit.", "",
             "| Name | Default | Description |", "|---|---|---|"]
    for e in all_entries():
        if e.is_internal:
            continue
        lines.append(f"| `{e.key}` | {e.default} | {e.doc} |")
    return "\n".join(lines) + "\n"
