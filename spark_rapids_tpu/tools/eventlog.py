"""Spark event-log parsing for the offline tools.

Ref: tools/src/main/scala/org/apache/spark/sql/rapids/tool/
EventProcessorBase.scala + ApplicationInfo — the reference replays a
Spark history event log (JSON lines) into per-app state.  The format is
hardware-neutral, so this layer is a faithful re-implementation: one
`AppInfo` per log, accumulating applications, executors, jobs, stages,
tasks (with metrics), and SQL executions (with their physical plan
trees).  Supports plain, .gz and .zstd logs like the reference's
EventLogPathProcessor.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import Any, Dict, Iterator, List, Optional


class PlanNode:
    """One node of a SparkPlanInfo tree.

    `prediction`/`actual` are the spark_rapids_tpu extensions the
    engine's self-emitted logs carry (tpuPrediction/tpuActual: the
    CBO's row/byte model + tmsan's peak-HBM bound vs what actually ran
    — the `tools profile --accuracy` inputs); None on foreign logs."""

    __slots__ = ("node_name", "simple_string", "children", "metrics",
                 "prediction", "actual", "placement")

    def __init__(self, node_name: str, simple_string: str = "",
                 children: Optional[List["PlanNode"]] = None,
                 metrics: Optional[List[dict]] = None,
                 prediction: Optional[dict] = None,
                 actual: Optional[dict] = None,
                 placement: str = ""):
        self.node_name = node_name
        self.simple_string = simple_string
        self.children = children or []
        self.metrics = metrics or []
        self.prediction = prediction
        self.actual = actual
        # "tpu" / "cpu" on self-emitted logs (the regression watchdog's
        # fallback-set field); "" on foreign Spark logs
        self.placement = placement

    @classmethod
    def from_json(cls, d: dict) -> "PlanNode":
        return cls(d.get("nodeName", ""), d.get("simpleString", ""),
                   [cls.from_json(c) for c in d.get("children", [])],
                   d.get("metrics", []),
                   d.get("tpuPrediction"), d.get("tpuActual"),
                   d.get("tpuPlacement", ""))

    def walk(self) -> Iterator["PlanNode"]:
        yield self
        for c in self.children:
            yield from c.walk()


class SQLExecution:
    __slots__ = ("sql_id", "description", "plan", "start_time", "end_time",
                 "failed", "job_ids", "peak_device_bytes",
                 "static_peak_bound")

    def __init__(self, sql_id: int, description: str, plan: PlanNode,
                 start_time: int):
        self.sql_id = sql_id
        self.description = description
        self.plan = plan
        self.start_time = start_time
        self.end_time: Optional[int] = None
        self.failed = False
        self.job_ids: List[int] = []
        # spark_rapids_tpu extensions (memsan-measured peak vs the tmsan
        # static bound); None on foreign logs
        self.peak_device_bytes: Optional[int] = None
        self.static_peak_bound: Optional[int] = None

    @property
    def duration(self) -> int:
        if self.end_time is None:
            return 0
        return self.end_time - self.start_time


class StageInfo:
    __slots__ = ("stage_id", "attempt", "name", "num_tasks", "submission",
                 "completion", "failure_reason")

    def __init__(self, stage_id: int, attempt: int, name: str,
                 num_tasks: int):
        self.stage_id = stage_id
        self.attempt = attempt
        self.name = name
        self.num_tasks = num_tasks
        self.submission: Optional[int] = None
        self.completion: Optional[int] = None
        self.failure_reason: Optional[str] = None

    @property
    def duration(self) -> int:
        if self.submission is None or self.completion is None:
            return 0
        return self.completion - self.submission


class TaskInfo:
    __slots__ = ("task_id", "stage_id", "attempt", "launch", "finish",
                 "failed", "executor_id", "duration", "run_time", "cpu_time",
                 "gc_time", "input_bytes", "output_bytes",
                 "shuffle_read_bytes", "shuffle_write_bytes",
                 "memory_spilled", "disk_spilled", "result_size")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k, 0))


class AppInfo:
    """All state replayed from one event log."""

    def __init__(self):
        self.app_name = ""
        self.app_id = ""
        self.start_time = 0
        self.end_time = 0
        self.spark_version = ""
        self.spark_props: Dict[str, str] = {}
        self.executors: Dict[str, dict] = {}
        self.jobs: Dict[int, dict] = {}
        self.stages: Dict[tuple, StageInfo] = {}
        self.tasks: List[TaskInfo] = []
        self.sql_executions: Dict[int, SQLExecution] = {}
        self.job_to_sql: Dict[int, int] = {}
        self.stage_to_job: Dict[int, int] = {}
        # flight-recorder span records (TpuSpanEvent lines from the
        # engine's self-emitted logs; empty for foreign Spark logs)
        self.spans: List[dict] = []

    @property
    def app_duration(self) -> int:
        return (self.end_time - self.start_time) if self.end_time else 0

    @property
    def duration_estimated(self) -> bool:
        return self.end_time == 0

    # ------------------------------------------------------------------
    def sql_task_duration(self, sql_id: int) -> int:
        """Sum of task run times (ms) attributed to one SQL execution."""
        stage_ids = {sid for sid, jid in self.stage_to_job.items()
                     if self.job_to_sql.get(jid) == sql_id}
        return sum(t.run_time for t in self.tasks
                   if t.stage_id in stage_ids)

    def executor_cpu_percent(self) -> float:
        run = sum(t.run_time for t in self.tasks)
        cpu = sum(t.cpu_time for t in self.tasks)  # ns in logs
        if run <= 0:
            return 0.0
        return round(min(100.0, 100.0 * (cpu / 1e6) / run), 2)


def _open_log(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rt", errors="replace")
    if path.endswith(".zstd") or path.endswith(".zst"):
        import io
        from ..native import codec as ncodec  # pragma: no cover
        raise NotImplementedError(
            "zstd event logs: decompress with the native codec CLI first")
    return open(path, "rt", errors="replace")


def parse_event_log(path: str) -> AppInfo:
    app = AppInfo()
    with _open_log(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue
            _apply_event(app, ev)
    return app


def _apply_event(app: AppInfo, ev: dict) -> None:
    kind = ev.get("Event", "")
    if kind == "SparkListenerApplicationStart":
        app.app_name = ev.get("App Name", "")
        app.app_id = ev.get("App ID", "")
        app.start_time = ev.get("Timestamp", 0)
    elif kind == "SparkListenerApplicationEnd":
        app.end_time = ev.get("Timestamp", 0)
    elif kind == "SparkListenerLogStart":
        app.spark_version = ev.get("Spark Version", "")
    elif kind == "SparkListenerEnvironmentUpdate":
        app.spark_props.update(ev.get("Spark Properties", {}) or {})
    elif kind == "SparkListenerExecutorAdded":
        app.executors[ev.get("Executor ID", "")] = {
            "host": ev.get("Executor Info", {}).get("Host", ""),
            "cores": ev.get("Executor Info", {}).get("Total Cores", 0),
            "add_time": ev.get("Timestamp", 0),
        }
    elif kind == "SparkListenerJobStart":
        jid = ev.get("Job ID", 0)
        props = ev.get("Properties", {}) or {}
        app.jobs[jid] = {"submission": ev.get("Submission Time", 0),
                         "completion": None, "result": None,
                         "stages": [s.get("Stage ID")
                                    for s in ev.get("Stage Infos", [])]}
        sql_id = props.get("spark.sql.execution.id")
        if sql_id is not None:
            app.job_to_sql[jid] = int(sql_id)
            sx = app.sql_executions.get(int(sql_id))
            if sx is not None:
                sx.job_ids.append(jid)
        for s in ev.get("Stage Infos", []):
            app.stage_to_job[s.get("Stage ID")] = jid
    elif kind == "SparkListenerJobEnd":
        jid = ev.get("Job ID", 0)
        if jid in app.jobs:
            app.jobs[jid]["completion"] = ev.get("Completion Time", 0)
            res = ev.get("Job Result", {})
            app.jobs[jid]["result"] = res.get("Result", "")
            if res.get("Result") == "JobFailed":
                sql_id = app.job_to_sql.get(jid)
                if sql_id is not None and sql_id in app.sql_executions:
                    app.sql_executions[sql_id].failed = True
    elif kind == "SparkListenerStageSubmitted":
        si = ev.get("Stage Info", {})
        key = (si.get("Stage ID"), si.get("Stage Attempt ID", 0))
        st = StageInfo(key[0], key[1], si.get("Stage Name", ""),
                       si.get("Number of Tasks", 0))
        st.submission = si.get("Submission Time")
        app.stages[key] = st
    elif kind == "SparkListenerStageCompleted":
        si = ev.get("Stage Info", {})
        key = (si.get("Stage ID"), si.get("Stage Attempt ID", 0))
        st = app.stages.get(key)
        if st is None:
            st = StageInfo(key[0], key[1], si.get("Stage Name", ""),
                           si.get("Number of Tasks", 0))
            app.stages[key] = st
        st.submission = si.get("Submission Time", st.submission)
        st.completion = si.get("Completion Time")
        st.failure_reason = si.get("Failure Reason")
    elif kind == "SparkListenerTaskEnd":
        ti = ev.get("Task Info", {})
        tm = ev.get("Task Metrics", {}) or {}
        sh_r = tm.get("Shuffle Read Metrics", {}) or {}
        sh_w = tm.get("Shuffle Write Metrics", {}) or {}
        app.tasks.append(TaskInfo(
            task_id=ti.get("Task ID", 0),
            stage_id=ev.get("Stage ID", 0),
            attempt=ti.get("Attempt", 0),
            launch=ti.get("Launch Time", 0),
            finish=ti.get("Finish Time", 0),
            failed=bool(ti.get("Failed", False)),
            executor_id=ti.get("Executor ID", ""),
            duration=max(0, ti.get("Finish Time", 0) -
                         ti.get("Launch Time", 0)),
            run_time=tm.get("Executor Run Time", 0),
            cpu_time=tm.get("Executor CPU Time", 0),
            gc_time=tm.get("JVM GC Time", 0),
            input_bytes=(tm.get("Input Metrics", {}) or {}).get(
                "Bytes Read", 0),
            output_bytes=(tm.get("Output Metrics", {}) or {}).get(
                "Bytes Written", 0),
            shuffle_read_bytes=sh_r.get("Remote Bytes Read", 0) +
            sh_r.get("Local Bytes Read", 0),
            shuffle_write_bytes=sh_w.get("Shuffle Bytes Written", 0),
            memory_spilled=tm.get("Memory Bytes Spilled", 0),
            disk_spilled=tm.get("Disk Bytes Spilled", 0),
            result_size=tm.get("Result Size", 0)))
    elif kind.endswith("SQLExecutionStart"):
        sql_id = ev.get("executionId", 0)
        plan = PlanNode.from_json(ev.get("sparkPlanInfo", {}) or {})
        app.sql_executions[sql_id] = SQLExecution(
            sql_id, ev.get("description", ""), plan, ev.get("time", 0))
    elif kind.endswith("SQLExecutionEnd"):
        sql_id = ev.get("executionId", 0)
        sx = app.sql_executions.get(sql_id)
        if sx is not None:
            sx.end_time = ev.get("time", 0)
            sx.peak_device_bytes = ev.get("tpuPeakDeviceBytes")
            sx.static_peak_bound = ev.get("tpuStaticPeakBound")
    elif kind.endswith("TpuSpanEvent"):
        app.spans.append(ev)
    elif kind.endswith("SQLAdaptiveExecutionUpdate"):
        sql_id = ev.get("executionId", 0)
        sx = app.sql_executions.get(sql_id)
        if sx is not None:
            sx.plan = PlanNode.from_json(ev.get("sparkPlanInfo", {}) or {})


def find_event_logs(paths: List[str]) -> List[str]:
    """Expand files/directories into individual event-log files."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            if os.path.exists(os.path.join(p, "eventLog")) or any(
                    n.startswith("events_") for n in os.listdir(p)):
                # rolling event log dir
                for n in sorted(os.listdir(p)):
                    if not n.startswith("."):
                        out.append(os.path.join(p, n))
            else:
                for n in sorted(os.listdir(p)):
                    fp = os.path.join(p, n)
                    if os.path.isfile(fp) and not n.startswith("."):
                        out.append(fp)
        else:
            out.append(p)
    return out
