"""`tools tail-report`: aggregate the latency observatory's per-query
ledger (obs/slo.py; ``latency_ledger.jsonl`` in the regress
HistoryDir) into per-tenant tail-latency attribution:

* **p50 vs p99 segment mix** — what a typical request spends its time
  on versus what the slowest requests spend it on.  A healthy tenant's
  two mixes look alike; a whale victim's p99 mix is dominated by
  ``queue_wait`` while its p50 stays compute-dominated.
* **Dominant tail segment** — the single segment that explains the
  most p99 wall time per tenant, the one-line answer ("tenant pool-3's
  p99 is 71% queue-wait") ROADMAP item 4's weighted-fair admission
  will be judged against.
* **Slowest-N receipts** — the reservoir rows behind the percentages,
  so a surprising mix can be chased to concrete queries.

The aggregation itself lives in obs/slo.py (``aggregate_tail``) so the
offline report and the live ``SessionPool.slo_report()`` can never
disagree about what "dominant" means.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List


def load_ledger(path: str) -> List[Dict]:
    """Parse one latency ledger (JSONL).  ``path`` may be the file or
    a directory containing ``latency_ledger.jsonl``.  Unparsable lines
    are skipped — the ledger is append-under-crash telemetry and a
    torn final line must not kill the report."""
    from ..obs.slo import LATENCY_LEDGER_FILENAME
    if os.path.isdir(path):
        path = os.path.join(path, LATENCY_LEDGER_FILENAME)
    records: List[Dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "wall_s" in rec:
                records.append(rec)
    return records


def aggregate_records(records: List[Dict], top: int = 3) -> Dict:
    """Group ledger records by tenant and run the shared tail
    aggregation over each group."""
    from ..obs.slo import aggregate_tail
    by_tenant: Dict[str, List[Dict]] = {}
    for r in records:
        by_tenant.setdefault(r.get("tenant") or "default", []).append(r)
    tenants: Dict[str, Dict] = {}
    for name in sorted(by_tenant):
        recs = by_tenant[name]
        agg = aggregate_tail(recs)
        if agg is None:
            continue
        slowest = sorted(recs, key=lambda r: -float(r["wall_s"]))[:top]
        agg["slowest"] = [
            {"wall_ms": round(float(r["wall_s"]) * 1000.0, 3),
             "label": r.get("label") or "",
             "failed": bool(r.get("failed"))}
            for r in slowest]
        tenants[name] = agg
    return {"queries": len(records), "tenants": tenants}


def run_tail_report(ledger: str, top: int = 3,
                    as_json: bool = False) -> int:
    try:
        records = load_ledger(ledger)
    except OSError as ex:
        print(f"tail-report: cannot read ledger: {ex}")
        return 1
    report = aggregate_records(records, top=top)
    if as_json:
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0
    from ..obs.slo import format_tail_report
    print(f"latency ledger: {report['queries']} queries, "
          f"{len(report['tenants'])} tenants")
    print(format_tail_report(report))
    return 0
