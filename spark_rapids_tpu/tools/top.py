"""`tools top` — the live in-flight query view (obs/progress.py).

Reads `GET /queries` from a running engine's health endpoint
(`spark.rapids.tpu.metrics.port`) and renders a `top`-style table:
one row per in-flight query with phase, blended progress ratio, ETA,
rows-vs-predicted, the deepest open operator, and any watchdog flags;
a short tail of recently finished queries for context.  `--watch`
refreshes in place; the default is one snapshot (scriptable, and what
the gate exercises).
"""

from __future__ import annotations

import json
import sys
import time
from typing import Dict, List, Optional
from urllib.request import urlopen


def fetch_view(url: str, timeout: float = 5.0) -> Dict:
    """One `GET /queries` document from a running engine."""
    if not url.startswith("http"):
        url = f"http://{url}"
    if not url.rstrip("/").endswith("/queries"):
        url = url.rstrip("/") + "/queries"
    with urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _bar(ratio: float, width: int = 12) -> str:
    filled = int(round(max(0.0, min(ratio, 1.0)) * width))
    return "#" * filled + "." * (width - filled)


def _fmt_eta(eta: Optional[float]) -> str:
    if eta is None:
        return "-"
    if eta >= 60:
        return f"{eta / 60:.1f}m"
    return f"{eta:.1f}s"


def format_top(view: Dict) -> str:
    """Render one live-view document as the `top` table."""
    lines: List[str] = []
    inflight = view.get("inflight") or []
    stalled = {(s.get("tenant"), s.get("query"))
               for s in view.get("stalled") or []}
    wd = view.get("watchdog") or {}
    lines.append(
        f"queries: {len(inflight)} in flight, "
        f"{len(stalled)} stalled "
        f"(watchdog stall={wd.get('stall_seconds')}s"
        + (f", auto-cancel={wd.get('auto_cancel_seconds')}s"
           if wd.get("auto_cancel_seconds") else "") + ")")
    if inflight:
        lines.append(
            f"{'TENANT':12s} {'QUERY':8s} {'PHASE':10s} "
            f"{'PROGRESS':14s} {'RATIO':>6s} {'ETA':>7s} "
            f"{'ROWS':>10s} {'PRED':>10s} {'ELAPSED':>8s}  OPERATOR")
        for q in inflight:
            flags = ""
            if (q.get("tenant"), q.get("query")) in stalled or \
                    q.get("stalled"):
                flags += " STALLED"
            if q.get("cancelled"):
                flags += f" CANCELLING({q.get('cancel_cause')})"
            ratio = q.get("progress_ratio") or 0.0
            lines.append(
                f"{str(q.get('tenant'))[:12]:12s} "
                f"{str(q.get('query'))[:8]:8s} "
                f"{str(q.get('phase'))[:10]:10s} "
                f"[{_bar(ratio)}] {ratio:6.1%} "
                f"{_fmt_eta(q.get('eta_s')):>7s} "
                f"{q.get('rows') or 0:>10d} "
                f"{q.get('predicted_rows') or 0:>10d} "
                f"{q.get('elapsed_s', 0.0):>7.1f}s  "
                f"{q.get('deepest_open_operator') or '-'}{flags}")
    else:
        lines.append("(no queries in flight)")
    recent = view.get("recent") or []
    if recent:
        lines.append("")
        lines.append("recent:")
        for q in recent[-5:]:
            outcome = q.get("error") or "ok"
            if q.get("cancelled"):
                outcome += f" (cancelled: {q.get('cancel_cause')})"
            lines.append(
                f"  {q.get('tenant')}/{q.get('query')} "
                f"{q.get('elapsed_s', 0.0):.2f}s "
                f"rows={q.get('rows') or 0} {outcome}")
    return "\n".join(lines) + "\n"


def run_top(url: str, interval: float = 2.0, watch: bool = False,
            as_json: bool = False) -> int:
    """CLI driver: one snapshot by default, refresh loop with
    ``--watch`` (Ctrl-C exits 0)."""
    try:
        while True:
            try:
                view = fetch_view(url)
            except OSError as ex:
                sys.stderr.write(
                    f"tools top: cannot reach {url}: {ex}\n"
                    f"(is the engine running with "
                    f"spark.rapids.tpu.metrics.port set?)\n")
                return 2
            if as_json:
                sys.stdout.write(json.dumps(view, indent=2) + "\n")
            else:
                if watch:
                    sys.stdout.write("\x1b[2J\x1b[H")  # clear screen
                sys.stdout.write(format_top(view))
                sys.stdout.flush()
            if not watch:
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0
