"""CLI for the offline tools (ref QualificationMain / ProfileMain):

    python -m spark_rapids_tpu.tools qualification <eventlogs...> [-o DIR]
    python -m spark_rapids_tpu.tools profiling     <eventlogs...> [-o DIR] [-c] [--accuracy]
    python -m spark_rapids_tpu.tools trace         <eventlog> [--export chrome|text] [-o FILE] [--merged]
    python -m spark_rapids_tpu.tools fleet         <eventlog|trace.json> [--json]
    python -m spark_rapids_tpu.tools lint --repo   [--baseline FILE]
    python -m spark_rapids_tpu.tools lint --plan   <fixture.py...> [--infer] [--memsan] [--determinism]
    python -m spark_rapids_tpu.tools lint --determinism [-o FILE]
    python -m spark_rapids_tpu.tools regress --history DIR --record <eventlog...> [--label L]
    python -m spark_rapids_tpu.tools regress --history DIR --check [--wall-threshold PCT]
    python -m spark_rapids_tpu.tools compile-report --ledger PATH [--top N] [--json]
    python -m spark_rapids_tpu.tools tail-report    --ledger PATH [--top N] [--json]
    python -m spark_rapids_tpu.tools estimator-report --ledger PATH [--top N] [--json]
    python -m spark_rapids_tpu.tools kernel-report  --compile-ledger PATH --estimator-ledger PATH [--top N] [--json]
    python -m spark_rapids_tpu.tools prewarm        --ledger DIR [--top K] [--cache-dir DIR]
    python -m spark_rapids_tpu.tools postmortem     <bundle.json|dir> [--json] [--last N]
    python -m spark_rapids_tpu.tools top            [--url HOST:PORT] [--watch] [--json]

`top` renders the progress observatory's live view (obs/progress.py;
served as `GET /queries` on the health endpoint): one row per
in-flight query with phase, blended progress ratio, ETA, rows vs the
planner's predicted rows, the deepest open operator span, and
stall/cancel flags from the stuck-query watchdog.

`postmortem` renders the failure black box's bundles
(obs/postmortem.py; dumped to <historyDir>/postmortems/ on query
failure, dirty memsan ledger or admission timeout): the failing
operator, its tenant/query, the per-tenant HBM occupancy split at
failure time and the memory-timeline window leading up to it.  Given a
directory it renders the newest bundle (or the newest --last N).

`compile-report` aggregates the compile observatory's cross-session
ledger (obs/compileprof.py; `--ledger` takes the JSONL file or the
history dir holding compile_ledger.jsonl) into top-programs-by-compile-
cost, miss causes, churn offenders and the bucket-canonicalization
dedupe projection — the evidence for the persistent-program-cache key
design (ROADMAP item 1).

`tail-report` aggregates the latency observatory's per-query ledger
(obs/slo.py; `--ledger` takes latency_ledger.jsonl or the history dir
holding it) into per-tenant p50-vs-p99 segment mixes and names each
tenant's dominant tail segment — the whale-victim evidence ROADMAP
item 4's weighted-fair admission will be judged against.

`estimator-report` is its planner-side twin: it aggregates the
estimator observatory's ledger (obs/estimator.py; `--ledger` takes the
JSONL file or the history dir holding estimator_ledger.jsonl) into the
planner calibration score, the exec kinds with the worst row-estimate
error (where feedback blending buys the most), the peak-HBM
bound-vs-measured error, and the exchange-boundary re-plan decisions
by (decision, cause).

`kernel-report` is the tpuxsan headline artifact: it joins the compile
ledger's per-program cost_analysis() figures against the estimator
ledger's measured span seconds and padding-waste bytes, computes each
exec kind's speed-of-light gap (analysis/hlocost.py), and ranks the
kinds and the named fusion pipelines (hash build/probe,
filter->project, grouped aggregate) by projected kernel savings — the
evidence that decides which Pallas kernel to write first.

`regress` is the cross-run watchdog (obs/history.py): --record distills
self-emitted event logs into per-query fingerprints appended to the
history dir; --check diffs the two most recent runs and exits nonzero
on DETERMINISTIC drift (new fallbacks, fetch-crossing growth, operator
row drift, plan/lint changes).  Wall-clock comparison is opt-in via
--wall-threshold and never fails CI.

`profiling --accuracy` and `trace` consume the engine's SELF-emitted
event logs (spark.rapids.tpu.eventLog.dir): predicted-vs-actual
rows/bytes per operator, and the flight-recorder span tree exported as
Chrome-trace JSON (chrome://tracing / Perfetto) or a text timeline.

Lint fixtures are Python files defining ``plan_*()`` builders, each
returning ``(exec_root, conf_dict)`` — the checked-in golden bad plans
under tests/goldens/lint/ are the reference examples.
"""

import argparse
import sys


def _run_plan_lint(paths, infer=False, memsan=False,
                   determinism=False):
    import runpy

    from ..analysis.diagnostics import format_diagnostics
    from ..analysis.plan_lint import lint_plan
    from ..config import RapidsConf

    any_error = False
    for path in paths:
        ns = runpy.run_path(path)
        builders = sorted(k for k in ns if k.startswith("plan_")
                          and callable(ns[k]))
        if not builders:
            sys.stderr.write(f"{path}: no plan_*() builders found\n")
            return 2
        for name in builders:
            root, conf_map = ns[name]()
            conf = RapidsConf(conf_map)
            diags = lint_plan(root, conf)
            sys.stdout.write(f"== {path}::{name}\n")
            if infer:
                # print the abstract interpreter's per-subtree states
                # (schema / residency / distribution / rows / liveness)
                from ..analysis.interp import format_states, infer_plan
                sys.stdout.write(format_states(root, infer_plan(root,
                                                                conf)))
            if memsan:
                # print the lifetime pass's per-subtree peak-byte bounds
                from ..analysis.lifetime import (analyze_memory,
                                                 format_memory)
                sys.stdout.write(format_memory(
                    root, analyze_memory(root, conf)))
            if determinism:
                # print per-subtree replay classes, then show what the
                # L016 in-place repair (canonical keyed merge) achieves
                from ..analysis.determinism import (classify_plan,
                                                    format_classes,
                                                    try_stabilize_repair)
                sys.stdout.write(format_classes(root, conf))
                res = classify_plan(root, conf)
                for d in res.diags:
                    if d.code != "TPU-L016" or d.node is None:
                        continue
                    if try_stabilize_repair(root, d.node, conf):
                        after = classify_plan(root, conf)
                        sys.stdout.write(
                            f"TPU-L016 repair applied at "
                            f"{d.node.name}: subtree now "
                            f"{after.effective(d.node.children[0])} "
                            f"(canonical keyed merge forced)\n")
                    else:
                        sys.stdout.write(
                            f"TPU-L016 at {d.node.name}: no "
                            f"stabilizing repair available\n")
            sys.stdout.write(format_diagnostics(diags))
            any_error |= any(d.is_error for d in diags)
    return 1 if any_error else 0


def _run_lock_graph(output):
    """Dump the tpucsan static lock-order artifact (the relation the
    runtime lock witness validates against) as JSON."""
    import json

    from ..analysis.concurrency import lock_order_artifact

    art = lock_order_artifact()
    text = json.dumps(art, indent=2, sort_keys=True) + "\n"
    if output:
        with open(output, "w", encoding="utf-8") as f:
            f.write(text)
        sys.stdout.write(
            f"lock graph: {len(art['locks'])} lock(s), "
            f"{len(art['edges'])} edge(s), {len(art['cycles'])} "
            f"cycle(s) -> {output}\n")
    else:
        sys.stdout.write(text)
    return 1 if art["cycles"] else 0


def _run_raise_graph(output):
    """Dump the tpufsan exception-flow artifact (what the fault-
    injection gate enumerates) as JSON."""
    import json

    from ..analysis.raiseflow import raise_graph_artifact

    art = raise_graph_artifact()
    text = json.dumps(art, indent=2, sort_keys=True) + "\n"
    leaks = sum(len(s["untyped"]) for s in art["seams"].values())
    if output:
        with open(output, "w", encoding="utf-8") as f:
            f.write(text)
        sys.stdout.write(
            f"raise graph: {len(art['seams'])} seam(s), "
            f"{len(art['taxonomy'])} typed error(s), "
            f"{len(art['injections'])} planned injection(s), "
            f"{leaks} untyped leak(s) -> {output}\n")
    else:
        sys.stdout.write(text)
    return 1 if leaks else 0


def _run_determinism_artifact(output):
    """Dump the tpudsan replay-class artifact (declared determinism of
    every registered operator + fingerprint hygiene) as JSON — the
    sibling of --lock-graph / --raise-graph."""
    import json

    from ..analysis.determinism import determinism_artifact

    art = determinism_artifact()
    text = json.dumps(art, indent=2, sort_keys=True) + "\n"
    hygiene = art["fingerprint_hygiene"]
    if output:
        with open(output, "w", encoding="utf-8") as f:
            f.write(text)
        sys.stdout.write(
            f"determinism artifact: {len(art['declarations'])} "
            f"operator declaration(s) over the "
            f"{len(art['lattice'])}-class lattice, "
            f"{len(hygiene)} fingerprint-hygiene finding(s) "
            f"-> {output}\n")
    else:
        sys.stdout.write(text)
    return 1 if hygiene else 0


def _run_repo_lint(baseline_path, update):
    from ..analysis.diagnostics import format_diagnostics
    from ..analysis.repo_lint import (lint_repo, load_baseline,
                                      new_violations, save_baseline)

    diags = lint_repo()
    if update:
        save_baseline(baseline_path, diags)
        sys.stdout.write(f"baseline updated: {len(diags)} violation(s) "
                         f"-> {baseline_path}\n")
        return 0
    baseline = load_baseline(baseline_path)
    fresh = new_violations(diags, baseline)
    if fresh:
        sys.stdout.write(format_diagnostics(fresh))
        sys.stdout.write(f"{len(fresh)} NEW violation(s) not in baseline "
                         f"({baseline_path})\n")
        return 1
    sys.stdout.write(f"repo lint clean ({len(diags)} baselined "
                     f"violation(s))\n")
    return 0


def _run_trace_export(log, fmt, output, sql_id, merged=False):
    import json

    from ..obs.export import spans_to_chrome, spans_to_text
    from .eventlog import parse_event_log

    app = parse_event_log(log)
    spans = [s for s in app.spans
             if sql_id is None or s.get("executionId") == sql_id]
    if not merged:
        # default view: THIS process's spans only; --merged includes
        # the remote serve spans grafted in by the fleet observatory
        # (they carry "proc" — the producing executor's identity)
        spans = [s for s in spans if not s.get("proc")]
    if not spans:
        sys.stderr.write(f"{log}: no flight-recorder spans "
                         f"(self-emitted logs only; was "
                         f"spark.rapids.tpu.eventLog.dir set?)\n")
        return 2
    if fmt == "text":
        text = spans_to_text(spans)
        if output:
            with open(output, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        return 0
    out_path = output or (log + ".trace.json")
    with open(out_path, "w") as f:
        json.dump(spans_to_chrome(spans), f)
    sys.stdout.write(f"{len(spans)} span(s) -> {out_path}\n")
    return 0


def _run_fleet_summary(log, sql_id, as_json=False):
    import json

    from ..obs.export import fleet_summary, format_fleet_summary

    spans = None
    if log.endswith(".json"):
        # a raw span dump (bench.py --dist writes one): either a bare
        # span-dict list or {"spans": [...]}
        try:
            with open(log) as f:
                doc = json.load(f)
            spans = doc if isinstance(doc, list) else doc.get("spans")
        except (OSError, ValueError):
            spans = None
    if spans is None:
        from .eventlog import parse_event_log
        app = parse_event_log(log)
        spans = [s for s in app.spans
                 if sql_id is None or s.get("executionId") == sql_id]
    if not spans:
        sys.stderr.write(f"{log}: no flight-recorder spans\n")
        return 2
    summary = fleet_summary(spans)
    if as_json:
        sys.stdout.write(json.dumps(summary, indent=2) + "\n")
    else:
        sys.stdout.write(format_fleet_summary(summary))
    return 0


def _run_regress(history_dir, record_logs, check, wall_threshold,
                 label=""):
    from ..obs.history import (HistoryDir, deterministic_drift,
                               diff_runs, distill_event_log)
    from .eventlog import find_event_logs

    hist = HistoryDir(history_dir)
    if record_logs:
        fps = []
        for log in find_event_logs(record_logs):
            fps += distill_event_log(log)
        if not fps:
            sys.stderr.write("regress --record: no queries found in "
                             "the given event log(s)\n")
            return 2
        path = hist.record(fps, label=label)
        sys.stdout.write(f"recorded {len(fps)} query fingerprint(s) "
                         f"-> {path}\n")
        if not check:
            return 0
    runs = hist.runs()
    if len(runs) < 2:
        sys.stderr.write(f"regress --check: need >= 2 recorded runs in "
                         f"{history_dir}, have {len(runs)}\n")
        return 2
    old, new = hist.load(runs[-2]), hist.load(runs[-1])
    drifts = diff_runs(old, new, wall_threshold_pct=wall_threshold)
    for d in drifts:
        sys.stdout.write(d.render() + "\n")
    hard = deterministic_drift(drifts)
    if hard:
        sys.stdout.write(f"regress: {len(hard)} deterministic drift "
                         f"signal(s) between {runs[-2].rsplit('/')[-1]} "
                         f"and {runs[-1].rsplit('/')[-1]}\n")
        return 1
    timing = len(drifts) - len(hard)
    sys.stdout.write(
        f"regress clean: no deterministic drift across "
        f"{len(new.get('queries', ()))} quer(ies)"
        + (f" ({timing} timing-only signal(s) above)" if timing
           else "") + "\n")
    return 0


def _run_prewarm(ledger, top, cache_dir):
    import os

    path = ledger
    if os.path.isdir(path):
        from ..obs.history import HistoryDir
        path = HistoryDir(path).compile_ledger_path()
    if not os.path.exists(path):
        sys.stderr.write(f"{ledger}: no compile ledger found\n")
        return 2
    if cache_dir:
        # same platform/XLA-flags/host scoping as the plugin wires at
        # session init, so the entries this replay writes are the ones
        # a real session will read
        import hashlib

        import jax

        from ..plugin import _host_cpu_fingerprint
        fp = hashlib.sha1(
            f"{jax.__version__}|{jax.default_backend()}|"
            f"{os.environ.get('XLA_FLAGS', '')}|"
            f"{_host_cpu_fingerprint()}".encode()).hexdigest()[:12]
        d = os.path.join(os.path.expanduser(cache_dir), fp)
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
    from ..obs.compileprof import CompileObservatory
    from ..obs.prewarm import prewarm_from_ledger
    CompileObservatory.get().configure(enabled=True, ledger_path=path)
    stats = prewarm_from_ledger(path, top_k=top)
    sys.stdout.write(
        f"prewarm: {stats['recipes']} recipe(s) replayed, "
        f"{stats['programs']} program(s) compiled in "
        f"{stats['seconds']:.2f}s ({stats['skipped']} without recipes, "
        f"{stats['errors']} error(s))\n")
    if stats["recipes"] == 0 and stats["errors"] == 0:
        sys.stdout.write(
            "no recipes found — run a session with "
            "spark.rapids.tpu.compile.ledgerDir set to record some\n")
    return 1 if stats["errors"] else 0


def _run_postmortem(target, as_json=False, last=1):
    import json
    import os

    from ..obs.postmortem import (list_bundles, load_bundle,
                                  render_postmortem)

    if os.path.isdir(target):
        paths = list_bundles(target)[-max(last, 1):]
        if not paths:
            sys.stderr.write(f"{target}: no post-mortem bundles "
                             f"(pm_*.json) found — was "
                             f"spark.rapids.tpu.hbm.postmortem.dir (or "
                             f"regress.historyDir) set when the query "
                             f"failed?\n")
            return 2
    else:
        paths = [target]
    rc = 0
    for path in paths:
        try:
            bundle = load_bundle(path)
        except (OSError, ValueError) as ex:
            sys.stderr.write(f"{path}: unreadable bundle: {ex}\n")
            rc = 2
            continue
        if as_json:
            sys.stdout.write(json.dumps(bundle, indent=2) + "\n")
        else:
            sys.stdout.write(f"== {path}\n")
            sys.stdout.write(render_postmortem(bundle))
    return rc


def _default_baseline():
    import os
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "devtools", "lint_baseline.txt")


def main(argv=None):
    p = argparse.ArgumentParser(prog="spark_rapids_tpu.tools")
    sub = p.add_subparsers(dest="cmd", required=True)
    q = sub.add_parser("qualification",
                       help="score apps for TPU acceleration benefit")
    q.add_argument("logs", nargs="+")
    q.add_argument("-o", "--output", default="qual_output")
    pr = sub.add_parser("profiling", help="profile apps from event logs")
    pr.add_argument("logs", nargs="+")
    pr.add_argument("-o", "--output", default="profile_output")
    pr.add_argument("-c", "--compare", action="store_true")
    pr.add_argument("-a", "--accuracy", action="store_true",
                    help="print the predicted-vs-actual report "
                         "(self-emitted logs embed the CBO/tmsan "
                         "model and measured rows/bytes per operator)")
    tr = sub.add_parser("trace",
                        help="export the flight-recorder span tree "
                             "from a self-emitted event log")
    tr.add_argument("log")
    tr.add_argument("--export", choices=["chrome", "text"],
                    default="chrome")
    tr.add_argument("-o", "--output", default=None,
                    help="output file (default: <log>.trace.json for "
                         "chrome; stdout for text)")
    tr.add_argument("--sql", type=int, default=None,
                    help="only this SQL execution id")
    tr.add_argument("--merged", action="store_true",
                    help="include the remote serve spans the fleet "
                         "observatory merged into the trace (one "
                         "clock-aligned multi-process timeline; each "
                         "producer gets its own Chrome process lane)")
    fl = sub.add_parser("fleet",
                        help="per-peer wire vs serve vs compute "
                             "summary of a merged trace")
    fl.add_argument("log", help="self-emitted event log (or a raw "
                                ".trace.json span dump)")
    fl.add_argument("--sql", type=int, default=None,
                    help="only this SQL execution id")
    fl.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of text")
    li = sub.add_parser("lint",
                        help="static plan/repo analysis (tpulint)")
    li.add_argument("--repo", action="store_true",
                    help="run the repo invariant lint over the package")
    li.add_argument("--plan", nargs="*", metavar="FIXTURE",
                    help="lint physical plans built by plan_*() "
                         "functions in the given Python files")
    li.add_argument("--infer", action="store_true",
                    help="with --plan: print the abstract "
                         "interpreter's inferred per-subtree states "
                         "(schema/residency/partitioning/rows) before "
                         "the diagnostics")
    li.add_argument("--memsan", action="store_true",
                    help="with --plan: print the lifetime pass's "
                         "per-subtree peak-device-byte bounds "
                         "(hold/retained/peak vs the HBM budget) "
                         "before the diagnostics")
    li.add_argument("--determinism", action="store_true",
                    help="dump the tpudsan replay-class artifact "
                         "(declared determinism per operator + "
                         "fingerprint hygiene) as JSON; with --plan, "
                         "print per-subtree replay classes and the "
                         "TPU-L016 repair outcome instead")
    li.add_argument("--baseline", default=None,
                    help="repo-lint baseline file "
                         "(default: devtools/lint_baseline.txt)")
    li.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current violations")
    li.add_argument("--lock-graph", action="store_true",
                    help="dump the tpucsan static lock-order artifact "
                         "(locks, acquisition edges, cycles, thread "
                         "roots) as JSON; exits 1 if the graph has a "
                         "cycle")
    li.add_argument("--raise-graph", action="store_true",
                    help="dump the tpufsan exception-flow artifact "
                         "(per-seam typed/untyped escape sets, the "
                         "typed-error taxonomy with raise sites, and "
                         "the fault-injection plan) as JSON; exits 1 "
                         "when any seam leaks an untyped operational "
                         "exception")
    li.add_argument("-o", "--output", default=None,
                    help="with --lock-graph/--raise-graph: write the "
                         "JSON here instead of stdout")
    rg = sub.add_parser("regress",
                        help="cross-run regression watchdog over "
                             "self-emitted event-log fingerprints")
    rg.add_argument("--history", required=True,
                    help="append-only fingerprint history directory "
                         "(spark.rapids.tpu.regress.historyDir)")
    rg.add_argument("--record", nargs="*", metavar="EVENTLOG",
                    default=None,
                    help="distill these event logs into one run "
                         "appended to the history")
    rg.add_argument("--check", action="store_true",
                    help="diff the two most recent runs; exit 1 on "
                         "deterministic drift")
    rg.add_argument("--wall-threshold", type=float, default=None,
                    metavar="PCT",
                    help="also report wall-clock regressions above "
                         "this percentage (advisory: timing drift "
                         "never fails the check)")
    rg.add_argument("--label", default="",
                    help="free-form label stored on the recorded run")
    cr = sub.add_parser("compile-report",
                        help="aggregate the compile observatory "
                             "ledger into the compile-cost report")
    cr.add_argument("--ledger", required=True,
                    help="compile_ledger.jsonl or the history dir "
                         "containing it "
                         "(spark.rapids.tpu.compile.ledgerDir)")
    cr.add_argument("--top", type=int, default=10,
                    help="rows per ranking section")
    cr.add_argument("--json", action="store_true",
                    help="emit the aggregate as JSON instead of text")
    tr = sub.add_parser("tail-report",
                        help="contrast per-tenant p50 vs p99 segment "
                             "mixes from the latency observatory "
                             "ledger and name each tenant's dominant "
                             "tail segment")
    tr.add_argument("--ledger", required=True,
                    help="latency_ledger.jsonl or the history dir "
                         "containing it "
                         "(spark.rapids.tpu.regress.historyDir)")
    tr.add_argument("--top", type=int, default=3,
                    help="slowest queries listed per tenant")
    tr.add_argument("--json", action="store_true",
                    help="emit the aggregate as JSON instead of text")
    kr = sub.add_parser("kernel-report",
                        help="rank compiled programs by kernel gap x "
                             "measured seconds x padding waste (the "
                             "Pallas target list)")
    kr.add_argument("--compile-ledger", required=True,
                    help="compile_ledger.jsonl or the dir containing "
                         "it (spark.rapids.tpu.compile.ledgerDir)")
    kr.add_argument("--estimator-ledger", required=True,
                    help="estimator_ledger.jsonl or the dir containing "
                         "it (spark.rapids.tpu.regress.historyDir)")
    kr.add_argument("--top", type=int, default=10,
                    help="rows per ranking section")
    kr.add_argument("--tolerance", type=float, default=8.0,
                    help="cost-model agreement ratio "
                         "(spark.rapids.tpu.xsan.costTolerance)")
    kr.add_argument("--json", action="store_true",
                    help="emit the aggregate as JSON instead of text")
    er = sub.add_parser("estimator-report",
                        help="aggregate the estimator observatory "
                             "ledger into the planner calibration "
                             "report")
    er.add_argument("--ledger", required=True,
                    help="estimator_ledger.jsonl or the history dir "
                         "containing it "
                         "(spark.rapids.tpu.regress.historyDir)")
    er.add_argument("--top", type=int, default=10,
                    help="rows per ranking section")
    er.add_argument("--json", action="store_true",
                    help="emit the aggregate as JSON instead of text")
    pw = sub.add_parser("prewarm",
                        help="replay the top-K ledger program recipes "
                             "to populate the persistent compile cache "
                             "out-of-band")
    pw.add_argument("--ledger", required=True,
                    help="compile_ledger.jsonl or the history dir "
                         "containing it (recipes live in its programs/ "
                         "subdirectory)")
    pw.add_argument("--top", type=int, default=32,
                    help="how many programs to replay, ranked by "
                         "cumulative compile seconds")
    pw.add_argument("--cache-dir", default=None,
                    help="persistent XLA compile cache to populate "
                         "(spark.rapids.tpu.jit.persistentCacheDir); "
                         "without it the replay only validates recipes")
    tp = sub.add_parser("top",
                        help="live in-flight query view (phase, "
                             "progress, ETA, deepest open operator, "
                             "watchdog flags) from a running engine's "
                             "GET /queries endpoint")
    tp.add_argument("--url", default="127.0.0.1:9090",
                    help="health endpoint host:port or full URL "
                         "(spark.rapids.tpu.metrics.port)")
    tp.add_argument("--watch", action="store_true",
                    help="refresh in place every --interval seconds "
                         "until Ctrl-C (default: one snapshot)")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="refresh period with --watch (seconds)")
    tp.add_argument("--json", action="store_true",
                    help="emit the raw /queries JSON instead of the "
                         "table")
    pm = sub.add_parser("postmortem",
                        help="render a failure black-box bundle "
                             "(failing operator, tenant, HBM occupancy "
                             "at failure time)")
    pm.add_argument("target",
                    help="a pm_*.json bundle, or a directory (history "
                         "dir or its postmortems/ subdir) — renders "
                         "the newest bundle(s)")
    pm.add_argument("--json", action="store_true",
                    help="emit the raw bundle JSON instead of the "
                         "report")
    pm.add_argument("--last", type=int, default=1,
                    help="with a directory: render the newest N "
                         "bundles (default 1)")
    args = p.parse_args(argv)

    if args.cmd == "qualification":
        from .qualification import format_summary, qualify
        results = qualify(args.logs, args.output)
        sys.stdout.write(format_summary(results))
    elif args.cmd == "profiling":
        from .profiling import profile
        reports = profile(args.logs, args.output, compare=args.compare)
        sys.stdout.write(f"profiled {len(reports)} application(s) -> "
                         f"{args.output}\n")
        if args.accuracy:
            from .eventlog import find_event_logs, parse_event_log
            from .profiling import format_accuracy
            for log in find_event_logs(args.logs):
                sys.stdout.write(format_accuracy(parse_event_log(log)))
    elif args.cmd == "trace":
        return _run_trace_export(args.log, args.export, args.output,
                                 args.sql, merged=args.merged)
    elif args.cmd == "fleet":
        return _run_fleet_summary(args.log, args.sql,
                                  as_json=args.json)
    elif args.cmd == "regress":
        if args.record is None and not args.check:
            p.error("regress needs --record and/or --check")
        return _run_regress(args.history, args.record, args.check,
                            args.wall_threshold, label=args.label)
    elif args.cmd == "compile-report":
        from .compile_report import run_compile_report
        return run_compile_report(args.ledger, top=args.top,
                                  as_json=args.json)
    elif args.cmd == "tail-report":
        from .tail_report import run_tail_report
        return run_tail_report(args.ledger, top=args.top,
                               as_json=args.json)
    elif args.cmd == "kernel-report":
        from .kernel_report import run_kernel_report
        return run_kernel_report(args.compile_ledger,
                                 args.estimator_ledger, top=args.top,
                                 as_json=args.json,
                                 tolerance=args.tolerance)
    elif args.cmd == "estimator-report":
        from .estimator_report import run_estimator_report
        return run_estimator_report(args.ledger, top=args.top,
                                    as_json=args.json)
    elif args.cmd == "prewarm":
        return _run_prewarm(args.ledger, args.top, args.cache_dir)
    elif args.cmd == "top":
        from .top import run_top
        return run_top(args.url, interval=args.interval,
                       watch=args.watch, as_json=args.json)
    elif args.cmd == "postmortem":
        return _run_postmortem(args.target, as_json=args.json,
                               last=args.last)
    else:
        if args.lock_graph:
            return _run_lock_graph(args.output)
        if args.raise_graph:
            return _run_raise_graph(args.output)
        if args.determinism and not args.plan:
            return _run_determinism_artifact(args.output)
        if args.plan:
            return _run_plan_lint(args.plan, infer=args.infer,
                                  memsan=args.memsan,
                                  determinism=args.determinism)
        # --repo is the default lint mode
        return _run_repo_lint(args.baseline or _default_baseline(),
                              args.update_baseline)
    return 0


if __name__ == "__main__":
    sys.exit(main())
