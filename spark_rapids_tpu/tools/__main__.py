"""CLI for the offline tools (ref QualificationMain / ProfileMain):

    python -m spark_rapids_tpu.tools qualification <eventlogs...> [-o DIR]
    python -m spark_rapids_tpu.tools profiling     <eventlogs...> [-o DIR] [-c] [--accuracy]
    python -m spark_rapids_tpu.tools trace         <eventlog> [--export chrome|text] [-o FILE]
    python -m spark_rapids_tpu.tools lint --repo   [--baseline FILE]
    python -m spark_rapids_tpu.tools lint --plan   <fixture.py...> [--infer] [--memsan]

`profiling --accuracy` and `trace` consume the engine's SELF-emitted
event logs (spark.rapids.tpu.eventLog.dir): predicted-vs-actual
rows/bytes per operator, and the flight-recorder span tree exported as
Chrome-trace JSON (chrome://tracing / Perfetto) or a text timeline.

Lint fixtures are Python files defining ``plan_*()`` builders, each
returning ``(exec_root, conf_dict)`` — the checked-in golden bad plans
under tests/goldens/lint/ are the reference examples.
"""

import argparse
import sys


def _run_plan_lint(paths, infer=False, memsan=False):
    import runpy

    from ..analysis.diagnostics import format_diagnostics
    from ..analysis.plan_lint import lint_plan
    from ..config import RapidsConf

    any_error = False
    for path in paths:
        ns = runpy.run_path(path)
        builders = sorted(k for k in ns if k.startswith("plan_")
                          and callable(ns[k]))
        if not builders:
            sys.stderr.write(f"{path}: no plan_*() builders found\n")
            return 2
        for name in builders:
            root, conf_map = ns[name]()
            conf = RapidsConf(conf_map)
            diags = lint_plan(root, conf)
            sys.stdout.write(f"== {path}::{name}\n")
            if infer:
                # print the abstract interpreter's per-subtree states
                # (schema / residency / distribution / rows / liveness)
                from ..analysis.interp import format_states, infer_plan
                sys.stdout.write(format_states(root, infer_plan(root,
                                                                conf)))
            if memsan:
                # print the lifetime pass's per-subtree peak-byte bounds
                from ..analysis.lifetime import (analyze_memory,
                                                 format_memory)
                sys.stdout.write(format_memory(
                    root, analyze_memory(root, conf)))
            sys.stdout.write(format_diagnostics(diags))
            any_error |= any(d.is_error for d in diags)
    return 1 if any_error else 0


def _run_repo_lint(baseline_path, update):
    from ..analysis.diagnostics import format_diagnostics
    from ..analysis.repo_lint import (lint_repo, load_baseline,
                                      new_violations, save_baseline)

    diags = lint_repo()
    if update:
        save_baseline(baseline_path, diags)
        sys.stdout.write(f"baseline updated: {len(diags)} violation(s) "
                         f"-> {baseline_path}\n")
        return 0
    baseline = load_baseline(baseline_path)
    fresh = new_violations(diags, baseline)
    if fresh:
        sys.stdout.write(format_diagnostics(fresh))
        sys.stdout.write(f"{len(fresh)} NEW violation(s) not in baseline "
                         f"({baseline_path})\n")
        return 1
    sys.stdout.write(f"repo lint clean ({len(diags)} baselined "
                     f"violation(s))\n")
    return 0


def _run_trace_export(log, fmt, output, sql_id):
    import json

    from ..obs.export import spans_to_chrome, spans_to_text
    from .eventlog import parse_event_log

    app = parse_event_log(log)
    spans = [s for s in app.spans
             if sql_id is None or s.get("executionId") == sql_id]
    if not spans:
        sys.stderr.write(f"{log}: no flight-recorder spans "
                         f"(self-emitted logs only; was "
                         f"spark.rapids.tpu.eventLog.dir set?)\n")
        return 2
    if fmt == "text":
        text = spans_to_text(spans)
        if output:
            with open(output, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        return 0
    out_path = output or (log + ".trace.json")
    with open(out_path, "w") as f:
        json.dump(spans_to_chrome(spans), f)
    sys.stdout.write(f"{len(spans)} span(s) -> {out_path}\n")
    return 0


def _default_baseline():
    import os
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "devtools", "lint_baseline.txt")


def main(argv=None):
    p = argparse.ArgumentParser(prog="spark_rapids_tpu.tools")
    sub = p.add_subparsers(dest="cmd", required=True)
    q = sub.add_parser("qualification",
                       help="score apps for TPU acceleration benefit")
    q.add_argument("logs", nargs="+")
    q.add_argument("-o", "--output", default="qual_output")
    pr = sub.add_parser("profiling", help="profile apps from event logs")
    pr.add_argument("logs", nargs="+")
    pr.add_argument("-o", "--output", default="profile_output")
    pr.add_argument("-c", "--compare", action="store_true")
    pr.add_argument("-a", "--accuracy", action="store_true",
                    help="print the predicted-vs-actual report "
                         "(self-emitted logs embed the CBO/tmsan "
                         "model and measured rows/bytes per operator)")
    tr = sub.add_parser("trace",
                        help="export the flight-recorder span tree "
                             "from a self-emitted event log")
    tr.add_argument("log")
    tr.add_argument("--export", choices=["chrome", "text"],
                    default="chrome")
    tr.add_argument("-o", "--output", default=None,
                    help="output file (default: <log>.trace.json for "
                         "chrome; stdout for text)")
    tr.add_argument("--sql", type=int, default=None,
                    help="only this SQL execution id")
    li = sub.add_parser("lint",
                        help="static plan/repo analysis (tpulint)")
    li.add_argument("--repo", action="store_true",
                    help="run the repo invariant lint over the package")
    li.add_argument("--plan", nargs="*", metavar="FIXTURE",
                    help="lint physical plans built by plan_*() "
                         "functions in the given Python files")
    li.add_argument("--infer", action="store_true",
                    help="with --plan: print the abstract "
                         "interpreter's inferred per-subtree states "
                         "(schema/residency/partitioning/rows) before "
                         "the diagnostics")
    li.add_argument("--memsan", action="store_true",
                    help="with --plan: print the lifetime pass's "
                         "per-subtree peak-device-byte bounds "
                         "(hold/retained/peak vs the HBM budget) "
                         "before the diagnostics")
    li.add_argument("--baseline", default=None,
                    help="repo-lint baseline file "
                         "(default: devtools/lint_baseline.txt)")
    li.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current violations")
    args = p.parse_args(argv)

    if args.cmd == "qualification":
        from .qualification import format_summary, qualify
        results = qualify(args.logs, args.output)
        sys.stdout.write(format_summary(results))
    elif args.cmd == "profiling":
        from .profiling import profile
        reports = profile(args.logs, args.output, compare=args.compare)
        sys.stdout.write(f"profiled {len(reports)} application(s) -> "
                         f"{args.output}\n")
        if args.accuracy:
            from .eventlog import find_event_logs, parse_event_log
            from .profiling import format_accuracy
            for log in find_event_logs(args.logs):
                sys.stdout.write(format_accuracy(parse_event_log(log)))
    elif args.cmd == "trace":
        return _run_trace_export(args.log, args.export, args.output,
                                 args.sql)
    else:
        if args.plan:
            return _run_plan_lint(args.plan, infer=args.infer,
                                  memsan=args.memsan)
        # --repo is the default lint mode
        return _run_repo_lint(args.baseline or _default_baseline(),
                              args.update_baseline)
    return 0


if __name__ == "__main__":
    sys.exit(main())
