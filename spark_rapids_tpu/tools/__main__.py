"""CLI for the offline tools (ref QualificationMain / ProfileMain):

    python -m spark_rapids_tpu.tools qualification <eventlogs...> [-o DIR]
    python -m spark_rapids_tpu.tools profiling     <eventlogs...> [-o DIR] [-c]
"""

import argparse
import sys


def main(argv=None):
    p = argparse.ArgumentParser(prog="spark_rapids_tpu.tools")
    sub = p.add_subparsers(dest="cmd", required=True)
    q = sub.add_parser("qualification",
                       help="score apps for TPU acceleration benefit")
    q.add_argument("logs", nargs="+")
    q.add_argument("-o", "--output", default="qual_output")
    pr = sub.add_parser("profiling", help="profile apps from event logs")
    pr.add_argument("logs", nargs="+")
    pr.add_argument("-o", "--output", default="profile_output")
    pr.add_argument("-c", "--compare", action="store_true")
    args = p.parse_args(argv)

    if args.cmd == "qualification":
        from .qualification import format_summary, qualify
        results = qualify(args.logs, args.output)
        sys.stdout.write(format_summary(results))
    else:
        from .profiling import profile
        reports = profile(args.logs, args.output, compare=args.compare)
        sys.stdout.write(f"profiled {len(reports)} application(s) -> "
                         f"{args.output}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
