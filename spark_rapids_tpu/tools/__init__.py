"""Offline tooling over Spark event logs (ref tools/): qualification
(which apps benefit from acceleration) and profiling (metrics
aggregation, health check, timeline, plan graphs).  Hardware-neutral —
ported behavior, not code."""

from .eventlog import AppInfo, parse_event_log  # noqa: F401
from .qualification import qualify  # noqa: F401
from .profiling import profile  # noqa: F401
