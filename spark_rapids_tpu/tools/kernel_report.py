"""`tools kernel-report`: the ranked kernel-gap report.

ROADMAP's Pallas question is "which hand-written kernel pays for
itself first?".  This report answers it by joining the two ledgers the
engine already writes:

* the **compile ledger** (obs/compileprof.py) carries, per compiled
  program, XLA's own ``cost_analysis()`` bytes-accessed and the
  capacity-bucket signature — what the program *moves*;
* the **estimator ledger** (obs/estimator.py) carries, per operator
  span, measured seconds (``time_ns``) and the padding-waste bytes the
  tracer booked — what the program *costs* and how much of its traffic
  is bucket padding.

Per exec kind the report computes the speed-of-light gap (XLA bytes
over 2x the live bytes, analysis/hlocost.py), the measured pad-waste
ratio, and the projected seconds a fused dynamic-shape kernel saves —
then ranks kinds and the named fusion pipelines (hash build/probe,
filter->project) by that product.  The --hlo gate replays the golden
corpus and asserts the report ranks the grouped-aggregate and
hash-join programs on top with nonzero projected savings.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from ..analysis import hlocost


def load_estimator_ledger(path: str) -> List[Dict]:
    """Parse one estimator ledger (JSONL); `path` may be the file or a
    directory containing ``estimator_ledger.jsonl``.  Torn lines are
    skipped — both ledgers are append-under-crash telemetry."""
    from ..obs.estimator import ESTIMATOR_LEDGER_FILENAME
    if os.path.isdir(path):
        path = os.path.join(path, ESTIMATOR_LEDGER_FILENAME)
    records: List[Dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return records


# The planner's join variants (broadcast, shuffled) all execute the
# HashJoinExec kernel programs — the compile ledger books them under
# the base kind, so the measured side folds onto it too or the report
# would never join the two ledgers for a broadcast join.
KIND_ALIASES = {
    "BroadcastHashJoinExec": "HashJoinExec",
    "ShuffledHashJoinExec": "HashJoinExec",
}


def _kind(k: str) -> str:
    return KIND_ALIASES.get(k, k)


# the fused pipelines a hand-written kernel would collapse; each names
# the exec kinds whose measured time the fusion attacks together
FUSION_PIPELINES = (
    ("fused hash build/probe", ("HashJoinExec",)),
    ("fused filter->project", ("FilterExec", "ProjectExec")),
    ("fused grouped aggregate (sort+segment-reduce)",
     ("TpuHashAggregateExec",)),
)


def aggregate_kernel_report(compile_records: List[Dict],
                            observe_records: List[Dict],
                            tolerance: float = 8.0) -> Dict:
    """Join the two ledgers by exec kind -> the report's data model."""
    builds = [r for r in compile_records if r.get("event") == "build"]

    # measured side: seconds / bytes / padding per exec kind
    measured: Dict[str, Dict] = {}
    for r in observe_records:
        if r.get("event") != "observe":
            continue
        k = _kind(r.get("exec", "?"))
        m = measured.setdefault(k, {"seconds": 0.0, "spans": 0,
                                    "act_bytes": 0, "pad_bytes": 0})
        m["spans"] += 1
        if r.get("time_ns") is not None:
            m["seconds"] += r["time_ns"] / 1e9
        m["act_bytes"] += r.get("act_bytes") or 0
        # None = the span predates pad accounting; absent is absent
        if r.get("pad_waste_bytes") is not None:
            m["pad_bytes"] += r["pad_waste_bytes"]

    # compiled side: per-program XLA bytes vs one launch's bucket bytes
    compiled: Dict[str, Dict] = {}
    seen_progs: set = set()
    for r in builds:
        k = _kind(r.get("exec", "?"))
        c = compiled.setdefault(k, {"programs": 0, "builds": 0,
                                    "gap_sum": 0.0, "gap_n": 0})
        c["builds"] += 1
        pid = (k, r.get("hlo_hash") or r.get("key", ""))
        if pid in seen_progs:
            continue
        seen_progs.add(pid)
        c["programs"] += 1
        xb = hlocost.xla_bytes(r)
        base = hlocost.record_base_bytes(r)
        if xb is not None and base > 0:
            pad = measured.get(k, {})
            total = pad.get("act_bytes", 0)
            ratio = (pad.get("pad_bytes", 0) / total) if total else 0.0
            live = base * max(1.0 - ratio, 1e-6)
            c["gap_sum"] += hlocost.kernel_gap(xb, live)
            c["gap_n"] += 1

    rows: List[Dict] = []
    for k in sorted(set(measured) | set(compiled)):
        m = measured.get(k, {"seconds": 0.0, "spans": 0,
                             "act_bytes": 0, "pad_bytes": 0})
        c = compiled.get(k, {"programs": 0, "builds": 0,
                             "gap_sum": 0.0, "gap_n": 0})
        pad_ratio = (m["pad_bytes"] / m["act_bytes"]) \
            if m["act_bytes"] else 0.0
        gap = (c["gap_sum"] / c["gap_n"]) if c["gap_n"] else None
        savings = hlocost.projected_savings_s(
            m["seconds"], gap if gap is not None else 1.0, pad_ratio)
        rows.append({
            "exec": k, "measured_s": m["seconds"], "spans": m["spans"],
            "programs": c["programs"], "builds": c["builds"],
            "act_bytes": m["act_bytes"],
            "pad_waste_bytes": m["pad_bytes"],
            "pad_ratio": pad_ratio, "gap": gap,
            "projected_savings_s": savings,
        })
    rows.sort(key=lambda r: -r["projected_savings_s"])

    by_kind = {r["exec"]: r for r in rows}
    targets: List[Dict] = []
    for name, kinds in FUSION_PIPELINES:
        members = [by_kind[k] for k in kinds if k in by_kind]
        if not members:
            continue
        # the fusion erases the handoff on top of each member's own
        # gap, so its floor is the sum of the member savings
        targets.append({
            "target": name, "kinds": list(kinds),
            "measured_s": sum(m["measured_s"] for m in members),
            "projected_savings_s": sum(m["projected_savings_s"]
                                       for m in members),
        })
    targets.sort(key=lambda t: -t["projected_savings_s"])

    return {
        "kinds": rows,
        "targets": targets,
        "cost_model": hlocost.validate_model(builds, tolerance),
    }


def format_kernel_report(agg: Dict, top: int = 10) -> str:
    out: List[str] = []
    w = out.append
    w("== kernel gap report (tpuxsan) ==")
    cm = agg["cost_model"]
    pct = cm["agreement_pct"]
    w(f"cost model: {cm['agreed']}/{cm['checked']} programs within "
      f"{cm['tolerance']:.0f}x of XLA cost_analysis"
      + (f" ({pct:.0f}%)" if pct is not None else " (no cost data)"))
    w("")
    w(f"-- top {top} exec kinds by projected kernel savings --")
    for r in agg["kinds"][:top]:
        gap = f"{r['gap']:.1f}x" if r["gap"] is not None else "   ?"
        w(f"  {r['projected_savings_s']:8.3f}s  {r['exec']:24s} "
          f"measured={r['measured_s']:7.3f}s gap={gap:>6s} "
          f"pad={100 * r['pad_ratio']:4.1f}% "
          f"programs={r['programs']} spans={r['spans']}")
    w("")
    w("-- ranked fusion targets (the Pallas list) --")
    if not agg["targets"]:
        w("  none: no compiled programs observed")
    for t in agg["targets"][:top]:
        w(f"  {t['projected_savings_s']:8.3f}s  {t['target']:44s} "
          f"over {'+'.join(t['kinds'])}")
    return "\n".join(out) + "\n"


def run_kernel_report(compile_ledger: str, estimator_ledger: str,
                      top: int = 10, as_json: bool = False,
                      tolerance: float = 8.0, out=None) -> int:
    import sys
    out = out or sys.stdout
    from .compile_report import load_ledger
    try:
        compile_records = load_ledger(compile_ledger)
        observe_records = load_estimator_ledger(estimator_ledger)
    except OSError as ex:
        sys.stderr.write(f"kernel-report: {ex}\n")
        return 2
    agg = aggregate_kernel_report(compile_records, observe_records,
                                  tolerance=tolerance)
    if as_json:
        out.write(json.dumps(agg, indent=2, sort_keys=True,
                             default=str) + "\n")
    else:
        out.write(format_kernel_report(agg, top=top))
    return 0
