"""`tools estimator-report`: aggregate the estimator observatory's
cross-session ledger (obs/estimator.py) into the planner report the
feedback loop is tuned against:

* **Calibration** — observations, mean relative row/byte error and the
  calibration score (1/(1+mean row error)), plus the peak-HBM
  static-bound-vs-measured error admission tickets ride.
* **Worst offenders** — exec kinds ranked by cumulative row-estimate
  error: where the static model is most wrong and where feedback
  blending buys the most.
* **Re-plan decisions** — the `replan` events by (decision, cause):
  how often a misestimate was caught at an exchange boundary and what
  was done about it (strategy_switch / oc_repair / ticket_reprice).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List


def load_estimator_ledger(path: str) -> List[Dict]:
    """Parse one estimator ledger (JSONL).  `path` may be the file or
    a directory containing ``estimator_ledger.jsonl``.  Unparsable
    lines are skipped and counted — append-under-crash telemetry, a
    torn final line must not kill the report."""
    from ..obs.estimator import ESTIMATOR_LEDGER_FILENAME
    if os.path.isdir(path):
        path = os.path.join(path, ESTIMATOR_LEDGER_FILENAME)
    records: List[Dict] = []
    rejected = 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                rejected += 1
    if rejected:
        records.append({"event": "_rejected", "count": rejected})
    return records


def aggregate_estimator_ledger(records: List[Dict]) -> Dict:
    """One pass over ledger records -> the report's data model."""
    observes = [r for r in records if r.get("event") == "observe"]
    peaks = [r for r in records if r.get("event") == "observe_peak"]
    replans = [r for r in records if r.get("event") == "replan"]
    rejected = sum(r.get("count", 0) for r in records
                   if r.get("event") == "_rejected")

    rows_err_total = sum(r["rows_err"] for r in observes
                         if r.get("rows_err") is not None)
    bytes_err_total = sum(r["bytes_err"] for r in observes
                          if r.get("bytes_err") is not None)
    n = len(observes)
    mean_rows_err = rows_err_total / max(n, 1)

    by_exec: Dict[str, Dict] = {}
    sigs: set = set()
    for r in observes:
        sigs.add((r.get("exec", "?"), r.get("sig", "")))
        agg = by_exec.setdefault(
            r.get("exec", "?"),
            {"count": 0, "rows_err": 0.0, "bytes_err": 0.0})
        agg["count"] += 1
        agg["rows_err"] += r.get("rows_err") or 0.0
        agg["bytes_err"] += r.get("bytes_err") or 0.0

    peak_errs = [r["err"] for r in peaks if r.get("err") is not None]
    by_decision: Dict[str, int] = {}
    for r in replans:
        key = f"{r.get('decision', '?')}/{r.get('cause', '?')}"
        by_decision[key] = by_decision.get(key, 0) + 1

    return {
        "observations": n,
        "signatures": len(sigs),
        "rejected_lines": rejected,
        "mean_rows_err": round(mean_rows_err, 6),
        "mean_bytes_err": round(bytes_err_total / max(n, 1), 6),
        "calibration_score": round(1.0 / (1.0 + mean_rows_err), 6),
        "peak_observations": len(peaks),
        "mean_peak_err": round(sum(peak_errs)
                               / max(len(peak_errs), 1), 6),
        "worst_execs": sorted(
            ({"exec": k, **v,
              "mean_rows_err": round(v["rows_err"]
                                     / max(v["count"], 1), 6)}
             for k, v in by_exec.items()),
            key=lambda d: -d["rows_err"]),
        "replans": len(replans),
        "replans_by_decision": by_decision,
    }


def format_estimator_report(agg: Dict, top: int = 10) -> str:
    out: List[str] = []
    w = out.append
    w("== estimator observatory report ==")
    w(f"observations: {agg['observations']}  distinct signatures: "
      f"{agg['signatures']}")
    w(f"mean relative error: rows {agg['mean_rows_err']:.4f}  "
      f"bytes {agg['mean_bytes_err']:.4f}  calibration score "
      f"{agg['calibration_score']:.4f} (1.0 = clairvoyant)")
    if agg["peak_observations"]:
        w(f"peak-HBM bound: {agg['peak_observations']} "
          f"observation(s), mean |static-measured| error "
          f"{agg['mean_peak_err']:.4f}")
    if agg.get("rejected_lines"):
        w(f"note: {agg['rejected_lines']} unparsable ledger line(s) "
          f"skipped")
    w("")
    w(f"-- top {top} exec kinds by cumulative row-estimate error --")
    for e in agg["worst_execs"][:top]:
        w(f"  {e['rows_err']:10.4f}  {e['exec']:28s} "
          f"{e['count']:5d} obs  mean {e['mean_rows_err']:.4f}")
    w("")
    w("-- exchange-boundary re-plans --")
    if not agg["replans"]:
        w("  none recorded (feedback off, or every estimate held)")
    for key, count in sorted(agg["replans_by_decision"].items(),
                             key=lambda kv: -kv[1]):
        w(f"  {count:5d}  {key}")
    return "\n".join(out) + "\n"


def run_estimator_report(ledger: str, top: int = 10,
                         as_json: bool = False, out=None) -> int:
    import sys
    out = out or sys.stdout
    try:
        records = load_estimator_ledger(ledger)
    except OSError as ex:
        sys.stderr.write(f"estimator-report: {ex}\n")
        return 2
    agg = aggregate_estimator_ledger(records)
    if not agg["observations"]:
        sys.stderr.write(
            "estimator-report: ledger has no observe records (was "
            "spark.rapids.tpu.regress.historyDir set?)\n")
        return 2
    if as_json:
        out.write(json.dumps(agg, indent=1, sort_keys=True,
                             default=str) + "\n")
    else:
        out.write(format_estimator_report(agg, top=top))
    return 0
