"""Registry-derived qualification data.

The reference's qualification tool loads GENERATED per-operator data
(supportedExecs.csv / supportedExprs.csv / operatorsScore.csv, consumed
by tools/.../qualification/PluginTypeChecker.scala) so its scoring can
never drift from what the plugin accepts.  Here the same data is read
LIVE from the engine registries (plan/overrides.py EXEC_SIGS +
EXPR_RULES — the tables the plan-rewrite engine itself consults), plus a
per-exec speedup-factor table calibrated against bench.py's suite
ratios (the operatorsScore analog)."""

from __future__ import annotations

import re
from functools import lru_cache
from typing import Dict, FrozenSet, List, Tuple

# Spark physical-plan nodeName prefix -> (engine exec class, speedup
# factor).  A row only counts as supported when its engine class is
# actually registered in EXEC_SIGS, so deleting an exec from the engine
# automatically downgrades qualification scores.
_EXEC_MAP: List[Tuple[str, str, float]] = [
    ("HashAggregate", "CpuHashAggregateExec", 3.0),
    ("ObjectHashAggregate", "CpuHashAggregateExec", 3.0),
    ("SortAggregate", "CpuHashAggregateExec", 3.0),
    ("SortMergeJoin", "CpuJoinExec", 3.0),
    ("ShuffledHashJoin", "CpuJoinExec", 3.0),
    ("BroadcastHashJoin", "BroadcastHashJoinExec", 3.0),
    ("BroadcastNestedLoopJoin", "BroadcastNestedLoopJoinExec", 2.0),
    ("CartesianProduct", "NestedLoopJoinExec", 2.0),
    ("TakeOrderedAndProject", "SortExec", 2.5),
    ("Sort", "SortExec", 2.5),
    ("Window", "WindowExec", 3.0),
    ("Project", "ProjectExec", 2.0),
    ("Filter", "FilterExec", 2.0),
    ("Expand", "ExpandExec", 2.0),
    ("Generate", "GenerateExec", 2.0),
    ("Union", "UnionExec", 1.5),
    ("Range", "RangeExec", 1.5),
    ("Sample", "SampleExec", 1.5),
    ("GlobalLimit", "GlobalLimitExec", 1.0),
    ("LocalLimit", "LocalLimitExec", 1.0),
    ("CollectLimit", "LocalLimitExec", 1.0),
    ("Coalesce", "CoalesceBatchesExec", 1.0),
    ("BroadcastExchange", "BroadcastExchangeExec", 2.0),
    ("ShuffleExchange", "ShuffleExchangeExec", 2.5),
    ("Exchange", "ShuffleExchangeExec", 2.5),
]

# wrapper/bookkeeping nodes: no engine exec needed; they neither count
# toward nor block a stage
TRANSPARENT_EXECS = frozenset({
    "WholeStageCodegen", "InputAdapter", "ColumnarToRow", "RowToColumnar",
    "AdaptiveSparkPlan", "ReusedExchange", "ReusedSubquery", "Subquery",
    "SubqueryBroadcast", "AQEShuffleRead", "CustomShuffleReader",
    "LocalTableScan", "SerializeFromObject", "DeserializeToObject",
})

# engine expression class -> the Spark SQL names it prints in plan
# simple-strings (where the lowercased class name differs)
_EXPR_ALIASES: Dict[str, Tuple[str, ...]] = {
    "Average": ("avg", "mean"),
    "StringReplace": ("replace",),
    "StringRepeat": ("repeat",),
    "Trim": ("trim",),
    "TrimLeft": ("ltrim",),
    "TrimRight": ("rtrim",),
    "StringLPad": ("lpad",),
    "StringRPad": ("rpad",),
    "StringLocate": ("locate", "position"),
    "SubstringIndex": ("substring_index",),
    "RegExpExtract": ("regexp_extract",),
    "RegExpReplace": ("regexp_replace",),
    "RLike": ("rlike",),
    "StringSplit": ("split",),
    "ConcatWs": ("concat_ws",),
    "GetJsonObject": ("get_json_object",),
    "DayOfMonth": ("dayofmonth", "day"),
    "DayOfWeek": ("dayofweek",),
    "DayOfYear": ("dayofyear",),
    "WeekDay": ("weekday",),
    "TruncDate": ("trunc",),
    "DateAdd": ("date_add",),
    "DateSub": ("date_sub",),
    "AddMonths": ("add_months",),
    "LastDay": ("last_day",),
    "DateDiff": ("datediff",),
    "FromUnixTime": ("from_unixtime",),
    "ToUnixTimestamp": ("to_unix_timestamp",),
    "UnixTimestamp": ("unix_timestamp",),
    "DateFormatClass": ("date_format",),
    "TimeAdd": ("time_add",),
    "TimeWindow": ("window",),
    "Murmur3Hash": ("hash",),
    "HiveHash": ("hive_hash",),
    "MonotonicallyIncreasingID": ("monotonically_increasing_id",),
    "SparkPartitionID": ("spark_partition_id",),
    "InputFileName": ("input_file_name",),
    "InputFileBlockStart": ("input_file_block_start",),
    "InputFileBlockLength": ("input_file_block_length",),
    "RowNumber": ("row_number",),
    "DenseRank": ("dense_rank",),
    "PercentRank": ("percent_rank",),
    "CumeDist": ("cume_dist",),
    "NTile": ("ntile",),
    "WindowSpec": ("windowspecdefinition",),
    "CollectList": ("collect_list",),
    "CollectSet": ("collect_set",),
    "StddevPop": ("stddev_pop",),
    "StddevSamp": ("stddev_samp", "stddev", "std"),
    "VariancePop": ("var_pop",),
    "VarianceSamp": ("var_samp", "variance"),
    "ApproximatePercentile": ("approx_percentile",
                              "percentile_approx"),
    "PivotFirst": ("pivotfirst",),
    "NormalizeNaNAndZero": ("normalizenanandzero", "knownfloatingpointnormalized"),
    "CreateNamedStruct": ("named_struct", "struct"),
    "CreateArray": ("array",),
    "CreateMap": ("map",),
    "GetStructField": ("getstructfield",),
    "GetArrayItem": ("getarrayitem",),
    "ElementAt": ("element_at",),
    "GetMapValue": ("getmapvalue",),
    "MapKeys": ("map_keys",),
    "MapValues": ("map_values",),
    "MapEntries": ("map_entries",),
    "TransformKeys": ("transform_keys",),
    "TransformValues": ("transform_values",),
    "ArrayTransform": ("transform",),
    "ArrayFilter": ("filter",),
    "ArrayExists": ("exists",),
    "ArrayForAll": ("forall",),
    "ArrayContains": ("array_contains",),
    "ArrayMax": ("array_max",),
    "ArrayMin": ("array_min",),
    "SortArray": ("sort_array",),
    "PosExplode": ("posexplode",),
    "IntegralDivide": ("div",),
    "UnaryMinus": ("negative",),
    "UnaryPositive": ("positive",),
    "Remainder": ("mod",),
    "BitwiseNot": ("not",),
    "ShiftLeft": ("shiftleft",),
    "ShiftRight": ("shiftright",),
    "ShiftRightUnsigned": ("shiftrightunsigned",),
    "Logarithm": ("log",),
    "ToDegrees": ("degrees",),
    "ToRadians": ("radians",),
    "Bound": ("boundreference",),
    "EqualTo": ("equalto",),
    "EqualNullSafe": ("equalnullsafe",),
    "NullIf": ("nullif",),
    "Nvl": ("nvl", "ifnull"),
    "NaNvl": ("nanvl",),
    "AtLeastNNonNulls": ("atleastnnonnulls",),
    "Length": ("length", "char_length", "character_length"),
    "BitLength": ("bit_length",),
    "InitCap": ("initcap",),
    "Like": ("like",),
    "ScalarSubquery": ("scalar-subquery", "scalarsubquery"),
}

# tokens Spark prints structurally that never decide supportability
NEUTRAL_TOKENS = frozenset({
    "keys", "functions", "output", "aggregate", "arraybuffer", "list",
    "some", "none", "cast", "ansi_cast", "promote_precision",
    "check_overflow", "checkoverflow", "specifiedwindowframe",
    "windowexpression", "sortorder", "exprid", "decimal", "dynamicpruning",
    "unscaled", "unscaledvalue", "makedecimal", "staticinvoke",
    "knownnotnull", "aggregateexpression", "alias", "attributereference",
})


@lru_cache(maxsize=1)
def supported_exec_factors() -> Dict[str, float]:
    """Spark nodeName prefix -> speedup factor, for execs whose engine
    class is registered right now."""
    from ..plan.overrides import EXEC_SIGS
    registered = {c.__name__ for c in EXEC_SIGS}
    return {spark: factor for spark, engine, factor in _EXEC_MAP
            if engine in registered}


@lru_cache(maxsize=1)
def supported_expr_tokens() -> FrozenSet[str]:
    """Lowercased Spark function tokens the expression registry covers."""
    from ..plan.overrides import EXPR_RULES
    toks = set()
    for cls in EXPR_RULES:
        name = cls.__name__
        toks.add(name.lower())
        # CamelCase -> snake_case (DenseRank -> dense_rank)
        toks.add(re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower())
        toks.update(_EXPR_ALIASES.get(name, ()))
    return frozenset(toks)


_TOKEN_RE = re.compile(r"([A-Za-z_][A-Za-z0-9_]*)\s*\(")


def unsupported_expr_tokens(simple_string: str) -> List[str]:
    """Function-shaped tokens in a plan node's simple string that neither
    the expression registry nor the structural-token list covers — the
    node would fall back (the reference parses expressions out of plan
    strings the same way, PluginTypeChecker.getNotSupportedExprs)."""
    known = supported_expr_tokens()
    execs = {s.lower() for s in supported_exec_factors()}
    execs |= {s.lower() for s in TRANSPARENT_EXECS}
    out = []
    for tok in _TOKEN_RE.findall(simple_string):
        t = tok.lower()
        if t.startswith("partial_") or t.startswith("merge_") or \
                t.startswith("finalmerge_"):
            t = t.split("_", 1)[1]
        if t.startswith("gpu") or t.startswith("tpu"):
            t = t[3:]
        if t in known or t in NEUTRAL_TOKENS or t in execs:
            continue
        out.append(tok)
    return out
