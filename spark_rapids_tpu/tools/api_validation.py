"""Registry/implementation consistency audit.

Ref: api_validation/ (ApiValidation.scala audits constructor-signature
parity between Spark execs and their Gpu replacements across versions).
The TPU-build analog audits the live registries for the drift that
actually bites this codebase:

  * every expression class with an ExprRule must have an evaluator
    registered (a rule without an evaluator converts to TPU and then
    crashes at runtime);
  * every exec class in EXEC_SIGS must implement the operator contract
    (output_names/output_types/execute_partition);
  * every aggregate function must declare matching update/buffer/merge
    arity.

Run: python -m spark_rapids_tpu.tools.api_validation
"""

from __future__ import annotations

import inspect
from typing import List


def validate() -> List[str]:
    problems: List[str] = []
    from ..expr import aggregates as agg
    from ..expr.core import (AttributeReference, BoundReference, Expression,
                             Literal, _EVALUATORS)
    from ..plan.overrides import EXEC_SIGS, EXPR_RULES

    no_evaluator_ok = {
        # evaluated structurally, not via the evaluator registry
        "Alias", "AttributeReference", "BoundReference", "Literal",
        "AggregateExpression", "LambdaFunction", "Cast",
        # window machinery evaluates inside WindowExec's sorted layout
        "WindowExpression", "WindowSpec", "RowNumber", "Rank",
        "DenseRank", "PercentRank", "CumeDist", "NTile", "Lead", "Lag",
        # resolved driver-side to a literal / extracted to a worker exec
        "ScalarSubquery", "PythonUDF",
        # host-only families are tagged off the device; their rules exist
        # so explain and docs state the reason
        "InputFileName", "DateFormatClass", "DateAddInterval",
    }
    from ..expr.collection import Generator
    for cls in EXPR_RULES:
        if issubclass(cls, agg.AggregateFunction):
            continue  # aggregates evaluate through update/merge/evaluate
        if issubclass(cls, Generator):
            continue  # generators evaluate inside GenerateExec
        if cls.__name__ in no_evaluator_ok:
            continue
        if cls not in _EVALUATORS and not any(
                base in _EVALUATORS for base in cls.__mro__[1:]):
            has_eval = any(
                getattr(m, "__self__", None) is None and n == "eval"
                and m.__qualname__.startswith(cls.__name__)
                for n, m in inspect.getmembers(cls, inspect.isfunction))
            if not has_eval:
                problems.append(
                    f"expression {cls.__name__} has a rule but no "
                    f"registered evaluator")

    for cls in EXEC_SIGS:
        for attr in ("output_names", "output_types"):
            if not hasattr(cls, attr):
                problems.append(f"exec {cls.__name__} missing {attr}")
        fn = getattr(cls, "execute_partition", None)
        if fn is None:
            problems.append(
                f"exec {cls.__name__} missing execute_partition")

    for cls in EXPR_RULES:
        if not issubclass(cls, agg.AggregateFunction) or \
                cls is agg.AggregateFunction:
            continue
        if inspect.isabstract(cls):
            continue
        try:
            inst = cls.__new__(cls)
            bt = cls.buffer_types
            mo = cls.merge_ops
        except Exception:
            continue
        # arity parity is checked structurally on a best-effort instance
        try:
            from ..expr.core import AttributeReference as A
            probe = cls(A("x", __import__(
                "spark_rapids_tpu.types", fromlist=["LONG"]).LONG)) \
                if _arity(cls) == 1 else cls()
            if len(probe.buffer_types()) != len(probe.merge_ops()):
                problems.append(
                    f"aggregate {cls.__name__}: buffer_types/merge_ops "
                    f"arity mismatch")
            if len(probe.update()) != len(probe.buffer_types()):
                problems.append(
                    f"aggregate {cls.__name__}: update/buffer arity "
                    f"mismatch")
        except Exception:
            pass  # constructors needing special args are exercised in tests
    return problems


def _arity(cls) -> int:
    try:
        sig = inspect.signature(cls.__init__)
        return len([p for p in sig.parameters.values()
                    if p.name != "self" and
                    p.default is inspect.Parameter.empty])
    except (TypeError, ValueError):
        return 0


if __name__ == "__main__":
    import sys
    issues = validate()
    for i in issues:
        print("PROBLEM:", i)
    print(f"{len(issues)} problem(s) found")
    sys.exit(1 if issues else 0)
