"""`tools compile-report`: aggregate the compile observatory's
cross-session ledger (obs/compileprof.py) into the evidence ROADMAP
item 1 needs to design the persistent program cache:

* **Totals + attribution coverage** — how much wall compile time the
  ledger explains, split trace/lower vs backend-compile, and whether
  every build carries a classified cause (the acceptance bar is >= 95%
  attribution with zero cause-less builds).
* **Top programs by compile cost** — where the seconds actually went,
  by (exec kind, key, shapes).
* **Churn offenders** — exec kinds ranked by compile seconds burned on
  shape_churn / dtype_churn / eviction_refault misses: recompiles a
  better cache key or bucket canonicalization would erase.
* **Dedupe projection** — group programs by their bucket-canonical
  identity (exec, canonical key hash, dtype signature): "N programs
  collapse to M; projected warm-session savings = X s" — the direct
  measurement of what keying the cache on (exec kind, dtype layout,
  capacity bucket) buys.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional


def load_ledger(path: str) -> List[Dict]:
    """Parse one compile ledger (JSONL).  `path` may be the file or a
    directory containing ``compile_ledger.jsonl``.  Unparsable lines
    are skipped and counted (the ledger is append-under-crash telemetry,
    a torn final line must not kill the report)."""
    from ..obs.compileprof import LEDGER_FILENAME
    if os.path.isdir(path):
        path = os.path.join(path, LEDGER_FILENAME)
    records: List[Dict] = []
    rejected = 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                rejected += 1
    if rejected:
        records.append({"event": "_rejected", "count": rejected})
    return records


def aggregate_ledger(records: List[Dict]) -> Dict:
    """One pass over ledger records -> the report's data model."""
    builds = [r for r in records if r.get("event") == "build"]
    evicts = [r for r in records if r.get("event") == "evict"]
    rejected = sum(r.get("count", 0) for r in records
                   if r.get("event") == "_rejected")

    total_s = sum(r.get("total_s") or 0.0 for r in builds)
    trace_s = sum(r.get("trace_s") or 0.0 for r in builds)
    compile_s = sum(r.get("compile_s") or 0.0 for r in builds)
    # attribution: a build is fully attributed when it carries an exec
    # kind, a cause and a split (trace_s/compile_s); AOT-fallback builds
    # carry total_s only
    attributed_s = sum(r.get("total_s") or 0.0 for r in builds
                       if r.get("exec") and r.get("cause"))
    causeless = [r for r in builds if not r.get("cause")]
    by_cause: Dict[str, Dict] = {}
    for r in builds:
        c = r.get("cause") or "?"
        agg = by_cause.setdefault(c, {"count": 0, "total_s": 0.0})
        agg["count"] += 1
        agg["total_s"] += r.get("total_s") or 0.0

    # distinct programs: last build wins (rebuilds refresh timing)
    programs: Dict[tuple, Dict] = {}
    prog_counts: Dict[tuple, int] = {}
    prog_seconds: Dict[tuple, float] = {}
    for r in builds:
        pid = (r.get("key", ""), r.get("shape", ""))
        programs[pid] = r
        prog_counts[pid] = prog_counts.get(pid, 0) + 1
        prog_seconds[pid] = prog_seconds.get(pid, 0.0) + \
            (r.get("total_s") or 0.0)

    top = sorted(programs.items(),
                 key=lambda kv: -prog_seconds[kv[0]])

    # churn: compile seconds burned on misses a better cache key erases
    churn: Dict[str, Dict] = {}
    for r in builds:
        if r.get("cause") in ("shape_churn", "dtype_churn",
                              "eviction_refault"):
            agg = churn.setdefault(
                r.get("exec", "?"),
                {"count": 0, "total_s": 0.0, "causes": {}})
            agg["count"] += 1
            agg["total_s"] += r.get("total_s") or 0.0
            c = r["cause"]
            agg["causes"][c] = agg["causes"].get(c, 0) + 1

    # dedupe projection: canonical identity = (exec, canon_key, dtypes)
    families: Dict[tuple, List[tuple]] = {}
    for pid, r in programs.items():
        fam = (r.get("exec", ""), r.get("canon_key", ""),
               tuple(r.get("dtypes") or ()))
        families.setdefault(fam, []).append(pid)
    saved_s = 0.0
    for members in families.values():
        if len(members) > 1:
            secs = sorted((prog_seconds[p] for p in members),
                          reverse=True)
            saved_s += sum(secs[1:])

    return {
        "builds": len(builds),
        "evictions": len(evicts),
        "rejected_lines": rejected,
        "total_s": total_s,
        "trace_s": trace_s,
        "compile_s": compile_s,
        "attributed_s": attributed_s,
        "attribution_pct": (100.0 * attributed_s / total_s)
        if total_s else 100.0,
        "causeless_builds": len(causeless),
        "by_cause": by_cause,
        "distinct_programs": len(programs),
        "top_programs": [
            {"exec": r.get("exec"), "key": pid[0], "shape": pid[1],
             "cause": r.get("cause"),
             "builds": prog_counts[pid],
             "total_s": prog_seconds[pid],
             "hlo_bytes": r.get("hlo_bytes", 0),
             "caps": r.get("caps"), "dtypes": r.get("dtypes")}
            for pid, r in top],
        "churn_offenders": sorted(
            ({"exec": k, **v} for k, v in churn.items()),
            key=lambda d: -d["total_s"]),
        "canonical_families": len(families),
        "projected_savings_s": saved_s,
    }


def format_report(agg: Dict, top: int = 10) -> str:
    out: List[str] = []
    w = out.append
    w("== compile observatory report ==")
    w(f"builds: {agg['builds']}  distinct programs: "
      f"{agg['distinct_programs']}  evictions: {agg['evictions']}")
    w(f"wall compile time: {agg['total_s']:.2f}s "
      f"(trace/lower {agg['trace_s']:.2f}s + backend compile "
      f"{agg['compile_s']:.2f}s)")
    w(f"attribution: {agg['attribution_pct']:.1f}% of wall compile "
      f"time carries (exec, cause); {agg['causeless_builds']} "
      f"cause-less build(s)")
    if agg.get("rejected_lines"):
        w(f"note: {agg['rejected_lines']} unparsable ledger line(s) "
          f"skipped")
    w("")
    w("-- misses by cause --")
    for c, v in sorted(agg["by_cause"].items(),
                       key=lambda kv: -kv[1]["total_s"]):
        w(f"  {c:18s} {v['count']:5d} build(s)  "
          f"{v['total_s']:8.2f}s")
    w("")
    w(f"-- top {top} programs by compile cost --")
    for p in agg["top_programs"][:top]:
        caps = ",".join("x".join(map(str, s))
                        for s in (p.get("caps") or [])[:4]) or "-"
        w(f"  {p['total_s']:8.2f}s  {p['exec']:24s} "
          f"cause={p['cause']:16s} builds={p['builds']} "
          f"key={p['key']} caps=[{caps}]")
    w("")
    w("-- churn offenders (recompiles a better cache key erases) --")
    if not agg["churn_offenders"]:
        w("  none: every build was a genuinely new program")
    for c in agg["churn_offenders"][:top]:
        causes = " ".join(f"{k}={v}" for k, v in
                          sorted(c["causes"].items()))
        w(f"  {c['total_s']:8.2f}s  {c['exec']:24s} "
          f"{c['count']} build(s)  {causes}")
    w("")
    n, m = agg["distinct_programs"], agg["canonical_families"]
    w("-- dedupe projection (bucket canonicalization) --")
    w(f"  {n} program(s) collapse to {m} under bucket "
      f"canonicalization; projected warm-session savings = "
      f"{agg['projected_savings_s']:.2f}s")
    return "\n".join(out) + "\n"


def run_compile_report(ledger: str, top: int = 10,
                       as_json: bool = False,
                       out=None) -> int:
    import sys
    out = out or sys.stdout
    try:
        records = load_ledger(ledger)
    except OSError as ex:
        sys.stderr.write(f"compile-report: {ex}\n")
        return 2
    agg = aggregate_ledger(records)
    if not agg["builds"]:
        sys.stderr.write(
            "compile-report: ledger has no build records (was "
            "spark.rapids.tpu.compile.ledgerDir or "
            "spark.rapids.tpu.regress.historyDir set?)\n")
        return 2
    if as_json:
        out.write(json.dumps(agg, indent=1, sort_keys=True,
                             default=str) + "\n")
    else:
        out.write(format_report(agg, top=top))
    return 0
