"""Profiling tool: metrics aggregation, health check, comparison,
timeline and plan-graph generation from Spark event logs.

Ref: tools/.../profiling/{ProfileMain,Profiler,Analysis,
CollectInformation,HealthCheck,CompareApplications,GenerateTimeline,
GenerateDot}.scala.
"""

from __future__ import annotations

import os
from statistics import median
from typing import Dict, List, Optional

from .eventlog import AppInfo, PlanNode, find_event_logs, parse_event_log


# ---------------------------------------------------------------------------
# Analysis (ref Analysis.scala jobAndStageMetricsAggregation /
# sqlMetricsAggregation)
# ---------------------------------------------------------------------------

def app_information(app: AppInfo) -> Dict:
    return {
        "appName": app.app_name, "appId": app.app_id,
        "sparkVersion": app.spark_version,
        "startTime": app.start_time, "endTime": app.end_time,
        "duration": app.app_duration,
        "durationEstimated": app.duration_estimated,
        "numExecutors": len(app.executors),
        "totalCores": sum(e.get("cores", 0) for e in
                          app.executors.values()),
    }


def stage_aggregates(app: AppInfo) -> List[Dict]:
    out = []
    for (sid, attempt), st in sorted(app.stages.items()):
        ts = [t for t in app.tasks if t.stage_id == sid]
        durs = [t.duration for t in ts] or [0]
        out.append({
            "stageId": sid, "attempt": attempt, "name": st.name[:60],
            "numTasks": st.num_tasks, "duration": st.duration,
            "taskDurMin": min(durs), "taskDurMed": int(median(durs)),
            "taskDurMax": max(durs),
            "inputBytes": sum(t.input_bytes for t in ts),
            "outputBytes": sum(t.output_bytes for t in ts),
            "shuffleRead": sum(t.shuffle_read_bytes for t in ts),
            "shuffleWrite": sum(t.shuffle_write_bytes for t in ts),
            "memSpilled": sum(t.memory_spilled for t in ts),
            "diskSpilled": sum(t.disk_spilled for t in ts),
            "gcTime": sum(t.gc_time for t in ts),
        })
    return out


def sql_aggregates(app: AppInfo) -> List[Dict]:
    out = []
    for sql_id, sx in sorted(app.sql_executions.items()):
        out.append({
            "sqlId": sql_id,
            "description": sx.description[:80],
            "duration": sx.duration,
            "taskDuration": app.sql_task_duration(sql_id),
            "failed": sx.failed,
        })
    return out


# ---------------------------------------------------------------------------
# Health check (ref HealthCheck.scala)
# ---------------------------------------------------------------------------

def health_check(app: AppInfo) -> Dict[str, List]:
    failed_tasks = [
        {"taskId": t.task_id, "stageId": t.stage_id,
         "attempt": t.attempt} for t in app.tasks if t.failed]
    failed_stages = [
        {"stageId": sid, "attempt": at, "reason": (st.failure_reason
                                                   or "")[:120]}
        for (sid, at), st in sorted(app.stages.items())
        if st.failure_reason]
    failed_jobs = [
        {"jobId": jid, "result": j.get("result")}
        for jid, j in sorted(app.jobs.items())
        if j.get("result") not in (None, "JobSucceeded")]
    return {"failedTasks": failed_tasks, "failedStages": failed_stages,
            "failedJobs": failed_jobs}


# ---------------------------------------------------------------------------
# Comparison (ref CompareApplications.scala)
# ---------------------------------------------------------------------------

def compare_apps(apps: List[AppInfo]) -> List[Dict]:
    rows = []
    for i, app in enumerate(apps):
        info = app_information(app)
        info["runIndex"] = i
        info["sqlDuration"] = sum(s.duration
                                  for s in app.sql_executions.values())
        info["taskDuration"] = sum(t.run_time for t in app.tasks)
        rows.append(info)
    return rows


# ---------------------------------------------------------------------------
# Timeline (ref GenerateTimeline.scala — emits an SVG lane chart)
# ---------------------------------------------------------------------------

def generate_timeline(app: AppInfo, path: str) -> None:
    t0 = app.start_time or min((t.launch for t in app.tasks), default=0)
    t1 = app.end_time or max((t.finish for t in app.tasks), default=t0 + 1)
    span = max(t1 - t0, 1)
    width, row_h = 1000, 14
    lanes = sorted({t.executor_id for t in app.tasks})
    height = row_h * (len(lanes) + 2)
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
             f'height="{height}">']
    for li, ex in enumerate(lanes):
        y = row_h * (li + 1)
        parts.append(f'<text x="2" y="{y + 10}" font-size="9">exec '
                     f'{ex}</text>')
        for t in app.tasks:
            if t.executor_id != ex:
                continue
            x = 60 + (t.launch - t0) / span * (width - 70)
            w = max(1.0, (t.finish - t.launch) / span * (width - 70))
            color = "#d62728" if t.failed else "#1f77b4"
            parts.append(f'<rect x="{x:.1f}" y="{y}" width="{w:.1f}" '
                         f'height="{row_h - 3}" fill="{color}"/>')
    parts.append("</svg>")
    with open(path, "w") as f:
        f.write("\n".join(parts))


# ---------------------------------------------------------------------------
# Plan graph (ref GenerateDot.scala)
# ---------------------------------------------------------------------------

def generate_dot(app: AppInfo, sql_id: int, path: str) -> None:
    sx = app.sql_executions[sql_id]
    lines = ["digraph plan {", '  node [shape=box, fontsize=10];']
    counter = [0]

    def emit(node: PlanNode) -> int:
        nid = counter[0]
        counter[0] += 1
        label = node.node_name.replace('"', "'")[:60]
        lines.append(f'  n{nid} [label="{label}"];')
        for c in node.children:
            cid = emit(c)
            lines.append(f"  n{cid} -> n{nid};")
        return nid

    emit(sx.plan)
    lines.append("}")
    with open(path, "w") as f:
        f.write("\n".join(lines))


# ---------------------------------------------------------------------------
# Driver (ref Profiler.scala)
# ---------------------------------------------------------------------------

def profile(paths: List[str], output_dir: Optional[str] = None,
            compare: bool = False) -> List[Dict]:
    apps = []
    for log in find_event_logs(paths):
        try:
            apps.append(parse_event_log(log))
        except OSError:
            continue
    reports = []
    for app in apps:
        reports.append({
            "application": app_information(app),
            "stages": stage_aggregates(app),
            "sql": sql_aggregates(app),
            "health": health_check(app),
        })
    if output_dir:
        os.makedirs(output_dir, exist_ok=True)
        for app, rep in zip(apps, reports):
            base = os.path.join(output_dir, app.app_id or app.app_name
                                or "app")
            with open(base + "_profile.txt", "w") as f:
                f.write(format_profile(rep))
            generate_timeline(app, base + "_timeline.svg")
            for sql_id in app.sql_executions:
                generate_dot(app, sql_id, f"{base}_sql{sql_id}.dot")
        if compare and len(apps) > 1:
            with open(os.path.join(output_dir, "compare.txt"), "w") as f:
                for row in compare_apps(apps):
                    f.write(f"{row}\n")
    return reports


def format_profile(rep: Dict) -> str:
    lines = ["### Application Information ###"]
    for k, v in rep["application"].items():
        lines.append(f"{k:20s} {v}")
    lines.append("\n### Stage Aggregates ###")
    for srow in rep["stages"]:
        lines.append(str(srow))
    lines.append("\n### SQL Executions ###")
    for srow in rep["sql"]:
        lines.append(str(srow))
    h = rep["health"]
    lines.append("\n### Health Check ###")
    lines.append(f"failed tasks:  {len(h['failedTasks'])}")
    lines.append(f"failed stages: {len(h['failedStages'])}")
    lines.append(f"failed jobs:   {len(h['failedJobs'])}")
    return "\n".join(lines) + "\n"
