"""Profiling tool: metrics aggregation, health check, comparison,
timeline and plan-graph generation from Spark event logs.

Ref: tools/.../profiling/{ProfileMain,Profiler,Analysis,
CollectInformation,HealthCheck,CompareApplications,GenerateTimeline,
GenerateDot}.scala.
"""

from __future__ import annotations

import os
from statistics import median
from typing import Dict, List, Optional

from .eventlog import AppInfo, PlanNode, find_event_logs, parse_event_log


# ---------------------------------------------------------------------------
# Analysis (ref Analysis.scala jobAndStageMetricsAggregation /
# sqlMetricsAggregation)
# ---------------------------------------------------------------------------

def app_information(app: AppInfo) -> Dict:
    return {
        "appName": app.app_name, "appId": app.app_id,
        "sparkVersion": app.spark_version,
        "startTime": app.start_time, "endTime": app.end_time,
        "duration": app.app_duration,
        "durationEstimated": app.duration_estimated,
        "numExecutors": len(app.executors),
        "totalCores": sum(e.get("cores", 0) for e in
                          app.executors.values()),
    }


def stage_aggregates(app: AppInfo) -> List[Dict]:
    out = []
    for (sid, attempt), st in sorted(app.stages.items()):
        ts = [t for t in app.tasks if t.stage_id == sid]
        durs = [t.duration for t in ts] or [0]
        out.append({
            "stageId": sid, "attempt": attempt, "name": st.name[:60],
            "numTasks": st.num_tasks, "duration": st.duration,
            "taskDurMin": min(durs), "taskDurMed": int(median(durs)),
            "taskDurMax": max(durs),
            "inputBytes": sum(t.input_bytes for t in ts),
            "outputBytes": sum(t.output_bytes for t in ts),
            "shuffleRead": sum(t.shuffle_read_bytes for t in ts),
            "shuffleWrite": sum(t.shuffle_write_bytes for t in ts),
            "memSpilled": sum(t.memory_spilled for t in ts),
            "diskSpilled": sum(t.disk_spilled for t in ts),
            "gcTime": sum(t.gc_time for t in ts),
        })
    return out


def sql_aggregates(app: AppInfo) -> List[Dict]:
    out = []
    for sql_id, sx in sorted(app.sql_executions.items()):
        out.append({
            "sqlId": sql_id,
            "description": sx.description[:80],
            "duration": sx.duration,
            "taskDuration": app.sql_task_duration(sql_id),
            "failed": sx.failed,
        })
    return out


# ---------------------------------------------------------------------------
# Operator metrics + predicted-vs-actual (spark_rapids_tpu self-emitted
# logs; the engine embeds drained metric values and the CBO/tmsan model
# into SparkPlanInfo — see obs/eventlog_writer.py)
# ---------------------------------------------------------------------------

_LEVEL_ORDER = {"ESSENTIAL": 0, "MODERATE": 1, "DEBUG": 2}


def operator_metrics(app: AppInfo, sql_id: int,
                     level: str = "MODERATE") -> List[tuple]:
    """(operator, metric, value) rows for one SQL execution, in the
    same pre-order walk and level filter as the live
    ``exec.base.metrics_report`` — the round-trip contract: parsing a
    self-emitted log reproduces ``last_query_metrics`` exactly."""
    sx = app.sql_executions.get(sql_id)
    if sx is None:
        return []
    cutoff = _LEVEL_ORDER.get(level, 1)
    out: List[tuple] = []
    for node in sx.plan.walk():
        for m in node.metrics:
            if "value" not in m:
                continue  # foreign Spark logs carry accumulator ids
            if _LEVEL_ORDER.get(m.get("level", "MODERATE"), 1) > cutoff:
                continue
            out.append((node.node_name, m.get("name", ""), m["value"]))
    return out


def accuracy_report(app: AppInfo) -> List[Dict]:
    """Predicted-vs-actual rows/bytes per operator across all SQL
    executions, ranked by row-prediction error (worst first) — the
    feedback signal CBO-tuning consumes.  Adds the query-level
    peak-HBM pair (tmsan static bound vs memsan-measured) when the log
    carries it."""
    from ..obs.export import accuracy_row
    rows: List[Dict] = []
    for sql_id, sx in sorted(app.sql_executions.items()):
        for node in sx.plan.walk():
            if node.prediction is None or node.actual is None:
                continue
            r = accuracy_row(node.node_name, node.prediction,
                             node.actual)
            r["sqlId"] = sql_id
            rows.append(r)
    rows.sort(key=lambda r: -r["rowsErr"])
    return rows


def format_accuracy(app: AppInfo) -> str:
    from ..obs.export import format_accuracy as _fmt
    rows = accuracy_report(app)
    peaks = [(sx.static_peak_bound, sx.peak_device_bytes)
             for sx in app.sql_executions.values()
             if sx.static_peak_bound is not None or
             sx.peak_device_bytes is not None]
    bound, measured = peaks[-1] if peaks else (None, None)
    return _fmt(rows, measured_peak=measured, static_bound=bound)


# ---------------------------------------------------------------------------
# Health check (ref HealthCheck.scala)
# ---------------------------------------------------------------------------

def health_check(app: AppInfo) -> Dict[str, List]:
    failed_tasks = [
        {"taskId": t.task_id, "stageId": t.stage_id,
         "attempt": t.attempt} for t in app.tasks if t.failed]
    failed_stages = [
        {"stageId": sid, "attempt": at, "reason": (st.failure_reason
                                                   or "")[:120]}
        for (sid, at), st in sorted(app.stages.items())
        if st.failure_reason]
    failed_jobs = [
        {"jobId": jid, "result": j.get("result")}
        for jid, j in sorted(app.jobs.items())
        if j.get("result") not in (None, "JobSucceeded")]
    return {"failedTasks": failed_tasks, "failedStages": failed_stages,
            "failedJobs": failed_jobs}


# ---------------------------------------------------------------------------
# Comparison (ref CompareApplications.scala)
# ---------------------------------------------------------------------------

def compare_apps(apps: List[AppInfo]) -> List[Dict]:
    rows = []
    for i, app in enumerate(apps):
        info = app_information(app)
        info["runIndex"] = i
        info["sqlDuration"] = sum(s.duration
                                  for s in app.sql_executions.values())
        info["taskDuration"] = sum(t.run_time for t in app.tasks)
        rows.append(info)
    return rows


# ---------------------------------------------------------------------------
# Timeline (ref GenerateTimeline.scala — emits an SVG lane chart)
# ---------------------------------------------------------------------------

def generate_timeline(app: AppInfo, path: str) -> None:
    t0 = app.start_time or min((t.launch for t in app.tasks), default=0)
    t1 = app.end_time or max((t.finish for t in app.tasks), default=t0 + 1)
    span = max(t1 - t0, 1)
    width, row_h = 1000, 14
    lanes = sorted({t.executor_id for t in app.tasks})
    height = row_h * (len(lanes) + 2)
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
             f'height="{height}">']
    for li, ex in enumerate(lanes):
        y = row_h * (li + 1)
        parts.append(f'<text x="2" y="{y + 10}" font-size="9">exec '
                     f'{ex}</text>')
        for t in app.tasks:
            if t.executor_id != ex:
                continue
            x = 60 + (t.launch - t0) / span * (width - 70)
            w = max(1.0, (t.finish - t.launch) / span * (width - 70))
            color = "#d62728" if t.failed else "#1f77b4"
            parts.append(f'<rect x="{x:.1f}" y="{y}" width="{w:.1f}" '
                         f'height="{row_h - 3}" fill="{color}"/>')
    parts.append("</svg>")
    with open(path, "w") as f:
        f.write("\n".join(parts))


# ---------------------------------------------------------------------------
# Plan graph (ref GenerateDot.scala)
# ---------------------------------------------------------------------------

def generate_dot(app: AppInfo, sql_id: int, path: str) -> None:
    sx = app.sql_executions[sql_id]
    lines = ["digraph plan {", '  node [shape=box, fontsize=10];']
    counter = [0]

    def emit(node: PlanNode) -> int:
        nid = counter[0]
        counter[0] += 1
        label = node.node_name.replace('"', "'")[:60]
        lines.append(f'  n{nid} [label="{label}"];')
        for c in node.children:
            cid = emit(c)
            lines.append(f"  n{cid} -> n{nid};")
        return nid

    emit(sx.plan)
    lines.append("}")
    with open(path, "w") as f:
        f.write("\n".join(lines))


# ---------------------------------------------------------------------------
# Driver (ref Profiler.scala)
# ---------------------------------------------------------------------------

def profile(paths: List[str], output_dir: Optional[str] = None,
            compare: bool = False) -> List[Dict]:
    apps = []
    for log in find_event_logs(paths):
        try:
            apps.append(parse_event_log(log))
        except OSError:
            continue
    reports = []
    for app in apps:
        rep = {
            "application": app_information(app),
            "stages": stage_aggregates(app),
            "sql": sql_aggregates(app),
            "health": health_check(app),
            # self-emitted logs only: per-operator metric values and the
            # predicted-vs-actual rows (empty for foreign Spark logs)
            "operators": {sql_id: operator_metrics(app, sql_id, "DEBUG")
                          for sql_id in sorted(app.sql_executions)},
            "accuracy": accuracy_report(app),
        }
        reports.append(rep)
    if output_dir:
        os.makedirs(output_dir, exist_ok=True)
        for app, rep in zip(apps, reports):
            base = os.path.join(output_dir, app.app_id or app.app_name
                                or "app")
            with open(base + "_profile.txt", "w") as f:
                f.write(format_profile(rep))
            generate_timeline(app, base + "_timeline.svg")
            for sql_id in app.sql_executions:
                generate_dot(app, sql_id, f"{base}_sql{sql_id}.dot")
        if compare and len(apps) > 1:
            with open(os.path.join(output_dir, "compare.txt"), "w") as f:
                for row in compare_apps(apps):
                    f.write(f"{row}\n")
    return reports


def format_profile(rep: Dict) -> str:
    lines = ["### Application Information ###"]
    for k, v in rep["application"].items():
        lines.append(f"{k:20s} {v}")
    lines.append("\n### Stage Aggregates ###")
    for srow in rep["stages"]:
        lines.append(str(srow))
    lines.append("\n### SQL Executions ###")
    for srow in rep["sql"]:
        lines.append(str(srow))
    h = rep["health"]
    lines.append("\n### Health Check ###")
    lines.append(f"failed tasks:  {len(h['failedTasks'])}")
    lines.append(f"failed stages: {len(h['failedStages'])}")
    lines.append(f"failed jobs:   {len(h['failedJobs'])}")
    return "\n".join(lines) + "\n"
