"""Qualification tool: which CPU Spark apps would benefit from the TPU
plugin.

Ref: tools/.../qualification/{QualificationMain,Qualification,
QualAppInfo,PluginTypeChecker}.scala — scores each app from its event
log: how much SQL-dataframe task time runs in operators the plugin can
accelerate, penalizing potential problems (UDFs, unsupported formats,
nested types).  Output matches the reference's CSV shape
(QualOutputWriter.scala headers) so downstream consumers carry over.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List, Optional, Set, Tuple

from .eventlog import AppInfo, PlanNode, find_event_logs, parse_event_log
from .supported_ops import (TRANSPARENT_EXECS, supported_exec_factors,
                            unsupported_expr_tokens)

SUPPORTED_READ_FORMATS = {"parquet", "orc", "csv"}
SUPPORTED_WRITE_FORMATS = {"parquet", "orc"}

PROBLEM_MARKERS = {
    "UDF": ("udf",),
    "DECIMAL": ("decimaltype", "decimal("),
}


class QualAppResult:
    def __init__(self, app: AppInfo):
        self.app = app
        self.sql_df_duration = 0
        self.sql_task_duration = 0
        self.supported_task_duration = 0
        self.problems: Set[str] = set()
        self.failed_sql_ids: List[int] = []
        self.problem_duration = 0
        self.unsupported_read_formats: Set[str] = set()
        self.unsupported_write_formats: Set[str] = set()
        self.complex_types: Set[str] = set()
        self.unsupported_exprs: Set[str] = set()
        # structured TPU-Lxxx hazards from the static analyzer's
        # event-log front end (analysis/plan_lint.lint_spark_plan) — the
        # same rule vocabulary the live pre-flight lint reports
        self.lint_diagnostics: List = []
        self._speedup_num = 0.0
        self._speedup_den = 0.0
        self._analyze()

    # ------------------------------------------------------------------
    def _analyze(self):
        app = self.app
        for sx in app.sql_executions.values():
            dur = sx.duration
            task_dur = app.sql_task_duration(sx.sql_id)
            self.sql_df_duration += dur
            self.sql_task_duration += task_dur
            if sx.failed:
                self.failed_sql_ids.append(sx.sql_id)
                continue
            problems = self._plan_problems(sx.plan)
            from ..analysis.plan_lint import lint_spark_plan
            self.lint_diagnostics.extend(lint_spark_plan(sx.plan))
            frac, speedup = self._plan_scores(sx.plan)
            self.supported_task_duration += int(task_dur * frac)
            self._speedup_num += task_dur * frac * speedup
            self._speedup_den += task_dur * frac
            if problems:
                self.problems |= problems
                self.problem_duration += dur

    def _plan_problems(self, plan: PlanNode) -> Set[str]:
        out: Set[str] = set()
        for node in plan.walk():
            text = (node.node_name + " " + node.simple_string).lower()
            for problem, markers in PROBLEM_MARKERS.items():
                if any(m in text for m in markers):
                    out.add(problem)
            if "scan" in node.node_name.lower():
                fmt = _scan_format(node)
                if fmt and fmt not in SUPPORTED_READ_FORMATS:
                    self.unsupported_read_formats.add(fmt.upper())
            if "insertintohadoopfs" in text or "datawritingcommand" in text:
                fmt = _write_format(node)
                if fmt and fmt not in SUPPORTED_WRITE_FORMATS:
                    self.unsupported_write_formats.add(fmt.upper())
            for marker in ("arraytype", "maptype", "structtype"):
                if marker in text:
                    self.complex_types.add(marker[:-4])
        return out

    def _plan_scores(self, plan: PlanNode) -> Tuple[float, float]:
        """(supported fraction, estimated speedup), driven by the LIVE
        engine registries (tools/supported_ops.py).  An operator counts
        as supported when (a) its exec translates and (b) every function
        token in its simple string is a registered expression.  The
        speedup estimate is Amdahl over the plan's operators: each
        supported op's unit of work shrinks by its per-op factor (the
        reference's operatorsScore.csv weighting in PluginTypeChecker),
        so a plan of cheap pass-through nodes no longer scores like an
        accelerated join/aggregate pipeline."""
        factors = supported_exec_factors()
        n = 0
        good = 0
        new_time = 0.0
        for node in plan.walk():
            base = node.node_name.split("(")[0].strip()
            if base in TRANSPARENT_EXECS or \
                    any(base.startswith(t) for t in TRANSPARENT_EXECS):
                continue
            n += 1
            if "scan" in base.lower():
                if _scan_format(node) in SUPPORTED_READ_FORMATS:
                    good += 1
                    new_time += 1 / 2.0
                else:
                    new_time += 1.0
                continue
            factor = next((f for prefix, f in factors.items()
                           if base.startswith(prefix)), None)
            bad = unsupported_expr_tokens(node.simple_string) \
                if factor is not None else []
            self.unsupported_exprs |= set(bad)
            if factor is None or bad:
                new_time += 1.0    # runs where it ran before
                continue
            good += 1
            new_time += 1.0 / factor
        if n == 0:
            return 0.0, 1.0
        return good / n, n / max(new_time, 1e-9)

    # ------------------------------------------------------------------
    @property
    def estimated_speedup(self) -> float:
        """Task-duration-weighted Amdahl estimate over the app's plans."""
        if self._speedup_den <= 0:
            return 1.0
        return self._speedup_num / self._speedup_den

    @property
    def score(self) -> float:
        """The reference's qualification score: supported SQL task time
        scaled by the registry-derived speedup estimate, discounted when
        reads are unsupported (QualAppInfo score calc +
        operatorsScore weighting)."""
        score = float(self.supported_task_duration) * \
            self.estimated_speedup
        if self.unsupported_read_formats:
            score *= 0.8
        if "UDF" in self.problems:
            score *= 0.8
        return round(score, 2)

    def row(self) -> List:
        app = self.app
        return [
            app.app_name, app.app_id, f"{self.score:.2f}",
            ";".join(sorted(self.problems)),
            self.sql_df_duration, self.sql_task_duration,
            app.app_duration, app.executor_cpu_percent(),
            str(app.duration_estimated).lower(),
            self.problem_duration,
            ";".join(str(i) for i in sorted(self.failed_sql_ids)),
            ";".join(sorted(self.unsupported_read_formats)),
            ";".join(sorted(self.unsupported_write_formats)),
            ";".join(sorted(self.complex_types)),
        ]


HEADERS = ["App Name", "App ID", "Score", "Potential Problems",
           "SQL Dataframe Duration", "SQL Dataframe Task Duration",
           "App Duration", "Executor CPU Time Percent",
           "App Duration Estimated", "SQL Duration with Potential Problems",
           "SQL Ids with Failures", "Unsupported Read File Formats and Types",
           "Unsupported Write Data Format", "Complex Types"]


def _scan_format(node: PlanNode) -> Optional[str]:
    text = node.simple_string.lower() + " " + node.node_name.lower()
    for fmt in ("parquet", "orc", "csv", "json", "avro", "text", "jdbc"):
        if fmt in text:
            return fmt
    return None


def _write_format(node: PlanNode) -> Optional[str]:
    return _scan_format(node)


def qualify(paths: List[str], output_dir: Optional[str] = None
            ) -> List[QualAppResult]:
    """Run qualification over event logs; returns results sorted by score
    descending and optionally writes the CSV + summary."""
    results = []
    for log in find_event_logs(paths):
        try:
            app = parse_event_log(log)
        except OSError:
            continue
        if app.app_name or app.sql_executions:
            results.append(QualAppResult(app))
    results.sort(key=lambda r: r.score, reverse=True)
    if output_dir:
        os.makedirs(output_dir, exist_ok=True)
        out_csv = os.path.join(output_dir,
                               "spark_rapids_tpu_qualification_output.csv")
        with open(out_csv, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(HEADERS)
            for r in results:
                w.writerow(r.row())
        with open(os.path.join(
                output_dir,
                "spark_rapids_tpu_qualification_output.log"), "w") as f:
            f.write(format_summary(results))
        with open(os.path.join(
                output_dir,
                "spark_rapids_tpu_qualification_lint.log"), "w") as f:
            f.write(format_lint(results))
    return results


def format_lint(results: List[QualAppResult]) -> str:
    """Per-app static-analysis hazards in the TPU-Lxxx rule vocabulary
    (codes documented in docs/static-analysis.md)."""
    lines = ["=" * 72, "Static-analysis hazards per application:",
             "=" * 72]
    for r in results:
        lines.append(f"{r.app.app_name} ({r.app.app_id}):")
        if not r.lint_diagnostics:
            lines.append("  no hazards detected")
            continue
        for d in r.lint_diagnostics:
            lines.append("  " + d.render())
    return "\n".join(lines) + "\n"


def format_summary(results: List[QualAppResult]) -> str:
    lines = ["=" * 72,
             f"Qualified {len(results)} application(s), best first:",
             "=" * 72]
    for r in results:
        lines.append(f"{r.app.app_name:40s} {r.app.app_id:24s} "
                     f"score={r.score:>12.2f} "
                     f"sqlDur={r.sql_df_duration}ms")
    return "\n".join(lines) + "\n"
