"""Qualification tool: which CPU Spark apps would benefit from the TPU
plugin.

Ref: tools/.../qualification/{QualificationMain,Qualification,
QualAppInfo,PluginTypeChecker}.scala — scores each app from its event
log: how much SQL-dataframe task time runs in operators the plugin can
accelerate, penalizing potential problems (UDFs, unsupported formats,
nested types).  Output matches the reference's CSV shape
(QualOutputWriter.scala headers) so downstream consumers carry over.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List, Optional, Set, Tuple

from .eventlog import AppInfo, PlanNode, find_event_logs, parse_event_log

# Spark exec nodeName fragments the TPU build accelerates (kept in sync
# with plan/overrides.py EXEC_SIGS; the reference derives the same list
# from supportedExecs in PluginTypeChecker)
SUPPORTED_EXECS = {
    "Project", "Filter", "HashAggregate", "SortAggregate",
    "ObjectHashAggregate", "Sort", "SortMergeJoin", "ShuffledHashJoin",
    "BroadcastHashJoin", "BroadcastNestedLoopJoin", "CartesianProduct",
    "Exchange", "ShuffleExchange", "BroadcastExchange", "Union", "Range",
    "Window", "Expand", "Generate", "Sample", "GlobalLimit", "LocalLimit",
    "TakeOrderedAndProject", "CollectLimit", "Coalesce",
    "WholeStageCodegen", "ColumnarToRow", "RowToColumnar", "Subquery",
    "ReusedExchange", "CustomShuffleReader", "AQEShuffleRead",
    "AdaptiveSparkPlan", "InputAdapter",
}

SUPPORTED_READ_FORMATS = {"parquet", "orc", "csv"}
SUPPORTED_WRITE_FORMATS = {"parquet", "orc"}

PROBLEM_MARKERS = {
    "UDF": ("udf",),
    "DECIMAL": ("decimaltype", "decimal("),
}


class QualAppResult:
    def __init__(self, app: AppInfo):
        self.app = app
        self.sql_df_duration = 0
        self.sql_task_duration = 0
        self.supported_task_duration = 0
        self.problems: Set[str] = set()
        self.failed_sql_ids: List[int] = []
        self.problem_duration = 0
        self.unsupported_read_formats: Set[str] = set()
        self.unsupported_write_formats: Set[str] = set()
        self.complex_types: Set[str] = set()
        self._analyze()

    # ------------------------------------------------------------------
    def _analyze(self):
        app = self.app
        for sx in app.sql_executions.values():
            dur = sx.duration
            task_dur = app.sql_task_duration(sx.sql_id)
            self.sql_df_duration += dur
            self.sql_task_duration += task_dur
            if sx.failed:
                self.failed_sql_ids.append(sx.sql_id)
                continue
            problems = self._plan_problems(sx.plan)
            frac = self._supported_fraction(sx.plan)
            self.supported_task_duration += int(task_dur * frac)
            if problems:
                self.problems |= problems
                self.problem_duration += dur

    def _plan_problems(self, plan: PlanNode) -> Set[str]:
        out: Set[str] = set()
        for node in plan.walk():
            text = (node.node_name + " " + node.simple_string).lower()
            for problem, markers in PROBLEM_MARKERS.items():
                if any(m in text for m in markers):
                    out.add(problem)
            if "scan" in node.node_name.lower():
                fmt = _scan_format(node)
                if fmt and fmt not in SUPPORTED_READ_FORMATS:
                    self.unsupported_read_formats.add(fmt.upper())
            if "insertintohadoopfs" in text or "datawritingcommand" in text:
                fmt = _write_format(node)
                if fmt and fmt not in SUPPORTED_WRITE_FORMATS:
                    self.unsupported_write_formats.add(fmt.upper())
            for marker in ("arraytype", "maptype", "structtype"):
                if marker in text:
                    self.complex_types.add(marker[:-4])
        return out

    def _supported_fraction(self, plan: PlanNode) -> float:
        total = 0
        good = 0
        for node in plan.walk():
            total += 1
            base = node.node_name.split("(")[0].strip()
            if any(base.startswith(s) or s in base
                   for s in SUPPORTED_EXECS):
                good += 1
            elif "scan" in base.lower():
                fmt = _scan_format(node)
                if fmt in SUPPORTED_READ_FORMATS:
                    good += 1
        return good / total if total else 0.0

    # ------------------------------------------------------------------
    @property
    def score(self) -> float:
        """The reference's qualification score: supported SQL task time,
        discounted when reads are unsupported (QualAppInfo score calc)."""
        score = float(self.supported_task_duration)
        if self.unsupported_read_formats:
            score *= 0.8
        if "UDF" in self.problems:
            score *= 0.8
        return round(score, 2)

    def row(self) -> List:
        app = self.app
        return [
            app.app_name, app.app_id, f"{self.score:.2f}",
            ";".join(sorted(self.problems)),
            self.sql_df_duration, self.sql_task_duration,
            app.app_duration, app.executor_cpu_percent(),
            str(app.duration_estimated).lower(),
            self.problem_duration,
            ";".join(str(i) for i in sorted(self.failed_sql_ids)),
            ";".join(sorted(self.unsupported_read_formats)),
            ";".join(sorted(self.unsupported_write_formats)),
            ";".join(sorted(self.complex_types)),
        ]


HEADERS = ["App Name", "App ID", "Score", "Potential Problems",
           "SQL Dataframe Duration", "SQL Dataframe Task Duration",
           "App Duration", "Executor CPU Time Percent",
           "App Duration Estimated", "SQL Duration with Potential Problems",
           "SQL Ids with Failures", "Unsupported Read File Formats and Types",
           "Unsupported Write Data Format", "Complex Types"]


def _scan_format(node: PlanNode) -> Optional[str]:
    text = node.simple_string.lower() + " " + node.node_name.lower()
    for fmt in ("parquet", "orc", "csv", "json", "avro", "text", "jdbc"):
        if fmt in text:
            return fmt
    return None


def _write_format(node: PlanNode) -> Optional[str]:
    return _scan_format(node)


def qualify(paths: List[str], output_dir: Optional[str] = None
            ) -> List[QualAppResult]:
    """Run qualification over event logs; returns results sorted by score
    descending and optionally writes the CSV + summary."""
    results = []
    for log in find_event_logs(paths):
        try:
            app = parse_event_log(log)
        except OSError:
            continue
        if app.app_name or app.sql_executions:
            results.append(QualAppResult(app))
    results.sort(key=lambda r: r.score, reverse=True)
    if output_dir:
        os.makedirs(output_dir, exist_ok=True)
        out_csv = os.path.join(output_dir,
                               "spark_rapids_tpu_qualification_output.csv")
        with open(out_csv, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(HEADERS)
            for r in results:
                w.writerow(r.row())
        with open(os.path.join(
                output_dir,
                "spark_rapids_tpu_qualification_output.log"), "w") as f:
            f.write(format_summary(results))
    return results


def format_summary(results: List[QualAppResult]) -> str:
    lines = ["=" * 72,
             f"Qualified {len(results)} application(s), best first:",
             "=" * 72]
    for r in results:
        lines.append(f"{r.app.app_name:40s} {r.app.app_id:24s} "
                     f"score={r.score:>12.2f} "
                     f"sqlDur={r.sql_df_duration}ms")
    return "\n".join(lines) + "\n"
