"""Cached-batch serializer: df.cache() materialized as parquet bytes.

Ref: the ParquetCachedBatchSerializer the reference installs for Spark
3.1.1+ (shims/spark311/.../SparkBaseShims.scala, docs/
additional-functionality/cache-serializer.md, tests-spark310+/): cached
DataFrames are stored as parquet-encoded byte blobs instead of Spark's
row-based CachedBatch, so re-reads decode straight to columnar batches.

Design here: a process-wide `CacheManager` keyed by logical-plan node.
Planning a query that contains a cached-and-materialized subtree swaps
in a `CachedScanExec` over the parquet blobs; the first execution after
`cache()` materializes them (one parquet blob per partition).  The shim
layer gates availability exactly like the reference (not supported on
the 3.0.x dialect)."""

from __future__ import annotations

import io
import threading
from typing import Dict, Iterator, List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from ..exec.base import (NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, Batch, Exec,
                         TPU)


class CachedPartition:
    __slots__ = ("blobs", "complete")

    def __init__(self):
        self.blobs: List[bytes] = []  # one parquet blob per batch
        self.complete = False  # generator ran to exhaustion


class CacheEntry:
    def __init__(self, lp):
        # retain the logical plan: the registry is keyed by id(lp), so a
        # strong reference both defines the cache lifetime (until
        # unpersist) and prevents CPython id reuse from aliasing a freed
        # plan's entry onto a new node
        self.lp = lp
        self.materialized = False
        self.partitions: List[CachedPartition] = []
        self.schema: Optional[pa.Schema] = None

    @property
    def size_bytes(self) -> int:
        return sum(len(b) for p in self.partitions for b in p.blobs)


class CacheManager:
    """Process-wide registry of cached logical plans (the CachedRDD/
    InMemoryRelation role)."""

    _lock = threading.Lock()
    _entries: Dict[int, CacheEntry] = {}

    @classmethod
    def cache(cls, lp) -> CacheEntry:
        with cls._lock:
            return cls._entries.setdefault(id(lp), CacheEntry(lp))

    @classmethod
    def lookup(cls, lp) -> Optional[CacheEntry]:
        with cls._lock:
            return cls._entries.get(id(lp))

    @classmethod
    def uncache(cls, lp) -> None:
        with cls._lock:
            cls._entries.pop(id(lp), None)

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._entries.clear()


def encode_batch(rb: pa.RecordBatch) -> bytes:
    """RecordBatch -> parquet blob (the serializer's convertForCache)."""
    sink = io.BytesIO()
    tbl = pa.Table.from_batches([rb])
    pq.write_table(tbl, sink, compression="snappy")
    return sink.getvalue()


def decode_blob(blob: bytes) -> List[pa.RecordBatch]:
    tbl = pq.read_table(io.BytesIO(blob))
    return tbl.combine_chunks().to_batches()


class CacheWriteExec(Exec):
    """Tees child batches into the cache while streaming them through
    (the materialization pass on the first action after cache())."""

    def __init__(self, entry: CacheEntry, child: Exec):
        super().__init__([child])
        self.entry = entry
        self.placement = child.placement
        self._lock = threading.Lock()

    @property
    def output_names(self):
        return self.children[0].output_names

    @property
    def output_types(self):
        return self.children[0].output_types

    def describe(self):
        return "CacheWrite(parquet)"

    def determinism(self):
        from ..analysis.determinism import Determinism, ORDER_STABLE
        return Determinism(
            ORDER_STABLE, "stores batches in child emission order; the "
            "cached partition's row multiset is invariant")

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        from ..exec.base import to_host_batch
        with self._lock:
            while len(self.entry.partitions) <= pid:
                self.entry.partitions.append(CachedPartition())
            part = self.entry.partitions[pid]
            part.blobs = []
            part.complete = False
        for b in self.children[0].execute_partition(pid, ctx):
            rb = to_host_batch(b, self.output_names)
            blob = encode_batch(rb)
            with self._lock:
                part.blobs.append(blob)
                if self.entry.schema is None:
                    self.entry.schema = rb.schema
            yield b
        with self._lock:
            part.complete = True
            if len(self.entry.partitions) == self.num_partitions and \
                    all(p.complete for p in self.entry.partitions):
                # a short-circuited run (e.g. under a limit) never
                # completes every partition and must not be served as a
                # full cache
                self.entry.materialized = True


class CachedScanExec(Exec):
    """Scan over parquet-cached partitions (the InMemoryTableScanExec
    replacement; decodes blobs straight to columnar batches)."""

    placement = TPU

    def __init__(self, entry: CacheEntry, names, dtypes):
        super().__init__([])
        self.entry = entry
        self._names = list(names)
        self._types = list(dtypes)

    @property
    def output_names(self):
        return self._names

    @property
    def output_types(self):
        return self._types

    @property
    def num_partitions(self):
        return max(1, len(self.entry.partitions))

    def describe(self):
        return (f"CachedScan(parquet, {self.num_partitions} partitions, "
                f"{self.entry.size_bytes}B)")

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        from ..columnar.device import batch_to_device
        xp = self.xp
        if pid >= len(self.entry.partitions):
            return
        for blob in self.entry.partitions[pid].blobs:
            for rb in decode_blob(blob):
                b = batch_to_device(rb, xp=xp)
                self.metrics[NUM_OUTPUT_ROWS] += b.num_rows
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield b
