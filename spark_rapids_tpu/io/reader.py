"""DataFrameReader: spark.read.parquet/orc/csv entry points.

Ref: the reader side of GpuReadParquetFileFormat / GpuReadOrcFileFormat /
GpuReadCSVFileFormat — schema discovery from footers, options handling.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.orc as paorc
import pyarrow.parquet as papq

from ..columnar.interop import from_arrow_type
from ..plan.logical import FileRelation


def _hidden_component(root: str, path: str) -> bool:
    """Any path component below `root` starting with '_' or '.' marks
    metadata/leftovers (_SUCCESS, _temporary/ from interrupted writes,
    hidden files) — Spark's readers skip these at every depth, not just
    the basename."""
    rel = os.path.relpath(path, root)
    return any(part.startswith(("_", ".")) for part in rel.split(os.sep))


def _expand(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            # recursive: partitioned writes lay out k=<v>/part-*.parquet
            for fmt_glob in ("*.parquet", "*.orc", "*.csv", "*"):
                hits = sorted(glob.glob(os.path.join(p, "**", fmt_glob),
                                        recursive=True))
                hits = [h for h in hits if os.path.isfile(h)
                        and not _hidden_component(p, h)]
                if hits:
                    out.extend(hits)
                    break
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    return out


class DataFrameReader:
    def __init__(self, session):
        self.session = session
        self._options: Dict = {}
        self._schema = None

    def option(self, key, value) -> "DataFrameReader":
        self._options[key] = value
        return self

    def schema(self, schema) -> "DataFrameReader":
        self._schema = schema
        return self

    def parquet(self, *paths):
        files = _expand(list(paths))
        if not files:
            raise FileNotFoundError(f"no parquet files under {paths}")
        schema = papq.read_schema(files[0])
        names = list(schema.names)
        dtypes = [from_arrow_type(f.type) for f in schema]
        from ..api.dataframe import DataFrame
        return DataFrame(FileRelation("parquet", files, names, dtypes,
                                      dict(self._options)), self.session)

    def orc(self, *paths):
        files = _expand(list(paths))
        if not files:
            raise FileNotFoundError(f"no orc files under {paths}")
        schema = paorc.ORCFile(files[0]).schema
        names = list(schema.names)
        dtypes = [from_arrow_type(f.type) for f in schema]
        from ..api.dataframe import DataFrame
        return DataFrame(FileRelation("orc", files, names, dtypes,
                                      dict(self._options)), self.session)

    def csv(self, *paths, header: bool = True):
        files = _expand(list(paths))
        if not files:
            raise FileNotFoundError(f"no csv files under {paths}")
        opts = dict(self._options)
        opts.setdefault("header", header)
        if self._schema is not None:
            names = [n for n, _ in self._schema]
            dtypes = [d for _, d in self._schema]
        else:
            ropts = pacsv.ReadOptions(
                autogenerate_column_names=not opts.get("header", True))
            sample = pacsv.read_csv(files[0], read_options=ropts)
            names = list(sample.schema.names)
            dtypes = [from_arrow_type(f.type) for f in sample.schema]
        from ..api.dataframe import DataFrame
        return DataFrame(FileRelation("csv", files, names, dtypes, opts),
                         self.session)
