"""File scan execs: Parquet / ORC / CSV with multi-file reader strategies.

Ref: GpuParquetScan.scala:81-1340 (PERFILE / COALESCING / MULTITHREADED
reader strategies, predicate pushdown via footer filters),
GpuMultiFileReader.scala:124-550 (shared multi-file machinery + thread
pools), GpuOrcScan.scala, GpuReadCSVFileFormat.scala,
GpuFileSourceScanExec.scala.

TPU mapping: column pruning + row-group predicate pushdown happen in the
host reader (pyarrow), mirroring the reference's CPU-side footer work;
decoded columns upload straight into bucketed device batches for the
fused TPU pipeline.  Strategies:
  PERFILE       — one read per file per task;
  COALESCING    — many small files concatenate into one batch before
                  upload (ref MultiFileParquetPartitionReader);
  MULTITHREADED — a thread pool prefetches file reads ahead of the
                  consuming task (ref MultiFileCloudParquetPartitionReader).
"""

from __future__ import annotations

import concurrent.futures as cf
from typing import Iterator, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.dataset as pads
import pyarrow.orc as paorc
import pyarrow.parquet as papq

from .. import config as cfg
from ..columnar.device import batch_to_device
from ..exec.base import (NUM_OUTPUT_BATCHES, NUM_OUTPUT_ROWS, OP_TIME, TPU,
                         Batch, Exec, MetricTimer)
from ..expr.core import Expression


def _pushdown_to_arrow(filters: List[Expression], names) -> Optional[object]:
    """Convert simple predicates to pyarrow dataset expressions for
    row-group pruning (ref getParquetFilters, SparkShims.scala:94)."""
    import pyarrow.compute as pc
    from ..expr import predicates as P
    from ..expr.core import AttributeReference, Literal

    def conv(e):
        if isinstance(e, P.And):
            a, b = conv(e.children[0]), conv(e.children[1])
            return a & b if a is not None and b is not None else None
        if isinstance(e, P.Or):
            a, b = conv(e.children[0]), conv(e.children[1])
            return a | b if a is not None and b is not None else None
        if isinstance(e, (P.EqualTo, P.LessThan, P.LessThanOrEqual,
                          P.GreaterThan, P.GreaterThanOrEqual)):
            l, r = e.children
            if isinstance(l, AttributeReference) and isinstance(r, Literal):
                field = pc.field(l.name)
                v = r.value
                if isinstance(v, bytes):
                    v = v.decode()
                ops = {P.EqualTo: field.__eq__, P.LessThan: field.__lt__,
                       P.LessThanOrEqual: field.__le__,
                       P.GreaterThan: field.__gt__,
                       P.GreaterThanOrEqual: field.__ge__}
                return ops[type(e)](v)
        if isinstance(e, P.IsNotNull) and isinstance(
                e.children[0], AttributeReference):
            return pc.field(e.children[0].name).is_valid()
        return None
    out = None
    for f in filters:
        c = conv(f)
        if c is not None:
            out = c if out is None else (out & c)
    return out


# thread-local "current input file" — the source of input_file_name()
# (ref InputFileBlockRule.scala: the reference pins scan+project together
# so the value is well-defined; here the pull-based iterator chain gives
# the same guarantee in-process, and exchange readers reset it to "")
import threading as _threading

_input_file_ctx = _threading.local()


def current_input_file() -> str:
    return getattr(_input_file_ctx, "path", "")


def set_current_input_file(path: str) -> None:
    _input_file_ctx.path = path


# process-level device pin for file scans: repeated queries over the
# same unchanged files skip host decode AND re-upload (the HBM entries
# register with the spill catalog and evict first under pressure, like
# the local-scan pin)
_FILESCAN_PIN: dict = {}


class FileScanExec(Exec):
    """Columnar file scan (ref GpuFileSourceScanExec + partition readers)."""

    def __init__(self, fmt: str, paths: List[str], names, dtypes,
                 options: dict, conf, pushed_filters=None,
                 required_columns: Optional[List[str]] = None):
        super().__init__([])
        self.fmt = fmt
        self.paths = list(paths)
        self._all_names = list(names)
        self._all_types = list(dtypes)
        self.required_columns = required_columns
        self.options = options or {}
        self.conf = conf
        self.pushed_filters = pushed_filters or []
        reader_type = conf.get(cfg.PARQUET_READER_TYPE)
        if reader_type == "AUTO":
            reader_type = "MULTITHREADED" if len(self.paths) > 4 \
                else ("COALESCING" if len(self.paths) > 1 else "PERFILE")
        self.reader_type = reader_type
        self.batch_rows = conf.get(cfg.MAX_READER_BATCH_SIZE_ROWS)

    @property
    def output_names(self):
        if self.required_columns is not None:
            return list(self.required_columns)
        return self._all_names

    @property
    def output_types(self):
        if self.required_columns is not None:
            idx = {n: i for i, n in enumerate(self._all_names)}
            return [self._all_types[idx[n]] for n in self.required_columns]
        return self._all_types

    @property
    def num_partitions(self):
        if self.reader_type == "COALESCING":
            return 1
        return max(1, len(self.paths))

    def describe(self):
        return (f"FileScan {self.fmt} [{len(self.paths)} files, "
                f"{self.reader_type}] cols={self.output_names}")

    def estimated_size_bytes(self):
        import os
        total = 0
        for p in self.paths:
            try:
                total += os.path.getsize(p)
            except OSError:
                return None
        # columnar files are compressed on disk; in-memory blowup factor
        # mirrors Spark's fileCompressionFactor default
        return int(total * 3) if self.fmt in ("parquet", "orc") else total

    # -- host decode ---------------------------------------------------------
    def _read_file(self, path: str) -> pa.Table:
        cols = self.output_names
        filt = _pushdown_to_arrow(self.pushed_filters, cols) \
            if self.fmt in ("parquet", "orc") else None
        if self.fmt == "parquet":
            if filt is not None:
                ds = pads.dataset(path, format="parquet")
                return ds.to_table(columns=cols, filter=filt)
            return papq.read_table(path, columns=cols, use_threads=False)
        if self.fmt == "orc":
            tbl = paorc.ORCFile(path).read(columns=cols)
            return tbl
        if self.fmt == "csv":
            ropts = pacsv.ReadOptions(
                autogenerate_column_names=not self.options.get("header",
                                                               True))
            copts = pacsv.ConvertOptions(include_columns=cols or None)
            tbl = pacsv.read_csv(path, read_options=ropts,
                                 convert_options=copts)
            from ..columnar.interop import to_arrow_schema
            want = to_arrow_schema(self.output_names, self.output_types)
            return tbl.select(self.output_names).cast(want)
        if self.fmt == "hivetext":
            # Hive's LazySimpleSerDe text layout; positional columns, so
            # the FULL schema parses and pruning selects after.  Options
            # come from ONE definition shared with hive.read_hive_text.
            from ..columnar.interop import to_arrow_schema
            from ..hive import hive_text_read_options
            full = to_arrow_schema(self._all_names, self._all_types)
            ropts, popts, copts = hive_text_read_options(self._all_names,
                                                         full)
            tbl = pacsv.read_csv(path, read_options=ropts,
                                 parse_options=popts,
                                 convert_options=copts)
            want = to_arrow_schema(self.output_names, self.output_types)
            return tbl.select(self.output_names).cast(want)
        raise ValueError(self.fmt)

    def _emit(self, table: pa.Table, path: str = "") -> Iterator[Batch]:
        xp = self.xp
        set_current_input_file(path)
        from ..columnar.interop import to_arrow_schema
        want = to_arrow_schema(self.output_names, self.output_types)
        table = table.cast(want)
        combined = table.combine_chunks()
        n = combined.num_rows
        step = min(self.batch_rows, max(n, 1))
        off = 0
        while off < n or (n == 0 and off == 0):
            piece = combined.slice(off, step)
            rbs = piece.to_batches()
            rb = rbs[0] if rbs else pa.RecordBatch.from_pydict(
                {f.name: pa.array([], type=f.type) for f in want})
            with MetricTimer(self.metrics[OP_TIME]):
                b = batch_to_device(rb, xp=xp)
            self.metrics[NUM_OUTPUT_ROWS] += rb.num_rows
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            yield b
            off += step
            if n == 0:
                break

    def _pin_key(self, pid):
        """Process-level device pin key: file identity (path, size,
        mtime) + everything that shapes the produced batches (schema,
        filters, reader shape, decode options).  A changed file changes
        the key, so stale reads are impossible.  File idents stat once
        per exec (= per query), not once per partition."""
        import os
        ident = getattr(self, "_file_ident", None)
        if ident is None:
            ident = []
            for p in self.paths:
                try:
                    st = os.stat(p)
                    ident.append((p, st.st_size, st.st_mtime_ns))
                except OSError:
                    ident = None
                    break
            self._file_ident = ident if ident is None else tuple(ident)
            ident = self._file_ident
        if ident is None:
            return None
        return (self.fmt, ident, tuple(self.output_names),
                tuple(repr(d) for d in self.output_types),
                tuple(repr(f) for f in self.pushed_filters),
                tuple(sorted((k, repr(v))
                             for k, v in self.options.items())),
                self.reader_type, self.batch_rows, self.placement, pid)

    def execute_partition(self, pid, ctx) -> Iterator[Batch]:
        from .. import config as cfg2
        pin = _FILESCAN_PIN if ctx.conf.get(cfg2.FILESCAN_PIN_DEVICE) \
            and self.placement == TPU else None
        key = self._pin_key(pid) if pin is not None else None
        if key is not None and key in pin:
            for path, b in pin[key]:
                set_current_input_file(path)
                self.metrics[NUM_OUTPUT_ROWS] += int(b.num_rows)
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield b
            return
        if key is not None:
            produced = []
            inner = self._execute_partition_uncached(pid, ctx)
            for path, b in self._trace_paths(inner):
                produced.append((path, b))
                yield b
            pin[key] = produced
            from ..memory.spill import SpillCatalog
            SpillCatalog.get().register_pinned(
                pin, key, [b for _, b in produced])
            return
        yield from self._execute_partition_uncached(pid, ctx)

    def _trace_paths(self, gen):
        """Pair each emitted batch with the input file current at yield
        time (input_file_name must replay correctly from the pin)."""
        for b in gen:
            yield current_input_file(), b

    def _execute_partition_uncached(self, pid, ctx) -> Iterator[Batch]:
        if not self.paths:
            from ..columnar.interop import to_arrow_schema
            yield from self._emit(to_arrow_schema(
                self.output_names, self.output_types).empty_table())
            return
        if self.reader_type == "COALESCING":
            tables = [self._read_file(p) for p in self.paths]
            yield from self._emit(pa.concat_tables(tables),
                              ",".join(self.paths))
            return
        if self.reader_type == "MULTITHREADED":
            # pool shared per exec; partition pid consumes its own file but
            # the pool prefetches the rest (cloud-reader analog)
            pool = getattr(self, "_pool", None)
            if pool is None:
                nthreads = self.conf.get(
                    cfg.PARQUET_MULTITHREAD_READ_NUM_THREADS)
                pool = self._pool = cf.ThreadPoolExecutor(
                    max_workers=min(nthreads, max(len(self.paths), 1)))
                self._futures = {
                    i: pool.submit(self._read_file, p)
                    for i, p in enumerate(self.paths)}
            yield from self._emit(self._futures[pid].result(),
                              self.paths[pid])
            return
        yield from self._emit(self._read_file(self.paths[pid]),
                              self.paths[pid])


def make_scan_exec(relation, conf, extra_filters=None) -> Exec:
    from ..plan.logical import FileRelation
    rel: FileRelation = relation
    filters = list(rel.pushed_filters) + list(extra_filters or [])
    return FileScanExec(rel.fmt, rel.paths, rel._names, rel._types,
                        rel.options, conf, filters)
