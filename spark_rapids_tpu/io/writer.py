"""Columnar file writers: parquet / orc / csv with dynamic partitioning
and write statistics.

Ref: GpuParquetFileFormat.scala, GpuOrcFileFormat.scala,
ColumnarOutputWriter.scala, GpuFileFormatWriter/DataWriter (dynamic
partition handling), BasicColumnarWriteStatsTracker.scala.
"""

from __future__ import annotations

import os
import shutil
import uuid
from typing import Dict, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.orc as paorc
import pyarrow.parquet as papq


def _host_assisted_table(df) -> Optional[pa.Table]:
    """Write-side transfer elision: when the plan is only row filtering /
    column pruning over a source whose bytes already exist on the host
    (in-memory table, file scan), fetch just the boolean keep-mask from
    the device (bit-packed by the fetch plan) and apply it to the host
    copy — instead of round-tripping the full filtered payload over the
    interconnect (the role GDS plays for the reference's write path:
    never moving bytes that don't have to move, ref
    GpuParquetFileFormat.scala).  Returns None when the plan computes
    anything beyond selection, so the caller falls back to collect()."""
    from ..expr.core import Alias, AttributeReference
    from ..expr.predicates import And
    from ..plan import logical as L

    lp = df._lp
    conditions = []
    node = lp
    while True:
        if isinstance(node, L.Project):
            if not all(isinstance(e, AttributeReference)
                       for e in node.exprs):
                return None
            node = node.children[0]
        elif isinstance(node, L.Filter):
            conditions.append(node.condition)
            node = node.children[0]
        elif isinstance(node, (L.LocalRelation, L.FileRelation)):
            break
        else:
            return None

    if isinstance(node, L.LocalRelation):
        host = node.table
    else:
        # decode on host through the CPU scan path (no pushed filters,
        # so the row set matches the unfiltered mask plan below)
        from ..exec.base import ExecContext
        from .scan import make_scan_exec
        rel = L.FileRelation(node.fmt, node.paths, node._names,
                             node._types, node.options)
        host = make_scan_exec(rel, df.session.conf).execute_collect(
            ExecContext(df.session.conf))

    if conditions:
        combined = conditions[0]
        for c in conditions[1:]:
            combined = And(combined, c)
        mask_lp = L.Project([Alias(combined, "__keep__")], node)
        mask = df.session.execute(mask_lp).column("__keep__")
        # Spark's filter keeps only TRUE rows; arrow's default
        # null_selection_behavior='drop' matches
        host = host.filter(mask)
    names = lp.schema()[0]
    if list(host.schema.names) != names:
        host = host.select(names)
    return host


class WriteStatsTracker:
    """Per-job write statistics (ref BasicColumnarWriteStatsTracker)."""

    def __init__(self):
        self.num_files = 0
        self.num_rows = 0
        self.num_bytes = 0
        self.partitions: List[str] = []

    def file_written(self, path: str, rows: int):
        self.num_files += 1
        self.num_rows += rows
        try:
            self.num_bytes += os.path.getsize(path)
        except OSError:
            pass


class DataFrameWriter:
    def __init__(self, df):
        self.df = df
        self._mode = "error"
        self._partition_by: List[str] = []
        self._options: Dict = {}
        self.stats = WriteStatsTracker()

    def mode(self, m: str) -> "DataFrameWriter":
        assert m in ("error", "errorifexists", "overwrite", "append",
                     "ignore")
        self._mode = m
        return self

    def partition_by(self, *cols) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    partitionBy = partition_by

    def option(self, k, v) -> "DataFrameWriter":
        self._options[k] = v
        return self

    # -- formats -------------------------------------------------------------
    def parquet(self, path: str):
        self._write(path, "parquet")

    def orc(self, path: str):
        self._write(path, "orc")

    def csv(self, path: str):
        self._write(path, "csv")

    # -- implementation ------------------------------------------------------
    def _prepare_dir(self, path: str) -> bool:
        if os.path.exists(path):
            if self._mode == "overwrite":
                shutil.rmtree(path)
            elif self._mode == "ignore":
                return False
            elif self._mode in ("error", "errorifexists"):
                raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)
        return True

    def _write_one(self, table: pa.Table, directory: str, fmt: str):
        name = f"part-{uuid.uuid4().hex[:12]}.{fmt}"
        out = os.path.join(directory, name)
        if fmt == "parquet":
            papq.write_table(table, out,
                             compression=self._options.get("compression",
                                                           "snappy"))
        elif fmt == "orc":
            paorc.write_table(table, out)
        else:
            pacsv.write_csv(table, out)
        self.stats.file_written(out, table.num_rows)

    def _collect(self) -> pa.Table:
        from .. import config as cfg
        conf = self.df.session.conf
        if conf.sql_enabled and conf.get(cfg.HOST_ASSISTED_WRITE):
            table = _host_assisted_table(self.df)
            if table is not None:
                return table
        return self.df.collect()

    def _write(self, path: str, fmt: str):
        if not self._prepare_dir(path):
            return
        table = self._collect()
        if not self._partition_by:
            self._write_one(table, path, fmt)
            return
        # dynamic partitioning (ref GpuDynamicPartitionDataWriter):
        # one directory per distinct partition-key tuple
        keys = self._partition_by
        import pyarrow.compute as pc
        distinct = table.select(keys).group_by(keys).aggregate([])
        for row in distinct.to_pylist():
            mask = None
            for k in keys:
                col = table.column(k)
                cond = pc.is_null(col) if row[k] is None else \
                    pc.equal(col, pa.scalar(row[k], col.type))
                mask = cond if mask is None else pc.and_(mask, cond)
            part = table.filter(mask).drop_columns(keys)
            sub = os.path.join(
                path, *(f"{k}={'__HIVE_DEFAULT_PARTITION__' if row[k] is None else row[k]}"
                        for k in keys))
            os.makedirs(sub, exist_ok=True)
            self.stats.partitions.append(sub)
            self._write_one(part, sub, fmt)
