"""Columnar file writers: parquet / orc / csv with dynamic partitioning
and write statistics.

Ref: GpuParquetFileFormat.scala, GpuOrcFileFormat.scala,
ColumnarOutputWriter.scala, GpuFileFormatWriter/DataWriter (dynamic
partition handling), BasicColumnarWriteStatsTracker.scala.
"""

from __future__ import annotations

import os
import shutil
import uuid
from typing import Dict, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.orc as paorc
import pyarrow.parquet as papq


class WriteStatsTracker:
    """Per-job write statistics (ref BasicColumnarWriteStatsTracker)."""

    def __init__(self):
        self.num_files = 0
        self.num_rows = 0
        self.num_bytes = 0
        self.partitions: List[str] = []

    def file_written(self, path: str, rows: int):
        self.num_files += 1
        self.num_rows += rows
        try:
            self.num_bytes += os.path.getsize(path)
        except OSError:
            pass


class DataFrameWriter:
    def __init__(self, df):
        self.df = df
        self._mode = "error"
        self._partition_by: List[str] = []
        self._options: Dict = {}
        self.stats = WriteStatsTracker()

    def mode(self, m: str) -> "DataFrameWriter":
        assert m in ("error", "errorifexists", "overwrite", "append",
                     "ignore")
        self._mode = m
        return self

    def partition_by(self, *cols) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    partitionBy = partition_by

    def option(self, k, v) -> "DataFrameWriter":
        self._options[k] = v
        return self

    # -- formats -------------------------------------------------------------
    def parquet(self, path: str):
        self._write(path, "parquet")

    def orc(self, path: str):
        self._write(path, "orc")

    def csv(self, path: str):
        self._write(path, "csv")

    # -- implementation ------------------------------------------------------
    def _prepare_dir(self, path: str) -> bool:
        if os.path.exists(path):
            if self._mode == "overwrite":
                shutil.rmtree(path)
            elif self._mode == "ignore":
                return False
            elif self._mode in ("error", "errorifexists"):
                raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)
        return True

    def _write_one(self, table: pa.Table, directory: str, fmt: str):
        name = f"part-{uuid.uuid4().hex[:12]}.{fmt}"
        out = os.path.join(directory, name)
        if fmt == "parquet":
            papq.write_table(table, out,
                             compression=self._options.get("compression",
                                                           "snappy"))
        elif fmt == "orc":
            paorc.write_table(table, out)
        else:
            pacsv.write_csv(table, out)
        self.stats.file_written(out, table.num_rows)

    def _write(self, path: str, fmt: str):
        if not self._prepare_dir(path):
            return
        table = self.df.collect()
        if not self._partition_by:
            self._write_one(table, path, fmt)
            return
        # dynamic partitioning (ref GpuDynamicPartitionDataWriter):
        # one directory per distinct partition-key tuple
        keys = self._partition_by
        import pyarrow.compute as pc
        distinct = table.select(keys).group_by(keys).aggregate([])
        for row in distinct.to_pylist():
            mask = None
            for k in keys:
                col = table.column(k)
                cond = pc.is_null(col) if row[k] is None else \
                    pc.equal(col, pa.scalar(row[k], col.type))
                mask = cond if mask is None else pc.and_(mask, cond)
            part = table.filter(mask).drop_columns(keys)
            sub = os.path.join(
                path, *(f"{k}={'__HIVE_DEFAULT_PARTITION__' if row[k] is None else row[k]}"
                        for k in keys))
            os.makedirs(sub, exist_ok=True)
            self.stats.partitions.append(sub)
            self._write_one(part, sub, fmt)
