"""Host staging arena (RMM's pooled-allocator role on the host side,
ref GpuDeviceManager.scala:216 initializeRmm / pinned pool at :302).

A bump arena over one page-aligned native allocation: spill/shuffle
staging buffers allocate in O(1) and free all-at-once per task, so hot
paths never touch malloc.  Falls back to plain bytearray blocks when the
native library is unavailable."""

from __future__ import annotations

import ctypes
import threading
from typing import Optional

from . import get_lib


def _ledger():
    """The installed tmsan shadow ledger (no-op when disabled)."""
    from ..memory import memsan
    return memsan.active_ledger()


def _timeline():
    """The HBM observatory's occupancy timeline (None when disabled)."""
    from ..obs import memprof
    return memprof.active_timeline()


def _tenant_ctx():
    """(tenant, query) charged for the current arena operation — the
    thread's memprof attribution scope, or the unattributed sentinel.
    Arena exhaustion events historically recorded only the requesting
    operator; the tenant label is what lets the black box name the
    culprit rather than just the victim."""
    from ..obs import memprof
    ctx = memprof.current_context()
    if ctx is None:
        return memprof.UNATTRIBUTED_TENANT, ""
    return ctx


def _trace_event(name: str, **attrs) -> None:
    """Flight-recorder hook (no-op without an installed tracer)."""
    from ..obs import tracer
    tr = tracer.active_tracer()
    if tr is not None:
        tr.event(name, **attrs)


def _metrics():
    """(allocs_total, exhaustions_total, used_bytes, utilization)."""
    from ..obs import metrics as m
    return (
        m.counter("tpu_arena_allocs_total",
                  "staging-arena allocations served"),
        m.counter("tpu_arena_exhaustions_total",
                  "allocations refused because the arena was full",
                  ("tenant",)),
        m.gauge("tpu_arena_used_bytes",
                "bytes currently bump-allocated in the staging arena"),
        m.gauge("tpu_arena_utilization_ratio",
                "staging-arena used/capacity at the last allocation"),
    )


class HostArena:
    def __init__(self, capacity: int = 64 << 20):
        self.capacity = capacity
        self._closed = False
        self._arena_id = f"arena-{id(self):x}"
        self._lock = threading.Lock()
        self._lib = get_lib()
        if self._lib is not None:
            self._arena = self._lib.tpu_arena_create(capacity)
            if not self._arena:
                raise MemoryError(f"cannot reserve {capacity} arena bytes")
        else:
            self._arena = None
            self._buf = bytearray(capacity)
            self._used = 0
            self._high = 0
            self._n = 0

    def alloc(self, size: int, align: int = 64) -> Optional[memoryview]:
        """A writable view of `size` bytes, or None when exhausted."""
        led = _ledger()
        if led is not None:
            # alloc-after-close is the arena's use-after-free shape; the
            # ledger also tracks the staging high-water mark
            led.on_arena_alloc(
                self._arena_id,
                size if self._closed else self.used + size, self._closed)
        mm = _metrics()
        tenant, query = _tenant_ctx()
        with self._lock:
            if self._arena is not None:
                off = self._lib.tpu_arena_alloc(self._arena, size, align)
                if off < 0:
                    _trace_event("arena.exhausted", wanted=size,
                                 capacity=self.capacity, tenant=tenant,
                                 query=query)
                    mm[1].labels(tenant=tenant).inc()
                    return None
                base = self._lib.tpu_arena_base(self._arena)
                out = memoryview(
                    (ctypes.c_uint8 * size).from_address(
                        ctypes.addressof(base.contents) + off)).cast("B")
            else:
                off = (self._used + align - 1) & ~(align - 1)
                if off + size > self.capacity:
                    _trace_event("arena.exhausted", wanted=size,
                                 capacity=self.capacity, tenant=tenant,
                                 query=query)
                    mm[1].labels(tenant=tenant).inc()
                    return None
                self._used = off + size
                self._high = max(self._high, self._used)
                self._n += 1
                out = memoryview(self._buf)[off:off + size]
            used = self.used
        mm[0].inc()
        mm[2].set(used)
        mm[3].set(used / self.capacity if self.capacity else 0.0)
        tl = _timeline()
        if tl is not None:
            tl.on_arena_alloc(self._arena_id, used, self.capacity)
        return out

    def reset(self):
        with self._lock:
            if self._arena is not None:
                self._lib.tpu_arena_reset(self._arena)
            else:
                self._used = 0
        mm = _metrics()
        mm[2].set(0)
        mm[3].set(0.0)
        tl = _timeline()
        if tl is not None:
            tl.on_arena_reset(self._arena_id)

    def stage(self, data) -> bytes:
        """Stage a bytes-like payload through the arena: alloc, copy,
        hand back an immutable copy backed by the (page-aligned, native
        when available) staging buffer.  A full arena resets first —
        staged payloads are consumed immediately by the caller, so the
        bump pointer can recycle; a payload larger than the whole arena
        bypasses it (counted as an exhaustion by alloc())."""
        size = len(data)
        if self._closed:
            return bytes(data)
        if size == 0 or size > self.capacity:
            if size > self.capacity:
                _metrics()[1].labels(tenant=_tenant_ctx()[0]).inc()
            return bytes(data)
        mv = self.alloc(size)
        if mv is None:
            self.reset()
            mv = self.alloc(size)
            if mv is None:
                return bytes(data)
        mv[:] = data
        return bytes(mv)

    @property
    def used(self) -> int:
        if self._arena is not None:
            return self._lib.tpu_arena_used(self._arena)
        return self._used

    @property
    def high_water(self) -> int:
        if self._arena is not None:
            return self._lib.tpu_arena_high_water(self._arena)
        return self._high

    @property
    def n_allocs(self) -> int:
        if self._arena is not None:
            return self._lib.tpu_arena_allocs(self._arena)
        return self._n

    def close(self):
        if not self._closed:
            _trace_event("arena.close", high_water=self.high_water,
                         allocs=self.n_allocs)
            tl = _timeline()
            if tl is not None:
                tl.on_arena_reset(self._arena_id)
        self._closed = True
        if self._arena is not None:
            self._lib.tpu_arena_destroy(self._arena)
            self._arena = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# process-wide shared staging arena
# (spark.rapids.memory.pinnedPool.size; the reference's pinned staging
#  pool, GpuDeviceManager.scala:302 — serialize/spill payloads stage
#  through ONE page-aligned native buffer instead of per-call mallocs)
# ---------------------------------------------------------------------------

_shared: "Optional[HostArena]" = None
_shared_lock = threading.Lock()


def configure_shared_arena(capacity: int) -> "Optional[HostArena]":
    """(Re)create the shared staging arena; capacity <= 0 disables it.
    Called by the executor plugin from the pinnedPool.size config."""
    global _shared
    with _shared_lock:
        if _shared is not None:
            _shared.close()
            _shared = None
        if capacity > 0:
            _shared = HostArena(capacity)
        return _shared


def shared_arena() -> "Optional[HostArena]":
    return _shared


def stage_bytes(data) -> bytes:
    """Stage a serialized payload through the shared arena when one is
    configured (spill/shuffle serialization calls this); plain bytes
    otherwise."""
    a = _shared
    if a is None:
        return data if isinstance(data, bytes) else bytes(data)
    return a.stage(data)
