"""Host staging arena (RMM's pooled-allocator role on the host side,
ref GpuDeviceManager.scala:216 initializeRmm / pinned pool at :302).

A bump arena over one page-aligned native allocation: spill/shuffle
staging buffers allocate in O(1) and free all-at-once per task, so hot
paths never touch malloc.  Falls back to plain bytearray blocks when the
native library is unavailable."""

from __future__ import annotations

import ctypes
import threading
from typing import Optional

from . import get_lib


def _ledger():
    """The installed tmsan shadow ledger (no-op when disabled)."""
    from ..memory import memsan
    return memsan.active_ledger()


def _trace_event(name: str, **attrs) -> None:
    """Flight-recorder hook (no-op without an installed tracer)."""
    from ..obs import tracer
    tr = tracer.active_tracer()
    if tr is not None:
        tr.event(name, **attrs)


class HostArena:
    def __init__(self, capacity: int = 64 << 20):
        self.capacity = capacity
        self._closed = False
        self._arena_id = f"arena-{id(self):x}"
        self._lock = threading.Lock()
        self._lib = get_lib()
        if self._lib is not None:
            self._arena = self._lib.tpu_arena_create(capacity)
            if not self._arena:
                raise MemoryError(f"cannot reserve {capacity} arena bytes")
        else:
            self._arena = None
            self._buf = bytearray(capacity)
            self._used = 0
            self._high = 0
            self._n = 0

    def alloc(self, size: int, align: int = 64) -> Optional[memoryview]:
        """A writable view of `size` bytes, or None when exhausted."""
        led = _ledger()
        if led is not None:
            # alloc-after-close is the arena's use-after-free shape; the
            # ledger also tracks the staging high-water mark
            led.on_arena_alloc(
                self._arena_id,
                size if self._closed else self.used + size, self._closed)
        with self._lock:
            if self._arena is not None:
                off = self._lib.tpu_arena_alloc(self._arena, size, align)
                if off < 0:
                    _trace_event("arena.exhausted", wanted=size,
                                 capacity=self.capacity)
                    return None
                base = self._lib.tpu_arena_base(self._arena)
                return memoryview(
                    (ctypes.c_uint8 * size).from_address(
                        ctypes.addressof(base.contents) + off)).cast("B")
            off = (self._used + align - 1) & ~(align - 1)
            if off + size > self.capacity:
                _trace_event("arena.exhausted", wanted=size,
                             capacity=self.capacity)
                return None
            self._used = off + size
            self._high = max(self._high, self._used)
            self._n += 1
            return memoryview(self._buf)[off:off + size]

    def reset(self):
        with self._lock:
            if self._arena is not None:
                self._lib.tpu_arena_reset(self._arena)
            else:
                self._used = 0

    @property
    def used(self) -> int:
        if self._arena is not None:
            return self._lib.tpu_arena_used(self._arena)
        return self._used

    @property
    def high_water(self) -> int:
        if self._arena is not None:
            return self._lib.tpu_arena_high_water(self._arena)
        return self._high

    @property
    def n_allocs(self) -> int:
        if self._arena is not None:
            return self._lib.tpu_arena_allocs(self._arena)
        return self._n

    def close(self):
        if not self._closed:
            _trace_event("arena.close", high_water=self.high_water,
                         allocs=self.n_allocs)
        self._closed = True
        if self._arena is not None:
            self._lib.tpu_arena_destroy(self._arena)
            self._arena = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
