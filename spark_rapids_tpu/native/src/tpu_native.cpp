// Native runtime support library.
//
// The TPU-build analog of the reference's native dependencies:
//   * LZ4 block codec  (role of nvcomp, ref NvcompLZ4CompressionCodec.scala)
//     — our own implementation of the public LZ4 block format, used to
//     compress shuffle payloads and spill buffers on the host.
//   * Host arena allocator (role of RMM's pooled allocator,
//     ref GpuDeviceManager.scala:216 initializeRmm) — a bump arena with
//     aligned allocation and O(1) reset, used for host staging buffers so
//     spill/shuffle hot paths do not churn malloc.
//
// Exposed as a C ABI consumed from Python via ctypes
// (spark_rapids_tpu/native/__init__.py builds and binds it).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>

extern "C" {

// ---------------------------------------------------------------------------
// LZ4 block format codec
//
// Format (public spec): a block is a sequence of
//   [token][lit-len ext...][literals][offset LE16][match-len ext...]
// token high nibble = literal count (15 => extension bytes, each 255 adds),
// token low nibble = match length - 4 (15 => extension bytes).
// The final sequence carries literals only.  Matches must not start within
// the last 12 bytes, and must end at least 5 bytes before block end.
// ---------------------------------------------------------------------------

static inline uint32_t read32(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
}

static inline uint32_t hash32(uint32_t v) {
    return (v * 2654435761u) >> 16;  // 16-bit table index
}

// Worst-case compressed size for n input bytes.
int64_t tpu_lz4_bound(int64_t n) {
    return n + n / 255 + 16;
}

// Returns compressed size, or -1 if dst is too small.
int64_t tpu_lz4_compress(const uint8_t* src, int64_t n,
                         uint8_t* dst, int64_t dst_cap) {
    if (n < 0 || dst_cap < 0) return -1;
    const int64_t MFLIMIT = 12;   // no match may start in the last 12 bytes
    const int64_t LASTLIT = 5;    // matches end >= 5 bytes before the end
    uint32_t table[1 << 16];
    std::memset(table, 0xff, sizeof(table));  // 0xffffffff = empty

    const uint8_t* ip = src;
    const uint8_t* anchor = src;
    const uint8_t* iend = src + n;
    const uint8_t* mflimit = n > MFLIMIT ? iend - MFLIMIT : src;
    uint8_t* op = dst;
    uint8_t* oend = dst + dst_cap;

    auto emit = [&](const uint8_t* lit_start, int64_t lit_len,
                    int64_t offset, int64_t match_len) -> bool {
        // token + worst-case extensions + literals + offset
        int64_t need = 1 + lit_len / 255 + 1 + lit_len + 2 +
                       (match_len >= 0 ? match_len / 255 + 1 : 0);
        if (op + need > oend) return false;
        int64_t ml = match_len >= 0 ? match_len - 4 : 0;
        uint8_t token =
            (uint8_t)((lit_len >= 15 ? 15 : lit_len) << 4 |
                      (match_len >= 0 ? (ml >= 15 ? 15 : ml) : 0));
        *op++ = token;
        if (lit_len >= 15) {
            int64_t rest = lit_len - 15;
            while (rest >= 255) { *op++ = 255; rest -= 255; }
            *op++ = (uint8_t)rest;
        }
        std::memcpy(op, lit_start, lit_len);
        op += lit_len;
        if (match_len < 0) return true;  // final literals-only sequence
        *op++ = (uint8_t)(offset & 0xff);
        *op++ = (uint8_t)(offset >> 8);
        if (ml >= 15) {
            int64_t rest = ml - 15;
            while (rest >= 255) { *op++ = 255; rest -= 255; }
            *op++ = (uint8_t)rest;
        }
        return true;
    };

    if (n >= MFLIMIT) {
        while (ip < mflimit) {
            uint32_t h = hash32(read32(ip));
            uint32_t cand = table[h];
            table[h] = (uint32_t)(ip - src);
            const uint8_t* ref = src + cand;
            if (cand != 0xffffffffu && ip - ref <= 65535 &&
                read32(ref) == read32(ip)) {
                // extend match (end at least LASTLIT before iend)
                const uint8_t* match_limit = iend - LASTLIT;
                int64_t len = 4;
                while (ip + len < match_limit && ref[len] == ip[len]) len++;
                if (!emit(anchor, ip - anchor, ip - ref, len)) return -1;
                ip += len;
                anchor = ip;
            } else {
                ip++;
            }
        }
    }
    // final literals
    if (!emit(anchor, iend - anchor, 0, -1)) return -1;
    return op - dst;
}

// Returns decompressed size, or -1 on malformed/overflow input.
int64_t tpu_lz4_decompress(const uint8_t* src, int64_t n,
                           uint8_t* dst, int64_t dst_cap) {
    const uint8_t* ip = src;
    const uint8_t* iend = src + n;
    uint8_t* op = dst;
    uint8_t* oend = dst + dst_cap;

    while (ip < iend) {
        uint8_t token = *ip++;
        int64_t lit = token >> 4;
        if (lit == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                lit += b;
            } while (b == 255);
        }
        if (ip + lit > iend || op + lit > oend) return -1;
        std::memcpy(op, ip, lit);
        ip += lit;
        op += lit;
        if (ip >= iend) break;  // final sequence has no match part
        if (ip + 2 > iend) return -1;
        int64_t offset = ip[0] | ((int64_t)ip[1] << 8);
        ip += 2;
        if (offset == 0 || op - dst < offset) return -1;
        int64_t ml = (token & 15);
        if (ml == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                ml += b;
            } while (b == 255);
        }
        ml += 4;
        if (op + ml > oend) return -1;
        const uint8_t* match = op - offset;
        // overlapping copy must be byte-wise
        for (int64_t i = 0; i < ml; i++) op[i] = match[i];
        op += ml;
    }
    return op - dst;
}

// ---------------------------------------------------------------------------
// Host bump arena
// ---------------------------------------------------------------------------

struct Arena {
    uint8_t* base;
    int64_t capacity;
    int64_t used;
    int64_t high_water;
    int64_t n_allocs;
};

void* tpu_arena_create(int64_t capacity) {
    void* mem = nullptr;
    if (posix_memalign(&mem, 4096, (size_t)capacity) != 0) return nullptr;
    Arena* a = new (std::nothrow) Arena();
    if (!a) { free(mem); return nullptr; }
    a->base = (uint8_t*)mem;
    a->capacity = capacity;
    a->used = 0;
    a->high_water = 0;
    a->n_allocs = 0;
    return a;
}

// Returns an offset into the arena base, or -1 when exhausted.
int64_t tpu_arena_alloc(void* arena, int64_t size, int64_t align) {
    Arena* a = (Arena*)arena;
    if (align <= 0) align = 64;
    int64_t off = (a->used + align - 1) & ~(align - 1);
    if (off + size > a->capacity) return -1;
    a->used = off + size;
    if (a->used > a->high_water) a->high_water = a->used;
    a->n_allocs++;
    return off;
}

uint8_t* tpu_arena_base(void* arena) { return ((Arena*)arena)->base; }
int64_t tpu_arena_used(void* arena) { return ((Arena*)arena)->used; }
int64_t tpu_arena_high_water(void* arena) {
    return ((Arena*)arena)->high_water;
}
int64_t tpu_arena_allocs(void* arena) { return ((Arena*)arena)->n_allocs; }

void tpu_arena_reset(void* arena) { ((Arena*)arena)->used = 0; }

void tpu_arena_destroy(void* arena) {
    Arena* a = (Arena*)arena;
    free(a->base);
    delete a;
}

}  // extern "C"
