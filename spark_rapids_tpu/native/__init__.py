"""Native runtime library: build-on-first-import + ctypes binding.

The reference consumes its native muscle (cuDF/RMM/nvcomp/UCX) as
prebuilt JNI libraries; here the native layer is small enough to compile
from source at first import (g++ -O3 -shared), cached next to the source.
If no compiler is available the codec layer falls back to Python zlib —
slower, still correct — mirroring the reference's ability to run with
compression disabled."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_SRC = os.path.join(os.path.dirname(__file__), "src", "tpu_native.cpp")
_SO = os.path.join(os.path.dirname(__file__), "build", "libtpu_native.so")

_lock = threading.Lock()
_lib = None
_build_error: str = ""


def _build() -> str:
    """Ensure the .so exists: wheel installs ship it prebuilt (setup.py);
    source checkouts compile on first import; read-only installs without
    a shipped binary compile into a per-user cache dir."""
    global _SO
    if os.path.exists(_SO) and (not os.path.exists(_SRC) or
                                os.path.getmtime(_SO) >=
                                os.path.getmtime(_SRC)):
        return ""
    if not os.path.exists(_SRC):
        return f"native build failed: neither {_SO} nor {_SRC} exists"
    target = _SO
    try:
        os.makedirs(os.path.dirname(target), exist_ok=True)
        probe = os.path.join(os.path.dirname(target), ".writable")
        with open(probe, "w"):
            pass
        os.unlink(probe)
    except OSError:
        try:
            cache = os.path.join(
                os.environ.get("XDG_CACHE_HOME",
                               os.path.expanduser("~/.cache")),
                "spark_rapids_tpu")
            os.makedirs(cache, exist_ok=True)
            target = os.path.join(cache, "libtpu_native.so")
            if os.path.exists(target) and \
                    os.path.getmtime(target) >= os.path.getmtime(_SRC):
                _SO = target
                return ""
        except OSError as ex:
            # nowhere writable: record the reason; codec falls back to
            # pure python (get_lib()'s graceful-degradation contract)
            return f"native build failed: no writable dir ({ex})"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", target, _SRC]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as ex:
        return f"native build failed: {ex}"
    if r.returncode != 0:
        return f"native build failed: {r.stderr[-2000:]}"
    _SO = target
    return ""


def get_lib():
    """The loaded native library, or None (with a recorded reason)."""
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error:
            return _lib
        _build_error = _build()
        if _build_error:
            return None
        lib = ctypes.CDLL(_SO)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.tpu_lz4_bound.restype = ctypes.c_int64
        lib.tpu_lz4_bound.argtypes = [ctypes.c_int64]
        for fn in (lib.tpu_lz4_compress, lib.tpu_lz4_decompress):
            fn.restype = ctypes.c_int64
            fn.argtypes = [u8p, ctypes.c_int64, u8p, ctypes.c_int64]
        lib.tpu_arena_create.restype = ctypes.c_void_p
        lib.tpu_arena_create.argtypes = [ctypes.c_int64]
        lib.tpu_arena_alloc.restype = ctypes.c_int64
        lib.tpu_arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.c_int64]
        lib.tpu_arena_base.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.tpu_arena_base.argtypes = [ctypes.c_void_p]
        for fn in (lib.tpu_arena_used, lib.tpu_arena_high_water,
                   lib.tpu_arena_allocs):
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_void_p]
        lib.tpu_arena_reset.restype = None
        lib.tpu_arena_reset.argtypes = [ctypes.c_void_p]
        lib.tpu_arena_destroy.restype = None
        lib.tpu_arena_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def build_error() -> str:
    return _build_error
