"""Compression codecs for shuffle payloads and spill buffers.

Ref: TableCompressionCodec.scala + NvcompLZ4CompressionCodec.scala — the
reference compresses shuffle slices / spilled tables with nvcomp on the
GPU.  On the TPU build compression runs on the host around the Arrow IPC
body (the data is staged through the host for transport anyway):

  * lz4  — our own C++ LZ4-block codec (native/src/tpu_native.cpp).
  * zstd — the system libzstd, bound via ctypes (an external native
           library, exactly how the reference consumes nvcomp).
  * fallback — zlib from the Python stdlib when neither is available.

Frames carry a tiny header with the uncompressed size (the LZ4 block
format does not record it)."""

from __future__ import annotations

import ctypes
import ctypes.util
import struct
import threading
import zlib

from . import get_lib

_FRAME = struct.Struct("<qB")  # uncompressed size, backend id
_B_NATIVE_LZ4 = 1
_B_ZLIB = 2
_B_ZSTD = 3


class CodecCorruptionError(RuntimeError):
    """A compressed frame failed to decode: short/garbled header, an
    unknown backend id, or a backend reporting a size/CRC mismatch.
    Typed (instead of a bare RuntimeError) so transport and spill can
    surface corruption distinctly from infrastructure failures."""


def _unpack_frame(data: bytes):
    if len(data) < _FRAME.size:
        raise CodecCorruptionError(
            f"codec frame too short: {len(data)} bytes < "
            f"{_FRAME.size}-byte header")
    n, backend = _FRAME.unpack_from(data, 0)
    if n < 0:
        raise CodecCorruptionError(
            f"codec frame declares negative size {n}")
    return n, backend, data[_FRAME.size:]


# --- lz4 -------------------------------------------------------------------

def lz4_compress(data: bytes) -> bytes:
    lib = get_lib()
    if lib is None:
        return _FRAME.pack(len(data), _B_ZLIB) + zlib.compress(data, 1)
    n = len(data)
    bound = lib.tpu_lz4_bound(n)
    dst = (ctypes.c_uint8 * bound)()
    src = (ctypes.c_uint8 * max(n, 1)).from_buffer_copy(data or b"\0")
    m = lib.tpu_lz4_compress(src, n, dst, bound)
    if m < 0:
        raise RuntimeError("lz4 compress overflow")
    return _FRAME.pack(n, _B_NATIVE_LZ4) + bytes(dst[:m])


def lz4_decompress(data: bytes) -> bytes:
    n, backend, body = _unpack_frame(data)
    if backend == _B_ZLIB:
        return _zlib_decompress(body, n)
    if backend != _B_NATIVE_LZ4:
        raise CodecCorruptionError(
            f"lz4 frame carries unknown backend id {backend}")
    lib = get_lib()
    if lib is None:
        raise RuntimeError(
            "payload was lz4-compressed but the native codec is "
            "unavailable: " + __import__(
                "spark_rapids_tpu.native", fromlist=["build_error"]
            ).build_error())
    dst = (ctypes.c_uint8 * max(n, 1))()
    src = (ctypes.c_uint8 * max(len(body), 1)).from_buffer_copy(body or b"\0")
    m = lib.tpu_lz4_decompress(src, len(body), dst, n)
    if m != n:
        raise CodecCorruptionError(
            f"lz4 decompress: expected {n} bytes, got {m}")
    return bytes(dst[:n])


def _zlib_decompress(body: bytes, n: int) -> bytes:
    try:
        out = zlib.decompress(body)
    except zlib.error as ex:
        raise CodecCorruptionError(f"zlib decompress failed: {ex}") from ex
    if len(out) != n:
        raise CodecCorruptionError(
            f"zlib decompress: expected {n} bytes, got {len(out)}")
    return out


# --- zstd ------------------------------------------------------------------

_zstd_lib = None
_zstd_checked = False
_zstd_init_lock = threading.Lock()


def _zstd():
    # Double-checked init: codec callers run on every thread root
    # (query threads, block-server handlers, the async fetcher).  The
    # unguarded fast-path READ is safe under the GIL; both WRITES stay
    # inside the lock, and _zstd_checked flips only after _zstd_lib is
    # fully configured, so no thread can observe checked=True with a
    # half-bound library and silently take the zlib fallback.
    global _zstd_lib, _zstd_checked
    if _zstd_checked:
        return _zstd_lib
    with _zstd_init_lock:
        if _zstd_checked:
            return _zstd_lib
        name = ctypes.util.find_library("zstd") or "libzstd.so.1"
        try:
            lib = ctypes.CDLL(name)
        except OSError:
            _zstd_checked = True
            return None
        lib.ZSTD_compressBound.restype = ctypes.c_size_t
        lib.ZSTD_compressBound.argtypes = [ctypes.c_size_t]
        lib.ZSTD_compress.restype = ctypes.c_size_t
        lib.ZSTD_compress.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                      ctypes.c_void_p, ctypes.c_size_t,
                                      ctypes.c_int]
        lib.ZSTD_decompress.restype = ctypes.c_size_t
        lib.ZSTD_decompress.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                        ctypes.c_void_p, ctypes.c_size_t]
        lib.ZSTD_isError.restype = ctypes.c_uint
        lib.ZSTD_isError.argtypes = [ctypes.c_size_t]
        _zstd_lib = lib
        _zstd_checked = True
        return _zstd_lib


def zstd_compress(data: bytes, level: int = 1) -> bytes:
    lib = _zstd()
    if lib is None:
        return _FRAME.pack(len(data), _B_ZLIB) + zlib.compress(data, 6)
    n = len(data)
    bound = lib.ZSTD_compressBound(n)
    dst = ctypes.create_string_buffer(bound)
    m = lib.ZSTD_compress(dst, bound, data, n, level)
    if lib.ZSTD_isError(m):
        raise RuntimeError("zstd compress error")
    return _FRAME.pack(n, _B_ZSTD) + dst.raw[:m]


def zstd_decompress(data: bytes) -> bytes:
    n, backend, body = _unpack_frame(data)
    if backend == _B_ZLIB:
        return _zlib_decompress(body, n)
    if backend != _B_ZSTD:
        raise CodecCorruptionError(
            f"zstd frame carries unknown backend id {backend}")
    lib = _zstd()
    if lib is None:
        raise RuntimeError("payload was zstd-compressed but libzstd "
                           "is unavailable")
    dst = ctypes.create_string_buffer(max(n, 1))
    m = lib.ZSTD_decompress(dst, n, body, len(body))
    if lib.ZSTD_isError(m) or m != n:
        raise CodecCorruptionError(
            f"zstd decompress: expected {n} bytes, got {m}")
    return dst.raw[:n]


def compress(codec: str, data: bytes) -> bytes:
    if codec == "lz4":
        return lz4_compress(data)
    if codec == "zstd":
        return zstd_compress(data)
    raise ValueError(f"unknown codec {codec!r}")


def decompress(codec: str, data: bytes) -> bytes:
    if codec == "lz4":
        return lz4_decompress(data)
    if codec == "zstd":
        return zstd_decompress(data)
    raise ValueError(f"unknown codec {codec!r}")
