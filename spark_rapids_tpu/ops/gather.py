"""Row gather over device columns (the TPU analog of cuDF gather maps,
ref JoinGatherer.scala / cudf Table.gather usage throughout the reference).

`gather_column(xp, col, indices, valid)` builds a new column whose row i is
`col[indices[i]]` (null when `valid[i]` is false).  Variable-length types
(strings, arrays) re-pack their child buffers with the searchsorted span
technique from ops/strings.py — O(out_cap + out_child_cap), static shapes.
"""

from __future__ import annotations

import numpy as np

from .. import types as t
from ..columnar.device import DeviceBatch, DeviceColumn
from . import strings as sops
from .scan import cumsum_fast


def gather_spans(xp, offsets, indices, valid, out_child_cap: int):
    """(new_offsets, src_positions, in_range) for span-structured columns."""
    idx = xp.clip(indices, 0, offsets.shape[0] - 2)
    src_start = offsets[idx]
    src_len = xp.where(valid, offsets[idx + 1] - src_start,
                       xp.zeros((), dtype=offsets.dtype))
    new_offs = xp.concatenate([
        xp.zeros((1,), offsets.dtype),
        cumsum_fast(xp, src_len, dtype=offsets.dtype)])
    p = xp.arange(out_child_cap, dtype=xp.int32)
    if xp is np:
        row = np.clip(np.searchsorted(new_offs[1:], p, side="right"),
                      0, indices.shape[0] - 1).astype(np.int32)
    else:
        from .scan import fill_rows_from_starts
        row = xp.clip(
            fill_rows_from_starts(xp, new_offs[:-1].astype(xp.int32),
                                  src_len > 0, out_child_cap),
            0, indices.shape[0] - 1)
    src_pos = src_start[row] + (p - new_offs[row])
    in_range = p < new_offs[-1]
    return new_offs, src_pos, in_range


def gather_column(xp, col: DeviceColumn, indices, valid,
                  out_char_cap: int = 0) -> DeviceColumn:
    dtype = col.dtype
    out_n = indices.shape[0]
    idx = xp.clip(indices, 0, col.capacity - 1)
    if col.validity is not None:
        new_valid = valid & col.validity[idx]
    else:
        new_valid = valid

    if isinstance(dtype, (t.StringType, t.BinaryType)):
        cap = out_char_cap or int(col.data.shape[0])
        new_offs, src_pos, in_range = gather_spans(
            xp, col.offsets, idx, new_valid, cap)
        src_pos = xp.clip(src_pos, 0, col.data.shape[0] - 1)
        chars = xp.where(in_range, col.data[src_pos],
                         xp.zeros((), dtype=xp.uint8))
        return DeviceColumn(dtype, data=chars, offsets=new_offs,
                            validity=new_valid)

    if isinstance(dtype, t.ArrayType):
        child = col.children[0]
        cap = out_char_cap or child.capacity
        new_offs, src_pos, in_range = gather_spans(
            xp, col.offsets, idx, new_valid, cap)
        src_pos = xp.clip(src_pos, 0, child.capacity - 1).astype(xp.int32)
        new_child = gather_column(xp, child, src_pos, in_range)
        return DeviceColumn(dtype, offsets=new_offs, validity=new_valid,
                            children=(new_child,))

    if isinstance(dtype, t.MapType):
        kcol, vcol = col.children
        cap = out_char_cap or kcol.capacity
        new_offs, src_pos, in_range = gather_spans(
            xp, col.offsets, idx, new_valid, cap)
        src_pos = xp.clip(src_pos, 0, kcol.capacity - 1).astype(xp.int32)
        return DeviceColumn(dtype, offsets=new_offs, validity=new_valid,
                            children=(gather_column(xp, kcol, src_pos,
                                                    in_range),
                                      gather_column(xp, vcol, src_pos,
                                                    in_range)))

    if isinstance(dtype, t.StructType):
        children = tuple(gather_column(xp, c, idx, new_valid)
                         for c in col.children)
        return DeviceColumn(dtype, validity=new_valid, children=children)

    if isinstance(dtype, t.NullType):
        return DeviceColumn(dtype, data=xp.zeros((out_n,), xp.int8),
                            validity=xp.zeros((out_n,), dtype=bool))

    data = xp.where(new_valid, col.data[idx],
                    xp.zeros((), dtype=col.data.dtype))
    out = DeviceColumn(dtype, data=data, validity=new_valid)
    if col.data_hi is not None:
        out.data_hi = xp.where(new_valid, col.data_hi[idx],
                               xp.zeros((), dtype=col.data_hi.dtype))
    return out


def gather_batch(xp, batch: DeviceBatch, indices, valid, new_num_rows,
                 char_caps=None) -> DeviceBatch:
    cols = []
    for i, c in enumerate(batch.columns):
        cc = 0 if char_caps is None else char_caps[i]
        cols.append(gather_column(xp, c, indices, valid, cc))
    return DeviceBatch(cols, new_num_rows, batch.names)
