"""TPU string kernels over (offsets:int32, chars:uint8) byte tensors.

The reference gets string kernels from libcudf (substr, concat, compare,
hash — ref GpuOverrides string rules, stringFunctions.scala).  TPUs have no
native string support, so every primitive here is expressed as static-shape
vector ops over the character buffer:

* equality    — string length + two independent 64-bit polynomial rolling
                hashes (computed in O(char_cap) with a single cumsum); the
                double hash makes false-positive probability ~2^-120 per
                pair.  Exact for strings <= PREFIX_BYTES via prefix compare.
* ordering    — big-endian packed uint64 prefix words (PREFIX_BYTES bytes);
                lexicographic byte order == numeric order of the words.
                Strings equal in the first PREFIX_BYTES bytes tie-break by
                length (documented corner: >32-byte shared-prefix ordering
                is approximate; gate via incompatibleOps like the reference
                gates corner-case ops).
* gather      — build a new (offsets, chars) pair for a row selection using
                cumsum offsets + a scatter of source spans (O(char_cap)).

All functions take `xp` (numpy or jax.numpy) so the CPU fallback engine runs
the identical semantics.
"""

from __future__ import annotations

import numpy as np
from .scan import cumsum_fast, cumprod_fast

PREFIX_BYTES = 32  # 4 uint64 words
_HASH_BASE_1 = np.uint64(0x100000001B3)          # FNV-ish odd base
_HASH_BASE_2 = np.uint64(0x9E3779B97F4A7C15)     # golden-ratio odd base
_HASH_INV_1 = np.uint64(pow(int(_HASH_BASE_1), -1, 1 << 64))
_HASH_INV_2 = np.uint64(pow(int(_HASH_BASE_2), -1, 1 << 64))


def lengths(xp, offsets):
    return offsets[1:] - offsets[:-1]


def _rolling_hash(xp, offsets, chars, base, inv_base):
    """hash_i = sum_{j in span_i} (chars[j]+1) * base^(j-start_i)  (mod 2^64).

    Computed globally: prefix[k] = sum_{j<k} (c_j+1) * base^j, then
    hash_i = (prefix[end] - prefix[start]) * base^{-start}.
    """
    n = chars.shape[0]
    powers = cumprod_fast(xp, xp.full((n,), base, dtype=xp.uint64)) * inv_base
    inv_powers = cumprod_fast(xp, xp.full((n,), inv_base, dtype=xp.uint64)) * base
    contrib = (chars.astype(xp.uint64) + xp.uint64(1)) * powers
    prefix = xp.concatenate([xp.zeros((1,), xp.uint64), cumsum_fast(xp, contrib)])
    starts = offsets[:-1].astype(xp.int32)
    ends = offsets[1:].astype(xp.int32)
    span = prefix[ends] - prefix[starts]
    # base^{-start}; start == n only for empty spans (span == 0), clip is safe
    start_inv = inv_powers[xp.clip(starts, 0, n - 1)]
    return span * start_inv


def string_hashes(xp, offsets, chars):
    """Two independent 64-bit content hashes per string."""
    h1 = _rolling_hash(xp, offsets, chars, _HASH_BASE_1, _HASH_INV_1)
    h2 = _rolling_hash(xp, offsets, chars, _HASH_BASE_2, _HASH_INV_2)
    return h1, h2


def string_eq(xp, offs_a, chars_a, offs_b, chars_b):
    """Elementwise string equality (bool[cap])."""
    la = lengths(xp, offs_a)
    lb = lengths(xp, offs_b)
    a1, a2 = string_hashes(xp, offs_a, chars_a)
    b1, b2 = string_hashes(xp, offs_b, chars_b)
    return (la == lb) & (a1 == b1) & (a2 == b2)


def prefix_words(xp, offsets, chars, n_words: int = PREFIX_BYTES // 8):
    """[cap, n_words] uint64 big-endian packed prefixes for ordering."""
    cap = offsets.shape[0] - 1
    lens = lengths(xp, offsets)
    k = xp.arange(n_words * 8, dtype=xp.int32)
    idx = offsets[:-1][:, None] + k[None, :]
    in_range = k[None, :] < lens[:, None]
    idx = xp.clip(idx, 0, chars.shape[0] - 1)
    b = xp.where(in_range, chars[idx], xp.zeros((), dtype=chars.dtype))
    b = b.astype(xp.uint64).reshape(cap, n_words, 8)
    shifts = xp.uint64(8) * (xp.uint64(7) - xp.arange(8, dtype=xp.uint64))
    words = xp.sum(b << shifts[None, None, :], axis=-1, dtype=xp.uint64)
    return words


def order_keys(xp, offsets, chars):
    """Columns (most-significant first) for lexicographic string ordering:
    prefix words then length as tie-break."""
    words = prefix_words(xp, offsets, chars)
    lens = lengths(xp, offsets).astype(xp.uint64)
    cols = [words[:, i] for i in range(words.shape[1])]
    cols.append(lens)
    return cols


def gather_strings(xp, offsets, chars, indices, valid, out_char_cap: int):
    """Build (offsets', chars') for rows chars[span(indices[i])].

    `indices` int32[out_cap] source row per output slot; `valid` bool[out_cap]
    marks live slots (invalid slots become empty strings).  O(out_cap +
    out_char_cap) using a scatter of span starts + cummax trick:

      For output position p in [0, out_char_cap): find which output row it
      belongs to via searchsorted over the new offsets, then read
      chars[src_start[row] + (p - new_start[row])].
    """
    src_start = offsets[indices]
    src_len = xp.where(valid, offsets[indices + 1] - src_start,
                       xp.zeros((), dtype=offsets.dtype))
    new_offs = xp.concatenate([
        xp.zeros((1,), offsets.dtype),
        cumsum_fast(xp, src_len, dtype=offsets.dtype)])
    p = xp.arange(out_char_cap, dtype=offsets.dtype)
    row = xp.searchsorted(new_offs[1:], p, side="right").astype(xp.int32)
    row = xp.clip(row, 0, indices.shape[0] - 1)
    src_pos = src_start[row] + (p - new_offs[row])
    src_pos = xp.clip(src_pos, 0, chars.shape[0] - 1)
    total = new_offs[-1]
    new_chars = xp.where(p < total, chars[src_pos],
                         xp.zeros((), dtype=chars.dtype))
    return new_offs, new_chars


def pack_rows(xp, bytes_mat, lens, valid, out_char_cap: int):
    """Build (offsets, chars) from left-aligned per-row byte matrices.

    bytes_mat: uint8[cap, W] with row content in columns [0, lens[i]);
    invalid rows become empty strings.  O(cap*W + out_char_cap).
    """
    cap = bytes_mat.shape[0]
    lens = xp.where(valid, lens, xp.zeros((), dtype=lens.dtype)).astype(xp.int32)
    offs = xp.concatenate([xp.zeros((1,), xp.int32),
                           cumsum_fast(xp, lens, dtype=xp.int32)])
    p = xp.arange(out_char_cap, dtype=xp.int32)
    row = xp.clip(xp.searchsorted(offs[1:], p, side="right"),
                  0, cap - 1).astype(xp.int32)
    col = xp.clip(p - offs[row], 0, bytes_mat.shape[1] - 1)
    chars = xp.where(p < offs[-1], bytes_mat[row, col],
                     xp.zeros((), dtype=xp.uint8))
    return offs, chars


def window_bytes(xp, offsets, chars, width: int):
    """[cap, width] uint8 window of each string's first `width` bytes
    (zero beyond the string's length), plus lengths."""
    lens = lengths(xp, offsets)
    k = xp.arange(width, dtype=xp.int32)
    idx = xp.clip(offsets[:-1][:, None] + k[None, :], 0, chars.shape[0] - 1)
    b = xp.where(k[None, :] < lens[:, None], chars[idx],
                 xp.zeros((), dtype=chars.dtype))
    return b, lens


def concat_char_buffers(xp, offs_list, chars_list, out_char_cap: int):
    """Concatenate several (offsets, chars) columns into one buffer."""
    total = 0
    new_chars = xp.zeros((out_char_cap,), dtype=xp.uint8)
    new_offs_parts = []
    base = xp.zeros((), dtype=offs_list[0].dtype)
    pos = xp.arange(out_char_cap, dtype=xp.int32)
    for offs, chars in zip(offs_list, chars_list):
        n = chars.shape[0]
        nbytes = offs[-1]
        in_span = (pos >= base) & (pos < base + nbytes)
        src = xp.clip(pos - base, 0, n - 1)
        new_chars = xp.where(in_span, chars[src], new_chars)
        new_offs_parts.append(offs[:-1] + base)
        base = base + nbytes
    new_offs = xp.concatenate(new_offs_parts +
                              [base[None].astype(offs_list[0].dtype)])
    return new_offs, new_chars
