"""Equi-join kernels: sorted-key binary-search probe + pair expansion.

TPU replacement for cuDF's hash join (ref GpuHashJoin.scala /
JoinGatherer.scala): instead of a device hash table, the build side's keys
collapse to a single 64-bit combined hash, get sorted once, and each probe
row finds its match range with two vectorized binary searches
(searchsorted).  Pair expansion uses the same searchsorted-span technique
as the string gather — all static shapes.

Two-phase protocol (one host sync, like cuDF sizing its gather maps):
  phase 1 (jitted `count_matches`): per-probe match ranges + totals;
  host picks a bucketed output capacity;
  phase 2 (jitted `expand_pairs`): materialize (probe_idx, build_idx,
  probe_valid, build_valid) gather maps at that static capacity.

Key hashing: per-column 64-bit words (value hash or content hash for
strings) mixed with a splitmix-style combiner.  Equal keys always collide
onto equal hashes; unequal keys collide with probability ~2^-64 —
documented, same tradeoff as the string-equality design.
"""

from __future__ import annotations

import numpy as np

from .. import types as t
from ..columnar.device import DeviceColumn
from . import strings as sops
from .scan import cumsum_fast

_MIX = np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = np.uint64(0x94D049BB133111EB)
_NULL_BUILD = np.uint64(0x9E3779B97F4A7C15)   # sentinel: build-side null key
_NULL_PROBE = np.uint64(0xC2B2AE3D27D4EB4F)   # distinct: probe-side null key


def _mix64(xp, h):
    h = (h ^ (h >> np.uint64(30))) * _MIX
    h = (h ^ (h >> np.uint64(27))) * _MIX2
    return h ^ (h >> np.uint64(31))


def combined_key_hash(xp, key_cols, cap, null_matches: bool = False,
                      side: str = "build"):
    """uint64[cap] combined hash over the key columns; rows with any null
    key get a side-specific sentinel so nulls never match (unless
    null_matches, for null-safe equality)."""
    from .segmented import encode_float_ordered, encode_int_ordered
    h = xp.full((cap,), np.uint64(0x12345678DEADBEEF), dtype=xp.uint64)
    any_null = xp.zeros((cap,), dtype=bool)
    for col in key_cols:
        dtype = col.dtype
        if isinstance(dtype, (t.StringType, t.BinaryType)):
            h1, h2 = sops.string_hashes(xp, col.offsets, col.data)
            w = _mix64(xp, h1 ^ (h2 * _MIX))
        elif isinstance(dtype, (t.FloatType, t.DoubleType)):
            w = _mix64(xp, encode_float_ordered(xp, col.data))
        elif isinstance(dtype, t.NullType):
            w = xp.zeros((cap,), dtype=xp.uint64)
        else:
            w = _mix64(xp, encode_int_ordered(xp, col.data))
        h = _mix64(xp, h ^ (w + np.uint64(0x9E3779B97F4A7C15) +
                            (h << np.uint64(6)) + (h >> np.uint64(2))))
        if col.validity is not None:
            any_null = any_null | ~col.validity
    if not null_matches:
        sentinel = _NULL_BUILD if side == "build" else _NULL_PROBE
        h = xp.where(any_null, sentinel + xp.arange(cap, dtype=xp.uint64)
                     * xp.uint64(2654435761), h)
    return h


def count_matches(xp, build_hash, build_live, probe_hash, probe_live):
    """Per-probe-row match ranges against the sorted build side.

    Returns (sorted_build_order, lo, counts) where build rows
    sorted_build_order[lo[i]:lo[i]+counts[i]] match probe row i.

    TPU path: ONE combined stable sort over (hash, side, index) finds
    every probe row's build run — within a hash segment build rows sort
    first, so a probe row's running build count minus the count at the
    segment start is exactly its match count, and the count at the
    segment start is its `lo` into the hash-sorted build order.  A
    per-position binary search (searchsorted) would cost ~log(n) gather
    rounds; this is one sort + two scans + two int32 scatters."""
    cap_b = build_hash.shape[0]
    # park dead build rows at +inf end
    bh = xp.where(build_live, build_hash, xp.uint64(0xFFFFFFFFFFFFFFFF))
    if xp is np:
        order = np.argsort(bh, kind="stable").astype(np.int32)
        sorted_h = bh[order]
        lo = np.searchsorted(sorted_h, probe_hash, side="left").astype(
            np.int32)
        hi = np.searchsorted(sorted_h, probe_hash, side="right").astype(
            np.int32)
        counts = np.where(probe_live, hi - lo, 0).astype(np.int64)
        return order, lo, counts
    from jax import lax
    from .scan import cummax_i32, cumsum_fast
    cap_p = probe_hash.shape[0]
    iota_b = xp.arange(cap_b, dtype=xp.int32)
    _, order = lax.sort((bh, iota_b), num_keys=1, is_stable=True)
    allh = xp.concatenate([bh, probe_hash])
    side = xp.concatenate([xp.zeros((cap_b,), xp.uint8),
                           xp.ones((cap_p,), xp.uint8)])
    idx = xp.concatenate([iota_b, xp.arange(cap_p, dtype=xp.int32)])
    sh, ss, si = lax.sort((allh, side, idx), num_keys=2, is_stable=True)
    is_b = (ss == 0).astype(xp.int32)
    prev = xp.concatenate([sh[:1], sh[:-1]])
    nb = (sh != prev)
    n_all = cap_b + cap_p
    if n_all > 0:
        nb = nb | (xp.arange(n_all) == 0)
    # running build count, exclusive of the current row
    bexcl = cumsum_fast(xp, is_b) - is_b
    # broadcast the segment-start value (bexcl is non-decreasing)
    seg_start_excl = cummax_i32(xp, xp.where(nb, bexcl, xp.int32(-1)))
    cnt_row = bexcl - seg_start_excl        # builds before row in its seg
    # probe rows sort after every build row of their segment, so cnt_row
    # IS the match count; scatter (lo, cnt) to original probe positions
    probe_tgt = xp.where(ss == 1, si, xp.int32(cap_p))
    lo = xp.zeros((cap_p,), xp.int32).at[probe_tgt].set(
        seg_start_excl, mode="drop", unique_indices=True)
    cnt = xp.zeros((cap_p,), xp.int32).at[probe_tgt].set(
        cnt_row, mode="drop", unique_indices=True)
    counts = xp.where(probe_live, cnt, 0).astype(xp.int64)
    return order, lo, counts


def expand_pairs(xp, order, lo, counts, probe_live, out_cap: int,
                 join_type: str = "inner"):
    """Materialize the pair lists at static capacity `out_cap`.

    Returns (probe_idx, build_idx, pair_valid, probe_side_valid,
    build_side_valid, total).  For outer-left, probe rows with zero
    matches emit one pair with build side invalid."""
    outer_left = join_type in ("left", "full")
    eff_counts = xp.maximum(counts, 1) if outer_left else counts
    eff_counts = xp.where(probe_live, eff_counts, 0)
    eff32 = eff_counts.astype(xp.int32)
    offs = xp.concatenate([xp.zeros((1,), xp.int32),
                           cumsum_fast(xp, eff32)])
    total = offs[-1].astype(xp.int64)
    p = xp.arange(out_cap, dtype=xp.int32)
    if xp is np:
        row = np.clip(np.searchsorted(offs[1:], p, side="right"),
                      0, counts.shape[0] - 1).astype(np.int32)
    else:
        # scatter each row's index at its span start, running-max fills
        # the span (replaces a per-position binary search)
        from .scan import fill_rows_from_starts
        row = xp.clip(fill_rows_from_starts(xp, offs[:-1], eff32 > 0,
                                            out_cap),
                      0, counts.shape[0] - 1)
    k = (p - offs[row]).astype(xp.int32)
    pair_valid = p < total
    matched = counts[row] > 0
    build_pos = xp.clip(lo[row] + xp.minimum(k, xp.maximum(
        counts[row].astype(xp.int32) - 1, 0)), 0, order.shape[0] - 1)
    build_idx = order[build_pos]
    build_valid = pair_valid & matched
    probe_idx = row
    probe_valid = pair_valid
    return probe_idx, build_idx, pair_valid, probe_valid, build_valid, total


def build_matched_flags(xp, order, lo, counts, probe_live, build_cap: int):
    """bool[build_cap]: build rows matched by at least one probe row
    (for right/full outer unmatched emission).  Scatter +1 at range starts
    and -1 after range ends over sorted positions, prefix-sum."""
    n = counts.shape[0]
    delta = xp.zeros((build_cap + 1,), dtype=xp.int32)
    starts = xp.clip(lo, 0, build_cap)
    ends = xp.clip(lo + counts.astype(xp.int32), 0, build_cap)
    live = probe_live & (counts > 0)
    if xp is np:
        np.add.at(delta, starts[live], 1)
        np.add.at(delta, ends[live], -1)
    else:
        ones = live.astype(xp.int32)
        delta = delta.at[starts].add(ones)
        delta = delta.at[ends].add(-ones)
    covered = cumsum_fast(xp, delta[:-1]) > 0
    # covered is in sorted-order positions; map back to original rows
    matched = xp.zeros((build_cap,), dtype=bool)
    if xp is np:
        matched[order] = covered
    else:
        matched = matched.at[order].set(covered)
    return matched
