"""Segmented reductions + order-preserving key encodings.

The TPU replacement for cuDF's hash-based groupby (ref aggregate.scala's
cudf groupBy calls): sort rows by an order-preserving uint64 encoding of
the keys, detect segment boundaries, then segment-reduce.  Sort+segment
maps perfectly onto XLA (lax.sort is a native TPU op; segment_sum lowers
to scatter-add) and needs no dynamic shapes.

All entry points take `xp` so the numpy CPU engine shares the semantics.
"""

from __future__ import annotations

import numpy as np

from .. import types as t
from ..columnar.device import DeviceColumn
from . import strings as sops


# ---------------------------------------------------------------------------
# order-preserving uint64 encodings
# ---------------------------------------------------------------------------

def encode_int_ordered(xp, data):
    """int -> uint64 preserving order (flip sign bit)."""
    return (data.astype(xp.int64).astype(xp.uint64)
            ^ xp.uint64(0x8000000000000000))


def encode_float_ordered(xp, data):
    """float64 -> uint64 with Spark's total order (NaN last, -0==... well
    -0 sorts before +0 which matches IEEE; Spark treats -0.0 == 0.0 in
    comparisons — normalize first)."""
    d = data.astype(xp.float64)
    d = xp.where(d == 0.0, xp.zeros_like(d), d)          # -0.0 -> +0.0
    d = xp.where(xp.isnan(d), xp.full_like(d, xp.nan), d)  # canonical NaN
    bits = d.view(xp.int64) if hasattr(d, "view") else d.view(np.int64)
    neg = bits < 0
    enc = xp.where(neg, ~bits, bits | np.int64(-(2**63)))
    return enc.astype(xp.uint64)


def key_words_for_column(xp, col: DeviceColumn, live_mask,
                         for_grouping: bool = True, nulls_first: bool = True,
                         ascending: bool = True):
    """uint64 sort-key words (most-significant first) for one column.

    Word 0 is the null indicator (nulls group/sort together); remaining
    words encode the value.  Strings use content hashes when only grouping
    (equality) is needed, or prefix words for true ordering.
    """
    dtype = col.dtype
    validity = col.validity
    if validity is None:
        validity = xp.ones((col.capacity,), dtype=bool)
    null_word = xp.where(validity, xp.uint64(1 if nulls_first else 0),
                         xp.uint64(0 if nulls_first else 1))
    words = [null_word]
    if isinstance(dtype, (t.StringType, t.BinaryType)):
        if for_grouping:
            h1, h2 = sops.string_hashes(xp, col.offsets, col.data)
            words += [h1, h2]
        else:
            words += sops.order_keys(xp, col.offsets, col.data)
    elif isinstance(dtype, (t.FloatType, t.DoubleType)):
        words.append(encode_float_ordered(xp, col.data))
    elif isinstance(dtype, t.BooleanType):
        words.append(col.data.astype(xp.uint64))
    elif isinstance(dtype, t.NullType):
        pass
    elif isinstance(dtype, t.DecimalType) and col.data_hi is not None:
        # decimal128: order by (hi signed, lo unsigned) word pair
        words.append(encode_int_ordered(xp, col.data_hi))
        words.append(col.data.astype(xp.uint64))
    elif isinstance(dtype, t.StructType):
        for ch in col.children:
            words += key_words_for_column(xp, ch, live_mask, for_grouping,
                                          nulls_first, True)
    else:
        words.append(encode_int_ordered(xp, col.data))
    if not ascending:
        # descending: invert value words; the null word already encodes the
        # requested nulls_first/last placement independently
        words = [words[0]] + [~w for w in words[1:]]
    return words


def lexsort(xp, key_words, capacity: int):
    """Stable ascending lexicographic argsort over uint64 key word lists
    (most-significant first).  Uses lax.sort's multi-operand lexicographic
    mode on TPU, np.lexsort on CPU."""
    if xp is np:
        # np.lexsort: last key is primary
        return np.lexsort(tuple(reversed(key_words))).astype(np.int32)
    import jax
    from jax import lax
    iota = xp.arange(capacity, dtype=xp.int32)
    out = lax.sort(tuple(key_words) + (iota,), num_keys=len(key_words),
                   is_stable=True)
    return out[-1]


# ---------------------------------------------------------------------------
# segmented reduce
# ---------------------------------------------------------------------------

def segment_boundaries(xp, sorted_words, live_sorted):
    """new_group flags over sorted rows: first live row or any key word
    differs from the previous row's."""
    n = sorted_words[0].shape[0]
    diff = xp.zeros((n,), dtype=bool)
    for w in sorted_words:
        prev = xp.concatenate([w[:1], w[:-1]])
        d = w != prev
        diff = diff | d
    first = xp.zeros((n,), dtype=bool)
    if n > 0:
        first = xp.arange(n) == 0
    new_group = (diff | first) & live_sorted
    return new_group


def segment_ids(xp, new_group):
    return (xp.cumsum(new_group.astype(xp.int32)) - 1).astype(xp.int32)


def segment_reduce(xp, op: str, values, seg_ids, num_segments: int, valid):
    """Reduce `values` per segment.  Returns (out[num_segments],
    count_valid[num_segments]).  op in {sum, min, max, first, last}.
    Invalid rows don't contribute."""
    seg = xp.where(valid, seg_ids, num_segments - 1)  # park invalids anywhere
    ones = valid.astype(xp.int64)
    if xp is np:
        cnt = np.zeros((num_segments,), np.int64)
        np.add.at(cnt, seg_ids[valid], 1)
        if op == "sum":
            out = np.zeros((num_segments,), values.dtype)
            np.add.at(out, seg_ids[valid], values[valid])
        elif op == "min" or op == "max":
            init = _extreme_init(np, values.dtype, op == "min")
            out = np.full((num_segments,), init, values.dtype)
            fn = np.minimum if op == "min" else np.maximum
            fn.at(out, seg_ids[valid], values[valid])
        elif op in ("first", "last"):
            idx = np.full((num_segments,),
                          2**31 - 1 if op == "first" else -1, np.int64)
            pos = np.arange(values.shape[0], dtype=np.int64)
            (np.minimum if op == "first" else np.maximum).at(
                idx, seg_ids[valid], pos[valid])
            safe = np.clip(idx, 0, values.shape[0] - 1).astype(np.int64)
            out = values[safe]
        else:
            raise ValueError(op)
        return out, cnt
    # jax path
    import jax
    cnt = jax.ops.segment_sum(ones, seg, num_segments=num_segments)
    if op == "sum":
        vals = xp.where(valid, values, xp.zeros_like(values))
        out = jax.ops.segment_sum(vals, seg, num_segments=num_segments)
    elif op in ("min", "max"):
        init = _extreme_init(xp, values.dtype, op == "min")
        vals = xp.where(valid, values, xp.full_like(values, init))
        fn = jax.ops.segment_min if op == "min" else jax.ops.segment_max
        out = fn(vals, seg, num_segments=num_segments)
    elif op in ("first", "last"):
        pos = xp.arange(values.shape[0], dtype=xp.int64)
        sentinel = np.int64(2**62) if op == "first" else np.int64(-1)
        p = xp.where(valid, pos, xp.full_like(pos, sentinel))
        fn = jax.ops.segment_min if op == "first" else jax.ops.segment_max
        idx = fn(p, seg, num_segments=num_segments)
        safe = xp.clip(idx, 0, values.shape[0] - 1).astype(xp.int32)
        out = values[safe]
    else:
        raise ValueError(op)
    return out, cnt


def segment_sum128(xp, lo, hi, seg_ids, num_segments: int, valid):
    """128-bit segmented sum over (lo: int64 bit-pattern of the unsigned
    low word, hi: int64 high word) columns.  Carries propagate through
    32-bit partial sums, so per-segment row counts up to 2^31 are exact.
    Returns (lo_out, hi_out, count_valid)."""
    mask32 = xp.uint64(0xFFFFFFFF)
    lo_u = lo.astype(xp.uint64)
    lo32 = lo_u & mask32
    hi32 = (lo_u >> xp.uint64(32)) & mask32
    seg = xp.where(valid, seg_ids, num_segments - 1)
    zero_u = xp.zeros((), xp.uint64)
    lo32 = xp.where(valid, lo32, zero_u)
    hi32 = xp.where(valid, hi32, zero_u)
    hi_v = xp.where(valid, hi, xp.zeros_like(hi))
    if xp is np:
        s0 = np.zeros((num_segments,), np.uint64)
        s1 = np.zeros((num_segments,), np.uint64)
        sh = np.zeros((num_segments,), np.int64)
        cnt = np.zeros((num_segments,), np.int64)
        np.add.at(s0, seg, lo32)
        np.add.at(s1, seg, hi32)
        np.add.at(sh, seg, hi_v)
        np.add.at(cnt, seg, valid.astype(np.int64))
    else:
        import jax
        s0 = jax.ops.segment_sum(lo32, seg, num_segments=num_segments)
        s1 = jax.ops.segment_sum(hi32, seg, num_segments=num_segments)
        sh = jax.ops.segment_sum(hi_v, seg, num_segments=num_segments)
        cnt = jax.ops.segment_sum(valid.astype(xp.int64), seg,
                                  num_segments=num_segments)
    low32 = s0 & mask32
    c0 = s0 >> xp.uint64(32)
    tmid = s1 + c0
    high32 = tmid & mask32
    c1 = (tmid >> xp.uint64(32)).astype(xp.int64)
    lo_out = (low32 | (high32 << xp.uint64(32))).astype(xp.int64)
    hi_out = sh + c1
    return lo_out, hi_out, cnt


def _extreme_init(xp, dtype, is_min: bool):
    dt = np.dtype(dtype)
    if dt.kind == "f":
        return np.array(np.inf if is_min else -np.inf, dt)
    if dt.kind == "b":
        return np.array(True if is_min else False, dt)
    info = np.iinfo(dt)
    return np.array(info.max if is_min else info.min, dt)


def first_index_per_segment(xp, seg_ids, num_segments: int, live):
    """Index of the first row of each segment (for gathering group keys)."""
    pos = xp.arange(seg_ids.shape[0], dtype=xp.int64)
    if xp is np:
        idx = np.full((num_segments,), 2**31 - 1, np.int64)
        np.minimum.at(idx, seg_ids[live], pos[live])
        return np.clip(idx, 0, seg_ids.shape[0] - 1).astype(np.int32)
    import jax
    seg = xp.where(live, seg_ids, num_segments - 1)
    p = xp.where(live, pos, xp.full_like(pos, 2**62))
    idx = jax.ops.segment_min(p, seg, num_segments=num_segments)
    return xp.clip(idx, 0, seg_ids.shape[0] - 1).astype(xp.int32)
