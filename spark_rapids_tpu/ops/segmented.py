"""Segmented reductions + order-preserving key encodings.

The TPU replacement for cuDF's hash-based groupby (ref aggregate.scala's
cudf groupBy calls): sort rows by an order-preserving word encoding of
the keys, detect segment boundaries, then segment-reduce.  Sort+segment
maps perfectly onto XLA (lax.sort is a native TPU op) and needs no
dynamic shapes.

Kernel-structure rules learned from profiling the real chip (round 4):

* 64-bit scatters (segment_sum on int64/float64/uint64) are ~1000x the
  cost of 32-bit scatters on TPU — the X64 rewrite emulates the combiner
  with carry chains.  Every reduction here is therefore built from
  32-bit scatters, elementwise ops, gathers, and Hillis-Steele scans:
  - sums of 64-bit values go through `cumsum_fast` (pad-shift scan:
    log2(n) elementwise adds; compiles in ~2s vs ~180s for the stock
    cumsum lowering and runs at memory speed for every dtype) plus two
    boundary gathers;
  - min/max of 64-bit values run a two-pass (high word, low word)
    tournament over int32-ordered halves, then gather the winning row;
  - first/last reduce int32 positions.
* Counts are int32 scatters widened to int64 at the boundary, keeping
  the external (out, cnt:int64) contract.

All entry points take `xp` so the numpy CPU engine shares the semantics.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .. import types as t
from ..columnar.device import DeviceColumn
from . import strings as sops
from .scan import cumsum_fast, cumprod_fast  # noqa: F401  (re-export)

_I32_MAX = np.int32(2**31 - 1)


# ---------------------------------------------------------------------------
# order-preserving encodings
# ---------------------------------------------------------------------------

def encode_int_ordered(xp, data):
    """int -> uint64 preserving order (flip sign bit)."""
    return (data.astype(xp.int64).astype(xp.uint64)
            ^ xp.uint64(0x8000000000000000))


def encode_float_ordered(xp, data):
    """float64 -> uint64 with Spark's total order (NaN last; Spark treats
    -0.0 == 0.0 in comparisons — normalize first)."""
    d = data.astype(xp.float64)
    d = xp.where(d == 0.0, xp.zeros_like(d), d)          # -0.0 -> +0.0
    d = xp.where(xp.isnan(d), xp.full_like(d, xp.nan), d)  # canonical NaN
    bits = d.view(xp.int64) if hasattr(d, "view") else d.view(np.int64)
    neg = bits < 0
    enc = xp.where(neg, ~bits, bits | np.int64(-(2**63)))
    return enc.astype(xp.uint64)


def encode_int_ordered32(xp, data):
    """int (<=32 bit) -> uint32 preserving order."""
    return (data.astype(xp.int32).astype(xp.uint32) ^ xp.uint32(0x80000000))


def encode_float_ordered32(xp, data):
    """float32 -> uint32 total order (NaN last, -0 == +0)."""
    d = data.astype(xp.float32)
    d = xp.where(d == 0.0, xp.zeros_like(d), d)
    d = xp.where(xp.isnan(d), xp.full_like(d, xp.nan), d)
    bits = d.view(xp.int32) if hasattr(d, "view") else d.view(np.int32)
    neg = bits < 0
    enc = xp.where(neg, ~bits, bits | np.int32(-(2**31)))
    return enc.astype(xp.uint32)


_NARROW_INTS = (t.ByteType, t.ShortType, t.IntegerType, t.DateType)


def key_words_for_column(xp, col: DeviceColumn, live_mask,
                         for_grouping: bool = True, nulls_first: bool = True,
                         ascending: bool = True):
    """Sort-key words (most-significant first) for one column.

    Word 0 is the null indicator (uint8; nulls group/sort together);
    remaining words encode the value — uint32 for types that fit 32 bits
    (half the sort-comparator cost on TPU), uint64 otherwise.  Strings
    use content hashes when only grouping (equality) is needed, or
    prefix words for true ordering."""
    dtype = col.dtype
    validity = col.validity
    if validity is None:
        validity = xp.ones((col.capacity,), dtype=bool)
    null_word = xp.where(validity, xp.uint8(1 if nulls_first else 0),
                         xp.uint8(0 if nulls_first else 1))
    words = [null_word]
    if isinstance(dtype, (t.StringType, t.BinaryType)):
        if for_grouping:
            h1, h2 = sops.string_hashes(xp, col.offsets, col.data)
            words += [h1, h2]
        else:
            words += sops.order_keys(xp, col.offsets, col.data)
    elif isinstance(dtype, t.FloatType):
        words.append(encode_float_ordered32(xp, col.data))
    elif isinstance(dtype, t.DoubleType):
        words.append(encode_float_ordered(xp, col.data))
    elif isinstance(dtype, t.BooleanType):
        words.append(col.data.astype(xp.uint8))
    elif isinstance(dtype, t.NullType):
        pass
    elif isinstance(dtype, t.DecimalType) and col.data_hi is not None:
        # decimal128: order by (hi signed, lo unsigned) word pair
        words.append(encode_int_ordered(xp, col.data_hi))
        words.append(col.data.astype(xp.uint64))
    elif isinstance(dtype, t.StructType):
        for ch in col.children:
            words += key_words_for_column(xp, ch, live_mask, for_grouping,
                                          nulls_first, True)
    elif isinstance(dtype, _NARROW_INTS):
        words.append(encode_int_ordered32(xp, col.data))
    else:
        words.append(encode_int_ordered(xp, col.data))
    if not ascending:
        # descending: invert value words; the null word already encodes the
        # requested nulls_first/last placement independently
        words = [words[0]] + [~w for w in words[1:]]
    return words


def lexsort(xp, key_words, capacity: int):
    """Stable ascending lexicographic argsort over key word lists
    (most-significant first).  Uses lax.sort's multi-operand lexicographic
    mode on TPU, np.lexsort on CPU."""
    if xp is np:
        # np.lexsort: last key is primary
        return np.lexsort(tuple(reversed(key_words))).astype(np.int32)
    from jax import lax
    iota = xp.arange(capacity, dtype=xp.int32)
    out = lax.sort(tuple(key_words) + (iota,), num_keys=len(key_words),
                   is_stable=True)
    return out[-1]


# ---------------------------------------------------------------------------
# segmented reduce
# ---------------------------------------------------------------------------

def segment_boundaries(xp, sorted_words, live_sorted):
    """new_group flags over sorted rows: first live row or any key word
    differs from the previous row's."""
    n = sorted_words[0].shape[0]
    diff = xp.zeros((n,), dtype=bool)
    for w in sorted_words:
        prev = xp.concatenate([w[:1], w[:-1]])
        d = w != prev
        diff = diff | d
    first = xp.zeros((n,), dtype=bool)
    if n > 0:
        first = xp.arange(n) == 0
    new_group = (diff | first) & live_sorted
    return new_group


def segment_ids(xp, new_group):
    if xp is np:
        return (np.cumsum(new_group.astype(np.int32), dtype=np.int32)
                - 1).astype(np.int32)
    return cumsum_fast(xp, new_group.astype(xp.int32)) - 1


def _seg_scatter_min(xp, vals_i32, seg, num_segments: int):
    import jax
    return jax.ops.segment_min(vals_i32, seg, num_segments=num_segments,
                               indices_are_sorted=False)


def _seg_scatter_max(xp, vals_i32, seg, num_segments: int):
    import jax
    return jax.ops.segment_max(vals_i32, seg, num_segments=num_segments,
                               indices_are_sorted=False)


def _park(xp, seg_ids, valid, num_segments: int):
    """Segment ids with invalid rows parked on the last slot (the 32-bit
    scatter init values make parked rows no-ops)."""
    return xp.where(valid, seg_ids, num_segments - 1).astype(xp.int32)


def _ordered_words32(xp, values, descending: bool) -> List:
    """int32-ordered word list (most-significant first) whose joint
    lexicographic order equals the value order.  1 word for <=32-bit
    dtypes, 2 words for 64-bit ones.  `descending` flips the order so a
    min-tournament computes a max."""
    dt = np.dtype(values.dtype)
    if dt.kind == "b":
        w = values.astype(xp.int32)
        return [-w] if descending else [w]
    if dt == np.float32:
        enc = encode_float_ordered32(xp, values)
        if descending:
            enc = ~enc
        return [(enc ^ xp.uint32(0x80000000)).astype(xp.int32)]
    if dt == np.float64:
        enc = encode_float_ordered(xp, values)
        if descending:
            enc = ~enc
        hi = (enc >> xp.uint64(32)).astype(xp.uint32)
        lo = enc.astype(xp.uint32)
        return [(hi ^ xp.uint32(0x80000000)).astype(xp.int32),
                (lo ^ xp.uint32(0x80000000)).astype(xp.int32)]
    if dt.itemsize <= 4:
        enc = encode_int_ordered32(xp, values)
        if descending:
            enc = ~enc
        return [(enc ^ xp.uint32(0x80000000)).astype(xp.int32)]
    enc = values.astype(xp.uint64) if dt.kind == "u" else \
        encode_int_ordered(xp, values)
    if descending:
        enc = ~enc
    hi = (enc >> xp.uint64(32)).astype(xp.uint32)
    lo = enc.astype(xp.uint32)
    return [(hi ^ xp.uint32(0x80000000)).astype(xp.int32),
            (lo ^ xp.uint32(0x80000000)).astype(xp.int32)]


def _argext_rows(xp, values, seg, num_segments: int, valid, is_min: bool):
    """Row index of the per-segment extreme value (ties -> first row),
    via a word-at-a-time int32 tournament.  Works for any seg layout."""
    words = _ordered_words32(xp, values, descending=not is_min)
    sel = valid
    iota = xp.arange(values.shape[0], dtype=xp.int32)
    for w in words:
        masked = xp.where(sel, w, _I32_MAX)
        best = _seg_scatter_min(xp, masked, seg, num_segments)
        sel = sel & (w == best[seg])
    pos = xp.where(sel, iota, _I32_MAX)
    row = _seg_scatter_min(xp, pos, seg, num_segments)
    return xp.clip(row, 0, values.shape[0] - 1).astype(xp.int32)


def _counts(xp, seg, num_segments: int, valid):
    import jax
    c = jax.ops.segment_sum(valid.astype(xp.int32), seg,
                            num_segments=num_segments)
    return c.astype(xp.int64)


def segment_reduce(xp, op: str, values, seg_ids, num_segments: int, valid,
                   sorted_ids: bool = False, ctx: Optional["SegContext"] = None):
    """Reduce `values` per segment.  Returns (out[num_segments],
    count_valid[num_segments]).  op in {sum, min, max, first, last}.
    Invalid rows don't contribute.

    `sorted_ids=True` asserts seg_ids is non-decreasing over rows (true
    for every sort-then-segment caller) and unlocks the scan-based sum
    path; `ctx` shares the per-kernel segment structure across ops."""
    if xp is np:
        cnt = np.zeros((num_segments,), np.int64)
        np.add.at(cnt, seg_ids[valid], 1)
        if op == "sum":
            out = np.zeros((num_segments,), values.dtype)
            np.add.at(out, seg_ids[valid], values[valid])
        elif op == "min" or op == "max":
            init = _extreme_init(np, values.dtype, op == "min")
            out = np.full((num_segments,), init, values.dtype)
            fn = np.minimum if op == "min" else np.maximum
            fn.at(out, seg_ids[valid], values[valid])
        elif op in ("first", "last"):
            idx = np.full((num_segments,),
                          2**31 - 1 if op == "first" else -1, np.int64)
            pos = np.arange(values.shape[0], dtype=np.int64)
            (np.minimum if op == "first" else np.maximum).at(
                idx, seg_ids[valid], pos[valid])
            safe = np.clip(idx, 0, values.shape[0] - 1).astype(np.int64)
            out = values[safe]
        else:
            raise ValueError(op)
        return out, cnt

    # jax path — 32-bit scatters / scans only
    seg = _park(xp, seg_ids, valid, num_segments)
    cnt = ctx.counts_for(xp, seg, valid) if ctx is not None else \
        _counts(xp, seg, num_segments, valid)
    if op == "sum":
        dt = np.dtype(values.dtype)
        if dt.itemsize <= 4:
            import jax
            out = jax.ops.segment_sum(
                xp.where(valid, values, xp.zeros_like(values)), seg,
                num_segments=num_segments)
            return out, cnt
        vals0 = xp.where(valid, values, xp.zeros_like(values))
        if sorted_ids or ctx is not None:
            is_float = dt.kind == "f"
            if is_float:
                # prefix-sum differencing would let one segment's inf/nan
                # poison every later segment (inf - inf = nan).  Scan only
                # the finite values and rebuild IEEE addition semantics
                # from per-segment flags (int32 scatter-max is free).
                finite = xp.isfinite(vals0)
                scan_vals = xp.where(finite, vals0, xp.zeros_like(vals0))
                flag = xp.where(
                    valid & xp.isnan(values), xp.int32(4),
                    xp.where(valid & (values == xp.inf), xp.int32(1),
                             xp.where(valid & (values == -xp.inf),
                                      xp.int32(2), xp.int32(0))))
                has_pi = _seg_scatter_max(
                    xp, (flag == 1).astype(xp.int32), seg, num_segments)
                has_ni = _seg_scatter_max(
                    xp, (flag == 2).astype(xp.int32), seg, num_segments)
                has_nan = _seg_scatter_max(
                    xp, (flag == 4).astype(xp.int32), seg, num_segments)
            else:
                scan_vals = vals0
            cs = cumsum_fast(xp, scan_vals)
            iota = xp.arange(values.shape[0], dtype=xp.int32)
            if ctx is not None:
                # ctx start/end bracket every live row of the segment;
                # vals0 is masked to this op's own validity, so the span
                # sum is exact for any valid subset of live rows
                sp, ep = ctx.startpos, ctx.endpos
            else:
                sp = _seg_scatter_min(
                    xp, xp.where(valid, iota, _I32_MAX), seg, num_segments)
                ep = _seg_scatter_max(
                    xp, xp.where(valid, iota, -_I32_MAX), seg, num_segments)
            spc = xp.clip(sp, 0, values.shape[0] - 1)
            epc = xp.clip(ep, 0, values.shape[0] - 1)
            out = cs[epc] - cs[spc] + scan_vals[spc]
            if is_float:
                out = xp.where(has_nan + (has_pi & has_ni) > 0,
                               xp.full_like(out, xp.nan), out)
                out = xp.where((has_pi > 0) & (has_ni == 0) & (has_nan == 0),
                               xp.full_like(out, xp.inf), out)
                out = xp.where((has_ni > 0) & (has_pi == 0) & (has_nan == 0),
                               xp.full_like(out, -xp.inf), out)
            out = xp.where(cnt > 0, out, xp.zeros_like(out))
            return out, cnt
        # unsorted 64-bit sum: emulated scatter (rare; only reached by
        # callers that didn't sort — every engine path sorts first)
        import jax
        out = jax.ops.segment_sum(vals0, seg, num_segments=num_segments)
        return out, cnt
    if op in ("min", "max"):
        row = _argext_rows(xp, values, seg, num_segments, valid,
                           is_min=(op == "min"))
        return values[row], cnt
    if op in ("first", "last"):
        iota = xp.arange(values.shape[0], dtype=xp.int32)
        if op == "first":
            pos = xp.where(valid, iota, _I32_MAX)
            idx = _seg_scatter_min(xp, pos, seg, num_segments)
        else:
            pos = xp.where(valid, iota, -_I32_MAX)
            idx = _seg_scatter_max(xp, pos, seg, num_segments)
        safe = xp.clip(idx, 0, values.shape[0] - 1).astype(xp.int32)
        return values[safe], cnt
    raise ValueError(op)


class SegContext:
    """Per-kernel segment structure shared across segment_reduce calls:
    start/end row positions per slot and a per-validity-mask count cache.
    Valid for sorted seg_ids only (rows of a segment contiguous)."""

    def __init__(self, startpos, endpos, live_sorted):
        self.startpos = startpos
        self.endpos = endpos
        self._live = live_sorted
        self._cnt_cache: dict = {}

    def matches(self, valid) -> bool:
        return valid is self._live

    def counts_for(self, xp, seg, valid):
        # cache retains the mask: a bare id() key could alias a NEW mask
        # after a temporary is collected (np engine path)
        key = id(valid)
        hit = self._cnt_cache.get(key)
        if hit is not None and hit[0] is valid:
            return hit[1]
        cnt = _counts(xp, seg, self.startpos.shape[0], valid)
        self._cnt_cache[key] = (valid, cnt)
        return cnt


def build_segment_ctx(xp, seg_ids, num_segments: int, live_sorted):
    """Shared (startpos, endpos) per slot for a sorted segment layout."""
    iota = xp.arange(seg_ids.shape[0], dtype=xp.int32)
    seg = _park(xp, seg_ids, live_sorted, num_segments)
    sp = _seg_scatter_min(xp, xp.where(live_sorted, iota, _I32_MAX),
                          seg, num_segments)
    ep = _seg_scatter_max(xp, xp.where(live_sorted, iota, -_I32_MAX),
                          seg, num_segments)
    return SegContext(sp, ep, live_sorted)


def segment_sum128(xp, lo, hi, seg_ids, num_segments: int, valid,
                   sorted_ids: bool = False):
    """128-bit segmented sum over (lo: int64 bit-pattern of the unsigned
    low word, hi: int64 high word) columns.  Carries propagate through
    32-bit partial sums, so per-segment row counts up to 2^31 are exact.
    Returns (lo_out, hi_out, count_valid)."""
    mask32 = xp.uint64(0xFFFFFFFF)
    lo_u = lo.astype(xp.uint64)
    lo32 = lo_u & mask32
    hi32 = (lo_u >> xp.uint64(32)) & mask32
    zero_u = xp.zeros((), xp.uint64)
    lo32 = xp.where(valid, lo32, zero_u)
    hi32 = xp.where(valid, hi32, zero_u)
    hi_v = xp.where(valid, hi, xp.zeros_like(hi))
    if xp is np:
        seg = np.where(valid, seg_ids, num_segments - 1)
        s0 = np.zeros((num_segments,), np.uint64)
        s1 = np.zeros((num_segments,), np.uint64)
        sh = np.zeros((num_segments,), np.int64)
        cnt = np.zeros((num_segments,), np.int64)
        np.add.at(s0, seg_ids[valid], lo32[valid])
        np.add.at(s1, seg_ids[valid], hi32[valid])
        np.add.at(sh, seg_ids[valid], hi_v[valid])
        np.add.at(cnt, seg_ids[valid], 1)
    else:
        # one shared (startpos, endpos) pair serves all three word sums;
        # the span-based fast path is only valid for contiguous segments
        ctx = build_segment_ctx(xp, seg_ids, num_segments, valid) \
            if sorted_ids else None
        s0, cnt = segment_reduce(xp, "sum", lo32, seg_ids, num_segments,
                                 valid, sorted_ids=sorted_ids, ctx=ctx)
        s1, _ = segment_reduce(xp, "sum", hi32, seg_ids, num_segments,
                               valid, sorted_ids=sorted_ids, ctx=ctx)
        sh, _ = segment_reduce(xp, "sum", hi_v, seg_ids, num_segments,
                               valid, sorted_ids=sorted_ids, ctx=ctx)
    low32 = s0 & mask32
    c0 = s0 >> xp.uint64(32)
    tmid = s1 + c0
    high32 = tmid & mask32
    c1 = (tmid >> xp.uint64(32)).astype(xp.int64)
    lo_out = (low32 | (high32 << xp.uint64(32))).astype(xp.int64)
    hi_out = sh + c1
    return lo_out, hi_out, cnt


def _extreme_init(xp, dtype, is_min: bool):
    dt = np.dtype(dtype)
    if dt.kind == "f":
        return np.array(np.inf if is_min else -np.inf, dt)
    if dt.kind == "b":
        return np.array(True if is_min else False, dt)
    info = np.iinfo(dt)
    return np.array(info.max if is_min else info.min, dt)


def first_index_per_segment(xp, seg_ids, num_segments: int, live,
                            ctx: Optional[SegContext] = None):
    """Index of the first row of each segment (for gathering group keys)."""
    if xp is np:
        pos = np.arange(seg_ids.shape[0], dtype=np.int64)
        idx = np.full((num_segments,), 2**31 - 1, np.int64)
        np.minimum.at(idx, seg_ids[live], pos[live])
        return np.clip(idx, 0, seg_ids.shape[0] - 1).astype(np.int32)
    if ctx is not None and ctx.matches(live):
        return xp.clip(ctx.startpos, 0, seg_ids.shape[0] - 1)
    iota = xp.arange(seg_ids.shape[0], dtype=xp.int32)
    seg = _park(xp, seg_ids, live, num_segments)
    idx = _seg_scatter_min(xp, xp.where(live, iota, _I32_MAX), seg,
                           num_segments)
    return xp.clip(idx, 0, seg_ids.shape[0] - 1).astype(xp.int32)
