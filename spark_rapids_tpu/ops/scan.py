"""Pad-shift (Hillis-Steele) prefix scans.

TPU kernel-structure note: the stock jnp.cumsum/cumprod lowering compiles
in minutes for 64-bit dtypes on this platform and the emulated scan HLO
runs far off memory speed.  log2(n) elementwise pad+combine steps compile
in ~2s and run at bandwidth for every dtype, so all engine prefix sums
route through here.
"""

from __future__ import annotations

import numpy as np


def cumsum_fast(xp, v, dtype=None, axis=None):
    """Inclusive prefix sum via pad-shift doubling.  On TPU this lowers
    to log2(n) elementwise adds (no reduce-window / scan HLO), which both
    compiles ~100x faster than jnp.cumsum for 64-bit dtypes and avoids
    the emulated-scan slow path."""
    if axis is None:
        axis = 0
    if xp is np:
        return np.cumsum(v, axis=axis, dtype=dtype)
    if dtype is not None:
        v = v.astype(dtype)
    n = v.shape[axis]
    d = 1
    index = [slice(None)] * v.ndim
    index[axis] = slice(0, n)
    index = tuple(index)
    while d < n:
        pad = [(0, 0)] * v.ndim
        pad[axis] = (d, 0)
        v = v + xp.pad(v, pad)[index]
        d *= 2
    return v


def cumprod_fast(xp, v, dtype=None):
    """Inclusive prefix product, same pad-shift structure (pads with 1)."""
    if xp is np:
        return np.cumprod(v, dtype=dtype)
    if dtype is not None:
        v = v.astype(dtype)
    n = v.shape[0]
    d = 1
    while d < n:
        v = v * xp.pad(v, (d, 0), constant_values=1)[:n]
        d *= 2
    return v

def segmented_cumsum_fast(xp, v, seg_start):
    """Inclusive PER-SEGMENT prefix sum (segments restart where seg_start
    is True) via the segmented Hillis-Steele recurrence:

        v[i] += F[i] ? 0 : v[i-d];   F[i] |= F[i-d]

    Floats need this instead of global-scan differencing: a global prefix
    sum lets one segment's magnitude cancel catastrophically against
    another's (and inf/nan poison everything downstream)."""
    n = v.shape[0]
    f = seg_start.astype(bool)
    d = 1
    while d < n:
        if xp is np:
            pv = np.concatenate([np.zeros((d,), v.dtype), v[:-d]])
            pf = np.concatenate([np.ones((d,), bool), f[:-d]])
        else:
            pv = xp.pad(v, (d, 0))[:n]
            pf = xp.pad(f, (d, 0), constant_values=True)[:n]
        v = xp.where(f, v, v + pv)
        f = f | pf
        d *= 2
    return v

def cummax_i32(xp, v):
    """Running max of an int32 array via pad-shift doubling."""
    n = v.shape[0]
    d = 1
    lo = np.iinfo(np.int32).min
    while d < n:
        if xp is np:
            prev = np.concatenate([np.full((d,), lo, v.dtype), v[:-d]])
        else:
            prev = xp.pad(v, (d, 0), constant_values=lo)[:n]
        v = xp.maximum(v, prev)
        d *= 2
    return v


def fill_rows_from_starts(xp, starts_i32, active, out_cap: int):
    """For output positions p, the index of the input row whose span
    contains p: rows scatter their index at their span start (skipped
    when inactive/empty), then a running max fills the span — the
    scatter+scan replacement for the per-position binary search
    (searchsorted costs ~log(n) gather rounds on TPU; this is one int32
    scatter plus log2(n) elementwise maxes)."""
    n = starts_i32.shape[0]
    iota = xp.arange(n, dtype=xp.int32)
    if xp is np:
        seed = np.zeros((out_cap,), np.int32)
        tgt = np.where(active, np.clip(starts_i32, 0, out_cap), out_cap)
        keep = tgt < out_cap
        np.maximum.at(seed, tgt[keep], iota[keep])
        return np.maximum.accumulate(seed)
    tgt = xp.where(active, xp.clip(starts_i32, 0, out_cap), out_cap)
    seed = xp.zeros((out_cap,), xp.int32).at[tgt].max(iota, mode="drop")
    return cummax_i32(xp, seed)


def child_row_ids(xp, offsets, cap: int, child_cap: int):
    """(row_ids[child_cap], in_range[child_cap]): the owning row of each
    child/element position under a span-offsets column."""
    pos = xp.arange(child_cap, dtype=xp.int32)
    if xp is np:
        row = np.clip(np.searchsorted(offsets[1:], pos, side="right"),
                      0, cap - 1).astype(np.int32)
    else:
        spans = offsets[1:] - offsets[:-1]
        row = xp.clip(
            fill_rows_from_starts(xp, offsets[:-1].astype(xp.int32),
                                  spans > 0, child_cap), 0, cap - 1)
    return row, pos < offsets[-1]
