"""Carry-sorts: permute whole rows through lax.sort payload operands.

Profiling the chip (round 4) showed a 1M-row gather costs ~20ms (~400MB/s
— XLA TPU gather is row-at-a-time) while adding payload operands to an
existing lax.sort is unmeasurable at the dispatch floor.  So every
sort-then-permute path in the engine (filter compaction, sort exec,
group-by, window ordering) carries its row data THROUGH the sort instead
of gathering afterwards.  Columns with span structure (strings, arrays,
maps — anything with offsets) cannot ride a row permutation and fall back
to gather_column on the carried iota.

The numpy engine mirrors the semantics with fancy indexing per lane.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from ..columnar.device import DeviceColumn
from .gather import gather_column

# ---------------------------------------------------------------------------
# Compile-lean mode
# ---------------------------------------------------------------------------
# XLA's lowering of a many-operand 64-bit lax.sort costs MINUTES of
# compile at 1M rows (docs/performance.md:44-52) — the dominant cost of
# a cache-cold novel query.  In lean mode every sort call site traces
# the SAME tiny shape instead: an iterated 2-operand (uint64 key, int32
# iota) stable sort per key word, then gathers move the payload.  Warm
# cost rises (one ~20ms gather per payload lane at 1M rows); compile
# drops by an order of magnitude.  The session picks the mode from
# spark.rapids.tpu.sort.compileLean: 'auto' = lean exactly when the
# persistent XLA compile cache is cold (a fresh deployment's first
# queries), throughput kernels once the cache is warm.

_LEAN = False


def set_compile_lean(enabled: bool) -> None:
    global _LEAN
    _LEAN = bool(enabled)


def compile_lean_enabled() -> bool:
    return _LEAN


def _sort_rows_lean(xp, key_words, cols, cap, extras):
    """Iterated-pass lexicographic sort: one (uint64, iota) stable sort
    per key word, least-significant first, then gather everything by the
    final order.  Same results as the carry path, radically cheaper to
    compile (every pass lowers the same 2-operand sort)."""
    import jax
    from jax import lax
    order = xp.arange(cap, dtype=xp.int32)
    for w in reversed(list(key_words)):
        kw = w.astype(xp.uint64)[order]
        _, order = lax.sort((kw, order), num_keys=1, is_stable=True)
    ones = xp.ones((cap,), dtype=bool)
    out_cols = [gather_column(xp, c, order, ones) for c in cols]
    out_extras = [e[order] for e in extras]
    return order, out_cols, out_extras


def carriable(col: DeviceColumn) -> bool:
    """True when every lane of the column is row-aligned (no offsets
    anywhere in the tree), so a row permutation is just a lane permute."""
    if col.offsets is not None:
        return False
    return all(carriable(c) for c in col.children)


def _permute_col_np(col: DeviceColumn, order) -> DeviceColumn:
    import jax
    return jax.tree_util.tree_map(lambda lane: lane[order], col)


def sort_rows(xp, key_words: Sequence, cols: Sequence[DeviceColumn],
              cap: int, extras: Sequence = ()):
    """Stable ascending lexicographic sort by `key_words`; rows of `cols`
    and the 1-D arrays in `extras` travel with the permutation.

    Returns (order:int32[cap], out_cols, out_extras).  Non-carriable
    columns are gathered by `order` (validity preserved; a permutation
    never invents nulls)."""
    import jax
    if xp is np:
        order = np.lexsort(tuple(reversed(list(key_words)))).astype(np.int32)
        out_extras = [e[order] for e in extras]
        out_cols = []
        for c in cols:
            if carriable(c):
                out_cols.append(_permute_col_np(c, order))
            else:
                ones = np.ones((cap,), dtype=bool)
                out_cols.append(gather_column(np, c, order, ones))
        return order, out_cols, out_extras

    if _LEAN:
        return _sort_rows_lean(xp, key_words, cols, cap, extras)

    from jax import lax
    iota = xp.arange(cap, dtype=xp.int32)
    operands: List = list(key_words) + [iota]
    # payload slots, deduped by traced-array identity (the same lane may
    # back several logical columns)
    slot_of: dict = {}
    flats: List[Tuple[object, object]] = []  # (treedef, leaf slot indices)
    for c in cols:
        if not carriable(c):
            flats.append((None, None))
            continue
        leaves, treedef = jax.tree_util.tree_flatten(c)
        idxs = []
        for leaf in leaves:
            key = id(leaf)
            if key not in slot_of:
                slot_of[key] = len(operands)
                operands.append(leaf)
            idxs.append(slot_of[key])
        flats.append((treedef, idxs))
    extra_idx = []
    for e in extras:
        key = id(e)
        if key not in slot_of:
            slot_of[key] = len(operands)
            operands.append(e)
        extra_idx.append(slot_of[key])
    res = lax.sort(tuple(operands), num_keys=len(key_words), is_stable=True)
    order = res[len(key_words)]
    out_cols = []
    for c, (treedef, idxs) in zip(cols, flats):
        if treedef is None:
            ones = xp.ones((cap,), dtype=bool)
            out_cols.append(gather_column(xp, c, order, ones))
        else:
            out_cols.append(jax.tree_util.tree_unflatten(
                treedef, [res[i] for i in idxs]))
    out_extras = [res[i] for i in extra_idx]
    return order, out_cols, out_extras


def sort_lanes(xp, key_words: Sequence, lanes: Sequence, cap: int):
    """Lane-only carry-sort: returns (order, sorted_lanes)."""
    order, _, out = sort_rows(xp, key_words, (), cap, extras=lanes)
    return order, out


def compact_rows(xp, keep, cols: Sequence[DeviceColumn], cap: int,
                 extras: Sequence = ()):
    """Stable partition: rows with keep=True move to the front in
    original order (ONE u8-key carry-sort)."""
    key = (~keep).astype(np.uint8 if xp is np else xp.uint8)
    return sort_rows(xp, [key], cols, cap, extras=extras)


def mask_validity(xp, col: DeviceColumn, mask) -> DeviceColumn:
    """AND `mask` into the validity of every node of a column tree —
    restores the 'padding rows are invalid' batch contract after a
    carry permutation moved rows past num_rows."""
    validity = mask if col.validity is None else (col.validity & mask)
    # children of span columns are child-cap aligned — only row-aligned
    # (struct) children can take the row mask
    children = col.children if col.offsets is not None else tuple(
        mask_validity(xp, c, mask) for c in col.children)
    return DeviceColumn(col.dtype, data=col.data, validity=validity,
                        offsets=col.offsets, data_hi=col.data_hi,
                        children=children)
