"""Device-resident columnar data (the TPU analog of GpuColumnVector).

Re-design of the reference's L1 columnar layer
(ref: sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java)
for XLA's compilation model:

* A `DeviceColumn` is a pytree of JAX arrays padded to a static *capacity*
  bucket; the batch's true row count travels as a traced int32 scalar.
  XLA therefore compiles each operator once per (schema, capacity bucket),
  never per row count — the TPU answer to cuDF's dynamic-size kernels.
* Null handling: a bool `validity` lane per column; data under a null is
  canonical zero.  Rows at index >= num_rows are padding: validity False.
* Strings/binary are (offsets:int32[cap+1], data:uint8[char_cap]) tensors.
* DECIMAL(p<=18) is int64 unscaled values; (p<=38) adds a `data_hi` lane.
* ARRAY adds an offsets lane over a child column; STRUCT holds children.

Everything registers with jax.tree_util so batches flow through jit/shard_map
transparently.
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from .. import types as t
from .interop import from_arrow_type, to_arrow_type

DEFAULT_ROW_BUCKETS = (1024, 8192, 65536, 262144, 1048576, 4194304)
DEFAULT_CHAR_BUCKETS = (16384, 131072, 1048576, 8388608, 67108864, 268435456)


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n; beyond the largest, round up to a power of two."""
    n = max(int(n), 1)
    for b in buckets:
        if n <= b:
            return b
    return 1 << math.ceil(math.log2(n))


def bucket_floor(target: int, buckets: Sequence[int]) -> int:
    """Largest bucket <= target; below the smallest, the smallest bucket.
    The dual of ``bucket_for``: sizing DOWN to a capacity that fits a
    budget (sort's spill chunk sizing, the TPU-L018 re-bucket repair)
    instead of UP to one that fits the data."""
    target = int(target)
    floor = buckets[0]
    for b in buckets:
        if b <= target:
            floor = b
    return floor


class DeviceColumn:
    """One column of device data.  A pytree; static aux is the SQL dtype."""

    __slots__ = ("dtype", "data", "validity", "offsets", "data_hi", "children")

    def __init__(self, dtype: t.DataType, data=None, validity=None,
                 offsets=None, data_hi=None,
                 children: Tuple["DeviceColumn", ...] = ()):
        self.dtype = dtype
        self.data = data
        self.validity = validity
        self.offsets = offsets
        self.data_hi = data_hi
        self.children = tuple(children)

    # -- pytree -------------------------------------------------------------
    def tree_flatten(self):
        leaves = (self.data, self.validity, self.offsets, self.data_hi,
                  self.children)
        return leaves, self.dtype

    @classmethod
    def tree_unflatten(cls, dtype, leaves):
        data, validity, offsets, data_hi, children = leaves
        return cls(dtype, data, validity, offsets, data_hi, children)

    # -- properties ---------------------------------------------------------
    @property
    def capacity(self) -> int:
        if self.data is not None and not isinstance(self.dtype, (t.StringType, t.BinaryType)):
            return int(self.data.shape[0])
        if self.offsets is not None:
            return int(self.offsets.shape[0]) - 1
        if self.validity is not None:
            return int(self.validity.shape[0])
        raise ValueError("empty column")

    def row_mask(self, num_rows) -> jnp.ndarray:
        return jnp.arange(self.capacity, dtype=jnp.int32) < num_rows

    def __repr__(self):
        return f"DeviceColumn({self.dtype.name}, cap={self.capacity})"


jax.tree_util.register_pytree_node(
    DeviceColumn, DeviceColumn.tree_flatten, DeviceColumn.tree_unflatten)


class DeviceBatch:
    """A batch of device columns + traced row count (analog of ColumnarBatch
    over GpuColumnVector, ref GpuColumnVector.java / ColumnarBatch)."""

    __slots__ = ("columns", "num_rows", "names")

    def __init__(self, columns: Sequence[DeviceColumn], num_rows,
                 names: Optional[Sequence[str]] = None):
        self.columns = tuple(columns)
        if isinstance(num_rows, (int, np.integer)):
            num_rows = np.int32(num_rows)
        self.num_rows = num_rows
        self.names = tuple(names) if names is not None else tuple(
            f"c{i}" for i in range(len(self.columns)))

    def tree_flatten(self):
        return (self.columns, self.num_rows), self.names

    @classmethod
    def tree_unflatten(cls, names, leaves):
        columns, num_rows = leaves
        return cls(columns, num_rows, names)

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    @property
    def capacity(self) -> int:
        if not self.columns:
            return 0
        return self.columns[0].capacity

    @property
    def dtypes(self) -> List[t.DataType]:
        return [c.dtype for c in self.columns]

    def row_mask(self) -> jnp.ndarray:
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.num_rows

    def column(self, i: int) -> DeviceColumn:
        return self.columns[i]

    def with_columns(self, columns, names=None) -> "DeviceBatch":
        return DeviceBatch(columns, self.num_rows,
                           names if names is not None else None)

    def __repr__(self):
        return (f"DeviceBatch(cap={self.capacity}, cols="
                f"{[c.dtype.name for c in self.columns]})")


jax.tree_util.register_pytree_node(
    DeviceBatch, DeviceBatch.tree_flatten, DeviceBatch.tree_unflatten)


# ---------------------------------------------------------------------------
# capacity shrink (the TPU-L018 speculative re-bucket)
# ---------------------------------------------------------------------------

def shrink_column(col: DeviceColumn, cap: int) -> DeviceColumn:
    """Slice the leading `cap` rows of a column's row-dimension arrays
    (static shapes: `cap` is a Python int known at trace time).  Only
    sound when the live rows sit at the front (a compacted filter
    output) and their count is <= cap — the caller guards that with the
    speculation machinery.  Char data and span children keep their own
    capacities (they are byte/element-bucketed, not row-bucketed)."""
    dtype = col.dtype
    validity = None if col.validity is None else col.validity[:cap]
    if isinstance(dtype, (t.StringType, t.BinaryType)):
        return DeviceColumn(dtype, data=col.data, validity=validity,
                            offsets=col.offsets[:cap + 1])
    if isinstance(dtype, (t.ArrayType, t.MapType)):
        return DeviceColumn(dtype, validity=validity,
                            offsets=col.offsets[:cap + 1],
                            children=col.children)
    if isinstance(dtype, t.StructType):
        return DeviceColumn(dtype, validity=validity,
                            children=tuple(shrink_column(c, cap)
                                           for c in col.children))
    return DeviceColumn(
        dtype,
        data=None if col.data is None else col.data[:cap],
        validity=validity,
        data_hi=None if col.data_hi is None else col.data_hi[:cap])


def shrink_batch(batch: DeviceBatch, cap: int) -> DeviceBatch:
    """Re-bucket a batch DOWN to row capacity `cap` by slicing every
    column's leading rows.  num_rows rides along unchanged (still the
    traced live count); correctness requires num_rows <= cap, which the
    caller asserts via a speculation guard (exec/base.py
    SpeculativeSizingMiss re-executes on a missed guess)."""
    if cap >= batch.capacity:
        return batch
    return DeviceBatch([shrink_column(c, cap) for c in batch.columns],
                       batch.num_rows, batch.names)


# ---------------------------------------------------------------------------
# Host (Arrow) -> device
# ---------------------------------------------------------------------------

def _np_pad(arr: np.ndarray, cap: int, fill=0) -> np.ndarray:
    n = arr.shape[0]
    if n == cap:
        return arr
    out = np.full((cap,), fill, dtype=arr.dtype)
    out[:n] = arr
    return out


def _valid_np(arr: pa.Array) -> np.ndarray:
    if arr.null_count == 0:
        return np.ones(len(arr), dtype=np.bool_)
    return np.asarray(arr.is_valid())


def _decimal_unscaled(arr: pa.Array) -> Tuple[np.ndarray, np.ndarray]:
    """Extract (lo:int64, hi:int64) unscaled little-endian halves of a
    decimal128 array directly from its buffer."""
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    buf = arr.buffers()[1]
    raw = np.frombuffer(buf, dtype=np.int64,
                        count=2 * (len(arr) + arr.offset))
    raw = raw.reshape(-1, 2)[arr.offset:arr.offset + len(arr)]
    lo = raw[:, 0].copy()
    hi = raw[:, 1].copy()
    return lo, hi


def column_to_device(arr: pa.Array, dtype: t.DataType, cap: int,
                     char_buckets: Sequence[int] = DEFAULT_CHAR_BUCKETS,
                     xp=jnp) -> DeviceColumn:
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    n = len(arr)
    validity = xp.asarray(_np_pad(_valid_np(arr), cap, False))

    if isinstance(dtype, (t.StringType, t.BinaryType)):
        target = pa.large_binary() if isinstance(dtype, t.BinaryType) else pa.large_string()
        sarr = arr.cast(target)
        if sarr.null_count:
            sarr = sarr.fill_null(b"" if isinstance(dtype, t.BinaryType) else "")
        bufs = sarr.buffers()
        offs64 = np.frombuffer(bufs[1], dtype=np.int64,
                               count=n + 1 + sarr.offset)[sarr.offset:]
        base = offs64[0]
        offs = (offs64 - base).astype(np.int32)
        nbytes = int(offs[-1])
        if bufs[2] is not None:
            chars = np.frombuffer(bufs[2], dtype=np.uint8,
                                  count=base + nbytes)[base:]
        else:
            chars = np.zeros(0, dtype=np.uint8)
        char_cap = bucket_for(max(nbytes, 1), char_buckets)
        offs_p = np.full((cap + 1,), offs[-1] if n else 0, dtype=np.int32)
        offs_p[:n + 1] = offs
        return DeviceColumn(dtype,
                            data=xp.asarray(_np_pad(chars, char_cap)),
                            validity=validity,
                            offsets=xp.asarray(offs_p))

    if isinstance(dtype, t.DecimalType):
        lo, hi = _decimal_unscaled(arr)
        lo = np.where(np.asarray(_valid_np(arr)), lo, 0)
        col = DeviceColumn(dtype, data=xp.asarray(_np_pad(lo, cap)),
                           validity=validity)
        if not dtype.is64:
            hi = np.where(np.asarray(_valid_np(arr)), hi, 0)
            col.data_hi = xp.asarray(_np_pad(hi, cap))
        return col

    if isinstance(dtype, t.ArrayType):
        larr = arr.cast(pa.large_list(to_arrow_type(dtype.element_type)))
        if larr.null_count:
            larr = larr.fill_null([])
        offs64 = np.asarray(larr.offsets)
        base = offs64[0]
        offs = (offs64 - base).astype(np.int32)
        child = larr.values[base: base + int(offs[-1])]
        child_cap = bucket_for(len(child), DEFAULT_ROW_BUCKETS)
        child_col = column_to_device(child, dtype.element_type, child_cap,
                                     char_buckets, xp)
        offs_p = np.full((cap + 1,), offs[-1] if n else 0, dtype=np.int32)
        offs_p[:n + 1] = offs
        return DeviceColumn(dtype, validity=validity,
                            offsets=xp.asarray(offs_p),
                            children=(child_col,))

    if isinstance(dtype, t.MapType):
        # map<K,V> lowers as ARRAY<STRUCT<key,value>> minus the struct
        # wrapper: offsets + (keys child, values child).  pyarrow's
        # MapArray gives slice-adjusted offsets and full children.
        offs64 = np.asarray(arr.offsets).astype(np.int64)
        base = int(offs64[0])
        offs = (offs64 - base).astype(np.int32)
        keys_src = arr.keys
        items_src = arr.items
        if arr.null_count:
            # Arrow only RECOMMENDS zero-length spans under null slots;
            # a producer emitting kv pairs under null rows would inflate
            # nkv and break the engine invariant that null rows span
            # zero entries — drop those entries and rebuild offsets
            valid_np = _valid_np(arr)
            spans = offs[1:] - offs[:-1]
            spans0 = np.where(valid_np, spans, 0)
            if not np.array_equal(spans0, spans):
                keep = np.repeat(valid_np, spans)
                keep_idx = np.flatnonzero(keep) + base
                keys_src = keys_src.take(pa.array(keep_idx))
                items_src = items_src.take(pa.array(keep_idx))
                base = 0
                offs = np.concatenate(
                    [np.zeros(1, np.int32),
                     np.cumsum(spans0, dtype=np.int32)])
        nkv = int(offs[-1]) if n else 0
        child_cap = bucket_for(max(nkv, 1), DEFAULT_ROW_BUCKETS)
        kcol = column_to_device(keys_src.slice(base, nkv), dtype.key_type,
                                child_cap, char_buckets, xp)
        vcol = column_to_device(items_src.slice(base, nkv), dtype.value_type,
                                child_cap, char_buckets, xp)
        offs_p = np.full((cap + 1,), offs[-1] if n else 0, dtype=np.int32)
        offs_p[:n + 1] = offs
        return DeviceColumn(dtype, validity=validity,
                            offsets=xp.asarray(offs_p),
                            children=(kcol, vcol))

    if isinstance(dtype, t.StructType):
        children = []
        for i, f in enumerate(dtype.fields):
            children.append(column_to_device(arr.field(i), f.data_type, cap,
                                             char_buckets, xp))
        return DeviceColumn(dtype, validity=validity, children=tuple(children))

    if isinstance(dtype, t.NullType):
        return DeviceColumn(dtype, data=xp.zeros((cap,), xp.int8),
                            validity=xp.zeros((cap,), bool))

    # flat types
    np_dt = t.to_np_dtype(dtype)
    if arr.null_count:
        arr = arr.fill_null(False if isinstance(dtype, t.BooleanType) else 0)
    if isinstance(dtype, t.DateType):
        npdata = np.asarray(arr.cast(pa.int32()))
    elif isinstance(dtype, t.TimestampType):
        npdata = np.asarray(arr.cast(pa.timestamp("us", tz="UTC")).cast(pa.int64()))
    else:
        npdata = arr.to_numpy(zero_copy_only=False).astype(np_dt, copy=False)
    return DeviceColumn(dtype, data=xp.asarray(_np_pad(npdata, cap)),
                        validity=validity)


def batch_to_device(rb: pa.RecordBatch,
                    row_buckets: Sequence[int] = DEFAULT_ROW_BUCKETS,
                    char_buckets: Sequence[int] = DEFAULT_CHAR_BUCKETS,
                    capacity: Optional[int] = None, xp=jnp) -> DeviceBatch:
    """Upload an Arrow RecordBatch, padding to a capacity bucket."""
    n = rb.num_rows
    cap = capacity if capacity is not None else bucket_for(n, row_buckets)
    cols = []
    for i, f in enumerate(rb.schema):
        dtype = from_arrow_type(f.type)
        cols.append(column_to_device(rb.column(i), dtype, cap, char_buckets, xp))
    return DeviceBatch(cols, n, names=rb.schema.names)


# ---------------------------------------------------------------------------
# Device -> host (Arrow)
# ---------------------------------------------------------------------------

def column_to_arrow(col: DeviceColumn, n: int) -> pa.Array:
    validity = np.asarray(col.validity)[:n] if col.validity is not None else None
    mask = None if validity is None else ~validity
    dtype = col.dtype

    if isinstance(dtype, (t.StringType, t.BinaryType)):
        offs = np.asarray(col.offsets)[:n + 1].astype(np.int64)
        chars = np.asarray(col.data)
        nbytes = int(offs[-1]) if n else 0
        pa_type = pa.large_binary() if isinstance(dtype, t.BinaryType) else pa.large_string()
        arr = pa.Array.from_buffers(
            pa_type, n,
            [None, pa.py_buffer(offs.tobytes()),
             pa.py_buffer(chars[:max(nbytes, 1)].tobytes())])
        if mask is not None and mask.any():
            arr = pa.array(
                [None if m else v for v, m in zip(arr.to_pylist(), mask)],
                type=pa_type)
        return arr

    if isinstance(dtype, t.DecimalType):
        lo = np.asarray(col.data)[:n]
        if dtype.is64:
            vals = [None if (mask is not None and m) else int(v)
                    for v, m in zip(lo, mask if mask is not None else np.zeros(n, bool))]
        else:
            hi = np.asarray(col.data_hi)[:n]
            vals = []
            msk = mask if mask is not None else np.zeros(n, bool)
            for v_lo, v_hi, m in zip(lo, hi, msk):
                if m:
                    vals.append(None)
                else:
                    vals.append((int(v_hi) << 64) | (int(v_lo) & ((1 << 64) - 1)))
        import decimal as pydec
        scale = dtype.scale
        py = [None if v is None else
              pydec.Decimal(v).scaleb(-scale) for v in vals]
        return pa.array(py, type=pa.decimal128(dtype.precision, dtype.scale))

    if isinstance(dtype, t.ArrayType):
        offs = np.asarray(col.offsets)[:n + 1].astype(np.int64)
        child_n = int(offs[-1]) if n else 0
        child = column_to_arrow(col.children[0], child_n)
        arr = pa.LargeListArray.from_arrays(pa.array(offs, type=pa.int64()),
                                            child)
        if mask is not None and mask.any():
            arr = pa.array([None if m else v
                            for v, m in zip(arr.to_pylist(), mask)],
                           type=pa.large_list(to_arrow_type(dtype.element_type)))
        return arr

    if isinstance(dtype, t.MapType):
        offs = np.asarray(col.offsets)[:n + 1].astype(np.int32)
        child_n = int(offs[-1]) if n else 0
        keys = column_to_arrow(col.children[0], child_n)
        items = column_to_arrow(col.children[1], child_n)
        arr = pa.MapArray.from_arrays(pa.array(offs, type=pa.int32()),
                                      keys, items)
        if mask is not None and mask.any():
            arr = pa.array([None if m else v
                            for v, m in zip(arr.to_pylist(), mask)],
                           type=to_arrow_type(dtype))
        return arr

    if isinstance(dtype, t.StructType):
        children = [column_to_arrow(c, n) for c in col.children]
        names = [f.name for f in dtype.fields]
        arr = pa.StructArray.from_arrays(children, names=names)
        if mask is not None and mask.any():
            arr = pa.array([None if m else v
                            for v, m in zip(arr.to_pylist(), mask)],
                           type=to_arrow_type(dtype))
        return arr

    if isinstance(dtype, t.NullType):
        return pa.nulls(n)

    data = np.asarray(col.data)[:n]
    if isinstance(dtype, t.DateType):
        return pa.array(data.astype(np.int32), type=pa.date32(),
                        mask=mask)
    if isinstance(dtype, t.TimestampType):
        return pa.array(data.astype(np.int64),
                        type=pa.timestamp("us", tz="UTC"), mask=mask)
    if isinstance(dtype, t.BooleanType):
        data = data.astype(np.bool_)
    return pa.array(data, type=to_arrow_type(dtype), mask=mask)


def batch_to_arrow(batch: DeviceBatch) -> pa.RecordBatch:
    n = int(batch.num_rows)
    arrays = [column_to_arrow(c, n) for c in batch.columns]
    names = list(batch.names)
    return pa.RecordBatch.from_arrays(arrays, names=names)
