"""Single-round-trip device->host batch fetch.

The reference copies result batches over PCIe where per-transfer latency is
microseconds (GpuColumnarToRowExec.scala:358 pulls each column's buffers).
A tunneled TPU is a different animal: every host<->device round trip costs
tens of milliseconds of fixed latency and host bandwidth is ~tens of MB/s,
so the naive per-buffer fetch (one transfer per data/validity/offsets lane)
is the dominant query cost.  This module fetches a whole DeviceBatch in
exactly TWO round trips, transferring only the rows that exist AND only the
bytes that carry information:

  1. `sizes`: one jitted call returns [num_rows, var_len_0, ...] (char
     counts for strings, child row counts for arrays) plus per-lane stats
     (all-valid flags for bool lanes; min/max for integer lanes) as a
     single tiny array — one sync that also acts as the pipeline barrier.
  2. `shrink_pack`: a jitted function (cached per schema/capacity/plan)
     slices every lane to the smallest capacity bucket holding num_rows,
     then applies the transfer plan the host derived from the stats:
       * bool lanes that are all-true up to num_rows are SKIPPED (the
         host resynthesizes them from num_rows);
       * remaining bool lanes bit-pack 8 rows per byte;
       * integer lanes whose value range fits a narrower width travel as
         (lane - min) in uint8/16/32 — the device re-derives min so the
         plan key stays value-independent; the host adds back the min it
         already fetched with the sizes;
     and concatenates the lanes into one buffer PER TRANSFERRED DTYPE.
     No 64-bit bitcasting — the TPU X64-rewrite pass cannot compile it.

The host then rebuilds numpy-backed DeviceColumns from views of those
buffers; Arrow conversion proceeds on host exactly as before.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from .device import DeviceBatch, DeviceColumn, bucket_for, \
    DEFAULT_CHAR_BUCKETS, DEFAULT_ROW_BUCKETS


def _is_device(x) -> bool:
    return isinstance(x, jax.Array)


def _note_crossing(transfers: int, nbytes: int) -> None:
    """Account one device->host fetch: continuous counters plus a
    flight-recorder event — the per-query crossing count is a
    DETERMINISTIC regression-watchdog field (obs/history.py), so every
    sanctioned crossing must announce itself here."""
    from ..obs import metrics as m
    from ..obs.tracer import trace_event
    m.counter("tpu_fetch_crossings_total",
              "device->host transfer round trips through the "
              "sanctioned fetch path").inc(transfers)
    m.counter("tpu_fetch_bytes_total",
              "bytes moved device->host through the sanctioned fetch "
              "path").inc(nbytes)
    trace_event("fetch.crossing", transfers=transfers, bytes=nbytes)


def fetch_ints(scalars: Sequence) -> List[int]:
    """Resolve a mixed list of host/device integer scalars to python ints
    in at most ONE device transfer.

    This is the sanctioned crossing for host-driven control flow that
    needs a handful of device scalars (span byte counts, slice bounds):
    callers stack every scalar they need and pay a single tunnel round
    trip instead of one per value (TPU-R001's whole point)."""
    dev_idx: List[int] = []
    dev_vals: List = []
    out: List[Optional[int]] = []
    for s in scalars:
        if _is_device(s):
            out.append(None)
            dev_idx.append(len(out) - 1)
            dev_vals.append(jnp.asarray(s).astype(jnp.int64))
        else:
            out.append(int(s))
    if dev_vals:
        fetched = np.asarray(jnp.stack(dev_vals))  # one transfer
        _note_crossing(1, fetched.nbytes)
        for i, v in zip(dev_idx, fetched):
            out[i] = int(v)
    return out  # type: ignore[return-value]


def fetch_array(x) -> np.ndarray:
    """Sanctioned single-transfer host materialization of one device
    array (e.g. the join count phase's stacked sizes vector)."""
    out = np.asarray(x)
    if _is_device(x):
        _note_crossing(1, out.nbytes)
    return out


def batch_is_device(batch: DeviceBatch) -> bool:
    return any(_is_device(l) for l in jax.tree_util.tree_leaves(batch))


class FetchLayoutError(RuntimeError):
    """Device pack and host unpack disagreed about the buffer layout."""


# ---------------------------------------------------------------------------
# canonical lane walk (matches DeviceColumn.tree_flatten leaf order)
# ---------------------------------------------------------------------------

def _walk_lanes(col: DeviceColumn):
    """Yield (kind, lane) for every present lane: data, validity, offsets,
    data_hi, then children recursively — the tree_flatten leaf order."""
    if col.data is not None:
        yield ("data", col.data)
    if col.validity is not None:
        yield ("validity", col.validity)
    if col.offsets is not None:
        yield ("offsets", col.offsets)
    if col.data_hi is not None:
        yield ("hi", col.data_hi)
    for ch in col.children:
        yield from _walk_lanes(ch)


def _np_dtype_of(x) -> np.dtype:
    return np.dtype(x.dtype.name if hasattr(x.dtype, "name") else x.dtype)


# ---------------------------------------------------------------------------
# sizes + stats: [num_rows, varlen..., lane stats...] in walk order
# ---------------------------------------------------------------------------

def _var_sizes(col: DeviceColumn, n) -> List:
    """Device scalars for every variable-length lane under `col`, in a
    deterministic walk order shared with _shrink_column."""
    out: List = []
    dt = col.dtype
    if isinstance(dt, (t.StringType, t.BinaryType)):
        out.append(col.offsets[n].astype(jnp.int64))
    elif isinstance(dt, t.ArrayType):
        m = col.offsets[n]
        out.append(m.astype(jnp.int64))
        out += _var_sizes(col.children[0], m)
    elif isinstance(dt, t.MapType):
        m = col.offsets[n]
        out.append(m.astype(jnp.int64))
        out += _var_sizes(col.children[0], m)
        out += _var_sizes(col.children[1], m)
    elif isinstance(dt, t.StructType):
        for c in col.children:
            out += _var_sizes(c, n)
    return out


def _lane_stats(col: DeviceColumn, n) -> List:
    """Two device scalars per lane in walk order: bool lanes report
    (all_true_up_to_n, 0); integer data lanes report (min, max) over the
    LIVE rows only — padding rows are never read back (hosts slice to
    num_rows), so zero padding must not drag the range and defeat the
    narrowing; null rows within num_rows hold canonical zeros and are
    included, keeping null-zero reconstruction exact.  Offsets lanes use
    the full lane (their padding repeats the last live value).  Others
    report (0, 0).

    The device-side pack subtracts _narrow_min on the SAME masked lane,
    so host and device agree on the offset exactly.

    `n` is the live-row count at this column's level; children of span
    columns use their own child counts."""
    stats: List = []

    def visit(c: DeviceColumn, live_n):
        for kind, lane in [("data", c.data), ("validity", c.validity),
                           ("offsets", c.offsets), ("hi", c.data_hi)]:
            if lane is None:
                continue
            dt = _np_dtype_of(lane)
            if dt == np.bool_:
                io = jnp.arange(lane.shape[0], dtype=jnp.int32)
                allv = jnp.all(lane | (io >= live_n))
                stats.append(allv.astype(jnp.int64))
                stats.append(jnp.int64(0))
            elif dt.kind in "iu" and dt.itemsize >= 2:
                if kind == "offsets":
                    stats.append(jnp.min(lane).astype(jnp.int64))
                    stats.append(jnp.max(lane).astype(jnp.int64))
                else:
                    stats.append(_narrow_min(lane, live_n).astype(
                        jnp.int64))
                    io = jnp.arange(lane.shape[0], dtype=jnp.int32)
                    lo = np.iinfo(dt).min
                    mx = jnp.max(jnp.where(io < live_n, lane,
                                           lane.dtype.type(lo)))
                    stats.append(mx.astype(jnp.int64))
            else:
                stats.append(jnp.int64(0))
                stats.append(jnp.int64(0))
        cdt = c.dtype
        if isinstance(cdt, (t.ArrayType, t.MapType)):
            m = c.offsets[jnp.clip(live_n, 0, c.capacity)]
            for ch in c.children:
                visit(ch, m)
        else:
            for ch in c.children:
                visit(ch, live_n)

    visit(col, n)
    return stats


def _narrow_min(lane, live_n):
    """Min over live rows — the shared offset for integer narrowing.
    Empty batches degrade to dtype-max, making span negative so the plan
    never narrows."""
    dt = _np_dtype_of(lane)
    io = jnp.arange(lane.shape[0], dtype=jnp.int32)
    hi = np.iinfo(dt).max
    return jnp.min(jnp.where(io < live_n, lane, lane.dtype.type(hi)))


def _make_sizes_fn():
    def sizes(batch: DeviceBatch, extras=()):
        n = jnp.asarray(batch.num_rows).astype(jnp.int64)
        parts = [n]
        for col in batch.columns:
            parts += _var_sizes(col, jnp.asarray(batch.num_rows))
        for col in batch.columns:
            parts += _lane_stats(col, jnp.asarray(batch.num_rows))
        # ride-along scalars (speculation guards): verified by the caller
        # from the same transfer, so deferred checks cost no extra trip
        parts += [jnp.asarray(e).astype(jnp.int64) for e in extras]
        return jnp.stack(parts)
    return sizes


# ---------------------------------------------------------------------------
# transfer plan: one entry per lane in walk order
# ---------------------------------------------------------------------------
# entry: ("none",) | ("skip",) | ("bit",) | ("narrow", out_itemsize)
# host-side companions (not in the jit key): min values for narrowed lanes

_NARROW_NP = {1: np.uint8, 2: np.uint16, 4: np.uint32}
_NARROW_JNP = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}


def _build_plan(batch: DeviceBatch, stats: np.ndarray):
    """Per-lane transfer plan + per-lane host minima, in walk order."""
    plan: List[tuple] = []
    mins: List[int] = []
    i = 0
    for col in batch.columns:
        for kind, lane in _walk_lanes(col):
            s1, s2 = int(stats[2 * i]), int(stats[2 * i + 1])
            i += 1
            dt = _np_dtype_of(lane)
            if dt == np.bool_:
                if s1:
                    plan.append(("skip",))
                elif lane.shape[0] % 8 == 0:
                    plan.append(("bit",))
                else:
                    plan.append(("none",))
                mins.append(0)
                continue
            if dt.kind in "iu" and dt.itemsize >= 2:
                span = s2 - s1
                if 0 <= span < (1 << 8) and dt.itemsize > 1:
                    plan.append(("narrow", 1))
                elif 0 <= span < (1 << 16) and dt.itemsize > 2:
                    plan.append(("narrow", 2))
                elif 0 <= span < (1 << 32) and dt.itemsize > 4:
                    plan.append(("narrow", 4))
                else:
                    plan.append(("none",))
                mins.append(s1)
                continue
            plan.append(("none",))
            mins.append(0)
    return tuple(plan), mins


# ---------------------------------------------------------------------------
# shrink to bucket + pack per transferred dtype
# ---------------------------------------------------------------------------

def _slice_or_pad(a, cap: int):
    if a.shape[0] == cap:
        return a
    if a.shape[0] > cap:
        return a[:cap]
    pad = [(0, cap - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad)


def _shrink_column(col: DeviceColumn, out_cap: int, var_caps) -> DeviceColumn:
    """Copy of `col` with every lane sliced/padded to its output bucket.
    `var_caps` is an iterator of buckets in _var_sizes walk order."""
    dt = col.dtype
    validity = None if col.validity is None else \
        _slice_or_pad(col.validity, out_cap)
    if isinstance(dt, (t.StringType, t.BinaryType)):
        char_cap = next(var_caps)
        return DeviceColumn(dt, data=_slice_or_pad(col.data, char_cap),
                            validity=validity,
                            offsets=_slice_or_pad(col.offsets, out_cap + 1))
    if isinstance(dt, t.ArrayType):
        child_cap = next(var_caps)
        child = _shrink_column(col.children[0], child_cap, var_caps)
        return DeviceColumn(dt, validity=validity,
                            offsets=_slice_or_pad(col.offsets, out_cap + 1),
                            children=(child,))
    if isinstance(dt, t.MapType):
        child_cap = next(var_caps)
        kcol = _shrink_column(col.children[0], child_cap, var_caps)
        vcol = _shrink_column(col.children[1], child_cap, var_caps)
        return DeviceColumn(dt, validity=validity,
                            offsets=_slice_or_pad(col.offsets, out_cap + 1),
                            children=(kcol, vcol))
    if isinstance(dt, t.StructType):
        children = tuple(_shrink_column(c, out_cap, var_caps)
                         for c in col.children)
        return DeviceColumn(dt, validity=validity, children=children)
    out = DeviceColumn(dt,
                       data=None if col.data is None else
                       _slice_or_pad(col.data, out_cap),
                       validity=validity)
    if col.data_hi is not None:
        out.data_hi = _slice_or_pad(col.data_hi, out_cap)
    return out


def _transferred_dtype(lane_dtype: np.dtype, step: tuple) -> Optional[str]:
    """Wire dtype name for a lane under its plan step; None = skipped."""
    if step[0] == "skip":
        return None
    if step[0] == "bit":
        return "uint8"
    if step[0] == "narrow":
        return np.dtype(_NARROW_NP[step[1]]).name
    return "uint8" if lane_dtype == np.bool_ else lane_dtype.name


def _make_shrink_pack_fn(out_cap: int, var_caps: Tuple[int, ...],
                         plan: Tuple[tuple, ...]):
    def shrink_pack(batch: DeviceBatch):
        it = iter(var_caps)
        cols = [_shrink_column(c, out_cap, it) for c in batch.columns]
        groups: dict = {}  # insertion-ordered: wire dtype -> 1-D pieces
        pi = iter(plan)

        def visit(c: DeviceColumn, orig: DeviceColumn, live_n):
            for kind in ("data", "validity", "offsets", "hi"):
                attr = "data_hi" if kind == "hi" else kind
                leaf = getattr(c, attr)
                if leaf is None:
                    continue
                oleaf = getattr(orig, attr)
                step = next(pi)
                if step[0] == "skip":
                    continue
                if step[0] == "bit":
                    w = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
                    leaf = jnp.sum(
                        leaf.reshape(-1, 8).astype(jnp.uint8) * w,
                        axis=1, dtype=jnp.uint8)
                elif step[0] == "narrow":
                    # subtract exactly the offset the host fetched in the
                    # sizes stats: live-masked min for data/hi lanes,
                    # full-lane min for offsets
                    minv = jnp.min(oleaf) if kind == "offsets" else \
                        _narrow_min(oleaf, live_n)
                    leaf = (leaf - minv).astype(_NARROW_JNP[step[1]])
                elif leaf.dtype == jnp.bool_:
                    leaf = leaf.astype(jnp.uint8)
                key = _np_dtype_of(leaf).name
                groups.setdefault(key, []).append(leaf.reshape(-1))
            cdt = orig.dtype
            if isinstance(cdt, (t.ArrayType, t.MapType)):
                m = orig.offsets[jnp.clip(live_n, 0, orig.capacity)]
                for ch, och in zip(c.children, orig.children):
                    visit(ch, och, m)
            else:
                for ch, och in zip(c.children, orig.children):
                    visit(ch, och, live_n)

        n0 = jnp.asarray(batch.num_rows)
        for c, orig in zip(cols, batch.columns):
            visit(c, orig, n0)
        return tuple(
            jnp.concatenate(ls) if len(ls) > 1 else ls[0]
            for ls in groups.values())
    return shrink_pack


class _BufReader:
    """Per-dtype cursors over the fetched buffer group (walk order on host
    mirrors the device pack exactly, so sequential slices line up)."""

    def __init__(self, bufs_by_key: dict):
        self._bufs = bufs_by_key
        self._pos = {k: 0 for k in bufs_by_key}

    def take(self, count: int, wire_dtype: str) -> np.ndarray:
        buf, pos = self._bufs[wire_dtype], self._pos[wire_dtype]
        view = buf[pos:pos + count]
        if len(view) != count:
            raise FetchLayoutError(
                f"fetch underrun: wanted {count} x {wire_dtype}, "
                f"buffer has {len(buf) - pos} left")
        self._pos[wire_dtype] = pos + count
        return view


def _unpack_column(col: DeviceColumn, rd: _BufReader, out_cap: int,
                   var_caps, plan_it, mins_it, live_n: int) -> DeviceColumn:
    """Rebuild a numpy-backed shrunk column from the packed buffers,
    reversing each lane's transfer transform.  `live_n` is this level's
    live row count (for resynthesizing skipped validity lanes)."""
    dt = col.dtype

    def lane(template, cap: int) -> Optional[np.ndarray]:
        if template is None:
            return None
        step = next(plan_it)
        minv = next(mins_it)
        ldt = _np_dtype_of(template)
        if step[0] == "skip":
            return np.arange(cap, dtype=np.int32) < live_n
        if step[0] == "bit":
            raw = rd.take(cap // 8, "uint8")
            return np.unpackbits(raw, bitorder="little")[:cap].astype(
                np.bool_)
        if step[0] == "narrow":
            raw = rd.take(cap, np.dtype(_NARROW_NP[step[1]]).name)
            return raw.astype(ldt) + ldt.type(minv)
        wire = "uint8" if ldt == np.bool_ else ldt.name
        raw = rd.take(cap, wire)
        return raw.astype(np.bool_) if ldt == np.bool_ else raw

    if isinstance(dt, (t.StringType, t.BinaryType)):
        char_cap = next(var_caps)
        data = lane(col.data, char_cap)
        validity = lane(col.validity, out_cap)
        offsets = lane(col.offsets, out_cap + 1)
        return DeviceColumn(dt, data=data, validity=validity,
                            offsets=offsets)
    if isinstance(dt, (t.ArrayType, t.MapType)):
        child_cap = next(var_caps)
        validity = lane(col.validity, out_cap)
        offsets = lane(col.offsets, out_cap + 1)
        child_n = int(offsets[min(live_n, len(offsets) - 1)])
        children = tuple(
            _unpack_column(ch, rd, child_cap, var_caps, plan_it, mins_it,
                           child_n)
            for ch in col.children)
        return DeviceColumn(dt, validity=validity, offsets=offsets,
                            children=children)
    if isinstance(dt, t.StructType):
        validity = lane(col.validity, out_cap)
        children = tuple(
            _unpack_column(ch, rd, out_cap, var_caps, plan_it, mins_it,
                           live_n)
            for ch in col.children)
        return DeviceColumn(dt, validity=validity, children=children)
    data = lane(col.data, out_cap)
    validity = lane(col.validity, out_cap)
    out = DeviceColumn(dt, data=data, validity=validity)
    if col.data_hi is not None:
        out.data_hi = lane(col.data_hi, out_cap)
    return out


def _schema_key(batch: DeviceBatch) -> tuple:
    def col_key(c: DeviceColumn):
        return (repr(c.dtype), None if c.data is None else
                (str(c.data.dtype), tuple(c.data.shape)),
                c.validity is not None,
                None if c.offsets is None else
                (str(c.offsets.dtype), tuple(c.offsets.shape)),
                None if c.data_hi is None else str(c.data_hi.dtype),
                tuple(col_key(ch) for ch in c.children))
    return tuple(col_key(c) for c in batch.columns)


# last successful (out_cap, var_caps, plan) per schema key: lets a warm
# repeat dispatch the pack SPECULATIVELY alongside the sizes probe and
# pay ONE sync instead of two serial tunnel round trips.  The sizes
# still arrive and must re-derive the identical plan, or the
# speculative buffers are discarded (a narrowed lane under a stale
# narrower width would wrap silently — never trusted without the check).
_LAST_PLAN: dict = {}


def fetch_batch(batch: DeviceBatch,
                row_buckets: Sequence[int] = DEFAULT_ROW_BUCKETS,
                char_buckets: Sequence[int] = DEFAULT_CHAR_BUCKETS,
                extra_scalars: Sequence = ()):
    """Bring a device batch to host as numpy-backed DeviceBatch in two
    round trips (ONE when the speculative plan validates), transferring
    only bucket_for(num_rows) rows per lane and only
    information-carrying bytes per lane (see module doc).

    `extra_scalars` (device scalars, e.g. deferred speculation guards)
    ride the sizes transfer; when given, returns (batch, extras_array)."""
    n_extra = len(extra_scalars)
    if not batch_is_device(batch):
        # already host-side: just normalize num_rows to a python int
        out = DeviceBatch(batch.columns, int(batch.num_rows), batch.names)
        if n_extra:
            vals = np.asarray([int(np.asarray(e)) for e in extra_scalars])
            return out, vals
        return out
    from ..exec.base import process_jit
    skey = _schema_key(batch)
    sizes_fn = process_jit(("fetch_sizes", skey, n_extra), _make_sizes_fn)
    extras_t = tuple(extra_scalars)
    # plan memo key includes the bucket ladders: a caller alternating
    # bucket configs for one schema must not arm doomed speculation
    pkey = (skey, tuple(row_buckets), tuple(char_buckets))
    entry = _LAST_PLAN.get(pkey)
    spec = None
    spec_bufs = None
    if entry is not None and entry[1] >= 1:
        # speculate only after the plan repeated — a misprediction moves
        # a full wasted payload over the bandwidth-bound tunnel, so
        # alternating shapes must not thrash
        spec = entry[0]
        s_cap, s_vc, s_plan = spec
        spec_fn = process_jit(("fetch_pack", skey, s_cap, s_vc, s_plan),
                              lambda: _make_shrink_pack_fn(s_cap, s_vc,
                                                           s_plan))
        sizes_dev = sizes_fn(batch, extras_t)
        spec_out = spec_fn(batch)
        fetched = jax.device_get((sizes_dev,) + tuple(spec_out))  # 1 sync
        sizes = np.asarray(fetched[0])
        spec_bufs = fetched[1:]
        _note_crossing(1, sum(int(b.nbytes) for b in fetched))
    else:
        sizes = np.asarray(sizes_fn(batch, extras_t))  # round trip 1
        _note_crossing(1, sizes.nbytes)
    extra_vals = sizes[len(sizes) - n_extra:] if n_extra else None
    if n_extra:
        sizes = sizes[:len(sizes) - n_extra]
    n = int(sizes[0])
    out_cap = bucket_for(n, row_buckets)
    # decode var sizes in walk order -> buckets (char lanes use char
    # buckets; array-child row lanes use row buckets)
    var_caps: List[int] = []

    def walk(col: DeviceColumn, it):
        dt = col.dtype
        if isinstance(dt, (t.StringType, t.BinaryType)):
            var_caps.append(bucket_for(int(next(it)), char_buckets))
        elif isinstance(dt, t.ArrayType):
            m = int(next(it))
            var_caps.append(bucket_for(m, row_buckets))
            walk(col.children[0], it)
        elif isinstance(dt, t.MapType):
            m = int(next(it))
            var_caps.append(bucket_for(m, row_buckets))
            walk(col.children[0], it)
            walk(col.children[1], it)
        elif isinstance(dt, t.StructType):
            for c in col.children:
                walk(c, it)

    it = iter(sizes[1:])
    for c in batch.columns:
        walk(c, it)
    vc = tuple(var_caps)
    stats = sizes[1 + len(var_caps):]
    plan, mins = _build_plan(batch, stats)
    if spec_bufs is not None and spec == (out_cap, vc, plan):
        bufs = spec_bufs                         # speculation validated
    else:
        pack_fn = process_jit(("fetch_pack", skey, out_cap, vc, plan),
                              lambda: _make_shrink_pack_fn(out_cap, vc,
                                                           plan))
        bufs = jax.device_get(pack_fn(batch))    # round trip 2 (one sync)
        _note_crossing(1, sum(int(b.nbytes) for b in bufs))
    this_plan = (out_cap, vc, plan)
    prev = _LAST_PLAN.get(pkey)
    if len(_LAST_PLAN) > 256 and pkey not in _LAST_PLAN:
        # bounded memo: drop the oldest entry (insertion order)
        _LAST_PLAN.pop(next(iter(_LAST_PLAN)))
    _LAST_PLAN[pkey] = (this_plan,
                        (prev[1] + 1) if prev and prev[0] == this_plan
                        else 0)
    # reconstruct the device-side wire-dtype-group order from the template
    order: List[str] = []
    pi = iter(plan)
    for c in batch.columns:
        for kind, leaf in _walk_lanes(c):
            wd = _transferred_dtype(_np_dtype_of(leaf), next(pi))
            if wd is not None and wd not in order:
                order.append(wd)
    if len(order) != len(bufs):
        raise FetchLayoutError(
            f"fetch layout drift: host expects {order}, device sent "
            f"{[str(b.dtype) for b in bufs]}")
    rd = _BufReader(dict(zip(order, bufs)))
    caps_it = iter(vc)
    plan_it = iter(plan)
    mins_it = iter(mins)
    cols = [_unpack_column(c, rd, out_cap, caps_it, plan_it, mins_it, n)
            for c in batch.columns]
    out = DeviceBatch(cols, n, batch.names)
    return (out, extra_vals) if n_extra else out
