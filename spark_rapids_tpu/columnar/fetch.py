"""Single-round-trip device->host batch fetch.

The reference copies result batches over PCIe where per-transfer latency is
microseconds (GpuColumnarToRowExec.scala:358 pulls each column's buffers).
A tunneled TPU is a different animal: every host<->device round trip costs
tens of milliseconds of fixed latency and host bandwidth is limited, so the
naive per-buffer fetch (one transfer per data/validity/offsets lane) is the
dominant query cost.  This module fetches a whole DeviceBatch in exactly
TWO round trips, transferring only the rows that exist:

  1. `sizes`: one jitted call returns [num_rows, var_len_0, var_len_1, ...]
     (char counts for strings, child row counts for arrays) as a single
     tiny array — one sync that also acts as the pipeline barrier.
  2. `shrink_pack`: a jitted function (cached per schema/capacity shape)
     slices every lane down to the smallest capacity bucket that holds
     num_rows and concatenates the lanes into one buffer PER DTYPE
     (bools fold into uint8).  No bitcasting — the TPU X64-rewrite pass
     cannot compile 64-bit bitcast-convert — so instead of one uint8
     buffer the fetch is a handful of per-dtype buffers brought over in
     a single device_get (one sync).

The host then rebuilds numpy-backed DeviceColumns from views of those
buffers; Arrow conversion proceeds on host exactly as before.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as t
from .device import DeviceBatch, DeviceColumn, bucket_for, \
    DEFAULT_CHAR_BUCKETS, DEFAULT_ROW_BUCKETS


def _is_device(x) -> bool:
    return isinstance(x, jax.Array)


def batch_is_device(batch: DeviceBatch) -> bool:
    return any(_is_device(l) for l in jax.tree_util.tree_leaves(batch))


# ---------------------------------------------------------------------------
# sizes: [num_rows, varlen...] in column walk order
# ---------------------------------------------------------------------------

def _var_sizes(col: DeviceColumn, n) -> List:
    """Device scalars for every variable-length lane under `col`, in a
    deterministic walk order shared with _shrink_column."""
    out: List = []
    dt = col.dtype
    if isinstance(dt, (t.StringType, t.BinaryType)):
        out.append(col.offsets[n].astype(jnp.int64))
    elif isinstance(dt, t.ArrayType):
        m = col.offsets[n]
        out.append(m.astype(jnp.int64))
        out += _var_sizes(col.children[0], m)
    elif isinstance(dt, t.MapType):
        m = col.offsets[n]
        out.append(m.astype(jnp.int64))
        out += _var_sizes(col.children[0], m)
        out += _var_sizes(col.children[1], m)
    elif isinstance(dt, t.StructType):
        for c in col.children:
            out += _var_sizes(c, n)
    return out


def _make_sizes_fn():
    def sizes(batch: DeviceBatch):
        n = jnp.asarray(batch.num_rows).astype(jnp.int64)
        parts = [n]
        for col in batch.columns:
            parts += _var_sizes(col, jnp.asarray(batch.num_rows))
        return jnp.stack(parts)
    return sizes


# ---------------------------------------------------------------------------
# shrink to bucket + pack to one uint8 buffer
# ---------------------------------------------------------------------------

def _slice_or_pad(a, cap: int):
    if a.shape[0] == cap:
        return a
    if a.shape[0] > cap:
        return a[:cap]
    pad = [(0, cap - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad)


def _shrink_column(col: DeviceColumn, out_cap: int, var_caps) -> DeviceColumn:
    """Copy of `col` with every lane sliced/padded to its output bucket.
    `var_caps` is an iterator of buckets in _var_sizes walk order."""
    dt = col.dtype
    validity = None if col.validity is None else \
        _slice_or_pad(col.validity, out_cap)
    if isinstance(dt, (t.StringType, t.BinaryType)):
        char_cap = next(var_caps)
        return DeviceColumn(dt, data=_slice_or_pad(col.data, char_cap),
                            validity=validity,
                            offsets=_slice_or_pad(col.offsets, out_cap + 1))
    if isinstance(dt, t.ArrayType):
        child_cap = next(var_caps)
        child = _shrink_column(col.children[0], child_cap, var_caps)
        return DeviceColumn(dt, validity=validity,
                            offsets=_slice_or_pad(col.offsets, out_cap + 1),
                            children=(child,))
    if isinstance(dt, t.MapType):
        child_cap = next(var_caps)
        kcol = _shrink_column(col.children[0], child_cap, var_caps)
        vcol = _shrink_column(col.children[1], child_cap, var_caps)
        return DeviceColumn(dt, validity=validity,
                            offsets=_slice_or_pad(col.offsets, out_cap + 1),
                            children=(kcol, vcol))
    if isinstance(dt, t.StructType):
        children = tuple(_shrink_column(c, out_cap, var_caps)
                         for c in col.children)
        return DeviceColumn(dt, validity=validity, children=children)
    out = DeviceColumn(dt,
                       data=None if col.data is None else
                       _slice_or_pad(col.data, out_cap),
                       validity=validity)
    if col.data_hi is not None:
        out.data_hi = _slice_or_pad(col.data_hi, out_cap)
    return out


def _canon_key(x) -> str:
    """Buffer-group key for a lane: its dtype name, with bool folded into
    uint8 (bools travel as bytes).  The ONLY place the grouping rule
    lives — device pack and host unpack both call it, so they cannot
    drift."""
    d = np.dtype(x.dtype.name if hasattr(x.dtype, "name") else x.dtype)
    return "uint8" if d == np.bool_ else d.name


def _make_shrink_pack_fn(out_cap: int, var_caps: Tuple[int, ...]):
    def shrink_pack(batch: DeviceBatch):
        it = iter(var_caps)
        cols = [_shrink_column(c, out_cap, it) for c in batch.columns]
        groups: dict = {}  # insertion-ordered: key -> list of 1-D lanes
        for c in cols:
            for leaf in jax.tree_util.tree_leaves(c):
                k = _canon_key(leaf)
                if leaf.dtype == jnp.bool_:
                    leaf = leaf.astype(jnp.uint8)
                groups.setdefault(k, []).append(leaf.reshape(-1))
        return tuple(
            jnp.concatenate(ls) if len(ls) > 1 else ls[0]
            for ls in groups.values())
    return shrink_pack


# host-side mirror of the shrunk column layout: (shape, np dtype, is_bool)
def _np_dtype_of(x) -> np.dtype:
    return np.dtype(x.dtype.name if hasattr(x.dtype, "name") else x.dtype)


class _BufReader:
    """Per-dtype cursors over the fetched buffer group (walk order on host
    mirrors the device pack exactly, so sequential slices line up)."""

    def __init__(self, bufs_by_key: dict):
        self._bufs = bufs_by_key
        self._pos = {k: 0 for k in bufs_by_key}

    def take(self, cap: int, dtype: np.dtype) -> np.ndarray:
        k = _canon_key(np.empty(0, dtype))
        buf, pos = self._bufs[k], self._pos[k]
        view = buf[pos:pos + cap]
        self._pos[k] = pos + cap
        if dtype == np.bool_:
            return view.astype(np.bool_)
        return view


def _unpack_column(col: DeviceColumn, rd: _BufReader,
                   out_cap: int, var_caps) -> DeviceColumn:
    """Rebuild a numpy-backed shrunk column from the packed buffers."""
    dt = col.dtype
    take = rd.take

    if isinstance(dt, (t.StringType, t.BinaryType)):
        char_cap = next(var_caps)
        data = take(char_cap, np.dtype(np.uint8))
        validity = take(out_cap, np.dtype(np.bool_)) \
            if col.validity is not None else None
        offsets = take(out_cap + 1, _np_dtype_of(col.offsets))
        return DeviceColumn(dt, data=data, validity=validity,
                            offsets=offsets)
    if isinstance(dt, t.ArrayType):
        child_cap = next(var_caps)
        validity = take(out_cap, np.dtype(np.bool_)) \
            if col.validity is not None else None
        offsets = take(out_cap + 1, _np_dtype_of(col.offsets))
        child = _unpack_column(col.children[0], rd, child_cap, var_caps)
        return DeviceColumn(dt, validity=validity, offsets=offsets,
                            children=(child,))
    if isinstance(dt, t.MapType):
        child_cap = next(var_caps)
        validity = take(out_cap, np.dtype(np.bool_)) \
            if col.validity is not None else None
        offsets = take(out_cap + 1, _np_dtype_of(col.offsets))
        kcol = _unpack_column(col.children[0], rd, child_cap, var_caps)
        vcol = _unpack_column(col.children[1], rd, child_cap, var_caps)
        return DeviceColumn(dt, validity=validity, offsets=offsets,
                            children=(kcol, vcol))
    if isinstance(dt, t.StructType):
        validity = take(out_cap, np.dtype(np.bool_)) \
            if col.validity is not None else None
        children = tuple(_unpack_column(c, rd, out_cap, var_caps)
                         for c in col.children)
        return DeviceColumn(dt, validity=validity, children=children)
    data = take(out_cap, _np_dtype_of(col.data)) \
        if col.data is not None else None
    validity = take(out_cap, np.dtype(np.bool_)) \
        if col.validity is not None else None
    out = DeviceColumn(dt, data=data, validity=validity)
    if col.data_hi is not None:
        out.data_hi = take(out_cap, _np_dtype_of(col.data_hi))
    return out


def _schema_key(batch: DeviceBatch) -> tuple:
    def col_key(c: DeviceColumn):
        return (repr(c.dtype), None if c.data is None else
                (str(c.data.dtype), tuple(c.data.shape)),
                c.validity is not None,
                None if c.offsets is None else
                (str(c.offsets.dtype), tuple(c.offsets.shape)),
                None if c.data_hi is None else str(c.data_hi.dtype),
                tuple(col_key(ch) for ch in c.children))
    return tuple(col_key(c) for c in batch.columns)


def fetch_batch(batch: DeviceBatch,
                row_buckets: Sequence[int] = DEFAULT_ROW_BUCKETS,
                char_buckets: Sequence[int] = DEFAULT_CHAR_BUCKETS,
                ) -> DeviceBatch:
    """Bring a device batch to host as numpy-backed DeviceBatch in two
    round trips, transferring only bucket_for(num_rows) rows per lane."""
    if not batch_is_device(batch):
        # already host-side: just normalize num_rows to a python int
        return DeviceBatch(batch.columns, int(batch.num_rows), batch.names)
    from ..exec.base import process_jit
    skey = _schema_key(batch)
    sizes_fn = process_jit(("fetch_sizes", skey), _make_sizes_fn)
    sizes = np.asarray(sizes_fn(batch))          # round trip 1 (+ barrier)
    n = int(sizes[0])
    out_cap = bucket_for(n, row_buckets)
    # decode var sizes in walk order -> buckets (char lanes use char
    # buckets; array-child row lanes use row buckets)
    var_caps: List[int] = []

    def walk(col: DeviceColumn, it):
        dt = col.dtype
        if isinstance(dt, (t.StringType, t.BinaryType)):
            var_caps.append(bucket_for(int(next(it)), char_buckets))
        elif isinstance(dt, t.ArrayType):
            m = int(next(it))
            var_caps.append(bucket_for(m, row_buckets))
            walk(col.children[0], it)
        elif isinstance(dt, t.MapType):
            m = int(next(it))
            var_caps.append(bucket_for(m, row_buckets))
            walk(col.children[0], it)
            walk(col.children[1], it)
        elif isinstance(dt, t.StructType):
            for c in col.children:
                walk(c, it)

    it = iter(sizes[1:])
    for c in batch.columns:
        walk(c, it)
    vc = tuple(var_caps)
    pack_fn = process_jit(("fetch_pack", skey, out_cap, vc),
                          lambda: _make_shrink_pack_fn(out_cap, vc))
    bufs = jax.device_get(pack_fn(batch))        # round trip 2 (one sync)
    # reconstruct the device-side dtype-group order from the template
    order = list(dict.fromkeys(
        _canon_key(leaf) for c in batch.columns
        for leaf in jax.tree_util.tree_leaves(c)))
    assert len(order) == len(bufs), (order, [b.dtype for b in bufs])
    rd = _BufReader(dict(zip(order, bufs)))
    caps_it = iter(vc)
    cols = [_unpack_column(c, rd, out_cap, caps_it) for c in batch.columns]
    return DeviceBatch(cols, n, batch.names)
