"""DataType <-> pyarrow schema interop.

The host-side canonical columnar representation is Arrow (the reference's
host columns are also Arrow-compatible, ref HostColumnarToGpu.scala:436
zero-copy Arrow path).  This module converts between our SQL type lattice
(`spark_rapids_tpu.types`) and pyarrow types.
"""

from __future__ import annotations

from typing import List, Tuple

import pyarrow as pa

from .. import types as t


def to_arrow_type(dt: t.DataType) -> pa.DataType:
    if isinstance(dt, t.BooleanType):
        return pa.bool_()
    if isinstance(dt, t.ByteType):
        return pa.int8()
    if isinstance(dt, t.ShortType):
        return pa.int16()
    if isinstance(dt, t.IntegerType):
        return pa.int32()
    if isinstance(dt, t.LongType):
        return pa.int64()
    if isinstance(dt, t.FloatType):
        return pa.float32()
    if isinstance(dt, t.DoubleType):
        return pa.float64()
    if isinstance(dt, t.StringType):
        return pa.large_string()
    if isinstance(dt, t.BinaryType):
        return pa.large_binary()
    if isinstance(dt, t.DateType):
        return pa.date32()
    if isinstance(dt, t.TimestampType):
        return pa.timestamp("us", tz="UTC")
    if isinstance(dt, t.DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, t.NullType):
        return pa.null()
    if isinstance(dt, t.ArrayType):
        return pa.large_list(to_arrow_type(dt.element_type))
    if isinstance(dt, t.StructType):
        return pa.struct([pa.field(f.name, to_arrow_type(f.data_type),
                                   nullable=f.nullable) for f in dt.fields])
    if isinstance(dt, t.MapType):
        return pa.map_(to_arrow_type(dt.key_type), to_arrow_type(dt.value_type))
    raise TypeError(f"no arrow mapping for {dt}")


def from_arrow_type(at: pa.DataType) -> t.DataType:
    if pa.types.is_boolean(at):
        return t.BOOLEAN
    if pa.types.is_int8(at):
        return t.BYTE
    if pa.types.is_int16(at):
        return t.SHORT
    if pa.types.is_int32(at):
        return t.INT
    if pa.types.is_int64(at):
        return t.LONG
    if pa.types.is_uint8(at):
        return t.SHORT
    if pa.types.is_uint16(at):
        return t.INT
    if pa.types.is_uint32(at) or pa.types.is_uint64(at):
        return t.LONG
    if pa.types.is_float32(at):
        return t.FLOAT
    if pa.types.is_float64(at):
        return t.DOUBLE
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return t.STRING
    if pa.types.is_binary(at) or pa.types.is_large_binary(at):
        return t.BINARY
    if pa.types.is_date32(at):
        return t.DATE
    if pa.types.is_timestamp(at):
        return t.TIMESTAMP
    if pa.types.is_decimal(at):
        return t.DecimalType(at.precision, at.scale)
    if pa.types.is_null(at):
        return t.NULL
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return t.ArrayType(from_arrow_type(at.value_type))
    if pa.types.is_struct(at):
        return t.StructType([t.StructField(f.name, from_arrow_type(f.type),
                                           f.nullable) for f in at])
    if pa.types.is_map(at):
        return t.MapType(from_arrow_type(at.key_type),
                         from_arrow_type(at.item_type))
    raise TypeError(f"no mapping for arrow type {at}")


def to_arrow_schema(names: List[str], dtypes: List[t.DataType]) -> pa.Schema:
    return pa.schema([pa.field(n, to_arrow_type(d))
                      for n, d in zip(names, dtypes)])


def schema_of(batch: pa.RecordBatch) -> Tuple[List[str], List[t.DataType]]:
    names = list(batch.schema.names)
    dtypes = [from_arrow_type(f.type) for f in batch.schema]
    return names, dtypes
