"""Version-compat shim layer.

Ref: shims/ + ShimLoader.scala:20-60 + SparkShims.scala:84 — one plugin
artifact serves many Spark versions by routing every version-sensitive
behavior through a `SparkShims` trait, with per-version providers
discovered at runtime.  The TPU build targets pyspark-dialect semantics
the same way: each provider declares the version range it serves and
overrides only the behaviors that changed in that range.  `ShimLoader`
picks the matching provider for `spark.rapids.tpu.sparkVersion`.

The behaviors routed here are the ones the reference's shims actually
guard (SparkBaseShims deltas between 3.0.x / 3.1.x / 3.2.x).
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Type


def _parse_version(v: str) -> Tuple[int, int, int]:
    parts = (v.split("-")[0].split(".") + ["0", "0"])[:3]
    return tuple(int(x) for x in parts)  # type: ignore[return-value]


class SparkShims:
    """Version-sensitive behavior switchboard (ref SparkShims.scala:84).

    Defaults describe Spark 3.2 semantics; older providers override."""

    version = "3.2.0"

    # Spark 3.1 moved stddev/var to new evaluator semantics where empty
    # input yields null; 3.0 returned NaN (ref shims stddev handling)
    def legacy_statistical_aggregate(self) -> bool:
        return False

    # 3.0 parsed yyyy-M-d style dates leniently when casting string->date;
    # 3.1+ requires fully padded ISO forms unless legacy parser policy
    def lenient_string_to_date(self) -> bool:
        return False

    # parquet datetime rebase default mode (3.0: LEGACY, 3.1+: EXCEPTION
    # for ancient dates; ref GpuParquetScan rebase handling)
    def parquet_rebase_mode_default(self) -> str:
        return "CORRECTED"

    # 3.2 turned ANSI-mode interval arithmetic + error messages on paths
    # the plugin must mirror (ref shims' AnsiCast variations)
    def ansi_interval_support(self) -> bool:
        return True

    # whether df.cache() uses the parquet cached-batch serializer
    # (supported 3.1.1+; ref tests-spark310+)
    def cached_batch_serializer_supported(self) -> bool:
        return True

    # AQE custom shuffle reader class name changed 3.1 -> 3.2
    # (CustomShuffleReaderExec -> AQEShuffleReadExec)
    def aqe_shuffle_read_name(self) -> str:
        return "AQEShuffleRead"

    def describe(self) -> str:
        return f"{type(self).__name__}({self.version})"


class Spark320Shims(SparkShims):
    version = "3.2.0"


class Spark311Shims(SparkShims):
    version = "3.1.1"

    def ansi_interval_support(self) -> bool:
        return False

    def aqe_shuffle_read_name(self) -> str:
        return "CustomShuffleReader"


class Spark301Shims(SparkShims):
    version = "3.0.1"

    def legacy_statistical_aggregate(self) -> bool:
        return True

    def lenient_string_to_date(self) -> bool:
        return True

    def parquet_rebase_mode_default(self) -> str:
        return "LEGACY"

    def ansi_interval_support(self) -> bool:
        return False

    def cached_batch_serializer_supported(self) -> bool:
        return False

    def aqe_shuffle_read_name(self) -> str:
        return "CustomShuffleReader"


class ShimServiceProvider:
    """Registration record (ref SparkShimServiceProvider)."""

    def __init__(self, shim_cls: Type[SparkShims],
                 min_version: str, max_version_exclusive: str):
        self.shim_cls = shim_cls
        self.lo = _parse_version(min_version)
        self.hi = _parse_version(max_version_exclusive)

    def matches(self, version: Tuple[int, int, int]) -> bool:
        return self.lo <= version < self.hi


_PROVIDERS: List[ShimServiceProvider] = [
    ShimServiceProvider(Spark301Shims, "3.0.0", "3.1.0"),
    ShimServiceProvider(Spark311Shims, "3.1.0", "3.2.0"),
    ShimServiceProvider(Spark320Shims, "3.2.0", "4.0.0"),
]


_ACTIVE: Optional[SparkShims] = None


def set_active_shim(shim: SparkShims) -> None:
    """Install the session's dialect (ref ShimLoader.getSparkShims —
    one dialect per plugin lifecycle)."""
    global _ACTIVE
    _ACTIVE = shim


def active_shim() -> SparkShims:
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = Spark320Shims()
    return _ACTIVE


class ShimLoader:
    """Provider discovery + selection (ref ShimLoader.scala)."""

    _cached: Optional[SparkShims] = None
    _cached_version: Optional[str] = None

    @classmethod
    def register(cls, provider: ShimServiceProvider) -> None:
        _PROVIDERS.append(provider)

    @classmethod
    def get_shim(cls, version: str = "3.2.0") -> SparkShims:
        if cls._cached is not None and cls._cached_version == version:
            return cls._cached
        v = _parse_version(version)
        for p in _PROVIDERS:
            if p.matches(v):
                cls._cached = p.shim_cls()
                cls._cached_version = version
                return cls._cached
        raise ValueError(
            f"no shim provider for Spark version {version!r}; supported: "
            + ", ".join(f"[{p.lo}, {p.hi})" for p in _PROVIDERS))
