"""Self-emitted event log: JSON-lines in the SparkListener schema that
``tools/eventlog.py`` already parses, so ``tools profile`` /
``tools qualify`` work on this engine's OWN runs, not just foreign Spark
history logs (closing the producer/consumer loop the reference gets for
free from Spark's EventLoggingListener).

One file per session under ``spark.rapids.tpu.eventLog.dir``
(``events_<appId>``); every query appends one SQLExecutionStart /
JobStart / StageSubmitted / TaskEnd* / StageCompleted / JobEnd /
SQLExecutionEnd group plus the span records as
``...rapids.tpu.TpuSpanEvent`` lines (unknown to foreign parsers, which
skip unrecognized Event kinds — ours replays them for
``tools trace``).  Failed queries flush too, as JobFailed.

The emitted SparkPlanInfo embeds each operator's drained metric values
and its ``tpuPrediction`` (CBO rows/bytes + tmsan peak bound) /
``tpuActual`` (measured rows/bytes) — what ``tools profile --accuracy``
ranks."""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

import numpy as np


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, (np.bool_,)):
        return bool(o)
    return str(o)


def plan_info(node, tracer=None) -> Dict[str, Any]:
    """Serialize an Exec tree as SparkPlanInfo, embedding drained metric
    values (name/level/value) and the tracer's prediction/actual maps."""
    metrics = [{"name": m.name, "metricType": "sum", "level": m.level,
                "value": m.value}
               for m in node.metrics.values()]
    d: Dict[str, Any] = {
        "nodeName": type(node).__name__,
        "simpleString": node.describe(),
        "children": [plan_info(c, tracer) for c in node.children],
        "metrics": metrics,
        # host-vs-TPU placement rides the plan so the regression
        # watchdog (obs/history.py) can fingerprint the fallback set
        "tpuPlacement": getattr(node, "placement", ""),
    }
    if tracer is not None:
        pred = tracer.predictions.get(id(node))
        if pred is not None:
            d["tpuPrediction"] = pred
        act = tracer.actuals.get(id(node))
        if act is not None:
            d["tpuActual"] = act
    return d


class EventLogWriter:
    """Appends one session's queries to a single rolling-style log file."""

    def __init__(self, directory: str, app_id: str,
                 app_name: str = "spark_rapids_tpu",
                 spark_version: str = "", conf_map: Optional[Dict] = None):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.app_id = app_id
        self.app_name = app_name
        self.spark_version = spark_version
        self.conf_map = dict(conf_map or {})
        self.path = os.path.join(directory, f"events_{app_id}")
        self._lock = threading.Lock()
        self._started = False
        self.queries_flushed = 0

    # ------------------------------------------------------------------
    def _header(self, now_ms: int) -> List[Dict]:
        return [
            {"Event": "SparkListenerLogStart",
             "Spark Version": self.spark_version},
            {"Event": "SparkListenerApplicationStart",
             "App Name": self.app_name, "App ID": self.app_id,
             "Timestamp": now_ms},
            {"Event": "SparkListenerEnvironmentUpdate",
             "Spark Properties": {str(k): str(v) for k, v in
                                  self.conf_map.items()}},
            {"Event": "SparkListenerExecutorAdded", "Executor ID": "0",
             "Timestamp": now_ms,
             "Executor Info": {"Host": "localhost",
                               "Total Cores": os.cpu_count() or 1}},
        ]

    def write_query(self, sql_id: int, final_plan, tracer,
                    error: Optional[str] = None,
                    description: str = "") -> str:
        """Append one finalized query (tracer must be sealed).  Returns
        the log path."""
        spans = tracer.span_dicts()
        start_ms = tracer.wall_start_ms
        end_rel_ns = max((s["startNs"] + s["durNs"] for s in spans),
                        default=0)
        end_ms = start_ms + max(end_rel_ns // 1_000_000, 1)
        failed = error is not None
        stage_name = type(final_plan).__name__
        events: List[Dict] = []
        with self._lock:
            if not self._started:
                events += self._header(start_ms)
                self._started = True
            events.append({
                "Event": "org.apache.spark.sql.execution.ui."
                         "SparkListenerSQLExecutionStart",
                "executionId": sql_id,
                "description": description or f"query {sql_id}",
                "time": start_ms,
                "sparkPlanInfo": plan_info(final_plan, tracer),
            })
            events.append({
                "Event": "SparkListenerJobStart", "Job ID": sql_id,
                "Submission Time": start_ms,
                "Stage Infos": [{"Stage ID": sql_id,
                                 "Stage Attempt ID": 0,
                                 "Stage Name": stage_name,
                                 "Number of Tasks":
                                     final_plan.num_partitions}],
                "Properties": {"spark.sql.execution.id": str(sql_id)},
            })
            events.append({
                "Event": "SparkListenerStageSubmitted",
                "Stage Info": {"Stage ID": sql_id, "Stage Attempt ID": 0,
                               "Stage Name": stage_name,
                               "Number of Tasks":
                                   final_plan.num_partitions,
                               "Submission Time": start_ms},
            })
            events += self._task_events(sql_id, final_plan, spans,
                                        start_ms, failed)
            events.append({
                "Event": "SparkListenerStageCompleted",
                "Stage Info": {"Stage ID": sql_id, "Stage Attempt ID": 0,
                               "Stage Name": stage_name,
                               "Number of Tasks":
                                   final_plan.num_partitions,
                               "Submission Time": start_ms,
                               "Completion Time": end_ms,
                               "Failure Reason": error},
            })
            events.append({
                "Event": "SparkListenerJobEnd", "Job ID": sql_id,
                "Completion Time": end_ms,
                "Job Result": {"Result": "JobFailed" if failed
                               else "JobSucceeded"},
            })
            end_ev = {
                "Event": "org.apache.spark.sql.execution.ui."
                         "SparkListenerSQLExecutionEnd",
                "executionId": sql_id, "time": end_ms,
            }
            if tracer.measured_peak_device_bytes is not None:
                end_ev["tpuPeakDeviceBytes"] = \
                    tracer.measured_peak_device_bytes
            if tracer.static_peak_bound is not None:
                end_ev["tpuStaticPeakBound"] = \
                    int(tracer.static_peak_bound)
            events.append(end_ev)
            for s in spans:
                events.append({
                    "Event": "org.apache.spark.sql.rapids.tpu."
                             "TpuSpanEvent",
                    "executionId": sql_id, **s})
            events.append({"Event": "SparkListenerApplicationEnd",
                           "Timestamp": end_ms})
            with open(self.path, "a", encoding="utf-8") as f:
                for ev in events:
                    f.write(json.dumps(ev, default=_json_default) + "\n")
                f.flush()
                os.fsync(f.fileno())
            self.queries_flushed += 1
        return self.path

    def write_postmortem_pointer(self, bundle_path: str) -> None:
        """Append one pointer line naming the failure black box's
        post-mortem bundle — the log's reader (and a human tailing it)
        can jump straight from the JobFailed group to the artifact.
        Unknown Event kinds are skipped by foreign parsers."""
        with self._lock:
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(json.dumps({
                    "Event": "org.apache.spark.sql.rapids.tpu."
                             "TpuPostmortemEvent",
                    "bundlePath": bundle_path,
                }, default=_json_default) + "\n")
                f.flush()

    # ------------------------------------------------------------------
    def _task_events(self, sql_id: int, final_plan, spans: List[Dict],
                     start_ms: int, failed: bool) -> List[Dict]:
        """One TaskEnd per root-operator partition span (the engine's
        'task' = one partition holding the TPU semaphore); spill totals
        from the trace's spill events land on task 0."""
        root_spans = [s for s in spans if s.get("kind") == "operator"
                      and (s.get("attrs") or {}).get("op") ==
                      type(final_plan).__name__]
        mem_spilled = sum((s.get("attrs") or {}).get("bytes", 0)
                          for s in spans if s["name"] == "spill.host")
        disk_spilled = sum((s.get("attrs") or {}).get("bytes", 0)
                           for s in spans if s["name"] == "spill.disk")
        sh_write = sum((s.get("attrs") or {}).get("bytes", 0)
                       for s in spans
                       if s["name"] == "shuffle.map_write")
        if not root_spans:
            # degenerate fallback: one synthetic task spanning the query
            dur = max((s["startNs"] + s["durNs"] for s in spans),
                      default=1_000_000)
            root_spans = [{"pid": 0, "startNs": 0, "durNs": dur,
                           "rows": 0, "bytes": 0, "status": "ok"}]
        out = []
        for i, s in enumerate(sorted(root_spans,
                                     key=lambda x: x.get("pid", 0))):
            launch = start_ms + s["startNs"] // 1_000_000
            finish = launch + max(s["durNs"] // 1_000_000, 1)
            run_ms = max(s["durNs"] // 1_000_000, 1)
            out.append({
                "Event": "SparkListenerTaskEnd", "Stage ID": sql_id,
                "Task Info": {"Task ID": sql_id * 1000 + i,
                              "Attempt": 0, "Executor ID": "0",
                              "Launch Time": launch,
                              "Finish Time": finish,
                              "Failed": failed and
                              s.get("status") == "error"},
                "Task Metrics": {
                    "Executor Run Time": run_ms,
                    "Executor CPU Time": run_ms * 1_000_000,
                    "JVM GC Time": 0,
                    "Result Size": s.get("bytes", 0),
                    "Input Metrics": {"Bytes Read": 0},
                    "Output Metrics": {"Bytes Written":
                                       s.get("bytes", 0)},
                    "Shuffle Read Metrics": {"Remote Bytes Read": 0,
                                             "Local Bytes Read": 0},
                    "Shuffle Write Metrics": {
                        "Shuffle Bytes Written":
                            sh_write if i == 0 else 0},
                    "Memory Bytes Spilled":
                        mem_spilled if i == 0 else 0,
                    "Disk Bytes Spilled":
                        disk_spilled if i == 0 else 0,
                },
            })
        return out
