"""Progress observatory: live in-flight query introspection, ETA,
cooperative cancellation/deadlines, and the stuck-query watchdog.

Every observatory before this one (tracer, estimator, HBM, latency) is
post-hoc: it explains a query after it closed.  The reference plugin
leans on Spark's listener bus and live UI for in-flight visibility; we
own the whole execution loop, so we own the live surface too.

One process-wide :class:`ProgressTracker` keeps a bounded live view per
in-flight query, fed from three existing seams with no per-operator
edits:

* **operator open/batch/close** — ``exec.base._wrap_execute_partition``
  (the ``Exec.__init_subclass__`` instrumentation point the flight
  recorder already rides) additionally routes each produced iterator
  through :meth:`_QueryHandle.observe_operator`, which notes operator
  starts, per-batch row counts, and partition completions;
* **phase transitions** — ``QueryTrace.start`` notifies
  :func:`note_span_open` for ``phase:*`` and ``admission.wait`` spans,
  so the live view's ``phase`` tracks planning -> queued -> executing
  without the session narrating each step;
* **the planner's model** — the session hands the handle the same
  per-node row predictions it installs on the trace
  (:meth:`_QueryHandle.set_predictions`), so rows-so-far reads against
  the estimator ledger's predicted rows.

The ETA blends the two progress signals the same confidence-weighted
way ``plan/cost.estimate_rows`` blends ledger feedback into the static
model: ``w = clamp(n/(n+1), [0.25, 0.9])`` with ``n`` = closed
partition count, ``ratio = w*partitions + (1-w)*rows``.  The published
ratio is clamped monotone (a new operator registering its partition
total grows the denominator; the view must never appear to move
backwards) and reconciles to the sealed trace's span counts at query
end: closed partitions == closed operator spans, by construction.

**Cooperative cancellation.**  ``begin_query`` mints a
:class:`CancelToken` bound thread-local to the executing thread.
``TpuSession.cancel`` / ``SessionPool.cancel`` (or a deadline, or the
watchdog) set its flag; the flag is CHECKED — never preempted — at the
three blocking seams: partition boundaries
(``exec.base.Exec.execute_collect``), the admission queue wait
(``memory.admission.AdmissionController.admit``, which also registers
the controller's condition variable as a waker so a cancelled waiter
wakes immediately, leaves the FIFO through the existing ``finally``,
and notifies survivors), and the async shuffle fetch loop
(``shuffle.transport.AsyncBlockFetcher.blocks``).  Each checkpoint
raises the typed :class:`TpuQueryCancelled` /
:class:`TpuQueryDeadlineExceeded`, which unwind through the existing
release-obligation machinery — admission tickets, tracer spans,
shuffle blocks and spill registrations all release in the same
finally/except arms every other failure uses (tpufsan R012).

**Watchdog.**  Poll-driven like the rest of the health surface (no
thread of its own): every ``watchdog_scan`` — called from health
snapshots, ``GET /queries`` and the ``--progress`` gate — flags
queries with no progress event for ``watchdog.stallSeconds``, names
the deepest open operator span, emits one stall record to the failure
black box, and past ``watchdog.autoCancelSeconds`` of stall cancels
the query with cause ``watchdog``.

Metrics: ``tpu_queries_inflight{phase}``,
``tpu_query_progress_ratio{tenant}``,
``tpu_cancellations_total{cause}``, ``tpu_query_stalls_total``.
Exposition: ``GET /queries`` (obs/health.py) and ``tools top``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

#: host-resident scalar types safe to int() on the hot path — a traced
#: device scalar would force a sync (the tracer's deferred-fetch
#: discipline; rows it defers are counted by the trace, not the view)
_HOST_NUMS = (int, float, bool, np.integer, np.floating, np.bool_)

#: finished-query ring kept for /queries "recent" context
FINISHED_RING = 32

#: confidence-weight clamp for the partition/rows blend — the same
#: floor/cap the estimator feedback blend defaults to (obs/estimator).
BLEND_FLOOR = 0.25
BLEND_CAP = 0.9

#: below this blended ratio the ETA is noise, not a forecast
ETA_MIN_RATIO = 0.02

INFLIGHT_FAMILY = "tpu_queries_inflight"
RATIO_FAMILY = "tpu_query_progress_ratio"
CANCEL_FAMILY = "tpu_cancellations_total"
STALL_FAMILY = "tpu_query_stalls_total"

CAUSE_CLIENT = "client"
CAUSE_DEADLINE = "deadline"
CAUSE_WATCHDOG = "watchdog"

PHASE_STARTING = "starting"
PHASE_PLANNING = "planning"
PHASE_QUEUED = "queued"
PHASE_EXECUTING = "executing"

_PHASE_BY_SPAN = {
    "phase:host_assist": PHASE_PLANNING,
    "phase:plan": PHASE_PLANNING,
    "phase:planning": PHASE_PLANNING,
    "phase:subqueries": PHASE_PLANNING,
    "phase:overrides": PHASE_PLANNING,
    "phase:plan-retry": PHASE_PLANNING,
    "admission.wait": PHASE_QUEUED,
    "phase:execute": PHASE_EXECUTING,
    "phase:execute-retry": PHASE_EXECUTING,
}


class TpuQueryCancelled(RuntimeError):
    """The query observed its cancel flag at a cooperative checkpoint.

    ``cause`` is who set the flag (``client`` or ``watchdog``);
    ``checkpoint`` is which seam observed it (``compute`` /
    ``queue_wait`` / ``remote_fetch``); ``operator`` is the exec whose
    loop saw the flag, when one was running."""

    cause = CAUSE_CLIENT

    def __init__(self, message: str = "query cancelled",
                 query_id: Optional[str] = None,
                 operator: Optional[str] = None,
                 checkpoint: Optional[str] = None,
                 cause: Optional[str] = None):
        super().__init__(message)
        self.query_id = query_id
        self.operator = operator
        self.checkpoint = checkpoint
        if cause is not None:
            self.cause = cause


class TpuQueryDeadlineExceeded(RuntimeError):
    """The query ran past its ``deadline_ms`` and a cooperative
    checkpoint observed the expiry.  Deliberately NOT a subclass of
    :class:`TpuQueryCancelled`: the two are accounted differently — a
    client cancel is excluded from the tenant's SLO burn window (the
    engine didn't miss), a blown deadline counts BAD."""

    cause = CAUSE_DEADLINE

    def __init__(self, message: str = "query deadline exceeded",
                 query_id: Optional[str] = None,
                 operator: Optional[str] = None,
                 checkpoint: Optional[str] = None):
        super().__init__(message)
        self.query_id = query_id
        self.operator = operator
        self.checkpoint = checkpoint


def _registry():
    from . import metrics
    return metrics.registry()


def _fam_inflight():
    return _registry().gauge(
        INFLIGHT_FAMILY,
        "in-flight queries by live-view phase (obs/progress.py)",
        ("phase",))


def _fam_ratio():
    return _registry().gauge(
        RATIO_FAMILY,
        "latest blended progress ratio per tenant (monotone per "
        "query; partitions/rows confidence blend)", ("tenant",))


def _fam_cancellations():
    return _registry().counter(
        CANCEL_FAMILY,
        "typed cancellations that actually propagated, by cause "
        "(client / deadline / watchdog)", ("cause",))


def _fam_stalls():
    return _registry().counter(
        STALL_FAMILY,
        "queries the stuck-query watchdog flagged (no progress for "
        "watchdog.stallSeconds)")


class CancelToken:
    """One query's cancel flag + optional deadline.

    Setting the flag never interrupts anything by force: the running
    query observes it at the next cooperative checkpoint.  ``wakers``
    are condition variables of seams that BLOCK (the admission queue
    wait) — ``cancel()`` notifies them so a queued query unwinds
    immediately instead of sleeping out its admission timeout."""

    __slots__ = ("query_id", "tenant", "cause", "deadline_mono",
                 "_flag", "_lock", "_wakers")

    def __init__(self, query_id: str, tenant: str,
                 deadline_ms: Optional[int] = None):
        self.query_id = query_id
        self.tenant = tenant
        self.cause: Optional[str] = None
        self.deadline_mono = (
            None if deadline_ms is None
            else time.monotonic() + deadline_ms / 1000.0)
        self._flag = False
        self._lock = threading.Lock()
        self._wakers: List[Any] = []

    def cancel(self, cause: str = CAUSE_CLIENT) -> None:
        with self._lock:
            if self._flag:
                return
            self._flag = True
            self.cause = cause
            wakers = list(self._wakers)
        for cv in wakers:
            try:
                with cv:
                    cv.notify_all()
            except Exception:
                pass  # a dead waiter's cv must not block the rest

    @property
    def cancelled(self) -> bool:
        return self._flag

    @property
    def deadline_exceeded(self) -> bool:
        return self.deadline_mono is not None and \
            time.monotonic() > self.deadline_mono

    def deadline_remaining_s(self) -> Optional[float]:
        if self.deadline_mono is None:
            return None
        return self.deadline_mono - time.monotonic()

    def add_waker(self, cv) -> None:
        with self._lock:
            self._wakers.append(cv)

    def remove_waker(self, cv) -> None:
        with self._lock:
            try:
                self._wakers.remove(cv)
            except ValueError:
                pass

    def describe(self, checkpoint: str,
                 operator: Optional[str] = None) -> str:
        """Message body for the typed error a checkpoint raises."""
        where = f" in {operator}" if operator else ""
        if self.deadline_exceeded and not self._flag:
            return (f"query {self.query_id} exceeded its deadline "
                    f"(observed at {checkpoint}{where})")
        return (f"query {self.query_id} cancelled by "
                f"{self.cause or CAUSE_CLIENT} "
                f"(observed at {checkpoint}{where})")

    def check(self, checkpoint: str = "compute",
              operator: Optional[str] = None) -> None:
        """Raise the typed error when the flag or deadline tripped —
        the per-batch checkpoint the operator wrapper calls.  The
        blocking seams (admission wait, fetch loop, partition loop)
        keep their own explicit raise sites so tpufsan's static reach
        sees the (seam, error) pairs."""
        if self._flag:
            raise TpuQueryCancelled(
                self.describe(checkpoint, operator),
                query_id=self.query_id, operator=operator,
                checkpoint=checkpoint, cause=self.cause)
        if self.deadline_exceeded:
            raise TpuQueryDeadlineExceeded(
                self.describe(checkpoint, operator),
                query_id=self.query_id, operator=operator,
                checkpoint=checkpoint)


class _OpStats:
    __slots__ = ("op", "total", "done", "rows", "open",
                 "predicted_rows")

    def __init__(self, op: str, total: Optional[int]):
        self.op = op
        self.total = total
        self.done = 0
        self.rows = 0
        self.open = 0
        self.predicted_rows: Optional[int] = None


def _static_partitions(node) -> Optional[int]:
    """A node's partition count WITHOUT triggering lazy materialization
    (the estimator's signature-probe discipline: an AQE reader's
    ``num_partitions`` property runs the map stage)."""
    try:
        if hasattr(node, "exchange") and hasattr(node, "_specs"):
            return getattr(node.exchange, "num_partitions", None)
        return getattr(node, "num_partitions", None)
    except Exception:
        return None


class _QueryHandle:
    """One in-flight query's live record: the unit the tracker stores,
    ``/queries`` renders, and the checkpoints consult via the
    thread-local binding."""

    def __init__(self, tracker: "ProgressTracker", query_id: str,
                 tenant: str, label: str,
                 deadline_ms: Optional[int]):
        self._tracker = tracker
        self.query_id = query_id
        self.tenant = tenant
        self.label = label
        self.token = CancelToken(query_id, tenant,
                                 deadline_ms=deadline_ms)
        self.deadline_ms = deadline_ms
        self.started_mono = time.monotonic()
        self.started_wall_ms = int(time.time() * 1000)
        self.phase = PHASE_STARTING
        self.last_progress_mono = self.started_mono
        self._lock = threading.Lock()
        self._ops: Dict[int, _OpStats] = {}   # keyed by id(node)
        self._open_order: List[int] = []      # open node ids, FIFO
        self.predicted_rows_total: Optional[int] = None
        self._best_ratio = 0.0
        self.stalled = False
        self.stall_reported = False
        self.cancel_counted = False
        self.cancel_observed_at: Optional[str] = None
        self.cancel_observed_operator: Optional[str] = None
        self.finished = False
        self.error_type: Optional[str] = None
        self.overhead_ns = 0

    # -- feed side -----------------------------------------------------------
    def touch(self) -> None:
        with self._lock:
            self.last_progress_mono = time.monotonic()
            self.stalled = False

    def set_phase(self, phase: str) -> None:
        with self._lock:
            old = self.phase
            if phase == old:
                return
            self.phase = phase
        self.touch()
        self._tracker._phase_moved(old, phase)

    def set_predictions(self, predictions: Optional[Dict]) -> None:
        """Install the planner's per-node row model (the same dict the
        session installs on the trace: id(node) -> {"rows": ...})."""
        if not predictions:
            return
        total = 0
        seen = False
        with self._lock:
            for nid, pred in predictions.items():
                rows = pred.get("rows")
                if rows is None:
                    continue
                seen = True
                total += int(rows)
                st = self._ops.get(nid)
                if st is not None:
                    st.predicted_rows = int(rows)
                else:
                    st = _OpStats(pred.get("node", "?"), None)
                    st.predicted_rows = int(rows)
                    self._ops[nid] = st
            if seen:
                self.predicted_rows_total = total

    def _op_open(self, node) -> int:
        t0 = time.perf_counter_ns()
        nid = id(node)
        with self._lock:
            st = self._ops.get(nid)
            if st is None:
                st = _OpStats(type(node).__name__,
                              _static_partitions(node))
                self._ops[nid] = st
            else:
                st.op = type(node).__name__
                if st.total is None:
                    st.total = _static_partitions(node)
            st.open += 1
            self._open_order.append(nid)
        self.touch()
        self.overhead_ns += time.perf_counter_ns() - t0
        return nid

    def _op_batch(self, nid: int, batch) -> None:
        t0 = time.perf_counter_ns()
        n = getattr(batch, "num_rows", None)
        with self._lock:
            st = self._ops.get(nid)
            if st is not None and isinstance(n, _HOST_NUMS):
                st.rows += int(n)
        self.touch()
        self.overhead_ns += time.perf_counter_ns() - t0

    def _op_close(self, nid: int) -> None:
        t0 = time.perf_counter_ns()
        with self._lock:
            st = self._ops.get(nid)
            if st is not None:
                st.open = max(st.open - 1, 0)
                st.done += 1
            try:
                # remove the LAST occurrence: nested same-node opens
                # (retries) close innermost-first
                for i in range(len(self._open_order) - 1, -1, -1):
                    if self._open_order[i] == nid:
                        del self._open_order[i]
                        break
            except Exception:
                pass
        self.touch()
        self._tracker._publish_ratio(self)
        self.overhead_ns += time.perf_counter_ns() - t0

    def observe_operator(self, node, pid: int, inner):
        """Wrap one execute_partition iterator: note open/batch/close
        in the live view and check the cancel flag before every batch
        pull — the per-batch cooperative checkpoint."""
        it = iter(inner)
        tok = self.token

        def gen():
            nid = self._op_open(node)
            try:
                while True:
                    tok.check(checkpoint="compute",
                              operator=type(node).__name__)
                    try:
                        b = next(it)
                    except StopIteration:
                        break
                    self._op_batch(nid, b)
                    yield b
            finally:
                self._op_close(nid)

        return gen()

    # -- read side -----------------------------------------------------------
    def deepest_open_operator(self) -> Optional[str]:
        """The most recently opened still-open operator — the span the
        watchdog names (the innermost frame of the stuck stack)."""
        with self._lock:
            if not self._open_order:
                return None
            st = self._ops.get(self._open_order[-1])
            return st.op if st is not None else None

    def progress_ratio(self) -> float:
        """Confidence-weighted blend of partition progress and row
        progress, clamped monotone per query."""
        with self._lock:
            done = sum(st.done for st in self._ops.values())
            total = sum(st.total for st in self._ops.values()
                        if st.total)
            rows = sum(st.rows for st in self._ops.values())
            pred = self.predicted_rows_total
        part_ratio = min(done / total, 1.0) if total else None
        rows_ratio = min(rows / pred, 1.0) if pred else None
        if part_ratio is None and rows_ratio is None:
            ratio = 0.0
        elif rows_ratio is None:
            ratio = part_ratio
        elif part_ratio is None:
            ratio = rows_ratio
        else:
            w = min(BLEND_CAP, max(BLEND_FLOOR, done / (done + 1.0)))
            ratio = w * part_ratio + (1.0 - w) * rows_ratio
        if self.finished and self.error_type is None:
            ratio = 1.0
        with self._lock:
            if ratio > self._best_ratio:
                self._best_ratio = ratio
            return self._best_ratio

    def eta_s(self) -> Optional[float]:
        ratio = self.progress_ratio()
        if self.finished or ratio < ETA_MIN_RATIO:
            return None
        elapsed = time.monotonic() - self.started_mono
        return elapsed * (1.0 - ratio) / ratio

    def snapshot(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            ops = {}
            for st in self._ops.values():
                agg = ops.setdefault(
                    st.op, {"done": 0, "total": 0, "rows": 0,
                            "open": 0, "predicted_rows": 0})
                agg["done"] += st.done
                agg["total"] += st.total or 0
                agg["rows"] += st.rows
                agg["open"] += st.open
                agg["predicted_rows"] += st.predicted_rows or 0
            rows = sum(st.rows for st in self._ops.values())
            done = sum(st.done for st in self._ops.values())
        eta = self.eta_s()
        return {
            "query": self.query_id,
            "tenant": self.tenant,
            "label": self.label,
            "phase": self.phase,
            "started_wall_ms": self.started_wall_ms,
            "elapsed_s": round(now - self.started_mono, 6),
            "operators": ops,
            "partitions_done": done,
            "rows": rows,
            "predicted_rows": self.predicted_rows_total,
            "progress_ratio": round(self.progress_ratio(), 6),
            "eta_s": None if eta is None else round(eta, 6),
            "deadline_ms": self.deadline_ms,
            "cancelled": self.token.cancelled,
            "cancel_cause": self.token.cause,
            "stalled": self.stalled,
            "deepest_open_operator": self.deepest_open_operator(),
            "last_progress_s_ago":
                round(now - self.last_progress_mono, 6),
            "finished": self.finished,
            "error": self.error_type,
        }


class ProgressTracker:
    """Process-wide live view of in-flight queries (singleton like the
    compile/estimator/latency observatories)."""

    _instance: Optional["ProgressTracker"] = None
    _ilock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = True
        self.max_queries = 64
        self.stall_seconds = 30.0
        self.auto_cancel_seconds: Optional[float] = None
        self._live: Dict[tuple, _QueryHandle] = {}
        self._finished = deque(maxlen=FINISHED_RING)
        self._seq = 0

    @classmethod
    def get(cls) -> "ProgressTracker":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = ProgressTracker()
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> "ProgressTracker":
        with cls._ilock:
            cls._instance = ProgressTracker()
            return cls._instance

    def configure(self, enabled: Optional[bool] = None,
                  max_queries: Optional[int] = None,
                  stall_seconds: Optional[float] = None,
                  auto_cancel_seconds: Optional[float] = None
                  ) -> "ProgressTracker":
        """Session-init wiring; idempotent, None leaves values alone
        (pool sessions all configure with the same conf)."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if max_queries is not None:
                self.max_queries = int(max_queries)
            if stall_seconds is not None:
                self.stall_seconds = float(stall_seconds)
            if auto_cancel_seconds is not None:
                self.auto_cancel_seconds = float(auto_cancel_seconds)
        return self

    # -- lifecycle ------------------------------------------------------------
    def begin_query(self, query_id: str, tenant: str = "default",
                    label: str = "",
                    deadline_ms: Optional[int] = None
                    ) -> Optional[_QueryHandle]:
        if not self.enabled:
            return None
        tenant = tenant or "default"
        h = _QueryHandle(self, query_id, tenant, label, deadline_ms)
        with self._lock:
            self._seq += 1
            # bounded live view: a leaked registration (a crash that
            # skipped end_query) must not grow this forever — evict
            # the oldest entry past the cap, never reallocate
            while len(self._live) >= self.max_queries:
                old_key = next(iter(self._live))
                old = self._live.pop(old_key)
                self._phase_moved(old.phase, None)
            self._live[(tenant, query_id)] = h
        try:
            _fam_inflight().labels(phase=h.phase).gauge_inc()
        except Exception:
            pass
        return h

    def end_query(self, handle: Optional[_QueryHandle],
                  error: Optional[BaseException] = None) -> None:
        if handle is None:
            return
        handle.finished = True
        handle.error_type = type(error).__name__ \
            if error is not None else None
        if isinstance(error, (TpuQueryCancelled,
                              TpuQueryDeadlineExceeded)):
            handle.cancel_observed_at = getattr(error, "checkpoint",
                                                None)
            handle.cancel_observed_operator = getattr(error,
                                                      "operator", None)
            self.count_cancellation(handle, getattr(
                error, "cause", CAUSE_CLIENT) or CAUSE_CLIENT)
        with self._lock:
            was_live = self._live.pop(
                (handle.tenant, handle.query_id), None) is not None
            self._finished.append(handle)
        if was_live:  # an evicted handle already decremented its phase
            self._phase_moved(handle.phase, None)
        self._publish_ratio(handle)

    def count_cancellation(self, handle: Optional[_QueryHandle],
                           cause: str) -> None:
        """Count one PROPAGATED cancellation (at most once per query —
        several checkpoints may observe the same flag)."""
        if handle is not None:
            if handle.cancel_counted:
                return
            handle.cancel_counted = True
        try:
            _fam_cancellations().labels(cause=cause).inc()
        except Exception:
            pass

    # -- cancellation ---------------------------------------------------------
    def cancel(self, query_id: str, tenant: Optional[str] = None,
               cause: str = CAUSE_CLIENT) -> bool:
        """Set the cancel flag on a live query; returns whether a
        matching in-flight query was found.  ``tenant=None`` matches
        any tenant (single-session use)."""
        with self._lock:
            targets = [h for (t, q), h in self._live.items()
                       if q == query_id and
                       (tenant is None or t == tenant)]
        for h in targets:
            h.token.cancel(cause)
        return bool(targets)

    # -- feed hooks -----------------------------------------------------------
    def _phase_moved(self, old: Optional[str],
                     new: Optional[str]) -> None:
        try:
            fam = _fam_inflight()
            if old is not None:
                fam.labels(phase=old).dec()
            if new is not None:
                fam.labels(phase=new).gauge_inc()
        except Exception:
            pass

    def _publish_ratio(self, handle: _QueryHandle) -> None:
        try:
            _fam_ratio().labels(tenant=handle.tenant).set(
                round(handle.progress_ratio(), 6))
        except Exception:
            pass

    # -- watchdog -------------------------------------------------------------
    def watchdog_scan(self, now: Optional[float] = None
                      ) -> List[Dict[str, Any]]:
        """Flag queries with no progress for ``stall_seconds``; emit
        one black-box stall record per stalled query; auto-cancel past
        ``auto_cancel_seconds``.  Returns the stall list (the health
        monitor's ``progress`` component signals)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            live = list(self._live.values())
            stall_s = self.stall_seconds
            auto_s = self.auto_cancel_seconds
        out = []
        for h in live:
            idle = now - h.last_progress_mono
            if stall_s <= 0 or idle < stall_s:
                continue
            h.stalled = True
            op = h.deepest_open_operator()
            rec = {"query": h.query_id, "tenant": h.tenant,
                   "phase": h.phase, "stalled_s": round(idle, 3),
                   "deepest_open_operator": op}
            if not h.stall_reported:
                h.stall_reported = True
                try:
                    _fam_stalls().inc()
                except Exception:
                    pass
                self._blackbox_stall(h, idle, op)
            if auto_s is not None and idle >= auto_s and \
                    not h.token.cancelled:
                h.token.cancel(CAUSE_WATCHDOG)
                rec["auto_cancelled"] = True
            out.append(rec)
        return out

    def _blackbox_stall(self, h: _QueryHandle, idle: float,
                        op: Optional[str]) -> None:
        """One stall record into the failure black box (best-effort,
        via the background-error router's bundle directory)."""
        try:
            from . import bgerrors
            err = RuntimeError(
                f"query {h.query_id} (tenant {h.tenant}) made no "
                f"progress for {idle:.1f}s in phase {h.phase}"
                + (f"; deepest open operator span: {op}" if op
                   else ""))
            bgerrors.note_background_error("watchdog", err)
        except Exception:
            pass

    # -- read side ------------------------------------------------------------
    def live_view(self, scan: bool = True) -> Dict[str, Any]:
        """The ``GET /queries`` document: every in-flight query's
        snapshot plus the recent finished ring.  ``scan`` runs the
        watchdog first so a scrape is also a liveness check."""
        stalls = self.watchdog_scan() if scan else []
        with self._lock:
            live = [h.snapshot() for h in self._live.values()]
            finished = [h.snapshot() for h in list(self._finished)]
        live.sort(key=lambda d: d["started_wall_ms"])
        return {
            "inflight": live,
            "stalled": stalls,
            "recent": finished[-FINISHED_RING:],
            "watchdog": {
                "stall_seconds": self.stall_seconds,
                "auto_cancel_seconds": self.auto_cancel_seconds,
            },
        }

    def overhead(self) -> Dict[str, float]:
        """Tracker self-time booked by the feed hooks (the <5%
        anti-vacuity figure's numerator)."""
        with self._lock:
            handles = list(self._live.values()) + list(self._finished)
        ns = sum(h.overhead_ns for h in handles)
        return {"hook_s": round(ns / 1e9, 6), "queries": len(handles)}


# ---------------------------------------------------------------------------
# thread-local binding (what the cooperative checkpoints consult)
# ---------------------------------------------------------------------------

_TLS = threading.local()


def bind_to_thread(handle: Optional[_QueryHandle]) -> None:
    """Bind (or with None, unbind) the calling thread's in-flight
    query handle — the session sets this around query execution so the
    checkpoints in exec/admission/shuffle find their token without
    plumbing it through every signature."""
    _TLS.handle = handle


def current_handle() -> Optional[_QueryHandle]:
    return getattr(_TLS, "handle", None)


def current_token() -> Optional[CancelToken]:
    h = getattr(_TLS, "handle", None)
    return h.token if h is not None else None


def note_span_open(name: str, kind: str) -> None:
    """Tracer hook: phase transitions for the live view.  Called by
    ``QueryTrace.start`` for phase spans and ``admission.wait``; cheap
    no-op for threads with no bound handle."""
    h = getattr(_TLS, "handle", None)
    if h is None:
        return
    phase = _PHASE_BY_SPAN.get(name)
    if phase is not None:
        h.set_phase(phase)
