"""Runtime lock witness — the execution half of tpucsan.

``analysis/concurrency.py`` computes a static lock-order relation; this
module validates it against what threads actually do.  When
``spark.rapids.tpu.csan.enabled`` is on, the witness replaces the
engine's registered lock objects with thin proxies that

  * keep a per-thread stack of held witness locks,
  * record every nesting edge ``outer -> inner`` actually executed,
  * count blocked acquisitions into ``tpu_lock_contention_total{lock}``
    and time them into ``tpu_lock_wait_seconds{lock}`` (cardinality is
    bounded by the witness registry itself — one series per registered
    lock — on top of the metric family's own ``max_series`` cap),

and ``report()`` then fails the run if execution observed an
acquisition edge the static graph cannot explain (an *unmodeled* edge:
the pass has a hole) or if the observed edges close a lock-order cycle
(the ABBA interleaving TPU-R008 warns about actually happened).  Static
analysis validated by execution, execution checked against static
analysis — same contract as tmsan's plan-vs-ledger split.

Design constraints that shape the code:

  * ``maybe_register`` is called from inside constructors that may be
    holding locks — it only appends to a pending list under the
    witness's own raw mutex and never touches the metrics registry, so
    instrumentation cannot introduce lock edges of its own.  The actual
    wrapping (and metric-series resolution) happens in ``refresh()``,
    called from lock-free context at query start.
  * metric children are resolved ONCE at wrap time; the hot acquire
    path touches only the per-series child locks, never the registry
    locks — otherwise witnessing `MetricsRegistry._lock` would recurse
    into itself.
  * an unmodeled edge is judged against the TRANSITIVE CLOSURE of the
    static edges: the runtime stack sees ``A held while C acquired``
    even when the static pass modeled it as ``A -> B`` and ``B -> C``
    through a callee.
  * ``Condition.wait()`` releases and reacquires its inner lock without
    passing through the proxy; the held stack deliberately keeps the
    condition "held" across the wait — the thread is blocked, it cannot
    acquire anything else, so no spurious edges appear.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set, Tuple

_WAIT_BUCKETS = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                 1.0, 5.0)


class _LockProxy:
    """Wraps a Lock/RLock: same surface, plus witness bookkeeping."""

    def __init__(self, inner, name: str, witness: "LockWitness",
                 contended, wait_hist):
        self._inner = inner
        self._name = name
        self._witness = witness
        self._contended = contended   # pre-resolved counter child
        self._wait_hist = wait_hist   # pre-resolved histogram child

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if not blocking or timeout != -1:
            if timeout != -1:
                got = self._inner.acquire(blocking, timeout)
            else:
                got = self._inner.acquire(blocking)
        else:
            got = self._inner.acquire(False)
            if not got:
                self._contended.inc()
                t0 = time.perf_counter()
                got = self._inner.acquire()
                self._wait_hist.observe(time.perf_counter() - t0)
        if got:
            self._witness.on_acquired(self._name)
        return got

    def release(self):
        self._witness.on_released(self._name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()


class _CondProxy(_LockProxy):
    """Condition proxy: wait/notify delegate to the wrapped condvar
    (which owns the real lock, so ``wait`` still re-acquires it)."""

    def wait(self, timeout: Optional[float] = None):
        return self._inner.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        return self._inner.wait_for(predicate, timeout)

    def notify(self, n: int = 1):
        return self._inner.notify(n)

    def notify_all(self):
        return self._inner.notify_all()


def _closure(edges: Set[Tuple[str, str]]) -> Set[Tuple[str, str]]:
    succ: Dict[str, Set[str]] = {}
    for a, b in edges:
        succ.setdefault(a, set()).add(b)
    out: Set[Tuple[str, str]] = set()
    for start in succ:
        seen: Set[str] = set()
        stack = list(succ[start])
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(succ.get(cur, ()))
        out.update((start, s) for s in seen)
    return out


def _find_cycles(edges: Set[Tuple[str, str]]) -> List[List[str]]:
    """SCCs of size >= 2 (or self-loops) in the observed edge graph."""
    from ..analysis.concurrency import _tarjan
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles = [sorted(scc) for scc in _tarjan(graph) if len(scc) >= 2]
    cycles += [[a] for a, b in edges if a == b]
    return sorted(cycles)


class LockWitness:
    def __init__(self, artifact: Dict):
        self._mu = threading.Lock()        # raw: guards witness state
        self._tls = threading.local()
        static_edges = {tuple(e) for e in artifact.get("edges", ())}
        self.static_locks: Set[str] = set(artifact.get("locks", {}))
        self.static_cycles = [list(c) for c in artifact.get("cycles", ())]
        self._static_closure = _closure(static_edges) | static_edges
        self.observed: Set[Tuple[str, str]] = set()
        self.unmodeled: Set[Tuple[str, str]] = set()
        self.acquire_count: Dict[str, int] = {}
        # (owner, attr, original) for uninstall
        self._wrapped: List[Tuple[object, str, object]] = []
        self._pending: List[Tuple[str, object, str]] = []
        self._fams = None

    # -- registration --------------------------------------------------------
    def enqueue(self, name: str, owner: object, attr: str) -> None:
        with self._mu:
            self._pending.append((name, owner, attr))

    def _metric_children(self, name: str):
        from . import metrics as m
        if self._fams is None:
            self._fams = (
                m.counter("tpu_lock_contention_total",
                          "Blocked acquisitions of witness-registered "
                          "locks (csan lock witness).",
                          labelnames=("lock",)),
                m.histogram("tpu_lock_wait_seconds",
                            "Blocking-acquire wait time on witness-"
                            "registered locks (csan lock witness).",
                            labelnames=("lock",),
                            buckets=_WAIT_BUCKETS),
            )
        cont, wait = self._fams
        return cont.labels(lock=name), wait.labels(lock=name)

    def wrap(self, name: str, owner: object, attr: str) -> None:
        """Swap ``owner.attr`` for a proxy.  Call from lock-free
        context only (metric-series resolution takes registry locks)."""
        cur = getattr(owner, attr, None)
        if cur is None or isinstance(cur, _LockProxy):
            return
        cont, wait = self._metric_children(name)
        if isinstance(cur, threading.Condition):
            proxy = _CondProxy(cur, name, self, cont, wait)
        elif hasattr(cur, "acquire") and hasattr(cur, "release"):
            proxy = _LockProxy(cur, name, self, cont, wait)
        else:
            return
        setattr(owner, attr, proxy)
        self._wrapped.append((owner, attr, cur))

    def refresh(self) -> None:
        """Drain deferred registrations and wrap the engine's known
        long-lived lock owners that exist right now."""
        with self._mu:
            pending, self._pending = self._pending, []
        for name, owner, attr in pending:
            self.wrap(name, owner, attr)
        self._wrap_singletons()

    def _wrap_singletons(self) -> None:
        # Default witnessed set: the locks the serving path actually
        # interleaves.  Classes are wrapped unconditionally; instance
        # locks only when the singleton already exists (wrapping must
        # not CREATE singletons as a side effect).
        from . import metrics as m_mod
        reg = m_mod.MetricsRegistry
        self.wrap("obs.metrics.MetricsRegistry._ilock", reg, "_ilock")
        if reg._instance is not None:
            self.wrap("obs.metrics.MetricsRegistry._lock",
                      reg._instance, "_lock")
        from ..memory.admission import AdmissionController as AC
        self.wrap("memory.admission.AdmissionController._ilock",
                  AC, "_ilock")
        if AC._instance is not None:
            self.wrap("memory.admission.AdmissionController._cv",
                      AC._instance, "_cv")
        from ..memory.semaphore import TpuSemaphore
        self.wrap("memory.semaphore.TpuSemaphore._lock",
                  TpuSemaphore, "_lock")
        if getattr(TpuSemaphore, "_instance", None) is not None:
            self.wrap("memory.semaphore.TpuSemaphore._cv",
                      TpuSemaphore._instance, "_cv")
        from ..memory.spill import SpillCatalog
        self.wrap("memory.spill.SpillCatalog._lock", SpillCatalog,
                  "_lock")
        if SpillCatalog._instance is not None:
            self.wrap("memory.spill.SpillCatalog._reg_lock",
                      SpillCatalog._instance, "_reg_lock")
        from ..shuffle.manager import TpuShuffleManager
        self.wrap("shuffle.manager.TpuShuffleManager._lock",
                  TpuShuffleManager, "_lock")
        inst = TpuShuffleManager._instance
        if inst is not None:
            self.wrap("shuffle.manager.TpuShuffleManager._comp_lock",
                      inst, "_comp_lock")
            self.wrap("shuffle.manager.ShuffleBufferCatalog._lock",
                      inst.catalog, "_lock")

    # -- the hot path --------------------------------------------------------
    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def on_acquired(self, name: str) -> None:
        st = self._stack()
        new_edges = [(h, name) for h in st if h != name]
        st.append(name)
        with self._mu:
            self.acquire_count[name] = \
                self.acquire_count.get(name, 0) + 1
            for e in new_edges:
                self.observed.add(e)
                if e not in self._static_closure:
                    self.unmodeled.add(e)

    def on_released(self, name: str) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                break

    # -- verdict -------------------------------------------------------------
    def report(self) -> Dict:
        with self._mu:
            observed = sorted(self.observed)
            unmodeled = sorted(self.unmodeled)
            counts = dict(self.acquire_count)
        cycles = _find_cycles(set(observed))
        return {
            "locks_wrapped": sorted(
                {w[0].__class__.__name__ + "." + w[1]
                 for w in self._wrapped}),
            "n_wrapped": len(self._wrapped),
            "acquires": counts,
            "edges": observed,
            "unmodeled": unmodeled,
            "cycles": cycles,
            "ok": not unmodeled and not cycles,
        }

    def uninstall(self) -> None:
        for owner, attr, original in reversed(self._wrapped):
            cur = getattr(owner, attr, None)
            if isinstance(cur, _LockProxy):
                setattr(owner, attr, original)
        self._wrapped.clear()


# ---------------------------------------------------------------------------
# module-level lifecycle (mirrors tracer/memsan install semantics)
# ---------------------------------------------------------------------------

_WITNESS: Optional[LockWitness] = None


def install(artifact: Optional[Dict] = None) -> LockWitness:
    """Install (or return) the process witness.  ``artifact`` defaults
    to the repo's own static lock-order relation."""
    global _WITNESS
    if _WITNESS is None:
        if artifact is None:
            from ..analysis.concurrency import lock_order_artifact
            artifact = lock_order_artifact()
        _WITNESS = LockWitness(artifact)
    _WITNESS.refresh()
    return _WITNESS


def ensure_installed() -> LockWitness:
    return install()


def get_witness() -> Optional[LockWitness]:
    return _WITNESS


def maybe_register(name: str, owner: object, attr: str) -> None:
    """Deferred lock registration — safe to call while holding locks
    (constructors do); a no-op unless the witness is installed."""
    w = _WITNESS
    if w is not None:
        w.enqueue(name, owner, attr)


def report() -> Optional[Dict]:
    w = _WITNESS
    return w.report() if w is not None else None


def uninstall() -> None:
    global _WITNESS
    if _WITNESS is not None:
        _WITNESS.uninstall()
        _WITNESS = None


def reset_for_tests() -> None:
    uninstall()
