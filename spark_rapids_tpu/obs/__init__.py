"""Observability subsystem: the flight recorder (span tracer, self-
emitted SparkListener event logs, Chrome-trace/text exporters, the
predicted-vs-actual accuracy loop) plus the CONTINUOUS layer — the
process-wide metrics registry (obs/metrics.py), the Prometheus/health
exposition (obs/health.py), the cross-run regression watchdog
(obs/history.py) and the compile observatory (obs/compileprof.py:
split build timing, miss-cause classification and the cross-session
compile ledger at the process_jit seam).  See docs/observability.md."""

from .tracer import (QueryTrace, active_tracer, install, trace_event,
                     trace_span, uninstall)

__all__ = ["QueryTrace", "active_tracer", "install", "uninstall",
           "trace_event", "trace_span"]
