"""Observability subsystem (the flight recorder): span tracer, self-
emitted SparkListener event logs, Chrome-trace/text exporters, and the
predicted-vs-actual accuracy loop.  See docs/observability.md."""

from .tracer import (QueryTrace, active_tracer, install, trace_event,
                     trace_span, uninstall)

__all__ = ["QueryTrace", "active_tracer", "install", "uninstall",
           "trace_event", "trace_span"]
