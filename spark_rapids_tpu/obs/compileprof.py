"""Compile observatory: attribute, classify and persist every XLA
compilation the engine pays for.

BENCH_r05_builder measured the join suite at 68.6 s of compile against
0.372 s of device time — the engine is compile-bound, and until this
module the only record of a compilation was an unlabeled ``jit.build``
instant event with no duration, no cause and no cross-session memory.
The observatory sits at the single ``process_jit`` seam
(``exec/base.py``): every jit in exec/, parallel/, columnar/ and
shuffle/ already routes through that table, so one wrapper sees every
program the process ever builds.

What one build produces:

* **Split timing.**  The returned callable dispatches through an AOT
  proxy: the first call per input-shape signature runs
  ``jit(f).lower(*args)`` (trace + lower, timed) then
  ``lowered.compile()`` (backend compile, timed — this is the step the
  persistent disk cache can absorb) and caches the compiled executable
  for every later call with that signature.  The split is what ROADMAP
  item 1 needs: re-trace cost survives a disk cache, backend cost does
  not.
* **A program fingerprint.**  Exec kind parsed from the jit key, a
  stable hash of the semantic key, a bucket-canonical key hash (every
  int in the key or leading array dim that equals a configured
  capacity/string bucket is masked), the input dtype signature and the
  capacity signature, plus the lowered StableHLO size.
* **A classified cause.**  Every build is diffed against the index of
  previously seen programs (this process + the loaded ledger):

  - ``eviction_refault`` — this exact program was built before and is
    no longer resident (LRU eviction, cache clear, or a previous
    session: process death is the ultimate eviction);
  - ``shape_churn``     — the same program modulo capacity buckets was
    already built (same exec + canonical key + dtypes, different
    bucket) — the recompiles bucket canonicalization would erase;
  - ``dtype_churn``     — the same exec + capacity signature was built
    under a different dtype signature;
  - ``new_program``     — genuinely novel work.

* **Three sinks, one truth.**  Each build (a) stamps an enriched
  ``jit.build`` span on the active flight-recorder trace, (b) feeds the
  ``tpu_jit_{hits,misses,evictions,compile_seconds}_total`` metric
  families plus the ``tpu_jit_cache_size`` gauge, and (c) appends one
  JSONL record to the cross-session compile ledger
  (``compile_ledger.jsonl`` in the obs/history.py HistoryDir).  The CI
  gate (``devtools/run_lint.py --jit``) fails when the three disagree
  about the build count.

``tools compile-report`` aggregates the ledger into
top-programs-by-compile-cost, churn offenders and the dedupe projection
("N programs collapse to M under bucket canonicalization") — the
evidence the persistent-cache key design needs.

Overhead discipline: with the observatory disabled every ``process_jit``
call costs one extra attribute read; enabled, a warm call pays one
pytree flatten + dict lookup per batch (same cost class as the tracer's
per-batch bookkeeping, never a device touch or a lock on the warm
path).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger("spark_rapids_tpu.obs.compileprof")

LEDGER_FILENAME = "compile_ledger.jsonl"
LEDGER_VERSION = 1

# lowered-StableHLO persistence (the tpuxsan audit's raw material):
# blake2-keyed text files, deduped per program, size-capped so a
# pathological giant program cannot bloat the ledger dir
HLO_SUBDIR = "hlo"
HLO_SUFFIX = ".stablehlo.mlir"
HLO_MAX_BYTES = 2 * 1024 * 1024

# the canonical cost_analysis keys the audit consumes.  XLA backends
# report DIFFERENT subsets (CPU omits transcendentals and sometimes
# flops): only keys the backend actually returned are recorded — an
# absent key is absent, never zero.
COST_KEYS = ("flops", "bytes accessed", "transcendentals")


def hlo_key(text: str) -> str:
    """Content key of one lowered program's StableHLO text."""
    return hashlib.blake2b(text.encode("utf-8", "replace"),
                           digest_size=8).hexdigest()


def cost_summary(compiled) -> Optional[Dict[str, float]]:
    """The executable's own cost_analysis(), distilled to the canonical
    keys it actually reported.  Returns None when the backend offers no
    analysis at all — callers must treat that as 'unknown', not free."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {k: float(ca[k]) for k in COST_KEYS
           if k in ca and ca[k] is not None}
    return out or None

# miss-cause taxonomy (closed: every build carries exactly one)
CAUSE_NEW = "new_program"
CAUSE_SHAPE = "shape_churn"
CAUSE_DTYPE = "dtype_churn"
CAUSE_REFAULT = "eviction_refault"
CAUSES = (CAUSE_NEW, CAUSE_SHAPE, CAUSE_DTYPE, CAUSE_REFAULT)

# default bucket set for canonicalization, matching the config defaults
# (spark.rapids.tpu.batchCapacityBuckets / .stringDataBuckets); sessions
# override via configure() so changed bucket configs stay honest
_DEFAULT_BUCKETS = frozenset(
    (1024, 8192, 65536, 262144, 1048576, 4194304,
     16384, 131072, 8388608, 67108864, 268435456))

_CAP_MASK = "<cap>"

# jit families can out-card the default 64-series cap: exec kinds alone
# approach it, and misses fan out by cause
_JIT_MAX_SERIES = 256


def _stable_hash(obj: Any) -> str:
    """12-hex stable hash of a semantic key.  repr() is stable for the
    atoms semantic_sig produces (strings, ints, bytes, type names); the
    rare id()-keyed fallback entries hash per-process only — they can
    fragment cross-session aggregation, never corrupt it."""
    return hashlib.sha1(repr(obj).encode()).hexdigest()[:12]


def _mask_buckets(v: Any, buckets) -> Any:
    """The jit key with every capacity-bucket int replaced by a
    sentinel: two keys that differ only in bucket choice canonicalize
    to the same value (the dedupe axis of `tools compile-report`)."""
    if isinstance(v, bool):
        return v
    if isinstance(v, int):
        return _CAP_MASK if v in buckets else v
    if isinstance(v, tuple):
        return tuple(_mask_buckets(x, buckets) for x in v)
    if isinstance(v, list):
        return [_mask_buckets(x, buckets) for x in v]
    return v


def _exec_kind(key: tuple) -> str:
    """The operator kind from a process_jit key.  Keys arrive as
    (shim_version, kind, ...); the kind is the first string past the
    version for every call site in the tree."""
    for part in key[1:]:
        if isinstance(part, str):
            return part
    return str(key[1])[:40] if len(key) > 1 else "?"


# ---------------------------------------------------------------------------
# input-shape signatures
# ---------------------------------------------------------------------------

_PY_SCALARS = (int, float, bool, complex)


def _leaf_sig(leaf) -> Optional[Tuple]:
    """(dtype, shape, sharding) of one call-argument leaf, or None when
    the leaf has no stable signature (tracers under an enclosing trace,
    arbitrary objects) — the caller then falls back to plain jit
    dispatch.  The sharding joins the signature because an AOT-compiled
    executable bakes its input shardings in: a mesh-committed array
    (ICI stage output) and a single-device one are DIFFERENT programs
    (jit's own dispatch cache keys the same way)."""
    import jax
    if isinstance(leaf, jax.core.Tracer):
        return None
    dt = getattr(leaf, "dtype", None)
    shape = getattr(leaf, "shape", None)
    if dt is not None and shape is not None:
        return (str(dt), tuple(int(s) for s in shape),
                getattr(leaf, "sharding", None))
    if isinstance(leaf, _PY_SCALARS):
        # python scalars are weak-typed dynamic args under jit: the
        # TYPE picks the program, the value rides at call time
        return (type(leaf).__name__, (), None)
    return None


def _dispatch_key(args) -> Optional[tuple]:
    """Hashable per-call signature (treedef + leaf dtype/shape), or
    None when any leaf is unsignable."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    sigs = []
    for leaf in leaves:
        s = _leaf_sig(leaf)
        if s is None:
            return None
        sigs.append(s)
    return (treedef, tuple(sigs))


def _erase_sharding(sig: tuple) -> tuple:
    """A dispatch key with leaf shardings dropped.  Prewarmed programs
    are compiled from ShapeDtypeStruct skeletons (no sharding), while
    concrete query calls carry committed-device shardings — the
    warm-start lookup matches on shapes/dtypes and lets the executable
    itself reject a true sharding mismatch (caught, falls back to a
    cold build)."""
    treedef, leaf_sigs = sig
    return (treedef, tuple((d, s, None) for d, s, _ in leaf_sigs))


def _aval_dispatch_key(args) -> Optional[tuple]:
    """Like _dispatch_key, but tracer leaves sign by their abstract
    value (shape/dtype, no sharding — an enclosing trace has none to
    offer).  Lets the plain-jit fallback path dedupe and ledger its
    builds under the SAME canonical key instead of silently forking
    the key space."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    sigs = []
    for leaf in leaves:
        if isinstance(leaf, jax.core.Tracer):
            av = getattr(leaf, "aval", None)
            shape = getattr(av, "shape", None)
            dt = getattr(av, "dtype", None)
            if shape is None or dt is None:
                return None
            sigs.append((str(dt), tuple(int(d) for d in shape), None))
            continue
        s = _leaf_sig(leaf)
        if s is None:
            return None
        sigs.append(s)
    return (treedef, tuple(sigs))


def _shape_record(sig: tuple, buckets) -> Tuple[str, tuple, tuple, tuple]:
    """(shape_hash, dtype_sig, cap_sig, canon_caps) from a dispatch
    key.  cap_sig is the tuple of leaf shapes (the capacity buckets ride
    the leading dims); canon_caps masks bucket-valued dims.  The
    shardings join the shape hash (program identity) but not the
    dtype/cap signatures the cause classifier compares — a resharded
    rebuild reads as shape_churn, the nearest honest cause.  The
    treedef joins the hash too: same leaves under a different pytree
    structure (e.g. renamed batch columns) is a different program."""
    treedef, leaf_sigs = sig
    dtype_sig = tuple(s[0] for s in leaf_sigs)
    cap_sig = tuple(s[1] for s in leaf_sigs)
    shardings = tuple(repr(s[2]) for s in leaf_sigs)
    canon = tuple(tuple(_CAP_MASK if d in buckets else d for d in shp)
                  for shp in cap_sig)
    return (_stable_hash((repr(treedef), dtype_sig, cap_sig,
                          shardings)), dtype_sig, cap_sig, canon)


# ---------------------------------------------------------------------------
# the observatory
# ---------------------------------------------------------------------------

class CompileObservatory:
    """Process-wide singleton recording every XLA program build."""

    _instance: Optional["CompileObservatory"] = None
    _ilock = threading.Lock()

    def __init__(self):
        self._lock = threading.RLock()
        self.enabled = True
        self.ledger_path: Optional[str] = None
        self.hlo_dir: Optional[str] = None
        self.thrash_warn_ratio = 0.5
        self.buckets = frozenset(_DEFAULT_BUCKETS)
        # program index: pid = (key_hash, shape_hash)
        self._programs: Dict[Tuple[str, str], Dict] = {}
        self._resident: set = set()        # pids live in this process
        self._evicted: set = set()         # seen, no longer resident
        self._evicted_live: set = set()    # evicted by THIS process's LRU
        self._families: set = set()        # (exec, canon_key, dtype_hash)
        self._cap_index: Dict[Tuple[str, str], set] = {}
        # counters (read via snapshot(); the registry carries the
        # exported copies)
        self.builds = 0
        self.hits = 0
        self.evictions = 0
        self.refaults = 0
        self.compile_seconds_total = 0.0
        self.trace_seconds_total = 0.0
        self.by_cause: Dict[str, int] = {}
        self._warn_next = 1
        # warm-start tier: proxies readied from ledger recipes, waiting
        # for their process_jit miss to claim them (key -> _ProfiledJit)
        self._prewarm_staged: Dict[tuple, Any] = {}
        self.prewarm_hits = 0
        self.prewarm_seconds = 0.0
        self.prewarm_stats: Optional[Dict] = None

    # -- lifecycle -----------------------------------------------------------
    @classmethod
    def get(cls) -> "CompileObservatory":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = CompileObservatory()
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> "CompileObservatory":
        """Fresh observatory (tests and CI gates need known-empty
        indexes; production never calls this)."""
        with cls._ilock:
            cls._instance = CompileObservatory()
            return cls._instance

    def configure(self, enabled: Optional[bool] = None,
                  ledger_path: Optional[str] = None,
                  buckets=None,
                  thrash_warn_ratio: Optional[float] = None,
                  hlo_dir: Optional[str] = None) -> None:
        """Session-init wiring.  Setting a ledger path loads the prior
        sessions' program index, so cross-session rebuilds classify as
        refaults instead of novel work.  `hlo_dir` turns on lowered-
        StableHLO persistence (tpuxsan's raw material); the session
        defaults it to an hlo/ subdir next to the ledger."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if buckets is not None:
                self.buckets = frozenset(int(b) for b in buckets)
            if thrash_warn_ratio is not None:
                self.thrash_warn_ratio = float(thrash_warn_ratio)
            if hlo_dir is not None:
                self.hlo_dir = hlo_dir or None
            if ledger_path is not None and \
                    ledger_path != self.ledger_path:
                self.ledger_path = ledger_path
                self._load_ledger(ledger_path)

    def save_hlo(self, text: str) -> Tuple[str, bool]:
        """Persist one program's StableHLO text under its content key.
        Returns (key, persisted).  Dedupe is by filename: a program
        already on disk (this session or a prior one) is not rewritten.
        Oversized programs (> HLO_MAX_BYTES) record their key and size
        in the ledger but are not persisted."""
        key = hlo_key(text)
        d = self.hlo_dir
        if d is None or len(text) > HLO_MAX_BYTES:
            return key, False
        path = os.path.join(d, key + HLO_SUFFIX)
        if os.path.exists(path):
            return key, True
        try:
            os.makedirs(d, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(text)
            os.replace(tmp, path)
        except OSError as ex:  # persistence is telemetry, never fatal
            log.warning("HLO persist failed: %s", ex)
            return key, False
        return key, True

    def _load_ledger(self, path: str) -> None:
        if not os.path.exists(path):
            return
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if rec.get("event") != "build":
                        continue
                    pid = (rec.get("key", ""), rec.get("shape", ""))
                    if pid in self._resident:
                        continue
                    self._programs.setdefault(pid, rec)
                    self._evicted.add(pid)
                    self._families.add((rec.get("exec", ""),
                                        rec.get("canon_key", ""),
                                        rec.get("dtype_hash", "")))
                    self._cap_index.setdefault(
                        (rec.get("exec", ""), rec.get("cap_hash", "")),
                        set()).add(rec.get("dtype_hash", ""))
        except OSError as ex:
            log.warning("compile ledger unreadable: %s", ex)

    # -- the process_jit seam ------------------------------------------------
    def build(self, key: tuple, make_fn):
        """Called on a process_jit table miss: returns the callable the
        table stores.  Enabled -> an AOT proxy that times and records
        every per-shape program build; disabled -> plain jax.jit plus
        the legacy untimed jit.build event."""
        import jax
        fn = make_fn()
        jitted = jax.jit(fn)
        if not self.enabled:
            from .tracer import trace_event
            trace_event("jit.build", sig=str(_exec_kind(key))[:80])
            return jitted
        return _ProfiledJit(self, key, jitted, fn)

    def note_hit(self, key: tuple) -> None:
        if not self.enabled:
            return
        with self._lock:
            self.hits += 1
        _fam_hits().labels(exec=_exec_kind(key)).inc()
        self._update_shared_ratio()

    def note_eviction(self, key: tuple, fn) -> None:
        """One LRU eviction from the process jit table: counted,
        ledgered, and the entry's programs marked non-resident so a
        rebuild classifies as eviction_refault."""
        if not self.enabled:
            return
        exec_kind = _exec_kind(key)
        pids: List[Tuple[str, str]] = []
        if isinstance(fn, _ProfiledJit):
            pids = list(fn.built_pids())
        with self._lock:
            self.evictions += 1
            for pid in pids:
                self._resident.discard(pid)
                self._evicted.add(pid)
                self._evicted_live.add(pid)
        _fam_evictions().labels(exec=exec_kind).inc()
        self._append_ledger({
            "event": "evict", "exec": exec_kind,
            "key": _stable_hash(key),
            "programs": [p[1] for p in pids]})

    def note_clear(self) -> None:
        """clear_jit_cache(): a deliberate reset, not LRU pressure —
        resident programs become non-resident (rebuilds are honest
        refaults) but no eviction is counted and no thrash warning can
        arise from it."""
        with self._lock:
            self._evicted |= self._resident
            self._resident = set()

    def note_cache_size(self, n: int) -> None:
        if not self.enabled:
            return
        _fam_cache_size().set(n)

    # -- warm-start tier -----------------------------------------------------
    def save_recipe_for(self, key: tuple, key_hash: str, fn,
                        args: tuple) -> None:
        """Persist a program recipe after a successful AOT build so the
        next session (or `tools prewarm`) can replay it.  Best-effort:
        no ledger dir, no raw fn, or a failed pickle all no-op."""
        if not self.enabled or self.ledger_path is None or fn is None:
            return
        from . import prewarm as pw
        pw.save_recipe(self.ledger_path, key_hash, key, fn, args)

    def prewarm_entry(self, key: tuple, fn, abstract_list) -> int:
        """Replay one recipe: compile its recorded abstract signatures
        (flowing through JAX's persistent disk cache) and stage a
        dispatch-ready proxy for the matching process_jit miss.
        Returns the number of programs readied."""
        import jax
        if not self.enabled:
            return 0
        jitted = jax.jit(fn)
        proxy = _ProfiledJit(self, key, jitted, fn)
        n = 0
        for abstract in abstract_list:
            try:
                sig = _dispatch_key(abstract)
                if sig is None:
                    continue
                t0 = time.perf_counter()
                compiled = jitted.lower(*abstract).compile()
                dt = time.perf_counter() - t0
            except Exception as ex:
                log.debug("prewarm replay failed for %s: %s",
                          proxy._key_hash, ex)
                continue
            proxy._prewarmed[_erase_sharding(sig)] = compiled
            n += 1
            with self._lock:
                self.prewarm_seconds += dt
            _fam_prewarm_seconds().inc(dt)
            self._append_ledger({
                "event": "prewarm", "exec": proxy._exec,
                "key": proxy._key_hash,
                "canon_key": proxy._canon_key,
                "total_s": round(dt, 6)})
        if n:
            with self._lock:
                self._prewarm_staged[key] = proxy
        return n

    def take_prewarmed(self, key: tuple):
        """Claim the staged proxy for a process_jit key, if a recipe
        replay readied one (called on the table's miss path)."""
        with self._lock:
            return self._prewarm_staged.pop(key, None)

    def note_prewarm_hit(self, exec_kind: str,
                         pid: Optional[Tuple[str, str]] = None) -> None:
        """One query call served by a prewarmed executable — the build
        the warm-start tier just avoided."""
        if not self.enabled:
            return
        with self._lock:
            self.prewarm_hits += 1
            if pid is not None:
                self._resident.add(pid)
                self._evicted.discard(pid)
                self._evicted_live.discard(pid)
        _fam_prewarm_hits().labels(exec=exec_kind).inc()
        self._update_shared_ratio()

    def note_prewarm_session(self, stats: Dict) -> None:
        with self._lock:
            self.prewarm_stats = dict(stats)

    def _update_shared_ratio(self) -> None:
        """tpu_jit_shared_program_ratio = distinct resident programs
        over total jit dispatches; 1.0 means every call built its own
        program, ->0 means the bucket-canonical key space is doing its
        job."""
        with self._lock:
            calls = self.hits + self.builds + self.prewarm_hits
            n = len(self._resident)
        _fam_shared_ratio().set(n / max(1, calls))

    # -- recording -----------------------------------------------------------
    def classify(self, exec_kind: str, pid: Tuple[str, str],
                 canon_key: str, dtype_hash: str,
                 cap_hash: str) -> str:
        """Cause of one build against the seen-program index; caller
        holds the lock."""
        if pid in self._evicted:
            return CAUSE_REFAULT
        if (exec_kind, canon_key, dtype_hash) in self._families:
            return CAUSE_SHAPE
        seen_dtypes = self._cap_index.get((exec_kind, cap_hash))
        if seen_dtypes and dtype_hash not in seen_dtypes:
            return CAUSE_DTYPE
        return CAUSE_NEW

    def record_build(self, exec_kind: str, key_hash: str,
                     canon_key: str, sig: tuple,
                     trace_s: Optional[float],
                     compile_s: Optional[float], total_s: float,
                     hlo_bytes: int, key_head: str,
                     hlo_hash: Optional[str] = None,
                     cost: Optional[Dict[str, float]] = None) -> str:
        """Register one program build; returns the classified cause."""
        shape_hash, dtype_sig, cap_sig, canon_caps = \
            _shape_record(sig, self.buckets)
        dtype_hash = _stable_hash(dtype_sig)
        cap_hash = _stable_hash(cap_sig)
        pid = (key_hash, shape_hash)
        with self._lock:
            cause = self.classify(exec_kind, pid, canon_key,
                                  dtype_hash, cap_hash)
            was_live = pid in self._evicted_live
            self.builds += 1
            self.by_cause[cause] = self.by_cause.get(cause, 0) + 1
            self.compile_seconds_total += compile_s or 0.0
            self.trace_seconds_total += trace_s or 0.0
            self._programs[pid] = {
                "exec": exec_kind, "key": key_hash,
                "canon_key": canon_key, "shape": shape_hash,
                "cause": cause, "total_s": total_s}
            self._resident.add(pid)
            self._evicted.discard(pid)
            self._evicted_live.discard(pid)
            self._families.add((exec_kind, canon_key, dtype_hash))
            self._cap_index.setdefault(
                (exec_kind, cap_hash), set()).add(dtype_hash)
            warn = None
            if cause == CAUSE_REFAULT and was_live:
                self.refaults += 1
                rate = self.refaults / max(1, self.evictions)
                if rate > self.thrash_warn_ratio and \
                        self.refaults >= self._warn_next:
                    self._warn_next = max(2, self.refaults * 2)
                    warn = (self.refaults, self.evictions, rate)
        if warn is not None:
            log.warning(
                "JIT cache thrash: %d of %d evicted programs were "
                "rebuilt (refault rate %.0f%% > %.0f%% threshold) — "
                "raise SPARK_RAPIDS_TPU_JIT_CACHE_MAX or reduce "
                "distinct query shapes per process",
                warn[0], warn[1], 100 * warn[2],
                100 * self.thrash_warn_ratio)
        _fam_misses().labels(exec=exec_kind, cause=cause).inc()
        self._update_shared_ratio()
        if total_s:
            _fam_compile_seconds().labels(
                exec=exec_kind, cause=cause).inc(total_s)
        self._append_ledger({
            "event": "build", "exec": exec_kind, "key": key_hash,
            "canon_key": canon_key, "shape": shape_hash,
            "dtype_hash": dtype_hash, "cap_hash": cap_hash,
            "cause": cause,
            "trace_s": None if trace_s is None else round(trace_s, 6),
            "compile_s": None if compile_s is None
            else round(compile_s, 6),
            "total_s": round(total_s, 6), "hlo_bytes": hlo_bytes,
            # tpuxsan: content key of the persisted StableHLO (None =
            # not captured) and the backend's own cost_analysis keys —
            # ONLY those the backend reported (absent != zero)
            "hlo_hash": hlo_hash, "cost": cost,
            "dtypes": list(dtype_sig),
            "caps": [list(s) for s in cap_sig],
            "canon_caps": [list(s) for s in canon_caps],
            "key_head": key_head})
        from .tracer import trace_event
        trace_event("jit.build", op=exec_kind, cause=cause,
                    key=key_hash, shape=shape_hash,
                    total_s=round(total_s, 6),
                    trace_s=None if trace_s is None
                    else round(trace_s, 6),
                    compile_s=None if compile_s is None
                    else round(compile_s, 6),
                    hlo_bytes=hlo_bytes, sig=key_head)
        return cause

    def _append_ledger(self, rec: Dict) -> None:
        path = self.ledger_path
        if path is None:
            return
        rec = dict(rec, v=LEDGER_VERSION, ts=round(time.time(), 3),
                   os_pid=os.getpid())
        try:
            with self._lock:
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(rec, sort_keys=True) + "\n")
        except OSError as ex:  # the ledger is telemetry, never fatal
            log.warning("compile ledger append failed: %s", ex)

    # -- read side -----------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "builds": self.builds,
                "hits": self.hits,
                "evictions": self.evictions,
                "refaults": self.refaults,
                "compile_seconds_total":
                    round(self.compile_seconds_total, 6),
                "trace_seconds_total":
                    round(self.trace_seconds_total, 6),
                "by_cause": dict(self.by_cause),
                "distinct_programs": len(self._programs),
                "resident_programs": len(self._resident),
                "prewarm_hits": self.prewarm_hits,
                "prewarm_seconds": round(self.prewarm_seconds, 6),
                "prewarm": dict(self.prewarm_stats)
                if self.prewarm_stats else None,
            }


# ---------------------------------------------------------------------------
# the AOT proxy
# ---------------------------------------------------------------------------

class _ProfiledJit:
    """Callable stored in the process jit table: dispatches per
    input-shape signature to an AOT-compiled executable, timing the
    lower/compile split on each first-per-shape call."""

    __slots__ = ("_obs", "_key", "_key_hash", "_canon_key", "_exec",
                 "_key_head", "_jitted", "_fn", "_compiled",
                 "_prewarmed", "_traced_sigs", "_lock")

    def __init__(self, obs: CompileObservatory, key: tuple, jitted,
                 fn=None):
        self._obs = obs
        self._key = key
        self._exec = _exec_kind(key)
        self._key_hash = _stable_hash(key)
        self._canon_key = _stable_hash(_mask_buckets(key, obs.buckets))
        self._key_head = str(key[1] if len(key) > 1 else key)[:80]
        self._jitted = jitted
        self._fn = fn  # the raw traced callable (prewarm recipes)
        self._compiled: Dict[tuple, Any] = {}
        # warm-start tier: executables replayed from a prior session's
        # recipes, keyed by sharding-erased signature
        self._prewarmed: Dict[tuple, Any] = {}
        self._traced_sigs: set = set()  # aval sigs seen under a trace
        self._lock = threading.Lock()

    def built_pids(self) -> List[Tuple[str, str]]:
        return [(self._key_hash,
                 _shape_record(sk, self._obs.buckets)[0])
                for sk in list(self._compiled)]

    def __call__(self, *args):
        sig = _dispatch_key(args)
        if sig is None:
            # unsignable leaves (e.g. called under an enclosing trace):
            # plain jit dispatch, recorded under the same canonical key
            return self._traced_call(args)
        fn = self._compiled.get(sig)
        if fn is not None:
            return fn(*args)
        if self._prewarmed:
            fn = self._prewarmed.get(_erase_sharding(sig))
            if fn is not None:
                try:
                    out = fn(*args)
                except Exception:
                    # sharding/layout mismatch with the skeleton-compiled
                    # executable: cold-build honestly instead
                    return self._build_and_call(sig, args)
                with self._lock:
                    self._compiled.setdefault(sig, fn)
                self._obs.note_prewarm_hit(
                    self._exec,
                    (self._key_hash,
                     _shape_record(sig, self._obs.buckets)[0]))
                return out
        return self._build_and_call(sig, args)

    def _traced_call(self, args):
        """Plain-jit dispatch for tracer-leaf calls — but the first call
        per aval signature is still timed (the inline trace is real
        compile work) and record_build'ed under this entry's canonical
        key, so fallback builds dedupe and reach the ledger instead of
        vanishing."""
        sig = _aval_dispatch_key(args)
        if sig is None:
            return self._jitted(*args)
        with self._lock:
            known = sig in self._traced_sigs or sig in self._compiled
            if not known:
                self._traced_sigs.add(sig)
        if known:
            return self._jitted(*args)
        t0 = time.perf_counter()
        out = self._jitted(*args)
        dt = time.perf_counter() - t0
        self._obs.record_build(self._exec, self._key_hash,
                               self._canon_key, sig, dt, None, dt, 0,
                               self._key_head)
        return out

    def _build_and_call(self, sig, args):
        with self._lock:
            fn = self._compiled.get(sig)
            if fn is None:
                fn = self._build(sig, args)
                self._compiled[sig] = fn
        return fn(*args)

    def _build(self, sig, args):
        t0 = time.perf_counter()
        trace_s = compile_s = None
        hlo_bytes = 0
        hlo_hash = cost = None
        try:
            lowered = self._jitted.lower(*args)
            t1 = time.perf_counter()
            trace_s = t1 - t0
            try:
                text = lowered.as_text()
                hlo_bytes = len(text)
                hlo_hash, _ = self._obs.save_hlo(text)
            except Exception:
                hlo_bytes = 0
            fn = lowered.compile()
            compile_s = time.perf_counter() - t1
            cost = cost_summary(fn)
            self._obs.save_recipe_for(self._key, self._key_hash,
                                      self._fn, args)
        except Exception:
            # the AOT path is an observation vehicle: any lower/compile
            # surprise falls back to plain jit dispatch (which recompiles
            # internally and raises its own honest error if the program
            # itself is broken)
            fn = self._jitted
        total_s = time.perf_counter() - t0
        self._obs.record_build(self._exec, self._key_hash,
                               self._canon_key, sig, trace_s,
                               compile_s, total_s, hlo_bytes,
                               self._key_head, hlo_hash=hlo_hash,
                               cost=cost)
        return fn


# ---------------------------------------------------------------------------
# metric families (created idempotently; cached to keep the seam cheap)
# ---------------------------------------------------------------------------

def _registry():
    from . import metrics
    return metrics.registry()


def _fam_hits():
    return _registry().counter(
        "tpu_jit_hits_total", "process jit-table hits", ("exec",),
        max_series=_JIT_MAX_SERIES)


def _fam_misses():
    return _registry().counter(
        "tpu_jit_misses_total",
        "program builds (jit-table or per-shape misses), by cause",
        ("exec", "cause"), max_series=_JIT_MAX_SERIES)


def _fam_evictions():
    return _registry().counter(
        "tpu_jit_evictions_total", "process jit-table LRU evictions",
        ("exec",), max_series=_JIT_MAX_SERIES)


def _fam_compile_seconds():
    return _registry().counter(
        "tpu_jit_compile_seconds_total",
        "wall seconds spent building programs (trace+lower+compile)",
        ("exec", "cause"), max_series=_JIT_MAX_SERIES)


def _fam_cache_size():
    return _registry().gauge(
        "tpu_jit_cache_size", "live entries in the process jit table")


def _fam_prewarm_hits():
    return _registry().counter(
        "tpu_jit_prewarm_hits_total",
        "query calls served by a warm-start-tier (prewarmed) program",
        ("exec",), max_series=_JIT_MAX_SERIES)


def _fam_prewarm_seconds():
    return _registry().counter(
        "tpu_jit_prewarm_seconds_total",
        "wall seconds spent replaying program recipes at session init")


def _fam_shared_ratio():
    return _registry().gauge(
        "tpu_jit_shared_program_ratio",
        "distinct resident programs / jit dispatches "
        "(1.0 = no sharing, ->0 = canonical keys collapsing the space)")


# ---------------------------------------------------------------------------
# persistent disk-cache metrics (satellite of ROADMAP item 1)
# ---------------------------------------------------------------------------

_DISK_EVENTS = {
    "/jax/compilation_cache/cache_hits":
        ("tpu_jit_persistent_cache_hits_total",
         "persistent XLA compile-cache disk hits"),
    "/jax/compilation_cache/cache_misses":
        ("tpu_jit_persistent_cache_misses_total",
         "persistent XLA compile-cache disk misses"),
}

_disk_listener_installed = False


def install_persistent_cache_metrics() -> None:
    """Count JAX's own persistent-compilation-cache disk hits/misses
    into the registry (idempotent; wired at plugin init next to
    jax_compilation_cache_dir).  This is the measurement that tells
    ROADMAP item 1 whether the disk cache works."""
    global _disk_listener_installed
    if _disk_listener_installed:
        return
    try:
        import jax.monitoring as mon
    except Exception:
        return

    def _on_event(event, **kw):
        fam = _DISK_EVENTS.get(event)
        if fam is not None:
            _registry().counter(fam[0], fam[1]).inc()

    mon.register_event_listener(_on_event)
    _disk_listener_installed = True


def observatory() -> CompileObservatory:
    return CompileObservatory.get()
