"""Flight-recorder span tracer: the producer half of the diagnostic
story the reference plugin gets from GpuExec metrics + NVTX ranges +
Spark's event log.

One ``QueryTrace`` records a per-query span tree — session phases
(subqueries/planning/overrides/execute), per-operator per-partition
execute spans, out-of-core chunk spans, and instrumented events from the
memory/shuffle/parallel/bridge layers — under the same
deferred-device-scalar discipline as ``exec.base.Metric``: the hot path
never syncs or fetches; device row counts are stashed and resolved at
``finalize()`` through ONE ``columnar/fetch.fetch_ints`` crossing.
Timestamps come from the monotonic ``time.perf_counter_ns`` clock with a
wall-clock anchor captured once at trace start.

The buffer is bounded (``spark.rapids.tpu.trace.maxSpans``): past the
cap new spans are dropped and counted, never reallocated — a runaway
query degrades the trace, not the engine (Dapper-style always-on,
low-overhead discipline).

Instrumented modules reach the recorder through the installed-tracer
pattern the tmsan shadow ledger uses (``memory/memsan.py``): with no
query tracing, ``active_tracer()`` is None and every hook is a cheap
no-op.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

# span kinds
QUERY = "query"
PHASE = "phase"
OPERATOR = "operator"
SPAN = "span"
EVENT = "event"

_HOST_NUMS = (int, float, bool, np.integer, np.floating, np.bool_)


class Span:
    """One recorded interval (or instant event, t1 == t0)."""

    __slots__ = ("span_id", "parent_id", "name", "kind", "t0_ns", "t1_ns",
                 "tid", "status", "error", "attrs", "node_id", "pid",
                 "rows", "bytes", "batches", "cap_rows", "proc")

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 kind: str, t0_ns: int, tid: int,
                 node_id: Optional[int] = None,
                 pid: Optional[int] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.t0_ns = t0_ns
        self.t1_ns: Optional[int] = None
        self.tid = tid
        self.status = "open"
        self.error: Optional[str] = None
        self.attrs = attrs or {}
        self.node_id = node_id
        self.pid = pid
        self.rows = 0
        self.bytes = 0
        self.batches = 0
        # summed static batch capacities (tpuxsan padding-waste books:
        # device bytes are capacity-sized, so waste = bytes * (1 -
        # rows/cap_rows) once deferred row counts resolve)
        self.cap_rows = 0
        # producing process for merged remote spans (executor id or
        # "server:<port>"); None = this process.  NOT `pid` — that slot
        # is the PARTITION id.
        self.proc: Optional[str] = None

    @property
    def dur_ns(self) -> int:
        return 0 if self.t1_ns is None else self.t1_ns - self.t0_ns

    def pad_waste_bytes(self) -> int:
        """Device bytes this span's output batches spent on capacity
        padding: bytes are capacity-sized, rows are live.  Only valid
        after deferred row counts resolve (finalize)."""
        if self.cap_rows <= 0 or self.bytes <= 0:
            return 0
        live = min(max(int(self.rows), 0), self.cap_rows)
        return int(self.bytes * (self.cap_rows - live) / self.cap_rows)


class _SpanHandle:
    """What ``QueryTrace.span()`` yields: lets the block attach attrs
    after the fact without reaching into tracer internals."""

    __slots__ = ("_trace", "_sid")

    def __init__(self, trace: "QueryTrace", sid: Optional[int]):
        self._trace = trace
        self._sid = sid

    def __bool__(self) -> bool:
        return self._sid is not None

    def set(self, **attrs) -> None:
        if self._sid is not None:
            self._trace.add_attrs(self._sid, **attrs)


class QueryTrace:
    """Thread-safe bounded span recorder for ONE query execution."""

    def __init__(self, max_spans: int = 65536):
        self.max_spans = max_spans
        self.t0_ns = time.perf_counter_ns()
        self.wall_start_ms = int(time.time() * 1000)
        self.spans: List[Span] = []
        self._by_id: Dict[int, Span] = {}
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._ids = itertools.count(1)
        # deferred device scalars: (span, scalar) resolved at finalize
        # through ONE fetch_ints crossing (the Metric discipline)
        self._pending: List[tuple] = []
        self.dropped = 0
        self.sealed = False
        self.error: Optional[str] = None
        # predicted-vs-actual: id(exec node) -> dicts; predictions are
        # installed by the session from the CBO/interp/tmsan models,
        # actuals aggregate from operator spans at finalize
        self.predictions: Dict[int, Dict[str, Any]] = {}
        self.actuals: Dict[int, Dict[str, Any]] = {}
        self.measured_peak_device_bytes: Optional[int] = None
        self.static_peak_bound: Optional[float] = None
        # fleet identity: travels inside the shuffle wire's v2 trace
        # context so producer-side serve spans can be pulled back and
        # grafted under this trace's fetch spans
        from .fleet import new_trace_id
        self.trace_id = new_trace_id()
        self.remote_spans_merged = 0
        self.remote_spans_lost = 0
        self.root_id = self.start("query", QUERY)

    # -- parent stack (per thread) ------------------------------------------
    def _stack(self) -> List[int]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def _push(self, sid: int) -> None:
        self._stack().append(sid)

    def _pop(self) -> None:
        st = self._stack()
        if st:
            st.pop()

    def _default_parent(self) -> Optional[int]:
        # spans with no enclosing span (any thread) hang off the query
        # root, so the tree always has one top
        st = self._stack()
        if st:
            return st[-1]
        return getattr(self, "root_id", None)

    # -- core ---------------------------------------------------------------
    def start(self, name: str, kind: str, node_id: Optional[int] = None,
              pid: Optional[int] = None, parent: Optional[int] = None,
              **attrs) -> Optional[int]:
        with self._lock:
            if self.sealed:
                return None
            if len(self.spans) >= self.max_spans:
                self.dropped += 1
                return None
            sid = next(self._ids)
            if parent is None:
                parent = self._default_parent()
            sp = Span(sid, parent if parent != sid else None, name, kind,
                      time.perf_counter_ns(), threading.get_ident(),
                      node_id=node_id, pid=pid, attrs=dict(attrs))
            self.spans.append(sp)
            self._by_id[sid] = sp
        # progress observatory phase feed — outside the span lock (the
        # hook takes the tracker's own lock; never nest the two).
        # Phase spans and the admission wait are the only names that
        # move a query's live-view phase, so filter here on the hot path
        if kind == PHASE or name == "admission.wait":
            from . import progress as _progress
            _progress.note_span_open(name, kind)
        return sid

    def end(self, sid: Optional[int], status: str = "ok",
            error: Optional[str] = None) -> None:
        if sid is None:
            return
        with self._lock:
            sp = self._by_id.get(sid)
            if sp is None or sp.t1_ns is not None:
                return
            sp.t1_ns = time.perf_counter_ns()
            sp.status = status
            sp.error = error

    def event(self, name: str, **attrs) -> None:
        sid = self.start(name, EVENT, **attrs)
        if sid is not None:
            sp = self._by_id[sid]
            sp.t1_ns = sp.t0_ns
            sp.status = "ok"

    def add_attrs(self, sid: Optional[int], **attrs) -> None:
        if sid is None:
            return
        with self._lock:
            sp = self._by_id.get(sid)
            if sp is not None:
                sp.attrs.update(attrs)

    @contextlib.contextmanager
    def span(self, name: str, kind: str = SPAN, **attrs):
        sid = self.start(name, kind, **attrs)
        if sid is not None:
            self._push(sid)
        err: Optional[BaseException] = None
        try:
            yield _SpanHandle(self, sid)
        except BaseException as ex:
            err = ex
            raise
        finally:
            if sid is not None:
                self._pop()
                self.end(sid, "error" if err is not None else "ok",
                         repr(err) if err is not None else None)

    # -- operator spans ------------------------------------------------------
    def trace_operator(self, node, pid: int, inner):
        """Wrap one execute_partition iterator in an operator span: the
        span opens at first pull, accumulates output rows (deferred when
        the count is a traced device scalar — never a sync here), device
        bytes (array metadata only) and batches, and closes on
        exhaustion, abandonment (early-exit limits) or error — the
        exception is recorded on the span (post-mortem debugging)."""
        it = iter(inner)

        def gen():
            sid = self.start(f"{type(node).__name__}.execute", OPERATOR,
                             node_id=id(node), pid=pid,
                             op=type(node).__name__)
            try:
                while True:
                    if sid is not None:
                        self._push(sid)
                    try:
                        b = next(it)
                    except StopIteration:
                        break
                    finally:
                        if sid is not None:
                            self._pop()
                    if sid is not None:
                        self._note_batch(sid, b)
                    yield b
            except GeneratorExit:
                self.end(sid, "abandoned")
                raise
            except BaseException as ex:
                self.end(sid, "error", repr(ex))
                raise
            self.end(sid)

        return gen()

    def _note_batch(self, sid: int, batch) -> None:
        with self._lock:
            sp = self._by_id.get(sid)
            if sp is None:
                return
            sp.batches += 1
            n = getattr(batch, "num_rows", None)
            if isinstance(n, _HOST_NUMS):
                sp.rows += int(n)
            elif n is not None:
                self._pending.append((sp, n))
            try:
                from ..memory.spill import batch_device_bytes
                sp.bytes += batch_device_bytes(batch)
            except Exception:
                pass
            try:
                cap = getattr(batch, "capacity", None)
                if cap:
                    sp.cap_rows += int(cap)
            except Exception:
                pass

    # -- fleet merge ---------------------------------------------------------
    def add_remote_spans(self, parent_sid: Optional[int],
                         remote_spans: List[Dict[str, Any]],
                         offset_ns: int = 0, proc: str = "") -> int:
        """Graft producer-side span dicts (the /spans pull schema:
        spanId/parentId/remoteParent/name/t0Ns/t1Ns/status/proc/attrs,
        timestamps in the PRODUCER's perf_counter_ns domain) under the
        local fetch span ``parent_sid``.

        Remote clocks convert by ``t_local = t_peer - offset_ns`` (the
        hello handshake's NTP estimate), then clamp into the parent
        interval: the offset carries up to rtt/2 of error, and a child
        that leaks outside its parent would break every downstream
        renderer's nesting invariant — a clamped edge is the honest
        rendering of "within this fetch, at clock precision".

        Returns the number merged (counted into
        tpu_trace_remote_spans_merged_total)."""
        if not remote_spans:
            return 0
        merged = 0
        with self._lock:
            if self.sealed:
                return 0
            parent = self._by_id.get(parent_sid) if parent_sid else None
            if parent is None:
                return 0
            p0 = parent.t0_ns
            p1 = parent.t1_ns
            id_map: Dict[Any, int] = {}
            grafted: List[tuple] = []
            for rs in remote_spans:
                if len(self.spans) + len(grafted) >= self.max_spans:
                    self.dropped += 1
                    continue
                try:
                    rt0 = int(rs["t0Ns"]) - offset_ns
                    rt1 = int(rs["t1Ns"]) - offset_ns
                except (KeyError, TypeError, ValueError):
                    continue
                sid = next(self._ids)
                id_map[rs.get("spanId")] = sid
                grafted.append((sid, rs, rt0, rt1))
            for sid, rs, rt0, rt1 in grafted:
                if rt1 < rt0:
                    rt1 = rt0
                rt0 = max(rt0, p0)
                if p1 is not None:
                    rt0 = min(rt0, p1)
                    rt1 = min(rt1, p1)
                rt1 = max(rt1, rt0)
                if rs.get("remoteParent"):
                    rparent = parent_sid
                else:
                    rparent = id_map.get(rs.get("parentId"), parent_sid)
                sp = Span(sid, rparent, str(rs.get("name", "remote")),
                          SPAN, rt0, threading.get_ident(),
                          attrs=dict(rs.get("attrs") or {}))
                sp.t1_ns = rt1
                sp.status = str(rs.get("status", "ok"))
                if rs.get("error"):
                    sp.error = str(rs["error"])
                sp.proc = str(rs.get("proc") or proc or "remote")
                self.spans.append(sp)
                self._by_id[sid] = sp
                merged += 1
            self.remote_spans_merged += merged
        if merged:
            from .fleet import remote_merged_counter
            remote_merged_counter().inc(merged)
        return merged

    def note_remote_spans_lost(self, n: int = 1) -> None:
        """Producer spans that should have merged but never arrived
        (peer died mid-fetch / /spans pull failed); counted into
        tpu_trace_remote_spans_lost_total by the caller's orphan
        hygiene path."""
        with self._lock:
            self.remote_spans_lost += int(n)

    # -- failure / end of query ---------------------------------------------
    def interrupt(self, reason: str) -> None:
        """Close every still-open operator span with `reason` (the
        speculation-miss path: abandoned generators never see the
        exception, so their spans would otherwise dangle into the
        re-execution)."""
        self.event(reason)
        with self._lock:
            now = time.perf_counter_ns()
            for sp in self.spans:
                if sp.t1_ns is None and sp.kind == OPERATOR:
                    sp.t1_ns = now
                    sp.status = reason

    def finalize(self, error: Optional[BaseException] = None) -> None:
        """Seal the trace: close open spans (recording the exception on
        them for failed queries), resolve ALL deferred device scalars in
        one fetch crossing, and aggregate per-operator actuals."""
        with self._lock:
            if self.sealed:
                return
            self.sealed = True
            self.error = repr(error) if error is not None else None
            now = time.perf_counter_ns()
            for sp in self.spans:
                if sp.t1_ns is None:
                    sp.t1_ns = now
                    if error is not None:
                        sp.status = "error"
                        if sp.error is None:
                            sp.error = repr(error)
                    else:
                        sp.status = "ok"
            pending, self._pending = self._pending, []
        if pending:
            try:
                from ..columnar.fetch import fetch_ints
                vals = fetch_ints([v for _, v in pending])
                for (sp, _), v in zip(pending, vals):
                    sp.rows += int(v)
            except Exception:
                # failure paths may leave the device unusable; a trace
                # with unresolved row counts still beats no trace
                pass
        from . import metrics as m
        m.counter("tpu_trace_spans_total",
                  "flight-recorder spans sealed").inc(len(self.spans))
        if self.dropped:
            m.counter("tpu_trace_dropped_spans_total",
                      "spans dropped past trace.maxSpans") \
                .inc(self.dropped)
        pad_fam = m.counter("tpu_pad_waste_bytes_total",
                            "device bytes occupied by capacity-bucket "
                            "padding (live rows vs bucket capacity, "
                            "per launch; tpuxsan TPU-L018 books)",
                            ("exec",))
        bytes_fam = m.counter("tpu_operator_bytes_total",
                              "device bytes flowing through operator "
                              "spans (the pad-waste ratio denominator)",
                              ("exec",))
        for sp in self.spans:
            if sp.kind != OPERATOR or sp.node_id is None:
                continue
            agg = self.actuals.setdefault(
                sp.node_id, {"rows": 0, "bytes": 0, "batches": 0,
                             "timeNs": 0, "padWasteBytes": 0,
                             "node": sp.attrs.get("op", "")})
            agg["rows"] += sp.rows
            agg["bytes"] += sp.bytes
            agg["batches"] += sp.batches
            agg["timeNs"] += sp.dur_ns
            waste = sp.pad_waste_bytes()
            agg["padWasteBytes"] += waste
            try:
                if sp.bytes:
                    bytes_fam.labels(
                        exec=sp.attrs.get("op", "?")).inc(sp.bytes)
                if waste:
                    pad_fam.labels(
                        exec=sp.attrs.get("op", "?")).inc(waste)
            except Exception:
                pass

    # -- reports -------------------------------------------------------------
    def open_span_count(self) -> int:
        with self._lock:
            return sum(1 for s in self.spans if s.t1_ns is None)

    def span_dicts(self) -> List[Dict[str, Any]]:
        """Schema shared with the self-emitted event log's span lines
        and the export renderers (obs/export.py)."""
        out = []
        with self._lock:
            for s in self.spans:
                rel = s.t0_ns - self.t0_ns
                d = {"spanId": s.span_id, "parentId": s.parent_id,
                     "name": s.name, "kind": s.kind,
                     "startNs": rel, "durNs": s.dur_ns,
                     "wallMs": self.wall_start_ms + rel // 1_000_000,
                     "tid": s.tid, "status": s.status,
                     "attrs": dict(s.attrs)}
                if s.error:
                    d["error"] = s.error
                if s.pid is not None:
                    d["pid"] = s.pid
                if s.proc is not None:
                    d["proc"] = s.proc
                if s.kind == OPERATOR:
                    d["rows"] = int(s.rows)
                    d["bytes"] = int(s.bytes)
                    d["batches"] = int(s.batches)
                    d["capRows"] = int(s.cap_rows)
                    d["padWasteBytes"] = s.pad_waste_bytes()
                out.append(d)
        return out

    def operator_spans(self, node_id: Optional[int] = None) -> List[Span]:
        with self._lock:
            return [s for s in self.spans if s.kind == OPERATOR and
                    (node_id is None or s.node_id == node_id)]

    def accuracy_rows(self) -> List[Dict[str, Any]]:
        """Per-operator predicted-vs-actual rows/bytes, ranked worst
        first — the feedback signal for CBO tuning."""
        from .export import accuracy_row
        rows = []
        for nid, pred in self.predictions.items():
            act = self.actuals.get(nid)
            if act is None:
                continue
            rows.append(accuracy_row(act.get("node") or pred.get("node"),
                                     pred, act))
        rows.sort(key=lambda r: -r["rowsErr"])
        return rows

    def to_chrome(self) -> Dict[str, Any]:
        from .export import spans_to_chrome
        return spans_to_chrome(self.span_dicts())

    def to_text(self) -> str:
        from .export import spans_to_text
        return spans_to_text(self.span_dicts())


# ---------------------------------------------------------------------------
# installation (what the instrumented layers consult)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[QueryTrace] = None
_TLS = threading.local()


def install(trace: QueryTrace) -> QueryTrace:
    global _ACTIVE
    _ACTIVE = trace
    return trace


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def install_local(trace: QueryTrace) -> QueryTrace:
    """Thread-local install for concurrent serving (api/pool.py): each
    pool query's trace binds to ITS thread so co-running queries never
    interleave spans.  Single-session flows keep the process-global
    slot, where helper threads (scan prefetch, shuffle fetch) also
    report."""
    _TLS.active = trace
    return trace


def uninstall_local() -> None:
    _TLS.active = None


def active_tracer() -> Optional[QueryTrace]:
    tr = getattr(_TLS, "active", None)
    return tr if tr is not None else _ACTIVE


def trace_event(name: str, **attrs) -> None:
    """Record an instant event on the active trace (no-op otherwise)."""
    tr = active_tracer()
    if tr is not None:
        tr.event(name, **attrs)


@contextlib.contextmanager
def trace_span(name: str, kind: str = SPAN, **attrs):
    """Span context manager against the active trace; yields a handle
    with ``.set(**attrs)`` (or an inert one when tracing is off)."""
    tr = active_tracer()
    if tr is None:
        yield _SpanHandle_NULL
        return
    with tr.span(name, kind=kind, **attrs) as h:
        yield h


class _NullHandle:
    __slots__ = ()

    def __bool__(self) -> bool:
        return False

    def set(self, **attrs) -> None:
        pass


_SpanHandle_NULL = _NullHandle()
