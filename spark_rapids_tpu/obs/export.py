"""Trace renderers: Chrome-trace/Perfetto JSON, a text timeline, and
the predicted-vs-actual accuracy math.

All renderers operate on the neutral span-dict schema
(``QueryTrace.span_dicts()``), which is also exactly what the
self-emitted event log's span lines carry — so the live trace and a
replayed log render identically (``tools trace --export chrome``)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def spans_to_chrome(span_dicts: List[Dict[str, Any]],
                    process_name: str = "spark_rapids_tpu") -> Dict:
    """Chrome trace-event JSON (chrome://tracing / Perfetto): complete
    "X" events for intervals, instant "i" events, ts/dur in
    microseconds relative to query start.

    Spans carrying a ``proc`` (merged remote spans — obs/fleet.py) get
    their own Chrome PROCESS lane per producer, so the one merged
    timeline shows the consumer and each peer side by side.  Chrome
    "pid" here is a lane id, NOT the span-dict "pid" field (that one is
    the partition id and stays in args).

    ``hbm.sample`` / ``hbm.admitted`` instants (the HBM observatory's
    occupancy stream, obs/memprof.py) render as Perfetto COUNTER tracks
    ("C" events) instead of instants: one ``HBM <tenant>`` track per
    tenant with a per-buffer-class series, plus an ``HBM admitted
    <tenant>`` track for ticket reservations.  Merged remote samples
    keep their producer's lane, so a fleet trace shows each peer's HBM
    curve under its own span lane."""
    events: List[Dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
         "args": {"name": process_name}},
    ]
    lanes: Dict[str, int] = {}
    for s in span_dicts:
        args = dict(s.get("attrs") or {})
        args["status"] = s.get("status", "")
        for k in ("rows", "bytes", "batches", "error", "pid"):
            if s.get(k) not in (None, "", 0):
                args[k] = s[k]
        proc = s.get("proc")
        lane = 0
        if proc:
            lane = lanes.get(proc)
            if lane is None:
                lane = len(lanes) + 1
                lanes[proc] = lane
                events.append({"name": "process_name", "ph": "M",
                               "pid": lane, "tid": 0,
                               "args": {"name": str(proc)}})
            args["proc"] = proc
        if s["name"] in ("hbm.sample", "hbm.admitted"):
            attrs = s.get("attrs") or {}
            tenant = attrs.get("tenant", "?")
            if s["name"] == "hbm.admitted":
                track = f"HBM admitted {tenant}"
                series = {"admitted": attrs.get("bytes", 0)}
            else:
                track = f"HBM {tenant}"
                series = {attrs.get("cls", "bytes"):
                          attrs.get("bytes", 0)}
            events.append({"name": track, "ph": "C", "pid": lane,
                           "tid": 0, "ts": s["startNs"] / 1000.0,
                           "args": series})
            continue
        base = {"name": s["name"], "cat": s.get("kind", "span"),
                "pid": lane, "tid": s.get("tid", 0),
                "ts": s["startNs"] / 1000.0, "args": args}
        if s.get("kind") == "event" or not s.get("durNs"):
            events.append({**base, "ph": "i", "s": "t"})
        else:
            events.append({**base, "ph": "X",
                           "dur": max(s["durNs"] / 1000.0, 0.001)})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def spans_to_text(span_dicts: List[Dict[str, Any]]) -> str:
    """Indented text timeline (span tree in start order)."""
    by_parent: Dict[Optional[int], List[Dict]] = {}
    for s in span_dicts:
        by_parent.setdefault(s.get("parentId"), []).append(s)
    for kids in by_parent.values():
        kids.sort(key=lambda s: s["startNs"])
    ids = {s["spanId"] for s in span_dicts}
    roots = [s for s in span_dicts
             if s.get("parentId") is None or s["parentId"] not in ids]
    lines: List[str] = []

    def emit(s: Dict, depth: int) -> None:
        dur_ms = s.get("durNs", 0) / 1e6
        extra = ""
        if s.get("kind") == "operator":
            extra = (f" rows={s.get('rows', 0)}"
                     f" batches={s.get('batches', 0)}")
        if s.get("status") not in ("ok", "", None):
            extra += f" [{s['status']}]"
        if s.get("error"):
            extra += f" !{s['error']}"
        mark = "·" if s.get("kind") == "event" else "—"
        lines.append(f"{'  ' * depth}{mark} {s['name']} "
                     f"{dur_ms:.3f}ms{extra}")
        for c in by_parent.get(s["spanId"], []):
            emit(c, depth + 1)

    for r in sorted(roots, key=lambda s: s["startNs"]):
        emit(r, 0)
    return "\n".join(lines) + "\n"


def fleet_summary(span_dicts: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-peer wire vs serve vs compute decomposition of one merged
    trace (the ``tools fleet`` report).

    For every ``shuffle.fetch`` span: the peer's SERVE time is the
    merged remote serve roots under it (spans carrying ``proc``), and
    WIRE time is the remainder of the fetch — what the network and the
    fetch pipeline cost beyond the producer's own work.  COMPUTE is the
    query total minus all fetch time (local execution)."""
    by_parent: Dict[Optional[int], List[Dict]] = {}
    for s in span_dicts:
        by_parent.setdefault(s.get("parentId"), []).append(s)
    peers: Dict[str, Dict[str, Any]] = {}
    fetch_total = 0
    for s in span_dicts:
        if s.get("name") != "shuffle.fetch":
            continue
        attrs = s.get("attrs") or {}
        peer = str(attrs.get("peer", "?"))
        e = peers.setdefault(peer, {
            "fetches": 0, "fetchNs": 0, "serveNs": 0,
            "remoteSpans": 0, "spansLost": 0})
        e["fetches"] += 1
        e["fetchNs"] += int(s.get("durNs") or 0)
        fetch_total += int(s.get("durNs") or 0)
        if attrs.get("spans_lost"):
            e["spansLost"] += 1
        for c in by_parent.get(s.get("spanId"), []):
            if c.get("proc"):
                e["serveNs"] += int(c.get("durNs") or 0)
                e["remoteSpans"] += 1 + len(
                    by_parent.get(c.get("spanId"), []))
    for e in peers.values():
        e["wireNs"] = max(e["fetchNs"] - e["serveNs"], 0)
    query = next((s for s in span_dicts if s.get("kind") == "query"),
                 None)
    total = int(query.get("durNs") or 0) if query else fetch_total
    return {"peers": peers, "queryNs": total,
            "computeNs": max(total - fetch_total, 0)}


def format_fleet_summary(summary: Dict[str, Any]) -> str:
    """Text rendering of ``fleet_summary`` for the CLI."""
    lines = ["### Fleet: per-peer wire vs serve time ###",
             f"{'peer':20s} {'fetches':>8s} {'fetch ms':>10s} "
             f"{'serve ms':>10s} {'wire ms':>10s} {'spans':>6s} "
             f"{'lost':>5s}"]
    for peer, e in sorted(summary.get("peers", {}).items()):
        lines.append(
            f"{peer[:20]:20s} {e['fetches']:>8d} "
            f"{e['fetchNs'] / 1e6:>10.3f} {e['serveNs'] / 1e6:>10.3f} "
            f"{e['wireNs'] / 1e6:>10.3f} {e['remoteSpans']:>6d} "
            f"{e['spansLost']:>5d}")
    if not summary.get("peers"):
        lines.append("(no remote fetch spans in this trace)")
    lines.append(f"query total {summary.get('queryNs', 0) / 1e6:.3f}ms, "
                 f"local compute "
                 f"{summary.get('computeNs', 0) / 1e6:.3f}ms")
    return "\n".join(lines) + "\n"


def _err(pred, actual) -> float:
    """Relative prediction error: |pred - actual| / max(actual, 1).
    None predictions read as 'no model' and rank last (error -1)."""
    if pred is None:
        return -1.0
    return abs(float(pred) - float(actual)) / max(float(actual), 1.0)


def accuracy_row(node: str, pred: Dict[str, Any],
                 act: Dict[str, Any]) -> Dict[str, Any]:
    """One predicted-vs-actual report row — shared by the live trace
    (QueryTrace.accuracy_rows) and the event-log replay
    (tools/profiling.accuracy_report), so both rank identically."""
    p_rows, a_rows = pred.get("rows"), act.get("rows", 0)
    p_bytes, a_bytes = pred.get("bytes"), act.get("bytes", 0)
    return {
        "node": node,
        "predictedRows": None if p_rows is None else int(p_rows),
        "actualRows": int(a_rows),
        "rowsErr": round(_err(p_rows, a_rows), 4),
        "predictedBytes": None if p_bytes is None else int(p_bytes),
        "actualBytes": int(a_bytes),
        "bytesErr": round(_err(p_bytes, a_bytes), 4),
        "peakHbmBound": pred.get("peakHbmBound"),
    }


def format_accuracy(rows: List[Dict[str, Any]],
                    measured_peak: Optional[int] = None,
                    static_bound: Optional[float] = None) -> str:
    lines = ["### Predicted vs Actual (worst first) ###",
             f"{'operator':28s} {'predRows':>12s} {'actRows':>12s} "
             f"{'rowsErr':>8s} {'predBytes':>14s} {'actBytes':>14s} "
             f"{'bytesErr':>8s}"]
    for r in rows:
        lines.append(
            f"{str(r['node'])[:28]:28s} "
            f"{str(r['predictedRows']):>12s} {r['actualRows']:>12d} "
            f"{r['rowsErr']:>8.2f} {str(r['predictedBytes']):>14s} "
            f"{r['actualBytes']:>14d} {r['bytesErr']:>8.2f}")
    if static_bound is not None or measured_peak is not None:
        lines.append(f"peak HBM: static bound="
                     f"{int(static_bound) if static_bound else None} "
                     f"measured={measured_peak}")
    return "\n".join(lines) + "\n"
