"""Warm-start tier of the two-tier program cache.

The in-memory tier is the process jit table (exec/base.py) fronted by
the observatory's AOT proxies.  This module adds the cross-session
tier: every successful AOT build persists a **program recipe** next to
the compile ledger — the full bucket-canonical jit key, the raw traced
callable (cloudpickled with data-carrying captures stubbed out), and
the abstract (shape/dtype) argument pytrees each built signature was
compiled for.  A later session replays the top-K costliest recipes at
init: `jax.jit(fn).lower(*abstract).compile()` flows through JAX's
persistent compilation cache (disk hit, no backend compile) and the
resulting executables are staged into the observatory so the first
real query call dispatches straight to a ready program — zero
query-time builds, `compile_warm_s ~= 0`.

Everything here is best-effort telemetry-adjacent machinery: a recipe
that fails to pickle, load or replay is skipped and counted, never
fatal.  Stubbing is safe because traced kernels take their batches as
call ARGUMENTS — closure-captured scan tables / device buffers / locks
are never touched while tracing `_compute`-style bodies; if one ever
is, the replay raises, the recipe is dropped, and the query path
simply cold-builds as before.
"""

from __future__ import annotations

import io
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

RECIPES_DIRNAME = "programs"
RECIPE_VERSION = 1
# backstop against closures that smuggle real data past the stubs
MAX_RECIPE_BYTES = 8 << 20
# abstract signatures retained per recipe (bucketed shapes converge
# fast; an unbounded list would accrete one entry per join-size bucket)
MAX_SIGS_PER_RECIPE = 8


def _stub_none():
    return None


def _stub_types() -> tuple:
    import _thread

    import jax
    import pyarrow as pa
    types: List[type] = [pa.Table, pa.RecordBatch, pa.ChunkedArray,
                         pa.Array, jax.Array,
                         _thread.LockType, type(threading.RLock())]
    return tuple(types)


def _dumps_stubbed(obj) -> bytes:
    """cloudpickle with data-carrying / unpicklable captures replaced by
    None: recipes describe PROGRAMS (keys + traced code + abstract
    shapes), they must never ship table payloads or device buffers."""
    import cloudpickle
    stubs = _stub_types()

    class _StubPickler(cloudpickle.CloudPickler):
        def reducer_override(self, o):
            if isinstance(o, stubs):
                return (_stub_none, ())
            return super().reducer_override(o)

    buf = io.BytesIO()
    _StubPickler(buf).dump(obj)
    return buf.getvalue()


def _to_abstract(x):
    """One call-argument leaf -> its shape/dtype skeleton.  Python
    scalars pass through (weak-typed dynamic args: the type picks the
    program, the value is irrelevant to lowering)."""
    import jax
    dt = getattr(x, "dtype", None)
    shape = getattr(x, "shape", None)
    if dt is not None and shape is not None:
        return jax.ShapeDtypeStruct(tuple(int(s) for s in shape),
                                    np.dtype(dt))
    return x


def recipes_dir(ledger_path: str) -> str:
    return os.path.join(os.path.dirname(ledger_path), RECIPES_DIRNAME)


def _abstract_repr(abstract) -> str:
    """Stable text form of one abstract arg pytree (treedef + leaf
    shape/dtype) — the recipe's dedupe key for persisted signatures."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(abstract)
    return repr((str(treedef),
                 [(getattr(x, "shape", None),
                   str(getattr(x, "dtype", type(x).__name__)))
                  for x in leaves]))


# (recipe path, ) -> list of abstract arg pytrees already persisted
# (rewrite-from-memory keeps the save path free of load-modify-write
# cycles); keyed by the on-disk path, not the bare key_hash, so one
# process writing to several ledger dirs never cross-suppresses saves
_saved_sigs: Dict[str, List[Any]] = {}
_save_lock = threading.Lock()


def save_recipe(ledger_path: str, key_hash: str, key: tuple, fn,
                args: tuple) -> bool:
    """Persist/extend the recipe for one built program; returns True
    when written.  Called from the observatory after a successful AOT
    build — must never raise."""
    import jax
    try:
        abstract = jax.tree_util.tree_map(_to_abstract, args)
        sig_repr = _abstract_repr(abstract)
        d = recipes_dir(ledger_path)
        path = os.path.join(d, f"{key_hash}.pkl")
        cache_key = os.path.abspath(path)
        with _save_lock:
            sigs = _saved_sigs.get(cache_key)
            if sigs is None:
                # first save to this path in THIS process: merge the
                # signatures an earlier session already persisted so a
                # rewrite never sheds them
                sigs = _saved_sigs[cache_key] = []
                if os.path.exists(path):
                    try:
                        import cloudpickle
                        with open(path, "rb") as f:
                            prior = cloudpickle.load(f)
                        for a in (prior.get("abstract") or ()):
                            sigs.append((_abstract_repr(a), a))
                    except Exception:
                        pass
            if any(r == sig_repr for r, _ in sigs):
                return False
            if len(sigs) >= MAX_SIGS_PER_RECIPE:
                return False
            sigs.append((sig_repr, abstract))
            payload = _dumps_stubbed({
                "v": RECIPE_VERSION, "key": key,
                "fn": fn, "abstract": [a for _, a in sigs]})
        if len(payload) > MAX_RECIPE_BYTES:
            log.debug("recipe %s over size backstop (%d bytes), "
                      "not persisted", key_hash, len(payload))
            return False
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
        return True
    except Exception as ex:
        log.debug("recipe save failed for %s: %s", key_hash, ex)
        return False


def rank_ledger_programs(ledger_path: str) -> List[Tuple[str, float]]:
    """(key_hash, total compile seconds) from the ledger's build
    events, costliest first — the prewarm priority order."""
    import json
    costs: Dict[str, float] = {}
    try:
        with open(ledger_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("event") != "build":
                    continue
                k = rec.get("key", "")
                costs[k] = costs.get(k, 0.0) + (rec.get("total_s")
                                                or 0.0)
    except OSError:
        return []
    return sorted(costs.items(), key=lambda kv: -kv[1])


def prewarm_from_ledger(ledger_path: str, top_k: int = 32,
                        observatory=None) -> Dict[str, Any]:
    """Replay the top-K costliest recipes: compile each recorded
    abstract signature (hitting JAX's persistent disk cache when one is
    configured) and stage dispatch-ready proxies in the observatory so
    query-time calls build nothing.  Returns honest stats."""
    from .compileprof import CompileObservatory
    obs = observatory or CompileObservatory.get()
    stats = {"recipes": 0, "programs": 0, "skipped": 0, "errors": 0,
             "seconds": 0.0}
    ranked = rank_ledger_programs(ledger_path)[:max(0, int(top_k))]
    d = recipes_dir(ledger_path)
    for key_hash, _cost in ranked:
        path = os.path.join(d, f"{key_hash}.pkl")
        if not os.path.exists(path):
            stats["skipped"] += 1
            continue
        t0 = time.perf_counter()
        try:
            import cloudpickle
            with open(path, "rb") as f:
                doc = cloudpickle.load(f)
            if doc.get("v") != RECIPE_VERSION:
                stats["skipped"] += 1
                continue
            n = obs.prewarm_entry(doc["key"], doc["fn"],
                                  doc.get("abstract") or ())
        except Exception as ex:
            stats["errors"] += 1
            log.debug("recipe replay failed for %s: %s", key_hash, ex)
            continue
        dt = time.perf_counter() - t0
        stats["recipes"] += 1
        stats["programs"] += n
        stats["seconds"] += dt
    obs.note_prewarm_session(stats)
    return stats


def prewarm_session(ledger_path: str, top_k: int = 32,
                    background: bool = False) -> Optional[threading.Thread]:
    """Session-init entry: prewarm synchronously, or on a daemon thread
    so startup is not blocked (queries racing the thread simply
    cold-build — the staging tier is checked under the jit-table
    seam's normal locking)."""
    if not os.path.exists(ledger_path) or \
            not os.path.isdir(recipes_dir(ledger_path)):
        return None
    if not background:
        prewarm_from_ledger(ledger_path, top_k=top_k)
        return None
    t = threading.Thread(
        target=lambda: prewarm_from_ledger(ledger_path, top_k=top_k),
        name="tpu-jit-prewarm", daemon=True)
    t.start()
    return t
