"""Health & exposition: the machine-scrapable surface over the
process-wide MetricsRegistry (obs/metrics.py).

Three consumers, one source of truth:

* ``render_prometheus()`` — the registry in Prometheus text exposition
  format 0.0.4 (``# HELP`` / ``# TYPE`` + series lines; histograms as
  cumulative ``_bucket{le=...}`` plus ``_sum``/``_count``).
* ``HealthMonitor.snapshot()`` — a JSON health document whose status is
  DERIVED from the same counters: arena-exhaustion rate, dirty memsan
  ledgers, shuffle heartbeat misses and device-probe liveness each map
  to a component status; the worst component wins.  Rates are deltas
  since the previous snapshot, so a counter that stopped moving stops
  hurting the status (an exhaustion storm an hour ago is history, not
  an alert).
* ``MetricsServer`` — an opt-in stdlib HTTP endpoint
  (``spark.rapids.tpu.metrics.port``) serving ``GET /metrics``
  (Prometheus) and ``GET /healthz`` (the JSON snapshot), the scrape
  target a deployment points Prometheus/k8s probes at.  Daemon threads
  only: the server must never keep the engine process alive.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from . import metrics as M

OK = "ok"
DEGRADED = "degraded"
DOWN = "down"

_SEVERITY = {OK: 0, DEGRADED: 1, DOWN: 2}


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"") \
        .replace("\n", r"\n")


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"'
                          for k, v in labels.items()) + "}"


def render_prometheus(reg: Optional[M.MetricsRegistry] = None) -> str:
    """The whole registry in Prometheus text format."""
    reg = reg or M.registry()
    lines: List[str] = []
    for fam in reg.families():
        lines.append(f"# HELP {fam.name} {fam.doc}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for labels, ch in fam.series():
            if fam.kind == M.HISTOGRAM:
                for ub, cum in ch.cumulative():
                    le = "+Inf" if ub == float("inf") else _fmt_value(ub)
                    bl = dict(labels)
                    bl["le"] = le
                    lines.append(f"{fam.name}_bucket{_label_str(bl)} "
                                 f"{cum}")
                lines.append(f"{fam.name}_sum{_label_str(labels)} "
                             f"{_fmt_value(ch.sum)}")
                lines.append(f"{fam.name}_count{_label_str(labels)} "
                             f"{ch.count}")
            else:
                lines.append(f"{fam.name}{_label_str(labels)} "
                             f"{_fmt_value(ch.value)}")
    lines.append(f"# HELP tpu_metrics_series_overflow_total label sets "
                 f"evicted into _overflow series by the cardinality cap")
    lines.append("# TYPE tpu_metrics_series_overflow_total counter")
    lines.append(f"tpu_metrics_series_overflow_total "
                 f"{reg.overflow_total()}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# health derivation
# ---------------------------------------------------------------------------

def _counter_value(reg: M.MetricsRegistry, name: str) -> int:
    """Sum over every series of one family (0 when absent)."""
    for fam in reg.families():
        if fam.name == name:
            total = 0
            for _labels, ch in fam.series():
                total += getattr(ch, "value", 0)
            return total
    return 0


def _gauge_value(reg: M.MetricsRegistry, name: str) -> Optional[float]:
    """Aggregate read: sums every series so per-tenant gauges (the
    admission family) report their fleet-wide value; a label-less gauge
    has one series and sums to itself."""
    for fam in reg.families():
        if fam.name == name:
            total = None
            for _labels, ch in fam.series():
                total = (total or 0) + ch.value
            return total
    return None


class HealthMonitor:
    """Derives a status document from counter DELTAS between snapshots.

    Component map (ISSUE acceptance: arena-exhaustion rate, dirty memsan
    ledger, heartbeat misses, device-probe liveness):

      device     DOWN when ``tpu_device_probe_ok`` gauge reads 0 or a
                 probe failure was counted since the last snapshot
      arena      DEGRADED when ``tpu_arena_exhaustions_total`` moved
      memory     DOWN when ``tpu_memsan_dirty_ledgers_total`` moved
                 (a dirty ledger is a correctness signal, not load)
      shuffle    DEGRADED when ``tpu_shuffle_heartbeat_missed_total``
                 moved
      queries    DEGRADED when ``tpu_queries_failed_total`` moved
      slo        DEGRADED when the SAME tenant's SLO burn rate stays
                 above 1 for two consecutive snapshots (the burning
                 tenants are named in ``burning_tenants``)

    Overall status = worst component.  A component with no series yet
    reports OK — absence of a subsystem is not an alert.
    """

    _DELTA_RULES = (
        # (component, counter family, status when the delta is > 0)
        ("device", "tpu_device_probe_failures_total", DOWN),
        ("arena", "tpu_arena_exhaustions_total", DEGRADED),
        ("memory", "tpu_memsan_dirty_ledgers_total", DOWN),
        ("shuffle", "tpu_shuffle_heartbeat_missed_total", DEGRADED),
        ("queries", "tpu_queries_failed_total", DEGRADED),
        ("admission", "tpu_admission_timeouts_total", DEGRADED),
        ("background", "tpu_background_errors_total", DEGRADED),
    )

    # sustained admission backlog: queue depth at or above this for two
    # consecutive snapshots means the byte budget is oversubscribed (one
    # momentarily deep snapshot is ordinary burst absorption, not alert)
    _QUEUE_DEEP = 3

    # sustained HBM tightness (the observatory's degrade rule): live
    # device bytes at or above _HBM_HIGH_FRACTION of the budget while
    # the demotable share of them sits below _HBM_LOW_DEMOTABLE, for
    # two consecutive snapshots — the device is nearly full AND
    # spilling can't meaningfully relieve it (pinned/broadcast-heavy),
    # which is exactly when the next big admit stalls or OOMs
    _HBM_HIGH_FRACTION = 0.9
    _HBM_LOW_DEMOTABLE = 0.25

    def __init__(self, reg: Optional[M.MetricsRegistry] = None):
        self._reg = reg
        self._prev: Dict[str, int] = {}
        self._queue_deep_prev = False
        self._hbm_tight_prev = False
        self._slo_burning_prev: set = set()
        self._lock = threading.Lock()

    def snapshot(self) -> Dict:
        reg = self._reg or M.registry()
        components: Dict[str, Dict] = {}
        status = OK
        with self._lock:
            for comp, fam_name, bad in self._DELTA_RULES:
                cur = _counter_value(reg, fam_name)
                delta = cur - self._prev.get(fam_name, 0)
                self._prev[fam_name] = cur
                comp_status = bad if delta > 0 else OK
                entry = components.setdefault(
                    comp, {"status": OK, "signals": {}})
                entry["signals"][fam_name] = {"total": cur,
                                              "delta": delta}
                if _SEVERITY[comp_status] > _SEVERITY[entry["status"]]:
                    entry["status"] = comp_status
            depth = _gauge_value(reg, "tpu_admission_queue_depth")
            deep = depth is not None and depth >= self._QUEUE_DEEP
            adm = components.setdefault("admission",
                                        {"status": OK, "signals": {}})
            adm["signals"]["tpu_admission_queue_depth"] = depth
            adm["signals"]["tpu_admission_bytes_in_flight"] = \
                _gauge_value(reg, "tpu_admission_bytes_in_flight")
            if deep and self._queue_deep_prev and \
                    _SEVERITY[DEGRADED] > _SEVERITY[adm["status"]]:
                adm["status"] = DEGRADED
            self._queue_deep_prev = deep
            # HBM observatory: sustained high watermark with a low
            # demotable share (see class attrs above)
            total = _gauge_value(reg, "tpu_hbm_total_bytes")
            demotable = _gauge_value(reg, "tpu_hbm_demotable_bytes")
            budget = _gauge_value(reg, "tpu_hbm_budget_bytes")
            hbm = components.setdefault("hbm",
                                        {"status": OK, "signals": {}})
            hbm["signals"]["tpu_hbm_total_bytes"] = total
            hbm["signals"]["tpu_hbm_demotable_bytes"] = demotable
            hbm["signals"]["tpu_hbm_budget_bytes"] = budget
            tight = bool(
                budget and total is not None and
                total >= self._HBM_HIGH_FRACTION * budget and
                (demotable or 0) < self._HBM_LOW_DEMOTABLE * total)
            if tight and self._hbm_tight_prev and \
                    _SEVERITY[DEGRADED] > _SEVERITY[hbm["status"]]:
                hbm["status"] = DEGRADED
            self._hbm_tight_prev = tight
            # latency observatory: sustained per-tenant SLO burn.  The
            # gauge sum across tenants is meaningless here (one tenant
            # at burn 4 must not hide behind three at 0), so this rule
            # reads each tenant's series and degrades only when the
            # SAME tenant burns > 1 in two consecutive snapshots,
            # naming it — the page the operator gets says WHO
            burn_by_tenant: Dict[str, float] = {}
            for fam in reg.families():
                if fam.name == "tpu_slo_burn_rate":
                    for labels, ch in fam.series():
                        burn_by_tenant[labels.get("tenant", "?")] = \
                            ch.value
            slo = components.setdefault("slo",
                                        {"status": OK, "signals": {}})
            slo["signals"]["tpu_slo_burn_rate"] = burn_by_tenant
            burning = {t for t, v in burn_by_tenant.items()
                       if v is not None and v > 1.0}
            sustained = sorted(burning & self._slo_burning_prev)
            if sustained:
                slo["signals"]["burning_tenants"] = sustained
                if _SEVERITY[DEGRADED] > _SEVERITY[slo["status"]]:
                    slo["status"] = DEGRADED
            self._slo_burning_prev = burning
        # progress observatory: a watchdog scan per snapshot — stalled
        # queries degrade the endpoint and are NAMED (query, tenant,
        # phase, deepest open operator), so the page says which query
        # is stuck where, not just "something is slow"
        try:
            from .progress import ProgressTracker
            stalls = ProgressTracker.get().watchdog_scan()
        except Exception:
            stalls = []
        prg = components.setdefault("progress",
                                    {"status": OK, "signals": {}})
        prg["signals"]["stalled_queries"] = stalls
        prg["signals"]["tpu_query_stalls_total"] = \
            _counter_value(reg, "tpu_query_stalls_total")
        if stalls and _SEVERITY[DEGRADED] > _SEVERITY[prg["status"]]:
            prg["status"] = DEGRADED
        probe_ok = _gauge_value(reg, "tpu_device_probe_ok")
        dev = components.setdefault("device",
                                    {"status": OK, "signals": {}})
        dev["signals"]["tpu_device_probe_ok"] = probe_ok
        if probe_ok is not None and probe_ok == 0:
            dev["status"] = DOWN
        # fleet verdict (driver only — where an aggregator is
        # installed): any dead peer degrades the whole endpoint, so a
        # cluster probe pointed at the driver sees executor loss
        from .fleet import installed_aggregator
        agg = installed_aggregator()
        if agg is not None:
            try:
                verdict = agg.verdict(scrape_first=False)
            except Exception:
                verdict = None
            if verdict is not None:
                fc = components.setdefault(
                    "fleet", {"status": OK, "signals": {}})
                fc["signals"]["peers"] = verdict.get("peers")
                fc["signals"]["reasons"] = verdict.get("reasons")
                if verdict.get("status") != OK and \
                        _SEVERITY[DEGRADED] > _SEVERITY[fc["status"]]:
                    fc["status"] = DEGRADED
        for entry in components.values():
            if _SEVERITY[entry["status"]] > _SEVERITY[status]:
                status = entry["status"]
        return {
            "status": status,
            "timestamp_ms": int(time.time() * 1000),
            "components": components,
            "queries": {
                "active": _gauge_value(reg, "tpu_queries_active") or 0,
                "completed":
                    _counter_value(reg, "tpu_queries_completed_total"),
                "failed":
                    _counter_value(reg, "tpu_queries_failed_total"),
                "retried":
                    _counter_value(reg, "tpu_queries_retried_total"),
            },
            "series_overflow": reg.overflow_total(),
        }


# ---------------------------------------------------------------------------
# opt-in stdlib HTTP endpoint
# ---------------------------------------------------------------------------

class MetricsServer:
    """`GET /metrics` (Prometheus) + `GET /healthz` (JSON) on localhost.

    Stdlib only (http.server); one daemon thread; ``port=0`` binds an
    ephemeral port (tests).  Never raises into the engine: a scrape
    error is the scraper's problem.
    """

    def __init__(self, port: int, host: str = "127.0.0.1",
                 reg: Optional[M.MetricsRegistry] = None):
        import http.server

        monitor = HealthMonitor(reg)
        registry = reg

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib contract)
                try:
                    self._serve()
                except Exception as ex:
                    # a scrape must never kill the endpoint thread
                    # silently: count it, degrade health, black-box it,
                    # and tell the scraper (tpufsan TPU-R011)
                    from .bgerrors import note_background_error
                    note_background_error("metrics-http", ex)
                    try:
                        self.send_response(500)
                        self.end_headers()
                    except Exception:
                        pass  # client already gone; nothing to tell

            def _serve(self):
                if self.path.startswith("/metrics"):
                    from .fleet import fleet_refresh
                    fleet_refresh()
                    body = render_prometheus(registry).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.startswith("/healthz"):
                    from .fleet import fleet_refresh
                    fleet_refresh()
                    body = json.dumps(monitor.snapshot()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/queries"):
                    # the progress observatory's live view; the scrape
                    # doubles as a watchdog scan, so a stalled query is
                    # flagged the moment anyone looks
                    from .progress import ProgressTracker
                    body = json.dumps(
                        ProgressTracker.get().live_view()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/spans"):
                    # the fleet pull endpoint: a consumer that carried a
                    # trace context to this process collects the serve
                    # spans recorded under it.  drain=1 (the default)
                    # pops — a retried fetch group never double-merges.
                    from urllib.parse import parse_qs, urlparse
                    from .fleet import RemoteSpanStore
                    q = parse_qs(urlparse(self.path).query)
                    trace_id = (q.get("trace_id") or [None])[0]
                    drain = (q.get("drain") or ["1"])[0] != "0"
                    body = RemoteSpanStore.get().to_json(
                        trace_id, drain=drain).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-scrape stderr spam
                pass

        import socketserver

        class _Server(socketserver.ThreadingMixIn,
                      http.server.HTTPServer):
            daemon_threads = True

        self._httpd = _Server((host, port), _Handler)
        self.port = self._httpd.server_address[1]
        self.monitor = monitor
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="tpu-metrics-endpoint")
        self._thread.start()

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass


_SERVER: Optional[MetricsServer] = None
_SERVER_LOCK = threading.Lock()


def ensure_server(port: int) -> MetricsServer:
    """One endpoint per process: repeated sessions with the same port
    reuse it; a different port replaces it."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None and \
                (_SERVER.port == port or port == 0):
            return _SERVER
        if _SERVER is not None:
            _SERVER.close()
        _SERVER = MetricsServer(port)
        return _SERVER


def shutdown_server() -> None:
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            _SERVER.close()
            _SERVER = None
