"""Cross-run regression watchdog: per-query fingerprints distilled from
the self-emitted event logs (obs/eventlog_writer.py), an append-only
history directory, and a differ that flags drift between runs.

The reference's qualification/profiling tools answer "how did THIS run
go"; nothing in-repo answered "is run N quietly worse than run N−1" —
which is exactly how five benchmark rounds of ``rows/s = 0.0`` shipped
unnoticed.  This module closes that loop:

* ``query_fingerprint`` distills ONE SQL execution into a small dict
  with two strictly separated halves:

  - **deterministic** fields — identical across replays of the same
    query on the same data: plan shape, per-operator aggregate rows /
    batches, the fallback set (operators left on the host engine),
    device→host fetch-crossing count, and lint rule hits.  CI compares
    ONLY these (``devtools/run_lint.py --regress``), so the gate can
    demand exact equality without flaking.
  - **timing** fields — wall ms, per-operator time, measured peak
    device bytes.  ``tools regress`` compares them only when the caller
    opts in with a threshold (``--wall-threshold``), never in CI.

* ``HistoryDir`` appends one JSON document per recorded run
  (``run_<seq>_<stamp>.json``); existing files are never rewritten —
  the history is an audit log, not a cache.

* ``diff_runs`` emits typed ``Drift`` records: ``new_fallback``,
  ``crossing_growth``, ``operator_drift``, ``plan_change``,
  ``lint_drift``, ``replay_class_drift`` (deterministic) and
  ``wall_regression`` (timing, threshold-gated).
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Dict, List, Optional, Tuple

FINGERPRINT_VERSION = 2

#: fields the CI gate may compare (exact equality across replays).
#: distinct_programs / miss_causes come from the compile observatory's
#: enriched jit.build spans: in a fresh process the same query compiles
#: the same programs for the same causes, so recompile-count growth and
#: cause shifts are deterministic regressions, not noise.
DETERMINISTIC_FIELDS = ("plan_shape", "operators", "fallback_ops",
                        "fetch_crossings", "lint_rule_hits",
                        "distinct_programs", "miss_causes",
                        "replay_class")
#: advisory fields (never compared in CI)
TIMING_FIELDS = ("wall_ms", "operator_time_ns", "peak_device_bytes",
                 "compile_seconds", "estimate_rows_err",
                 "pad_waste_ratio", "slo_burn_rate",
                 "tail_dominant_segment")


# ---------------------------------------------------------------------------
# distillation
# ---------------------------------------------------------------------------

def _plan_shape(node) -> list:
    return [node.node_name, [_plan_shape(c) for c in node.children]]


def query_fingerprint(sql, spans: List[dict]) -> Dict:
    """Fingerprint one parsed ``SQLExecution`` (tools/eventlog.py) plus
    its flight-recorder span records."""
    operators: Dict[str, Dict[str, int]] = {}
    fallback: List[str] = []
    time_ns = 0
    est_errs: List[float] = []
    pad_bytes = None  # None until some actual carries the key
    total_bytes = 0
    for n in sql.plan.walk():
        act = n.actual or {}
        agg = operators.setdefault(
            n.node_name, {"rows": 0, "bytes": 0, "batches": 0})
        agg["rows"] += int(act.get("rows") or 0)
        agg["bytes"] += int(act.get("bytes") or 0)
        agg["batches"] += int(act.get("batches") or 0)
        time_ns += int(act.get("timeNs") or 0)
        total_bytes += int(act.get("bytes") or 0)
        if "padWasteBytes" in act:
            pad_bytes = (pad_bytes or 0) + \
                int(act.get("padWasteBytes") or 0)
        if getattr(n, "placement", None) == "cpu":
            fallback.append(n.node_name)
        pred = getattr(n, "prediction", None)
        if pred is not None and n.actual is not None and \
                pred.get("rows") is not None:
            from .export import _err
            est_errs.append(_err(pred.get("rows"),
                                 n.actual.get("rows", 0)))
    crossings = 0
    lint_hits: List[str] = []
    builds = 0
    miss_causes: Dict[str, int] = {}
    compile_s = 0.0
    replay_class = None
    for s in spans:
        attrs = s.get("attrs") or {}
        if s.get("name") == "fetch.crossing":
            crossings += int(attrs.get("transfers", 1))
        if s.get("name") == "phase:overrides":
            lint_hits += list(attrs.get("lint_rules", ()))
            replay_class = attrs.get("replay_class") or replay_class
        if s.get("name") == "jit.build":
            builds += 1
            cause = attrs.get("cause")
            if cause:
                miss_causes[cause] = miss_causes.get(cause, 0) + 1
            compile_s += float(attrs.get("total_s") or 0.0)
    return {
        "version": FINGERPRINT_VERSION,
        "sql_id": sql.sql_id,
        "description": sql.description,
        "failed": bool(sql.failed),
        # deterministic half
        "plan_shape": _plan_shape(sql.plan),
        "operators": operators,
        "fallback_ops": sorted(fallback),
        "fetch_crossings": crossings,
        "lint_rule_hits": sorted(set(lint_hits)),
        "distinct_programs": builds,
        "miss_causes": miss_causes,
        # tpudsan replay class of the final plan (phase:overrides span);
        # None when the log predates the sanitizer, so mixed histories
        # never false-trip
        "replay_class": replay_class,
        # timing half
        "wall_ms": sql.duration,
        "operator_time_ns": time_ns,
        "peak_device_bytes": sql.peak_device_bytes,
        "compile_seconds": round(compile_s, 6),
        # advisory estimator-accuracy field (fingerprint v2+): mean
        # relative row-estimate error over the operators that carried a
        # prediction; None when the log predates the estimator
        # observatory, so pre-feedback histories never false-trip
        "estimate_rows_err": round(sum(est_errs) / len(est_errs), 6)
        if est_errs else None,
        # advisory tpuxsan padding-waste share (timing class: batch
        # split and speculative re-bucketing legitimately move it);
        # None when the log predates pad accounting, so mixed
        # histories never false-trip
        "pad_waste_ratio": round(pad_bytes / total_bytes, 6)
        if pad_bytes is not None and total_bytes else None,
    }


def distill_event_log(path: str) -> List[Dict]:
    """Every query in one self-emitted event log, fingerprinted in
    execution order."""
    from ..tools.eventlog import parse_event_log
    app = parse_event_log(path)
    out = []
    for sql_id in sorted(app.sql_executions):
        spans = [s for s in app.spans
                 if s.get("executionId") == sql_id]
        out.append(query_fingerprint(app.sql_executions[sql_id], spans))
    return out


# ---------------------------------------------------------------------------
# append-only history
# ---------------------------------------------------------------------------

_RUN_RE = re.compile(r"^run_(\d{6})_.*\.json$")


class HistoryDir:
    """One directory of ``run_<seq>_<stamp>.json`` documents; strictly
    append-only (record() refuses to overwrite)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def runs(self) -> List[str]:
        """Absolute run-file paths, oldest first."""
        names = sorted(n for n in os.listdir(self.path)
                       if _RUN_RE.match(n))
        return [os.path.join(self.path, n) for n in names]

    def compile_ledger_path(self) -> str:
        """The cross-session compile ledger (JSONL, appended by the
        compile observatory, aggregated by `tools compile-report`) —
        it lives alongside the run fingerprints so one history dir
        answers both 'did behavior drift' and 'what did compiles cost'.
        """
        from .compileprof import LEDGER_FILENAME
        return os.path.join(self.path, LEDGER_FILENAME)

    def estimator_ledger_path(self) -> str:
        """The cross-session estimator ledger (JSONL, appended by
        obs/estimator.py): per-(exec kind, input signature)
        predicted-vs-actual observations and exchange-boundary re-plan
        decisions, loaded back at session init to warm the feedback
        model."""
        from .estimator import ESTIMATOR_LEDGER_FILENAME
        return os.path.join(self.path, ESTIMATOR_LEDGER_FILENAME)

    def latency_ledger_path(self) -> str:
        """The per-query latency ledger (JSONL, appended by the
        latency observatory, obs/slo.py): one line per traced query
        with its wall time, GOOD/BAD verdict and full critical-path
        segment breakdown — the third critical-path sink, read back by
        `tools tail-report`."""
        from .slo import LATENCY_LEDGER_FILENAME
        return os.path.join(self.path, LATENCY_LEDGER_FILENAME)

    def postmortems_dir(self) -> str:
        """The failure black box's bundle directory (obs/postmortem.py
        dumps one JSON bundle per failed query here, retention-capped
        by hbm.postmortem.maxBundles; `tools postmortem` renders them).
        Created on first access so a crashing query never also fails
        on a missing directory."""
        d = os.path.join(self.path, "postmortems")
        os.makedirs(d, exist_ok=True)
        return d

    def load(self, path: str) -> Dict:
        with open(path, encoding="utf-8") as f:
            return json.load(f)

    def latest(self, n: int = 1) -> List[Dict]:
        return [self.load(p) for p in self.runs()[-n:]]

    def record(self, fingerprints: List[Dict],
               label: str = "") -> str:
        """Append one run document; returns its path."""
        seq = len(self.runs())
        stamp = time.strftime("%Y%m%dT%H%M%S")
        name = f"run_{seq:06d}_{stamp}.json"
        path = os.path.join(self.path, name)
        if os.path.exists(path):  # same-second re-record: bump seq
            name = f"run_{seq:06d}_{stamp}_{os.getpid()}.json"
            path = os.path.join(self.path, name)
        doc = {"version": FINGERPRINT_VERSION,
               "recorded_at": stamp,
               "label": label,
               "queries": fingerprints}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.rename(tmp, path)
        return path


# ---------------------------------------------------------------------------
# the differ
# ---------------------------------------------------------------------------

class Drift:
    """One detected regression signal between two runs."""

    __slots__ = ("query", "kind", "detail", "deterministic")

    def __init__(self, query: str, kind: str, detail: str,
                 deterministic: bool):
        self.query = query
        self.kind = kind
        self.detail = detail
        self.deterministic = deterministic

    def render(self) -> str:
        tag = "DETERMINISTIC" if self.deterministic else "TIMING"
        return f"[{tag}] {self.query}: {self.kind} — {self.detail}"

    def __repr__(self):
        return f"Drift({self.render()!r})"


def _key(fp: Dict) -> Tuple[int, str]:
    return (fp.get("sql_id", -1), fp.get("description", ""))


def diff_fingerprints(old: Dict, new: Dict,
                      wall_threshold_pct: Optional[float] = None
                      ) -> List[Drift]:
    """Drift records between two fingerprints of the SAME query."""
    q = new.get("description") or f"query {new.get('sql_id')}"
    out: List[Drift] = []
    if old.get("plan_shape") != new.get("plan_shape"):
        out.append(Drift(q, "plan_change",
                         "physical plan shape changed between runs",
                         True))
    new_fb = set(new.get("fallback_ops", ())) - \
        set(old.get("fallback_ops", ()))
    if new_fb:
        out.append(Drift(
            q, "new_fallback",
            f"operator(s) newly on the host engine: "
            f"{sorted(new_fb)}", True))
    oc, nc = old.get("fetch_crossings", 0), new.get("fetch_crossings", 0)
    if nc > oc:
        out.append(Drift(
            q, "crossing_growth",
            f"device->host fetch crossings grew {oc} -> {nc}", True))
    oops, nops = old.get("operators", {}), new.get("operators", {})
    for op in sorted(set(oops) | set(nops)):
        a, b = oops.get(op), nops.get(op)
        if a is None or b is None:
            continue  # plan_change already covers added/removed nodes
        for f in ("rows", "batches"):
            if a.get(f) != b.get(f):
                out.append(Drift(
                    q, "operator_drift",
                    f"{op}.{f}: {a.get(f)} -> {b.get(f)}", True))
    new_lint = set(new.get("lint_rule_hits", ())) - \
        set(old.get("lint_rule_hits", ()))
    if new_lint:
        out.append(Drift(q, "lint_drift",
                         f"new lint rule hit(s): {sorted(new_lint)}",
                         True))
    # tpudsan replay class (fingerprint v2+): the same query on the
    # same data classifies identically, so ANY shift is deterministic
    # drift — a weakening means recomputed shuffle blocks may no
    # longer be digest-identical to lost ones.  Compared only when
    # BOTH runs carry the field (histories spanning the sanitizer
    # upgrade never false-trip).
    orc, nrc = old.get("replay_class"), new.get("replay_class")
    if orc and nrc and orc != nrc:
        out.append(Drift(
            q, "replay_class_drift",
            f"plan replay class changed {orc} -> {nrc} — the "
            f"recompute/replay guarantee shifted between runs", True))
    # compile-observatory fields (fingerprint v2): only compared when
    # BOTH runs carry them, so a history spanning the upgrade never
    # false-trips
    if "distinct_programs" in old and "distinct_programs" in new:
        op, np_ = old["distinct_programs"], new["distinct_programs"]
        if np_ > op:
            out.append(Drift(
                q, "recompile_drift",
                f"distinct compiled programs grew {op} -> {np_}", True))
        oc_, nc_ = old.get("miss_causes") or {}, \
            new.get("miss_causes") or {}
        if np_ <= op:
            # same-or-fewer total builds but some CAUSE count grew:
            # the miss mix shifted (e.g. canonicalization stopped
            # collapsing a shape and new_program became shape_churn)
            grown = sorted(c for c in nc_
                           if nc_[c] > oc_.get(c, 0))
            if grown:
                out.append(Drift(
                    q, "cause_shift",
                    f"miss-cause histogram shifted: {grown} grew "
                    f"({oc_} -> {nc_})", True))
    if wall_threshold_pct is not None and \
            "compile_seconds" in old and "compile_seconds" in new:
        ow, nw = old["compile_seconds"] or 0.0, \
            new["compile_seconds"] or 0.0
        if ow > 0.05 and nw > ow * (1.0 + wall_threshold_pct / 100.0):
            out.append(Drift(
                q, "compile_regression",
                f"compile seconds {ow:.2f}s -> {nw:.2f}s "
                f"(> {wall_threshold_pct:g}% threshold)", False))
    if wall_threshold_pct is not None:
        ow, nw = old.get("wall_ms") or 0, new.get("wall_ms") or 0
        if ow > 0 and nw > ow * (1.0 + wall_threshold_pct / 100.0):
            out.append(Drift(
                q, "wall_regression",
                f"wall {ow}ms -> {nw}ms "
                f"(> {wall_threshold_pct:g}% threshold)", False))
    # estimator-accuracy field (advisory, threshold-gated like wall):
    # only compared when BOTH runs carry it, so pre-feedback histories
    # never trip — and never deterministic, because accuracy depends on
    # what the warm ledger had seen
    if wall_threshold_pct is not None and \
            old.get("estimate_rows_err") is not None and \
            new.get("estimate_rows_err") is not None:
        oe, ne = old["estimate_rows_err"], new["estimate_rows_err"]
        if ne > oe + 0.05 and \
                ne > oe * (1.0 + wall_threshold_pct / 100.0):
            out.append(Drift(
                q, "estimate_accuracy_regression",
                f"mean row-estimate error {oe:.4f} -> {ne:.4f} "
                f"(> {wall_threshold_pct:g}% threshold)", False))
    # serving fingerprints (bench.py --serve): the admission counter
    # totals for a fixed mix+budget are deterministic (admitted,
    # repaired, timeouts, completed, failed — queued is scheduling-
    # dependent and deliberately excluded); latency percentiles are
    # timing.  Both guarded on both runs carrying the fields, so a
    # history spanning the serve upgrade never false-trips.
    if "serve_counters" in old and "serve_counters" in new:
        osc, nsc = old["serve_counters"] or {}, new["serve_counters"] or {}
        changed = sorted(f for f in set(osc) & set(nsc)
                         if osc[f] != nsc[f])
        if changed:
            out.append(Drift(
                q, "serve_counter_drift",
                "admission counters moved: " + ", ".join(
                    f"{f} {osc[f]} -> {nsc[f]}" for f in changed),
                True))
    if wall_threshold_pct is not None:
        for f in ("serve_p50_ms", "serve_p99_ms"):
            if f in old and f in new:
                ov, nv = old[f] or 0.0, new[f] or 0.0
                if ov > 0 and nv > ov * (1.0 + wall_threshold_pct / 100.0):
                    out.append(Drift(
                        q, "serve_latency_regression",
                        f"{f} {ov:.1f}ms -> {nv:.1f}ms "
                        f"(> {wall_threshold_pct:g}% threshold)", False))
        # tail-mix shift: a tenant whose dominant p99 segment changed
        # between runs (compute -> queue_wait is the classic whale
        # signature).  Timing-class discipline as above: only reported
        # when percentile checks were asked for, only when BOTH runs
        # carry the field, and never deterministic — the tail of a
        # concurrent mix is scheduling-dependent by nature.
        otd = old.get("tail_dominant_segment")
        ntd = new.get("tail_dominant_segment")
        if isinstance(otd, dict) and isinstance(ntd, dict):
            for tenant in sorted(set(otd) & set(ntd)):
                if otd[tenant] and ntd[tenant] and \
                        otd[tenant] != ntd[tenant]:
                    out.append(Drift(
                        q, "tail_mix_shift",
                        f"tenant {tenant} dominant tail segment "
                        f"{otd[tenant]} -> {ntd[tenant]}", False))
    return out


def diff_runs(old_run: Dict, new_run: Dict,
              wall_threshold_pct: Optional[float] = None) -> List[Drift]:
    """Drift between two run documents, matching queries by
    (sql_id, description); queries present in only one run are reported
    as corpus drift."""
    old_by = {_key(fp): fp for fp in old_run.get("queries", ())}
    new_by = {_key(fp): fp for fp in new_run.get("queries", ())}
    out: List[Drift] = []
    for k in sorted(set(old_by) | set(new_by),
                    key=lambda t: (t[0], t[1])):
        if k not in new_by:
            out.append(Drift(k[1] or f"query {k[0]}", "query_removed",
                             "query present in old run only", True))
        elif k not in old_by:
            out.append(Drift(k[1] or f"query {k[0]}", "query_added",
                             "query present in new run only", True))
        else:
            out += diff_fingerprints(old_by[k], new_by[k],
                                     wall_threshold_pct)
    return out


def deterministic_drift(drifts: List[Drift]) -> List[Drift]:
    return [d for d in drifts if d.deterministic]
