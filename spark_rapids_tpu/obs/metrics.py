"""Process-wide metrics registry: the CONTINUOUS half of the
observability story.

The flight recorder (obs/tracer.py) answers "what happened inside THAT
query"; this registry answers "how is the ENGINE doing" — monotonically
increasing counters, point-in-time gauges and fixed-bucket histograms
that every subsystem feeds (spill tier moves, arena utilization, shuffle
bytes, ICI path decisions, bridge round trips, fetch crossings, query
outcomes) and that obs/health.py exposes in Prometheus text format plus
a derived JSON health snapshot.

Design constraints, in order:

* **Hot-path cheap.**  An increment is one dict lookup plus one locked
  integer add; with the registry disabled
  (``spark.rapids.tpu.metrics.enabled=false``) every mutation
  short-circuits before taking a lock.  Nothing here ever touches the
  device or allocates per call.
* **Thread-safe and exact.**  Operators run partitions from multiple
  threads; counters use a per-child lock so concurrent increments never
  lose updates (the GIL does NOT make ``+=`` atomic).
* **Bounded cardinality.**  Every family has a hard cap on distinct
  label sets (default ``DEFAULT_MAX_SERIES``).  Past the cap, new label
  sets collapse into one ``_overflow`` series and the eviction is
  counted — a runaway label (say, per-query ids used as labels by
  mistake) degrades that family's resolution, never process memory.
  This is the registry analog of the tracer's ``maxSpans`` bound.
* **Fixed histogram buckets.**  Bucket boundaries are part of the
  family's identity, chosen at creation and immutable, so series from
  run N and run N−1 are always comparable (no adaptive re-bucketing).

Naming follows the Prometheus conventions the reference's
SQL-UI/Dropwizard metrics map onto: ``tpu_<subsystem>_<what>_<unit>``
with ``_total`` for counters.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

DEFAULT_MAX_SERIES = 64

# fixed latency ladder (seconds): tunneled-TPU round trips sit in the
# 10ms-1s decades, so the ladder is dense there
DEFAULT_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                           0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

# fixed byte-size ladder for payload histograms
DEFAULT_BYTES_BUCKETS = (1 << 10, 1 << 14, 1 << 17, 1 << 20, 1 << 23,
                         1 << 26, 1 << 29, 1 << 32)

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# label values of the single series that absorbs over-cap label sets
OVERFLOW_LABEL = "_overflow"


class _Child:
    """One (family, label-set) series."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    # counter ---------------------------------------------------------------
    def inc(self, v=1) -> None:
        if v < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += v

    # gauge -----------------------------------------------------------------
    def set(self, v) -> None:
        with self._lock:
            self.value = v

    def dec(self, v=1) -> None:
        with self._lock:
            self.value -= v

    def gauge_inc(self, v=1) -> None:
        with self._lock:
            self.value += v


class _HistChild:
    """One histogram series: per-bucket counts + sum + count."""

    __slots__ = ("_lock", "bounds", "bucket_counts", "sum", "count")

    def __init__(self, bounds: Tuple[float, ...]):
        self._lock = threading.Lock()
        self.bounds = bounds
        self.bucket_counts = [0] * (len(bounds) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, v) -> None:
        i = 0
        for i, b in enumerate(self.bounds):
            if v <= b:
                break
        else:
            i = len(self.bounds)
        with self._lock:
            self.bucket_counts[i] += 1
            self.sum += v
            self.count += 1

    def cumulative(self) -> List[Tuple[float, int]]:
        """(upper_bound, cumulative_count) pairs, +Inf last — the
        Prometheus ``_bucket{le=...}`` contract."""
        with self._lock:
            out = []
            acc = 0
            for b, c in zip(self.bounds, self.bucket_counts):
                acc += c
                out.append((b, acc))
            acc += self.bucket_counts[-1]
            out.append((float("inf"), acc))
            return out


class _NullChild:
    """What a disabled registry hands out: every mutation is a no-op."""

    __slots__ = ()

    def inc(self, v=1):
        pass

    def set(self, v):
        pass

    def dec(self, v=1):
        pass

    def gauge_inc(self, v=1):
        pass

    def observe(self, v):
        pass


_NULL = _NullChild()


class MetricFamily:
    """One named metric with a fixed label schema and a hard series cap."""

    def __init__(self, registry: "MetricsRegistry", name: str, kind: str,
                 doc: str, labelnames: Tuple[str, ...],
                 max_series: int = DEFAULT_MAX_SERIES,
                 buckets: Optional[Tuple[float, ...]] = None):
        self.registry = registry
        self.name = name
        self.kind = kind
        self.doc = doc
        self.labelnames = labelnames
        self.max_series = max_series
        self.buckets = buckets
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        self.overflowed = 0  # label sets evicted into the overflow series

    # -- child acquisition ---------------------------------------------------
    def _new_child(self):
        if self.kind == HISTOGRAM:
            return _HistChild(self.buckets)
        return _Child()

    def labels(self, **kv):
        """The series for this label set (creating it, or the overflow
        series past the cardinality cap)."""
        if not self.registry.enabled:
            return _NULL
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.labelnames)}")
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            ch = self._children.get(key)
            if ch is not None:
                return ch
            if len(self._children) >= self.max_series:
                # hard cap: the new label set never materializes; its
                # updates land in ONE shared overflow series (at most
                # max_series real series + this one exist, ever)
                self.overflowed += 1
                okey = (OVERFLOW_LABEL,) * len(self.labelnames)
                ch = self._children.get(okey)
                if ch is None:
                    ch = self._new_child()
                    self._children[okey] = ch
                return ch
            ch = self._new_child()
            self._children[key] = ch
            return ch

    def _default_child(self):
        """The unlabeled series (only for label-less families)."""
        if not self.registry.enabled:
            return _NULL
        if self.labelnames:
            raise ValueError(f"{self.name} requires labels "
                             f"{self.labelnames}")
        return self.labels()

    # -- unlabeled conveniences ---------------------------------------------
    def inc(self, v=1):
        self._default_child().inc(v)

    def set(self, v):
        self._default_child().set(v)

    def dec(self, v=1):
        self._default_child().dec(v)

    def gauge_inc(self, v=1):
        self._default_child().gauge_inc(v)

    def observe(self, v):
        self._default_child().observe(v)

    # -- read side -----------------------------------------------------------
    def series(self) -> List[Tuple[Dict[str, str], object]]:
        """(labels_dict, child) snapshot, insertion-ordered."""
        with self._lock:
            return [(dict(zip(self.labelnames, key)), ch)
                    for key, ch in self._children.items()]

    def value(self, **kv):
        """Point read of one series (0 when the series does not exist);
        histograms return (count, sum)."""
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            ch = self._children.get(key)
        if ch is None:
            return 0
        if isinstance(ch, _HistChild):
            return (ch.count, ch.sum)
        return ch.value

    def total(self):
        """Sum over every series (including the overflow series) — the
        label-blind read a caller uses when it cares about the family's
        aggregate, not a particular label set (counters/gauges only)."""
        with self._lock:
            children = list(self._children.values())
        out = 0
        for ch in children:
            if isinstance(ch, _HistChild):
                raise ValueError(f"{self.name}: total() on a histogram")
            out += ch.value
        return out


class MetricsRegistry:
    """Process-wide singleton; families are created idempotently so any
    module can say ``metrics.counter(name, doc)`` without coordination.
    """

    _instance: Optional["MetricsRegistry"] = None
    _ilock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, MetricFamily] = {}
        self.enabled = True

    @classmethod
    def get(cls) -> "MetricsRegistry":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = MetricsRegistry()
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> "MetricsRegistry":
        """Drop every family (tests and the CI metrics gate need a
        known-empty registry; production never calls this)."""
        with cls._ilock:
            cls._instance = MetricsRegistry()
            return cls._instance

    # -- family creation (idempotent) ----------------------------------------
    def _family(self, name: str, kind: str, doc: str,
                labelnames: Sequence[str],
                max_series: int = DEFAULT_MAX_SERIES,
                buckets: Optional[Sequence[float]] = None) -> MetricFamily:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name} re-registered as {kind}"
                        f"{labelnames}, was {fam.kind}{fam.labelnames}")
                return fam
            bounds = None
            if kind == HISTOGRAM:
                bounds = tuple(sorted(buckets or
                                      DEFAULT_LATENCY_BUCKETS))
            fam = MetricFamily(self, name, kind, doc, labelnames,
                               max_series=max_series, buckets=bounds)
            self._families[name] = fam
            return fam

    def counter(self, name: str, doc: str = "",
                labelnames: Sequence[str] = (),
                max_series: int = DEFAULT_MAX_SERIES) -> MetricFamily:
        return self._family(name, COUNTER, doc, labelnames, max_series)

    def gauge(self, name: str, doc: str = "",
              labelnames: Sequence[str] = (),
              max_series: int = DEFAULT_MAX_SERIES) -> MetricFamily:
        return self._family(name, GAUGE, doc, labelnames, max_series)

    def histogram(self, name: str, doc: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None,
                  max_series: int = DEFAULT_MAX_SERIES) -> MetricFamily:
        return self._family(name, HISTOGRAM, doc, labelnames, max_series,
                            buckets=buckets)

    # -- read side -----------------------------------------------------------
    def families(self) -> List[MetricFamily]:
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)

    def overflow_total(self) -> int:
        with self._lock:
            return sum(f.overflowed for f in self._families.values())


# ---------------------------------------------------------------------------
# module-level conveniences — what the instrumented subsystems call
# ---------------------------------------------------------------------------

def registry() -> MetricsRegistry:
    return MetricsRegistry.get()


def set_enabled(flag: bool) -> None:
    MetricsRegistry.get().enabled = bool(flag)


def enabled() -> bool:
    return MetricsRegistry.get().enabled


def counter(name: str, doc: str = "",
            labelnames: Sequence[str] = ()) -> MetricFamily:
    return MetricsRegistry.get().counter(name, doc, labelnames)


def gauge(name: str, doc: str = "",
          labelnames: Sequence[str] = ()) -> MetricFamily:
    return MetricsRegistry.get().gauge(name, doc, labelnames)


def histogram(name: str, doc: str = "", labelnames: Sequence[str] = (),
              buckets: Optional[Iterable[float]] = None) -> MetricFamily:
    return MetricsRegistry.get().histogram(name, doc, labelnames,
                                           buckets=buckets)
