"""Per-tenant SLO tracking and tail-latency attribution.

obs/critpath.py answers "where did *this* query's time go"; this
module answers the two serving questions built on top of it:

* **Is each tenant meeting its latency objective?**
  ``spark.rapids.tpu.slo.targetMs`` defines GOOD (wall <= target and
  not failed); ``spark.rapids.tpu.slo.objective`` is the fraction of
  requests that must be GOOD.  A count-based sliding window per tenant
  feeds a burn rate — ``(bad share in window) / (1 - objective)`` — so
  burn 1.0 means "spending error budget exactly as fast as allowed"
  and sustained burn > 1 degrades /healthz, naming the tenant
  (obs/health.py).  Published as ``tpu_slo_{good,total,burn_rate}``
  gauges labeled by tenant.

* **What makes the tail slow?**  A bounded reservoir keeps the
  slowest-N requests per tenant with their full segment breakdowns,
  alongside a recent ring for p50 context.  ``aggregate_tail``
  contrasts the p50 vs p99 segment mix and names the dominant tail
  segment — the evidence shape ROADMAP item 4 (weighted-fair
  admission) will gate on: "tenant pool-3's p99 is 71% queue-wait
  under tenant pool-0's whale".

Every recorded query is also appended to ``latency_ledger.jsonl`` in
the regress HistoryDir (obs/history.py) — the third critical-path
sink, read back by ``tools tail-report`` for cross-process and
post-hoc analysis.  Singleton discipline follows the compile/HBM
observatories: ``LatencyObservatory.get()`` everywhere,
``reset_for_tests()`` in gates.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

#: burn-rate window: last N requests per tenant.
BURN_WINDOW = 64
#: recent ring per tenant — p50/p99 mixes are computed over this.
RECENT_RING = 256
#: slowest-N reservoir per tenant: guarantees extreme-tail retention
#: even after the ring has rotated past a whale incident.
TAIL_RESERVOIR = 8

#: JSONL ledger filename inside the regress HistoryDir (obs/history.py)
LATENCY_LEDGER_FILENAME = "latency_ledger.jsonl"

GOOD_FAMILY = "tpu_slo_good"
TOTAL_FAMILY = "tpu_slo_total"
BURN_FAMILY = "tpu_slo_burn_rate"


def _pct(xs: Sequence[float], q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


def _mix(records: Sequence[dict]) -> Dict[str, float]:
    """Normalized segment shares across a set of per-query records."""
    totals: Dict[str, float] = {}
    for r in records:
        for seg, sec in (r.get("segments") or {}).items():
            totals[seg] = totals.get(seg, 0.0) + float(sec)
    denom = sum(totals.values())
    if denom <= 0:
        return {}
    return {k: v / denom for k, v in sorted(totals.items())}


def aggregate_tail(records: Sequence[dict]) -> Optional[dict]:
    """Contrast the p50 vs p99 segment mix for one tenant's records
    (each ``{"wall_s": float, "segments": {seg: sec}}``).  Shared by
    the live observatory and ``tools tail-report`` so both agree on
    what "dominant tail segment" means."""
    records = [r for r in records if r.get("wall_s") is not None]
    if not records:
        return None
    walls = [float(r["wall_s"]) for r in records]
    p50_s, p99_s = _pct(walls, 0.50), _pct(walls, 0.99)
    body = [r for r in records if float(r["wall_s"]) <= p50_s] or records
    tail = [r for r in records if float(r["wall_s"]) >= p99_s]
    if not tail:
        tail = [max(records, key=lambda r: float(r["wall_s"]))]
    p50_mix, p99_mix = _mix(body), _mix(tail)
    dominant = max(p99_mix, key=p99_mix.get) if p99_mix else None
    return {
        "count": len(records),
        "p50_ms": round(p50_s * 1000.0, 3),
        "p99_ms": round(p99_s * 1000.0, 3),
        "p50_mix": {k: round(v, 4) for k, v in p50_mix.items()},
        "p99_mix": {k: round(v, 4) for k, v in p99_mix.items()},
        "dominant_tail_segment": dominant,
        "dominant_tail_share": round(p99_mix.get(dominant, 0.0), 4)
        if dominant else 0.0,
    }


class _TenantState:
    __slots__ = ("good", "total", "window", "ring", "reservoir", "wall_s")

    def __init__(self):
        self.good = 0
        self.total = 0
        self.window = deque(maxlen=BURN_WINDOW)   # recent GOOD/BAD bits
        self.ring = deque(maxlen=RECENT_RING)     # recent records
        self.reservoir = []                       # slowest-N records
        self.wall_s = 0.0

    def burn_rate(self, objective: float) -> float:
        if not self.window:
            return 0.0
        bad = sum(1 for g in self.window if not g)
        return (bad / len(self.window)) / max(1e-9, 1.0 - objective)

    def tail_records(self) -> List[dict]:
        # ring plus reservoir, deduplicated by sequence stamp: the
        # reservoir re-surfaces whales the ring has already rotated out.
        seen = set()
        out = []
        for r in list(self.ring) + [r for _, _, r in self.reservoir]:
            if r["seq"] not in seen:
                seen.add(r["seq"])
                out.append(r)
        return out


class LatencyObservatory:
    """Process-wide singleton; per-tenant SLO windows + tail records."""

    _instance: Optional["LatencyObservatory"] = None
    _lock = threading.Lock()

    @classmethod
    def get(cls) -> "LatencyObservatory":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._instance = None

    def __init__(self):
        self._mu = threading.Lock()
        self._io = threading.Lock()
        self._target_ms: Optional[int] = None
        self._objective: float = 0.99
        self._ledger_path: Optional[str] = None
        self._tenants: Dict[str, _TenantState] = {}
        self._seq = 0
        self._extract_s = 0.0
        self._query_wall_s = 0.0

    # -- configuration ------------------------------------------------------
    def configure(self, target_ms: Optional[int] = None,
                  objective: Optional[float] = None,
                  ledger_path: Optional[str] = None) -> "LatencyObservatory":
        """Idempotent: pool sessions all configure with the same conf;
        None leaves the existing value in place so a late session does
        not wipe a configured target."""
        with self._mu:
            if target_ms is not None:
                self._target_ms = int(target_ms)
            if objective is not None:
                self._objective = float(objective)
            if ledger_path is not None:
                self._ledger_path = str(ledger_path)
        return self

    @property
    def target_ms(self) -> Optional[int]:
        return self._target_ms

    @property
    def objective(self) -> float:
        return self._objective

    # -- record side --------------------------------------------------------
    def record(self, tenant: str, wall_s: float, segments: Dict[str, float],
               failed: bool = False, label: str = "",
               reconciled: bool = True, extract_s: float = 0.0,
               cancelled: bool = False, deadline: bool = False) -> None:
        """``cancelled`` (a CLIENT cancel) excludes the request from the
        burn window entirely — the engine didn't miss, the caller
        changed its mind, and counting it either way would let a cancel
        storm mask (or fake) real burn.  ``deadline`` (the query blew
        its deadline_ms) counts BAD regardless of wall-vs-target: a
        deadline miss IS the latency failure the SLO exists to catch."""
        from .metrics import MetricsRegistry
        tenant = tenant or "default"
        wall_ms = wall_s * 1000.0
        with self._mu:
            st = self._tenants.setdefault(tenant, _TenantState())
            self._seq += 1
            good = (not failed) and (self._target_ms is None
                                     or wall_ms <= self._target_ms)
            if deadline:
                good = False
            client_cancel = cancelled and not deadline
            st.total += 1
            if good:
                st.good += 1
            if not client_cancel:
                st.window.append(good)
            rec = {"seq": self._seq, "wall_s": wall_s,
                   "segments": dict(segments), "failed": failed,
                   "label": label}
            st.ring.append(rec)
            st.reservoir.append((wall_s, self._seq, rec))
            st.reservoir.sort(key=lambda t: (-t[0], t[1]))
            del st.reservoir[TAIL_RESERVOIR:]
            st.wall_s += wall_s
            self._extract_s += extract_s
            self._query_wall_s += wall_s
            burn = st.burn_rate(self._objective)
            good_n, total_n = st.good, st.total
            ledger_path = self._ledger_path
            objective = self._objective
            target_ms = self._target_ms
        reg = MetricsRegistry.get()
        doc = "Per-tenant SLO tracking (obs/slo.py)."
        reg.gauge(GOOD_FAMILY, doc, ("tenant",)).labels(
            tenant=tenant).set(good_n)
        reg.gauge(TOTAL_FAMILY, doc, ("tenant",)).labels(
            tenant=tenant).set(total_n)
        reg.gauge(BURN_FAMILY, doc, ("tenant",)).labels(
            tenant=tenant).set(round(burn, 4))
        if ledger_path:
            line = json.dumps({
                "ts": round(time.time(), 3), "tenant": tenant,
                "label": label, "wall_s": round(wall_s, 6),
                "failed": failed, "good": good, "reconciled": reconciled,
                "target_ms": target_ms, "objective": objective,
                "segments": {k: round(v, 6) for k, v in segments.items()},
            }, sort_keys=True)
            try:
                with self._io:
                    with open(ledger_path, "a", encoding="utf-8") as fh:
                        fh.write(line + "\n")
            except OSError:
                pass  # advisory sink: a read-only HistoryDir must not fail queries

    # -- read side -----------------------------------------------------------
    def overhead(self) -> dict:
        with self._mu:
            pct = (100.0 * self._extract_s / self._query_wall_s
                   if self._query_wall_s > 0 else 0.0)
            return {"extract_s": round(self._extract_s, 6),
                    "query_wall_s": round(self._query_wall_s, 6),
                    "pct": round(pct, 4)}

    def slo_report(self) -> dict:
        with self._mu:
            tenants = {}
            for name in sorted(self._tenants):
                st = self._tenants[name]
                walls = [r["wall_s"] * 1000.0 for r in st.ring]
                agg = aggregate_tail(st.tail_records())
                tenants[name] = {
                    "good": st.good, "total": st.total,
                    "window": len(st.window),
                    "burn_rate": round(st.burn_rate(self._objective), 4),
                    "p50_ms": round(_pct(walls, 0.50), 3),
                    "p99_ms": round(_pct(walls, 0.99), 3),
                    "dominant_tail_segment":
                        agg["dominant_tail_segment"] if agg else None,
                }
            return {"enabled": self._target_ms is not None,
                    "target_ms": self._target_ms,
                    "objective": self._objective,
                    "burn_window": BURN_WINDOW,
                    "overhead": {
                        "extract_s": round(self._extract_s, 6),
                        "query_wall_s": round(self._query_wall_s, 6),
                        "pct": round(100.0 * self._extract_s
                                     / self._query_wall_s, 4)
                        if self._query_wall_s > 0 else 0.0},
                    "tenants": tenants}

    def tail_report(self) -> dict:
        with self._mu:
            tenants = {}
            for name in sorted(self._tenants):
                st = self._tenants[name]
                agg = aggregate_tail(st.tail_records())
                if agg is None:
                    continue
                agg["slowest"] = [
                    {"wall_ms": round(w * 1000.0, 3), "label": r["label"],
                     "failed": r["failed"]}
                    for w, _, r in st.reservoir]
                tenants[name] = agg
            return {"target_ms": self._target_ms,
                    "objective": self._objective, "tenants": tenants}


def format_tail_report(report: dict) -> str:
    """Human rendering shared by ``tools tail-report`` and the gate."""
    lines = []
    tenants = report.get("tenants") or {}
    if not tenants:
        return "tail-report: no recorded queries"
    for name, agg in tenants.items():
        p50d = max(agg["p50_mix"], key=agg["p50_mix"].get) \
            if agg.get("p50_mix") else None
        dom = agg.get("dominant_tail_segment")
        share = agg.get("dominant_tail_share", 0.0)
        lines.append(
            f"tenant {name}: n={agg['count']} p50={agg['p50_ms']:.1f}ms"
            f" ({p50d or '-'}) | p99={agg['p99_ms']:.1f}ms —"
            f" tenant {name}'s p99 is {share:.0%} {dom or '-'}")
        for s in agg.get("slowest", ())[:3]:
            lines.append(f"    slowest: {s['wall_ms']:.1f}ms"
                         f" {s['label'] or '(unlabeled)'}"
                         f"{' FAILED' if s.get('failed') else ''}")
    # name the heaviest tenant by total recorded wall — the usual whale
    by_wall = sorted(
        ((sum(s["wall_ms"] for s in agg.get("slowest", ())), name)
         for name, agg in tenants.items()), reverse=True)
    if by_wall and by_wall[0][0] > 0:
        lines.append(f"heaviest tail (sum of slowest-N wall): "
                     f"tenant {by_wall[0][1]}")
    return "\n".join(lines)
