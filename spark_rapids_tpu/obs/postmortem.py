"""Failure black box: bounded post-mortem bundles for failed queries.

Until this module existed every failed query evaporated its evidence —
the trace died with the session object, the metrics kept moving, and
the memory timeline's "who held HBM at failure time" answer was gone by
the time anyone asked.  ``dump_postmortem`` freezes all of it into ONE
JSON bundle under ``<historyDir>/postmortems/`` the moment the failure
unwinds through ``session._execute``:

  * the sealed trace (span dicts + measured/static peaks + drop count),
  * a full metrics snapshot (Prometheus exposition text),
  * the HBM observatory's occupancy report and recent sample window,
  * the failing plan's tree, the interp/tmsan analysis states,
  * the estimator's predicted-vs-actual grades,
  * and the session's effective config.

Bundles are retention-capped (``hbm.postmortem.maxBundles``) so a
crash-looping workload cannot fill the disk, and every step here is
best-effort — a black-box crash must never mask the query's own error.
``tools postmortem`` renders a bundle back into a human report.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

BUNDLE_VERSION = 1
BUNDLE_PREFIX = "pm_"
# hard byte bound on one serialized bundle: a post-mortem is a summary,
# not an archive — past it the sample window is halved until it fits
MAX_BUNDLE_BYTES = 4 << 20

# eager, not lazily created on first use: bundles are now written from
# background threads too, and a lazy `if _seq_lock is None: Lock()`
# init is itself a race (two first-callers can mint different locks)
_seq_lock = threading.Lock()
_seq = 0


def _next_seq() -> int:
    global _seq
    with _seq_lock:
        _seq += 1
        return _seq


def _classify(error) -> str:
    """Bundle kind from the failure's exception type."""
    from ..memory.admission import AdmissionTimeout
    from ..memory.memsan import LifecycleViolation
    from .progress import TpuQueryCancelled, TpuQueryDeadlineExceeded
    if isinstance(error, AdmissionTimeout):
        return "admission_timeout"
    if isinstance(error, LifecycleViolation):
        return "dirty_ledger"
    if isinstance(error, TpuQueryDeadlineExceeded):
        return "deadline_exceeded"
    if isinstance(error, TpuQueryCancelled):
        return "cancelled"
    name = type(error).__name__ if error is not None else ""
    if "Leak" in name or "leak" in str(error or "").lower()[:200]:
        return "dirty_ledger"
    return "query_failure"


def _failing_operator(span_dicts: List[Dict]) -> Optional[Dict]:
    """The INNERMOST operator span that closed with an error — the
    culprit the acceptance criteria want named.  When a query dies, the
    seal marks every still-open span on the stack errored, outermost
    first by start time, so the deepest (latest-started) errored span
    is the operator that actually threw; its ancestors are the
    unwind."""
    errored = [s for s in span_dicts
               if s.get("kind") == "operator"
               and s.get("status") == "error"]
    if not errored:
        return None
    s = max(errored, key=lambda s: s.get("startNs", 0))
    return {"name": s.get("name"),
            "operator": (s.get("attrs") or {}).get("op", s.get("name")),
            "error": s.get("error"),
            "startNs": s.get("startNs")}


def _json_safe(obj):
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return repr(obj)


def dump_postmortem(out_dir: str, error, session=None, tracer=None,
                    plan=None, tenant: str = "default",
                    max_bundles: int = 16,
                    kind: Optional[str] = None) -> Optional[str]:
    """Write one bundle; returns its path (None when the dump itself
    failed — callers treat the black box as strictly advisory).
    ``kind`` overrides the exception-type classification — the
    background-error router labels its bundles ``background_failure``
    regardless of the escaping type."""
    try:
        bundle = build_bundle(error, session=session, tracer=tracer,
                              plan=plan, tenant=tenant, kind=kind)
        return _write_bundle(out_dir, bundle, max_bundles)
    except Exception:
        return None


def dump_background_postmortem(out_dir: str, error, tenant: str,
                               max_bundles: int = 16) -> Optional[str]:
    """Black-box a background-thread failure (heartbeat loop, metrics
    endpoint).  Deliberately a LEAN bundle — header, metrics exposition
    and the HBM window — NOT ``build_bundle``: a background thread has
    no session, plan or tracer to freeze, and keeping this path off the
    planner/analysis machinery keeps the tpucsan reach of those thread
    roots (and therefore their shared-write surface) small and honest."""
    try:
        bundle = _bundle_header(error, tenant, "background_failure")
        _add_hbm_section(bundle)
        _add_metrics_section(bundle)
        return _write_bundle(out_dir, bundle, max_bundles)
    except Exception:
        return None


def _write_bundle(out_dir: str, bundle: Dict[str, Any],
                  max_bundles: int) -> Optional[str]:
    """Serialize one assembled bundle under ``<out_dir>/postmortems/``
    with the size clamp and retention cap applied."""
    try:
        from .history import HistoryDir
        pm_dir = HistoryDir(out_dir).postmortems_dir()
        stamp = time.strftime("%Y%m%d_%H%M%S")
        path = os.path.join(
            pm_dir, f"{BUNDLE_PREFIX}{stamp}_{_next_seq():04d}.json")
        body = json.dumps(bundle, default=repr)
        while len(body) > MAX_BUNDLE_BYTES and \
                len(bundle.get("hbm", {}).get("window", [])) > 8:
            w = bundle["hbm"]["window"]
            bundle["hbm"]["window"] = w[len(w) // 2:]
            bundle["hbm"]["window_truncated"] = True
            body = json.dumps(bundle, default=repr)
        with open(path, "w", encoding="utf-8") as f:
            f.write(body)
        _enforce_retention(pm_dir, max_bundles)
        return path
    except Exception:
        return None


def _bundle_header(error, tenant: str,
                   kind: Optional[str]) -> Dict[str, Any]:
    return {
        "version": BUNDLE_VERSION,
        "kind": kind or _classify(error),
        "wall_time_ms": int(time.time() * 1000),
        "tenant": tenant,
        "error": {"type": type(error).__name__ if error is not None
                  else None,
                  "message": str(error) if error is not None else None},
    }


def _add_hbm_section(bundle: Dict[str, Any]) -> None:
    # HBM observatory: occupancy split at failure time + recent window
    try:
        from .memprof import MemoryTimeline
        tl = MemoryTimeline.get()
        bundle["hbm"] = {"report": tl.report(), "window": tl.window()}
    except Exception as ex:
        bundle["hbm"] = {"error": repr(ex)}


def _add_metrics_section(bundle: Dict[str, Any]) -> None:
    # metrics: the full exposition text (grep-able, schema-stable)
    try:
        from .health import render_prometheus
        bundle["metrics"] = render_prometheus()
    except Exception as ex:
        bundle["metrics"] = f"# unavailable: {ex!r}"


def build_bundle(error, session=None, tracer=None, plan=None,
                 tenant: str = "default",
                 kind: Optional[str] = None) -> Dict[str, Any]:
    """Assemble the bundle dict.  Every section is individually
    best-effort: a dead subsystem contributes an error note, never an
    exception."""
    bundle = _bundle_header(error, tenant, kind)
    # cancellation context: who set the flag and which checkpoint /
    # operator observed it (the typed errors carry all three)
    try:
        from .progress import (TpuQueryCancelled,
                               TpuQueryDeadlineExceeded)
        if isinstance(error, (TpuQueryCancelled,
                              TpuQueryDeadlineExceeded)):
            bundle["cancellation"] = {
                "cause": getattr(error, "cause", None),
                "checkpoint": getattr(error, "checkpoint", None),
                "operator": getattr(error, "operator", None),
                "query_id": getattr(error, "query_id", None),
            }
    except Exception:
        pass
    try:
        # the attribution scope is still on this thread — the failure
        # unwinds through session._execute inside push_context/pop
        from .memprof import current_context
        ctx = current_context()
        if ctx is not None and ctx[1]:
            bundle["query"] = ctx[1]
    except Exception:
        pass
    # trace: sealed span dicts + the peak the memsan ledger measured
    try:
        if tracer is not None:
            spans = tracer.span_dicts()
            bundle["trace"] = {
                "spans": spans,
                "dropped": getattr(tracer, "dropped", 0),
                "measured_peak_device_bytes":
                    getattr(tracer, "measured_peak_device_bytes", None),
                "static_peak_bound":
                    _json_safe(getattr(tracer, "static_peak_bound",
                                       None)),
            }
            bundle["failing_operator"] = _failing_operator(spans)
    except Exception as ex:
        bundle["trace"] = {"error": repr(ex)}
    _add_hbm_section(bundle)
    _add_metrics_section(bundle)
    # plan + analysis states
    try:
        if plan is not None:
            bundle["plan"] = plan.tree_string()
    except Exception as ex:
        bundle["plan"] = f"(unavailable: {ex!r})"
    try:
        if plan is not None and session is not None:
            from ..analysis.interp import infer_plan
            from ..analysis.lifetime import analyze_memory, total_bytes
            interp = infer_plan(plan, session.conf)
            mem = analyze_memory(plan, session.conf, interp)
            states = []

            def visit(n):
                st = interp.state(n)
                if st is None:
                    return
                b = mem.bound(n)
                states.append({
                    "node": type(n).__name__,
                    "rows": None if st.rows is None else int(st.rows),
                    "bytes": int(total_bytes(st)),
                    "peak_hbm_bound": None if b is None else int(b),
                })
            plan.foreach(visit)
            bundle["analysis"] = {
                "states": states,
                "diags": [f"{d.code}: {d.message}"
                          for d in getattr(mem, "diags", [])],
            }
    except Exception as ex:
        bundle["analysis"] = {"error": repr(ex)}
    # tpudsan replay class of the failed plan: tells the operator
    # whether a recompute of the lost work is even guaranteed to
    # reproduce the failing state (order_dependent subtrees may not)
    try:
        if plan is not None and session is not None:
            from ..analysis.determinism import classify_plan
            res = classify_plan(plan, session.conf)
            bundle["replay"] = {
                "class": res.effective(plan),
                "reason": res.reason(plan),
                "weak_subtrees": [d.message for d in res.diags
                                  if d.code == "TPU-L016"],
            }
    except Exception as ex:
        bundle["replay"] = {"error": repr(ex)}
    # estimator grades: predicted-vs-actual for the failed run
    try:
        if tracer is not None:
            bundle["estimator"] = tracer.accuracy_rows()
    except Exception as ex:
        bundle["estimator"] = [{"error": repr(ex)}]
    # effective config (the session's raw map — what the operator set,
    # not every default; defaults are recoverable from docs/configs.md)
    try:
        if session is not None:
            bundle["config"] = {str(k): str(v) for k, v in
                                session._conf_map.items()}
    except Exception as ex:
        bundle["config"] = {"error": repr(ex)}
    return bundle


def _enforce_retention(pm_dir: str, max_bundles: int) -> None:
    try:
        bundles = sorted(
            f for f in os.listdir(pm_dir)
            if f.startswith(BUNDLE_PREFIX) and f.endswith(".json"))
        for stale in bundles[:-max_bundles] if max_bundles > 0 else []:
            try:
                os.unlink(os.path.join(pm_dir, stale))
            except OSError:
                pass
    except OSError:
        pass


# ---------------------------------------------------------------------------
# rendering (`tools postmortem`)

def list_bundles(out_dir: str) -> List[str]:
    """Bundle paths under out_dir, oldest first.  Accepts either the
    history dir (looks in its postmortems/ subdir) or the postmortems
    dir itself."""
    cand = os.path.join(out_dir, "postmortems")
    d = cand if os.path.isdir(cand) else out_dir
    if not os.path.isdir(d):
        return []
    return [os.path.join(d, f) for f in sorted(os.listdir(d))
            if f.startswith(BUNDLE_PREFIX) and f.endswith(".json")]


def load_bundle(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def _fmt_bytes(n) -> str:
    if n is None:
        return "?"
    n = int(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"


def render_postmortem(bundle: Dict[str, Any]) -> str:
    """Human report: what failed, who held HBM when it did."""
    lines = ["### Post-mortem bundle ###"]
    err = bundle.get("error") or {}
    lines.append(f"kind:    {bundle.get('kind', '?')}")
    lines.append(f"tenant:  {bundle.get('tenant', '?')}"
                 + (f"  query: {bundle['query']}"
                    if bundle.get("query") else ""))
    lines.append(f"error:   {err.get('type')}: {err.get('message')}")
    canc = bundle.get("cancellation")
    if canc:
        where = canc.get("checkpoint") or "?"
        if canc.get("operator"):
            where += f" in {canc['operator']}"
        lines.append(f"cancel:  cause={canc.get('cause') or 'deadline'}"
                     f", observed at {where}")
    op = bundle.get("failing_operator")
    if op:
        lines.append(f"failing operator: {op.get('operator')}"
                     f" ({op.get('error')})")
    else:
        lines.append("failing operator: (no errored operator span — "
                     "failure before/outside execution)")
    rep = bundle.get("replay")
    if rep and not rep.get("error"):
        line = f"replay class:   {rep.get('class')}"
        if rep.get("reason"):
            line += f" ({rep['reason']})"
        lines.append(line)
        for w in rep.get("weak_subtrees") or ():
            lines.append(f"  weak subtree: {w}")
    hbm = bundle.get("hbm") or {}
    rep = hbm.get("report") or {}
    lines.append("")
    lines.append(f"HBM at failure: total {_fmt_bytes(rep.get('total_bytes'))}"
                 f" / budget {_fmt_bytes(rep.get('budget_bytes'))}"
                 f", peak {_fmt_bytes(rep.get('peak_bytes'))}"
                 f", demotable {_fmt_bytes(rep.get('demotable_bytes'))}")
    tenants = rep.get("tenants") or {}
    if tenants:
        lines.append(f"{'tenant':16s} {'resident':>12s} {'pinned':>12s} "
                     f"{'demotable':>12s} {'closed-pend':>12s} "
                     f"{'arena':>12s} {'admitted':>12s}")
        for t, row in sorted(tenants.items()):
            lines.append(
                f"{t[:16]:16s} {_fmt_bytes(row.get('resident_bytes')):>12s} "
                f"{_fmt_bytes(row.get('pinned_bytes')):>12s} "
                f"{_fmt_bytes(row.get('demotable_bytes')):>12s} "
                f"{_fmt_bytes(row.get('closed_pending_bytes')):>12s} "
                f"{_fmt_bytes(row.get('arena_staging_bytes')):>12s} "
                f"{_fmt_bytes(row.get('admitted_bytes')):>12s}")
    window = hbm.get("window") or []
    if window:
        lines.append(f"timeline window: {len(window)} sample(s)"
                     + (" (truncated)" if hbm.get("window_truncated")
                        else ""))
        for s in window[-8:]:
            lines.append(
                f"  t={s.get('t_ns', 0) / 1e6:.3f}ms {s.get('tenant')}/"
                f"{s.get('class')} {s.get('delta'):+d} -> live "
                f"{_fmt_bytes(s.get('live'))} total "
                f"{_fmt_bytes(s.get('total'))}"
                + (f" op={s['operator']}" if s.get("operator") else ""))
    tr = bundle.get("trace") or {}
    if "spans" in tr:
        lines.append("")
        lines.append(
            f"trace: {len(tr['spans'])} span(s), "
            f"{tr.get('dropped', 0)} dropped, measured peak "
            f"{_fmt_bytes(tr.get('measured_peak_device_bytes'))}, "
            f"static bound {_fmt_bytes(tr.get('static_peak_bound'))}")
    if bundle.get("plan"):
        lines.append("")
        lines.append("plan:")
        lines += ["  " + l for l in
                  str(bundle["plan"]).splitlines()[:40]]
    diags = (bundle.get("analysis") or {}).get("diags") or []
    if diags:
        lines.append("analysis diags: " + "; ".join(diags[:10]))
    if bundle.get("config"):
        lines.append("")
        lines.append("config (explicitly set):")
        for k, v in sorted(bundle["config"].items()):
            lines.append(f"  {k}={v}")
    return "\n".join(lines) + "\n"
