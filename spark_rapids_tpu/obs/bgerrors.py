"""Typed routing for background-thread failures.

Long-lived daemon threads — the shuffle heartbeat loop, the metrics
HTTP server — used to swallow unexpected exceptions silently: the
thread either died without a trace or logged-and-continued, and the
only symptom was a peer quietly going stale.  tpufsan (TPU-R011)
formalizes why that is unacceptable; this module is the sanctioned
sink those threads route through instead.

``note_background_error(root, error)`` does three things, each
best-effort and none able to raise back into the calling thread:

1. increments ``tpu_background_errors_total{root=...}`` so the
   failure is visible on the metrics surface and drives the health
   monitor's delta rule (``background`` component degrades);
2. records the last error per root (type, message, monotonic count)
   for health snapshots and tests;
3. writes a postmortem bundle of kind ``background_failure`` when a
   black-box directory is configured — background failures get the
   same forensic treatment as query failures.

The bundle directory is process-global (`set_postmortem_dir`) because
background threads outlive any one session; ``TpuSession`` points it
at its own history dir when postmortems are enabled.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

_lock = threading.Lock()
_last_errors: Dict[str, Dict[str, Any]] = {}
_postmortem_dir: Optional[str] = None


def set_postmortem_dir(path: Optional[str]) -> None:
    """Point background-failure bundles at a history directory (None
    disables bundling; counting and last-error recording continue)."""
    global _postmortem_dir
    with _lock:
        _postmortem_dir = path


def last_error(root: str) -> Optional[Dict[str, Any]]:
    """The most recent recorded failure for ``root`` (or None):
    ``{"type", "message", "count"}``."""
    with _lock:
        rec = _last_errors.get(root)
        return dict(rec) if rec else None


def reset() -> None:
    """Test hook: forget recorded errors and the bundle directory."""
    global _postmortem_dir
    with _lock:
        _last_errors.clear()
        _postmortem_dir = None


def note_background_error(root: str, error: BaseException) -> None:
    """Route a background-thread failure through the typed path:
    counter + last-error record + (best-effort) postmortem bundle.

    Never raises — a broken observability stack must not take the
    heartbeat loop down with it."""
    try:
        from . import metrics as m
        m.counter("tpu_background_errors_total",
                  "unexpected exceptions in background threads, "
                  "by thread root",
                  labelnames=("root",)).labels(root=root).inc()
    except Exception:
        pass
    try:
        with _lock:
            rec = _last_errors.setdefault(
                root, {"type": "", "message": "", "count": 0})
            rec["type"] = type(error).__name__
            rec["message"] = str(error)
            rec["count"] += 1
            out_dir = _postmortem_dir
    except Exception:
        out_dir = None
    if out_dir:
        try:
            from .postmortem import dump_background_postmortem
            dump_background_postmortem(out_dir, error,
                                       tenant=f"background:{root}")
        except Exception:
            pass
