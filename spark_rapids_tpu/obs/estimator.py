"""Estimator observatory: grade the planner's row/byte/peak-HBM
predictions against execution, remember the grades, and feed them back.

The flight recorder already attaches predicted rows/bytes/peak-HBM to
every operator span (``api/session._install_predictions``) and ``tools
profile --accuracy`` ranks the misses — but nothing CONSUMED the
signal: the CBO, the L010/L012/L014 byte estimates and the admission
tickets all trusted a static model the recorder could prove wrong.
This module closes the loop:

* **The ledger.**  Every closed operator span distills its
  predicted-vs-actual (rows, bytes; plus the query-level measured peak
  device bytes vs the tmsan static bound) into running statistics
  keyed by (exec kind, input-shape/dtype signature), persisted as
  append-only JSONL (``estimator_ledger.jsonl``) in the regression
  HistoryDir — the same cross-session discipline as the compile
  ledger, and the same tolerant line-by-line load.
* **The metrics.**  ``tpu_estimator_observations_total{exec}`` and
  ``tpu_estimator_abs_error_total{exec}`` (cumulative relative error,
  so error-per-observation is a PromQL division away) plus the
  ``tpu_estimator_calibration_score`` gauge (1/(1+mean abs relative
  row error): 1.0 = clairvoyant, ->0 = guessing).
* **The feedback.**  With ``spark.rapids.tpu.feedback.enabled``,
  ``plan/cost.estimate_rows`` blends a matching signature's recorded
  mean into the static estimate with a confidence weight grown by
  observation count and clamped to [blendFloor, blendCap] — sharpening
  the one bound the CBO, the lint byte estimates and the admission
  tickets all ride.  Recording never depends on the flag; only the
  feedback does.

Exchange-boundary re-planning (``analysis/replan.py``) sinks its
decisions here too (``event: "replan"``), so one file answers both
"how wrong were we" and "what did we do about it".
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

log = logging.getLogger("spark_rapids_tpu.obs.estimator")

ESTIMATOR_LEDGER_FILENAME = "estimator_ledger.jsonl"
ESTIMATOR_LEDGER_VERSION = 1

# estimator families fan out by exec kind like the jit families do
_EST_MAX_SERIES = 256


def _stable_hash(obj: Any) -> str:
    """12-hex stable hash (repr is stable for the strings/ints/tuples
    signature_of produces) — matches the compile ledger's key hashing
    so the two ledgers aggregate the same way cross-session."""
    return hashlib.sha1(repr(obj).encode()).hexdigest()[:12]


def _static_partitions(c) -> Optional[int]:
    """A child's partition count WITHOUT triggering lazy computation:
    an AQE reader's ``num_partitions`` property MATERIALIZES the
    shuffle to count post-coalesce specs — a signature probe must never
    run device work, and the signature must not depend on whether the
    map stage happens to have run yet.  Use the underlying exchange's
    static count for those nodes; everything else answers statically."""
    if hasattr(c, "exchange") and hasattr(c, "_specs"):
        return getattr(c.exchange, "num_partitions", None)
    return getattr(c, "num_partitions", None)


def signature_of(node) -> str:
    """The (exec kind, input-shape/dtype) signature one operator's
    statistics accumulate under: the node's semantic description plus
    its dtype layout and, RECURSIVELY, each child's signature and
    partition count.  The recursion is what keeps two structurally
    different queries from pooling their statistics: a join's output
    coalesce and a sort's output coalesce can share dtypes and
    partition counts at one level deep, and blending the sort's 4000
    actual rows into the join's 97 would poison both estimates.
    Stable across processes (describe() renders bound expressions as
    SQL, not object ids), so a prior session's observations match this
    one's plans."""
    return _sig(node, {})


def _sig(node, memo: Dict[int, str]) -> str:
    nid = id(node)
    got = memo.get(nid)
    if got is not None:
        return got
    try:
        kind = type(node).__name__
        try:
            desc = node.describe()
        except Exception:
            desc = kind
        self_sig = tuple(dt.name for dt in node.output_types)
        children = tuple(getattr(node, "children", ()) or ())
        if not children:
            # AQE readers hang below their exchange without listing it
            # as a child; the map-side subtree is what distinguishes
            # two reads that share a dtype layout
            exch = getattr(node, "exchange", None)
            if exch is not None:
                children = (exch,)
        child_sig = tuple((_sig(c, memo), _static_partitions(c))
                          for c in children)
        out = _stable_hash((kind, desc, self_sig, child_sig))
    except Exception:
        out = _stable_hash(type(node).__name__)
    memo[nid] = out
    return out


def _rel_err(pred, actual) -> Optional[float]:
    """Relative prediction error |pred-actual|/max(actual,1); None
    prediction means 'no model' and produces no observation (same
    convention as obs/export._err, minus its -1 rank sentinel)."""
    if pred is None:
        return None
    return abs(float(pred) - float(actual)) / max(float(actual), 1.0)


class _SigStats:
    """Running statistics for one (exec kind, signature)."""

    __slots__ = ("n", "rows_sum", "bytes_sum", "rows_err_sum",
                 "bytes_err_sum")

    def __init__(self):
        self.n = 0
        self.rows_sum = 0.0
        self.bytes_sum = 0.0
        self.rows_err_sum = 0.0
        self.bytes_err_sum = 0.0

    def add(self, act_rows, act_bytes, rows_err, bytes_err) -> None:
        self.n += 1
        self.rows_sum += float(act_rows)
        self.bytes_sum += float(act_bytes)
        if rows_err is not None:
            self.rows_err_sum += rows_err
        if bytes_err is not None:
            self.bytes_err_sum += bytes_err

    @property
    def mean_rows(self) -> float:
        return self.rows_sum / max(self.n, 1)

    @property
    def mean_bytes(self) -> float:
        return self.bytes_sum / max(self.n, 1)


class EstimatorLedger:
    """Process-wide singleton of predicted-vs-actual statistics."""

    _instance: Optional["EstimatorLedger"] = None
    _ilock = threading.Lock()

    def __init__(self):
        self._lock = threading.RLock()
        self.enabled = True
        self.ledger_path: Optional[str] = None
        # feedback knobs (spark.rapids.tpu.feedback.*, pushed in by
        # session init so estimate_rows keeps its conf-free signature)
        self.feedback_enabled = False
        self.blend_floor = 0.25
        self.blend_cap = 0.9
        self.min_observations = 1
        self.replan_factor = 4.0
        self._stats: Dict[Tuple[str, str], _SigStats] = {}
        self.observations = 0
        self.rows_err_total = 0.0
        self.bytes_err_total = 0.0
        self.replans = 0

    # -- lifecycle -----------------------------------------------------------
    @classmethod
    def get(cls) -> "EstimatorLedger":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = EstimatorLedger()
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> "EstimatorLedger":
        """Fresh ledger (tests and CI gates need known-empty stats;
        production never calls this)."""
        with cls._ilock:
            cls._instance = EstimatorLedger()
            return cls._instance

    def configure(self, enabled: Optional[bool] = None,
                  ledger_path: Optional[str] = None,
                  feedback_enabled: Optional[bool] = None,
                  blend_floor: Optional[float] = None,
                  blend_cap: Optional[float] = None,
                  min_observations: Optional[int] = None,
                  replan_factor: Optional[float] = None) -> None:
        """Session-init wiring.  Setting a ledger path loads the prior
        sessions' observations, so the very next plan already blends a
        warm model (the cold->warm axis `bench.py --accuracy` and the
        `--feedback` gate measure)."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if feedback_enabled is not None:
                self.feedback_enabled = bool(feedback_enabled)
            if blend_floor is not None:
                self.blend_floor = float(blend_floor)
            if blend_cap is not None:
                self.blend_cap = float(blend_cap)
            if min_observations is not None:
                self.min_observations = int(min_observations)
            if replan_factor is not None:
                self.replan_factor = float(replan_factor)
            if ledger_path is not None and \
                    ledger_path != self.ledger_path:
                self.ledger_path = ledger_path
                self._load_ledger(ledger_path)

    def _load_ledger(self, path: str) -> None:
        if not os.path.exists(path):
            return
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if rec.get("event") != "observe":
                        continue
                    key = (rec.get("exec", ""), rec.get("sig", ""))
                    st = self._stats.setdefault(key, _SigStats())
                    st.add(rec.get("act_rows", 0) or 0,
                           rec.get("act_bytes", 0) or 0,
                           rec.get("rows_err"), rec.get("bytes_err"))
        except OSError as ex:
            log.warning("estimator ledger unreadable: %s", ex)

    def _append_ledger(self, rec: Dict) -> None:
        path = self.ledger_path
        if path is None:
            return
        rec = dict(rec, v=ESTIMATOR_LEDGER_VERSION,
                   ts=round(time.time(), 3), os_pid=os.getpid())
        try:
            with self._lock:
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(rec, sort_keys=True) + "\n")
        except OSError as ex:  # the ledger is telemetry, never fatal
            log.warning("estimator ledger append failed: %s", ex)

    # -- recording -----------------------------------------------------------
    def observe(self, exec_kind: str, sig: str,
                pred_rows, act_rows, pred_bytes, act_bytes,
                time_ns=None, pad_waste_bytes=None) -> None:
        """One closed operator span's predicted-vs-actual.  `time_ns`
        (measured span time) and `pad_waste_bytes` (capacity-padding
        bytes) feed the tpuxsan kernel-gap report; None = the trace did
        not carry them (old producers), never zero."""
        if not self.enabled:
            return
        rows_err = _rel_err(pred_rows, act_rows)
        bytes_err = _rel_err(pred_bytes, act_bytes)
        with self._lock:
            st = self._stats.setdefault((exec_kind, sig), _SigStats())
            st.add(act_rows, act_bytes, rows_err, bytes_err)
            self.observations += 1
            if rows_err is not None:
                self.rows_err_total += rows_err
            if bytes_err is not None:
                self.bytes_err_total += bytes_err
            calib = 1.0 / (1.0 + self.rows_err_total
                           / max(self.observations, 1))
        _fam_observations().labels(exec=exec_kind).inc()
        if rows_err is not None:
            _fam_abs_error().labels(exec=exec_kind).inc(rows_err)
        _fam_calibration().set(round(calib, 6))
        self._append_ledger({
            "event": "observe", "exec": exec_kind, "sig": sig,
            "pred_rows": None if pred_rows is None else int(pred_rows),
            "act_rows": int(act_rows),
            "pred_bytes": None if pred_bytes is None
            else int(pred_bytes),
            "act_bytes": int(act_bytes),
            "rows_err": None if rows_err is None
            else round(rows_err, 6),
            "bytes_err": None if bytes_err is None
            else round(bytes_err, 6),
            "time_ns": None if time_ns is None else int(time_ns),
            "pad_waste_bytes": None if pad_waste_bytes is None
            else int(pad_waste_bytes)})

    def observe_peak(self, static_bound, measured_peak) -> None:
        """Query-level measured peak device bytes vs the tmsan static
        bound — the calibration of the number admission tickets ride."""
        if not self.enabled or measured_peak is None:
            return
        err = _rel_err(static_bound, measured_peak)
        _fam_observations().labels(exec="__peak_hbm__").inc()
        if err is not None:
            _fam_abs_error().labels(exec="__peak_hbm__").inc(err)
        self._append_ledger({
            "event": "observe_peak",
            "static_bound": None if static_bound is None
            else int(static_bound),
            "measured_peak": int(measured_peak),
            "err": None if err is None else round(err, 6)})

    def record_query(self, predictions: Dict, actuals: Dict,
                     static_bound=None, measured_peak=None) -> int:
        """Distill one finished query: join the planner's per-node
        predictions against the trace's per-node operator actuals (both
        keyed by id(node)) and record every pair that carries an input
        signature.  Returns the number of observations taken."""
        if not self.enabled:
            return 0
        n = 0
        for nid, pred in (predictions or {}).items():
            act = (actuals or {}).get(nid)
            sig = pred.get("sig")
            if act is None or sig is None:
                continue
            self.observe(pred.get("node", "?"), sig,
                         pred.get("rows"), act.get("rows", 0),
                         pred.get("bytes"), act.get("bytes", 0),
                         time_ns=act.get("timeNs"),
                         pad_waste_bytes=act.get("padWasteBytes"))
            n += 1
        if measured_peak is not None:
            self.observe_peak(static_bound, measured_peak)
        return n

    def record_replan(self, decision: str, cause: str, **extra) -> None:
        """One exchange-boundary re-plan decision: the ledger sink of
        the triple (span + tpu_replan_total + ledger) the --feedback
        gate cross-checks."""
        with self._lock:
            self.replans += 1
        _fam_replans().labels(decision=decision, cause=cause).inc()
        rec = {"event": "replan", "decision": decision, "cause": cause}
        for k, v in extra.items():
            rec[k] = v
        self._append_ledger(rec)

    # -- feedback ------------------------------------------------------------
    def blend_rows(self, node, static_rows: float) -> Optional[float]:
        """Confidence-weight-blend the recorded mean actual row count
        for this node's signature into the static estimate, or None
        when feedback is off / the signature is unseen / too thin.
        w = clamp(n/(n+1), [blendFloor, blendCap]); the static model
        always keeps (1-w) so a stale ledger can be pulled back."""
        if not (self.enabled and self.feedback_enabled):
            return None
        key = (type(node).__name__, signature_of(node))
        with self._lock:
            st = self._stats.get(key)
            if st is None or st.n < self.min_observations:
                return None
            mean, n = st.mean_rows, st.n
        w = min(self.blend_cap,
                max(self.blend_floor, n / (n + 1.0)))
        return w * mean + (1.0 - w) * float(static_rows)

    def lookup(self, exec_kind: str, sig: str) -> Optional[_SigStats]:
        with self._lock:
            return self._stats.get((exec_kind, sig))

    # -- read side -----------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "observations": self.observations,
                "signatures": len(self._stats),
                "rows_err_total": round(self.rows_err_total, 6),
                "mean_rows_err": round(
                    self.rows_err_total / max(self.observations, 1), 6),
                "mean_bytes_err": round(
                    self.bytes_err_total / max(self.observations, 1), 6),
                "calibration_score": round(
                    1.0 / (1.0 + self.rows_err_total
                           / max(self.observations, 1)), 6),
                "replans": self.replans,
                "feedback_enabled": self.feedback_enabled,
            }


# ---------------------------------------------------------------------------
# metric families (created idempotently)
# ---------------------------------------------------------------------------

def _registry():
    from . import metrics
    return metrics.registry()


def _fam_observations():
    return _registry().counter(
        "tpu_estimator_observations_total",
        "predicted-vs-actual observations distilled into the "
        "estimator ledger", ("exec",), max_series=_EST_MAX_SERIES)


def _fam_abs_error():
    return _registry().counter(
        "tpu_estimator_abs_error_total",
        "cumulative relative row-estimate error "
        "(|pred-actual|/max(actual,1)); divide by observations for "
        "the mean", ("exec",), max_series=_EST_MAX_SERIES)


def _fam_calibration():
    return _registry().gauge(
        "tpu_estimator_calibration_score",
        "1/(1+mean abs relative row error): 1.0 = clairvoyant "
        "planner, ->0 = guessing")


def _fam_replans():
    return _registry().counter(
        "tpu_replan_total",
        "exchange-boundary re-plan decisions from measured map-stage "
        "partition stats", ("decision", "cause"))


def ledger() -> EstimatorLedger:
    return EstimatorLedger.get()
