"""HBM observatory: a tenant-attributed device-memory timeline.

The engine already *emits* every lifecycle transition that moves bytes
on or off the device — ``memory/spill.py`` (alloc / register / pin /
spill / unspill / materialize / close / evict), ``native/arena.py``
(staging-arena fills and resets) and ``memory/admission.py`` (ticket
grant / reprice / release) — but until now those streams only fed
end-state gauges and the memsan shadow ledger's peak.  Nobody could
answer "who held HBM at time t, and how much of it was demotable?".

``MemoryTimeline`` is a bounded, thread-safe subscriber to those
streams.  It maintains per-``(tenant, buffer class)`` occupancy series
where the buffer class is one of:

====================  ===================================================
``shuffle_block``     spill-registered shuffle partitions
                      (``SpillPriority.SHUFFLE``) — demotable
``working_set``       spill-registered operator working sets
                      (``ACTIVE`` / ``INPUT`` priorities) — demotable
``pinned_scan``       pinned scan/cache buffers (``register_pinned``) —
                      resident until evicted, *not* demotable
``broadcast``         raw (not spill-managed) broadcast-side retention —
                      closed-pending: freed only at plan release
``arena_staging``     host-side transfer-staging arena fill — reported
                      separately, excluded from the HBM split
====================  ===================================================

Tenant / query attribution comes from a thread-local context stack
pushed by ``session._execute`` (see :func:`push_context`).  Events that
arrive with no context are charged to the ``_unattributed`` tenant and
counted — the ``--hbm`` lint gate trips on any such allocation.

Samples (one per event, bounded ring) carry a ``perf_counter_ns``
timestamp on the same clock as ``QueryTrace.t0_ns`` so the exported
Chrome trace can stitch the occupancy curve under the span lanes as
Perfetto counter tracks (see ``obs/export.py``).  The timeline also
publishes ``tpu_hbm_*`` metrics and answers :meth:`report` — the
pinned / demotable / closed-pending split the admission controller's
queue and reprice decisions consume via ``hbm_holders()``.

Everything is disabled-cheap: when the observatory is off,
:func:`active_timeline` returns ``None`` and every hook site is a
single attribute load + ``is None`` test.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import metrics

# Buffer-class taxonomy (keep in sync with docs/observability.md).
SHUFFLE_BLOCK = "shuffle_block"
WORKING_SET = "working_set"
PINNED_SCAN = "pinned_scan"
BROADCAST = "broadcast"
ARENA_STAGING = "arena_staging"

BUFFER_CLASSES = (SHUFFLE_BLOCK, WORKING_SET, PINNED_SCAN, BROADCAST,
                  ARENA_STAGING)

# Device-resident classes, split the way admission wants to see them.
DEMOTABLE_CLASSES = (SHUFFLE_BLOCK, WORKING_SET)
PINNED_CLASSES = (PINNED_SCAN,)
CLOSED_PENDING_CLASSES = (BROADCAST,)
# Classes counted against the device (HBM) budget.  arena_staging is
# host-side transfer memory and is reported separately.
DEVICE_CLASSES = DEMOTABLE_CLASSES + PINNED_CLASSES + CLOSED_PENDING_CLASSES
# Classes the memsan shadow ledger also sees (it never observes raw
# broadcast retention) — the three-sinks-agree comparison uses this.
SPILL_BACKED_CLASSES = DEMOTABLE_CLASSES + PINNED_CLASSES

UNATTRIBUTED_TENANT = "_unattributed"

DEFAULT_MAX_SAMPLES = 4096


# ---------------------------------------------------------------------------
# tenant / query context (thread-local stack)

_CTX = threading.local()


def push_context(tenant: str, query: str = "") -> None:
    """Enter a (tenant, query) attribution scope on this thread."""
    stack = getattr(_CTX, "stack", None)
    if stack is None:
        stack = _CTX.stack = []
    stack.append((tenant or "default", query))


def pop_context() -> None:
    stack = getattr(_CTX, "stack", None)
    if stack:
        stack.pop()


def current_context() -> Optional[Tuple[str, str]]:
    """The innermost (tenant, query) scope on this thread, or None."""
    stack = getattr(_CTX, "stack", None)
    if stack:
        return stack[-1]
    return None


def _owning_operator() -> str:
    # Reuse memsan's frame walk: the nearest ``execute_partition`` /
    # ``_materialize`` caller names the operator responsible.
    try:
        from ..memory.memsan import _owning_exec
        return _owning_exec() or ""
    except Exception:
        return ""


class MemoryTimeline:
    """Process-wide occupancy timeline (singleton via :meth:`get`)."""

    _instance: Optional["MemoryTimeline"] = None
    _ilock = threading.Lock()

    def __init__(self, max_samples: int = DEFAULT_MAX_SAMPLES,
                 budget_bytes: int = 0) -> None:
        self._lock = threading.RLock()
        self.enabled = False
        self.max_samples = max_samples
        self.budget_bytes = budget_bytes
        with self._lock:
            self._reset_books()

    # -- lifecycle ----------------------------------------------------------

    @classmethod
    def get(cls) -> "MemoryTimeline":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = MemoryTimeline()
            return cls._instance

    @classmethod
    def configure(cls, enabled: bool = True,
                  max_samples: int = DEFAULT_MAX_SAMPLES,
                  budget_bytes: int = 0) -> "MemoryTimeline":
        tl = cls.get()
        with tl._lock:
            tl.enabled = enabled
            tl.max_samples = max(int(max_samples), 64)
            if budget_bytes:
                tl.budget_bytes = int(budget_bytes)
            tl._samples = deque(tl._samples, maxlen=tl.max_samples)
        if enabled:
            tl._publish_budget()
        return tl

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._ilock:
            cls._instance = None

    def _reset_books(self) -> None:
        # (tenant, class) -> live bytes
        self._series: Dict[Tuple[str, str], int] = {}
        # handle id -> [tenant, bclass, bytes-on-device, query, operator]
        self._handles: Dict[str, list] = {}
        # arena id -> {tenant: bytes}; arena id -> last observed `used`
        self._arena_books: Dict[str, Dict[str, int]] = {}
        self._arena_last: Dict[str, int] = {}
        # tenant -> admission-reserved bytes (tickets; not residency)
        self._admitted: Dict[str, int] = {}
        self._samples: deque = deque(maxlen=self.max_samples)
        self.total_live = 0           # device classes only
        self.peak_total = 0           # watermark incl. broadcast
        self.peak_spill = 0           # spill-backed only (== memsan view)
        self._tenant_live: Dict[str, int] = {}
        self._tenant_peak: Dict[str, int] = {}
        self._tenant_peak_demotable: Dict[str, int] = {}
        self.unattributed_total = 0
        self.samples_dropped = 0

    def clear(self) -> None:
        """Drop all books and samples (tests / gate replays)."""
        with self._lock:
            self._reset_books()

    # -- core accounting ----------------------------------------------------

    def _context(self) -> Tuple[str, str]:
        ctx = current_context()
        if ctx is None:
            with self._lock:
                self.unattributed_total += 1
            return UNATTRIBUTED_TENANT, ""
        return ctx

    def _apply(self, tenant: str, bclass: str, delta: int,
               query: str = "", operator: str = "") -> None:
        """Apply a byte delta under the lock, then emit outside it."""
        if delta == 0:
            return
        with self._lock:
            key = (tenant, bclass)
            self._series[key] = self._series.get(key, 0) + delta
            if self._series[key] <= 0:
                del self._series[key]
            if bclass in DEVICE_CLASSES:
                self.total_live += delta
                if self.total_live > self.peak_total:
                    self.peak_total = self.total_live
                live = self._tenant_live.get(tenant, 0) + delta
                if live > 0:
                    self._tenant_live[tenant] = live
                else:
                    self._tenant_live.pop(tenant, None)
                    live = 0
                if live > self._tenant_peak.get(tenant, 0):
                    self._tenant_peak[tenant] = live
                if bclass in SPILL_BACKED_CLASSES:
                    spill_live = sum(
                        v for (t, c), v in self._series.items()
                        if c in SPILL_BACKED_CLASSES)
                    if spill_live > self.peak_spill:
                        self.peak_spill = spill_live
                demo = sum(self._series.get((tenant, c), 0)
                           for c in DEMOTABLE_CLASSES)
                if demo > self._tenant_peak_demotable.get(tenant, 0):
                    self._tenant_peak_demotable[tenant] = demo
            if len(self._samples) == self._samples.maxlen:
                self.samples_dropped += 1
            self._samples.append({
                "t_ns": time.perf_counter_ns(),
                "tenant": tenant, "class": bclass, "delta": delta,
                "live": self._series.get((tenant, bclass), 0),
                "total": self.total_live,
                "query": query, "operator": operator,
            })
            live_now = self._series.get((tenant, bclass), 0)
        self._publish(tenant, bclass, live_now)
        self._emit_sample(tenant, bclass, live_now, query, operator)

    # -- event hooks (spill catalog) ---------------------------------------

    def on_alloc(self, handle_id: str, nbytes: int, bclass: str) -> None:
        tenant, query = self._context()
        op = _owning_operator()
        with self._lock:
            self._handles[handle_id] = [tenant, bclass, nbytes, query, op]
        self._apply(tenant, bclass, nbytes, query, op)

    # register is the same observation as alloc for already-built batches
    on_register = on_alloc

    def on_pin(self, handle_id: str, nbytes: int) -> None:
        self.on_alloc(handle_id, nbytes, PINNED_SCAN)

    def on_spill(self, handle_id: str, device_bytes_freed: int) -> None:
        with self._lock:
            rec = self._handles.get(handle_id)
            if rec is None or device_bytes_freed <= 0:
                return
            tenant, bclass = rec[0], rec[1]
            freed = min(device_bytes_freed, rec[2])
            rec[2] -= freed
            query, op = rec[3], rec[4]
        self._apply(tenant, bclass, -freed, query, op)

    def on_unspill(self, handle_id: str, nbytes: int) -> None:
        with self._lock:
            rec = self._handles.get(handle_id)
            if rec is None:
                return
            tenant, bclass = rec[0], rec[1]
            rec[2] += nbytes
            query, op = rec[3], rec[4]
        self._apply(tenant, bclass, nbytes, query, op)

    # a device-resident get() is a no-op for occupancy; materialize after
    # a spill comes back through on_unspill.
    def on_close(self, handle_id: str) -> None:
        with self._lock:
            rec = self._handles.pop(handle_id, None)
            if rec is None:
                return
            tenant, bclass, nbytes, query, op = rec
        if nbytes > 0:
            self._apply(tenant, bclass, -nbytes, query, op)

    # eviction of a pinned buffer frees its device bytes like a close
    on_evict = on_close

    # -- event hooks (broadcast raw retention) ------------------------------

    def on_broadcast(self, handle_id: str, nbytes: int) -> None:
        self.on_alloc(handle_id, nbytes, BROADCAST)

    on_broadcast_release = on_close

    # -- event hooks (staging arena) ----------------------------------------

    def on_arena_alloc(self, arena_id: str, used_now: int,
                       capacity: int) -> None:
        """Called after an arena alloc with the arena's new fill level.

        Deltas are computed as used-after differences so alignment
        padding reconciles exactly against ``tpu_arena_used_bytes``.
        """
        tenant, query = self._context()
        with self._lock:
            last = self._arena_last.get(arena_id, 0)
            delta = used_now - last
            self._arena_last[arena_id] = used_now
            if delta == 0:
                return
            book = self._arena_books.setdefault(arena_id, {})
            book[tenant] = book.get(tenant, 0) + delta
        if capacity > used_now:
            if metrics.enabled():
                metrics.histogram(
                    "tpu_hbm_arena_free_chunk_bytes",
                    "Free contiguous arena bytes observed at each "
                    "staging alloc (fragmentation proxy)",
                    buckets=metrics.DEFAULT_BYTES_BUCKETS,
                ).observe(capacity - used_now)
        self._apply(tenant, ARENA_STAGING, delta, query)

    def on_arena_reset(self, arena_id: str) -> None:
        """Arena reset/close: return every tenant's staging bytes."""
        with self._lock:
            book = self._arena_books.pop(arena_id, {})
            self._arena_last.pop(arena_id, None)
        for tenant, nbytes in book.items():
            if nbytes:
                self._apply(tenant, ARENA_STAGING, -nbytes)

    # -- event hooks (admission tickets) ------------------------------------

    def note_ticket(self, tenant: str, delta: int) -> None:
        """Track admission reservations (grant/reprice/release)."""
        tenant = tenant or "default"
        with self._lock:
            cur = self._admitted.get(tenant, 0) + delta
            if cur > 0:
                self._admitted[tenant] = cur
            else:
                self._admitted.pop(tenant, None)
                cur = 0
        from . import tracer
        tr = tracer.active_tracer()
        if tr is not None:
            tr.event("hbm.admitted", tenant=tenant, bytes=cur)

    # -- export -------------------------------------------------------------

    def _publish(self, tenant: str, bclass: str, live: int) -> None:
        if not metrics.enabled():
            return
        metrics.gauge("tpu_hbm_tenant_bytes",
                      "Live device/staging bytes per tenant and buffer "
                      "class", ("tenant", "class")).labels(
                          tenant=tenant, **{"class": bclass}).set(live)
        with self._lock:
            total = self.total_live
            demotable = sum(v for (t, c), v in self._series.items()
                            if c in DEMOTABLE_CLASSES)
            peak = self.peak_total
        metrics.gauge("tpu_hbm_total_bytes",
                      "Live device bytes across all tenants").set(total)
        metrics.gauge("tpu_hbm_demotable_bytes",
                      "Device bytes spillable right now (shuffle + "
                      "working set)").set(demotable)
        metrics.gauge("tpu_hbm_watermark_bytes",
                      "High-water mark of live device bytes").set(peak)

    def _publish_budget(self) -> None:
        if self.budget_bytes and metrics.enabled():
            metrics.gauge("tpu_hbm_budget_bytes",
                          "Configured device memory budget").set(
                              self.budget_bytes)

    def _emit_sample(self, tenant: str, bclass: str, live: int,
                     query: str, operator: str) -> None:
        from . import tracer
        tr = tracer.active_tracer()
        if tr is None:
            return
        attrs = {"tenant": tenant, "cls": bclass, "bytes": live}
        if query:
            attrs["query"] = query
        if operator:
            attrs["operator"] = operator
        tr.event("hbm.sample", **attrs)

    # -- queries ------------------------------------------------------------

    def live_bytes(self, bclass: Optional[str] = None,
                   tenant: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                v for (t, c), v in self._series.items()
                if (bclass is None or c == bclass)
                and (tenant is None or t == tenant))

    def spill_backed_bytes(self) -> int:
        """Live bytes in the classes the spill catalog also gauges."""
        with self._lock:
            return sum(v for (t, c), v in self._series.items()
                       if c in SPILL_BACKED_CLASSES)

    def arena_bytes(self) -> int:
        with self._lock:
            return sum(v for (t, c), v in self._series.items()
                       if c == ARENA_STAGING)

    def report(self) -> dict:
        """The pinned / demotable / closed-pending occupancy split.

        This is the "who holds what" answer the admission controller's
        queue and reprice decisions consume (``hbm_holders()``), and the
        payload behind ``session.hbm_report()``.
        """
        with self._lock:
            tenants: Dict[str, dict] = {}
            for (tenant, bclass), live in sorted(self._series.items()):
                row = tenants.setdefault(tenant, {
                    "classes": {}, "pinned_bytes": 0,
                    "demotable_bytes": 0, "closed_pending_bytes": 0,
                    "arena_staging_bytes": 0, "resident_bytes": 0,
                    "admitted_bytes": 0, "peak_bytes": 0,
                })
                row["classes"][bclass] = live
                if bclass in PINNED_CLASSES:
                    row["pinned_bytes"] += live
                elif bclass in DEMOTABLE_CLASSES:
                    row["demotable_bytes"] += live
                elif bclass in CLOSED_PENDING_CLASSES:
                    row["closed_pending_bytes"] += live
                elif bclass == ARENA_STAGING:
                    row["arena_staging_bytes"] += live
                if bclass in DEVICE_CLASSES:
                    row["resident_bytes"] += live
            for tenant, nbytes in self._admitted.items():
                row = tenants.setdefault(tenant, {
                    "classes": {}, "pinned_bytes": 0,
                    "demotable_bytes": 0, "closed_pending_bytes": 0,
                    "arena_staging_bytes": 0, "resident_bytes": 0,
                    "admitted_bytes": 0, "peak_bytes": 0,
                })
                row["admitted_bytes"] = nbytes
            for tenant, row in tenants.items():
                row["peak_bytes"] = self._tenant_peak.get(tenant, 0)
                row["peak_demotable_bytes"] = \
                    self._tenant_peak_demotable.get(tenant, 0)
            return {
                "enabled": self.enabled,
                "total_bytes": self.total_live,
                "peak_bytes": self.peak_total,
                "peak_spill_backed_bytes": self.peak_spill,
                "demotable_bytes": sum(
                    r["demotable_bytes"] for r in tenants.values()),
                "budget_bytes": self.budget_bytes,
                "unattributed_events": self.unattributed_total,
                "tenants": tenants,
            }

    def window(self, last: int = 256) -> List[dict]:
        """The most recent ``last`` samples (post-mortem window)."""
        with self._lock:
            samples = list(self._samples)
        return samples[-last:]

    def sample_count(self) -> int:
        with self._lock:
            return len(self._samples)


def active_timeline() -> Optional[MemoryTimeline]:
    """The process timeline iff the observatory is enabled, else None.

    Hook sites call this on every event — it must stay allocation-free
    and cheap on the disabled path.
    """
    tl = MemoryTimeline._instance
    if tl is not None and tl.enabled:
        return tl
    return None
