"""Fleet observatory: the CROSS-PROCESS half of the observability
story.

Everything in obs/ up to here is process-local — one QueryTrace, one
MetricsRegistry, one ``/metrics`` endpoint.  A distributed shuffle
(shuffle/transport.py serving another OS process's reduce reads) made
that a blind spot: the consumer's trace shows one opaque fetch span
while the producer's decode/catalog/serialize/compress/send work is
invisible, and no endpoint can answer "how is the CLUSTER doing".

Four pieces close the gap:

* ``TraceContext`` — the (trace_id, span_id, tenant) triple a consumer
  threads through the shuffle wire protocol (transport.py's v2 frame
  extension) so the producer can parent its serve spans under the
  requesting query's fetch span.
* ``RemoteSpanStore`` — the producer-side buffer of serve spans keyed
  by trace_id, bounded two ways (traces x spans-per-trace, evictions
  counted), drained by the consumer through the ``/spans`` pull
  endpoint obs/health.py serves next to ``/metrics``.
* ``ClockSync`` — per-peer clock-offset estimates from the transport's
  NTP-style four-timestamp hello handshake.  Both sides stamp with
  ``time.perf_counter_ns``, whose epoch is ARBITRARY PER PROCESS, so
  merging remote spans without the offset is not "slightly skewed", it
  is nonsense; ``offset = ((t1-t0)+(t2-t3))/2`` maps the server's clock
  domain onto the client's.
* ``FleetAggregator`` — driver-side: walks the heartbeat peer registry,
  scrapes each live peer's ``/metrics`` + ``/healthz``, re-exposes a
  bounded-cardinality rollup (``peer`` label, capped peer count) on the
  driver's own registry, and derives a fleet verdict: any peer that was
  seen alive and is now dead, unreachable, or self-reporting unhealthy
  degrades the fleet.
"""

from __future__ import annotations

import json
import struct
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

# wire format of the packed context blob carried by v2 request frames:
# 16 raw trace-id bytes, u64 parent span id, tenant length + utf-8
_CTX = struct.Struct("<16sQB")
_MAX_TENANT = 64


def remote_merged_counter():
    from . import metrics as m
    return m.counter("tpu_trace_remote_spans_merged_total",
                     "producer-side serve spans merged into a consumer "
                     "trace via the /spans pull path")


def remote_lost_counter():
    from . import metrics as m
    return m.counter("tpu_trace_remote_spans_lost_total",
                     "remote fetches whose producer spans could not be "
                     "recovered (peer died or /spans pull failed); the "
                     "fetch span closes with a spans_lost annotation "
                     "instead of dangling")


class TraceContext:
    """What crosses the wire: enough to parent remote spans, nothing
    else (no payloads, no attrs — the context must stay header-sized)."""

    __slots__ = ("trace_id", "span_id", "tenant")

    def __init__(self, trace_id: str, span_id: int, tenant: str = ""):
        self.trace_id = trace_id  # 32-char hex
        self.span_id = int(span_id)
        self.tenant = tenant[:_MAX_TENANT]

    def pack(self) -> bytes:
        tb = self.tenant.encode()[:_MAX_TENANT]
        return _CTX.pack(bytes.fromhex(self.trace_id), self.span_id,
                         len(tb)) + tb

    @classmethod
    def unpack(cls, blob: bytes) -> "TraceContext":
        tid, sid, tlen = _CTX.unpack_from(blob, 0)
        tenant = blob[_CTX.size:_CTX.size + tlen].decode(errors="replace")
        return cls(tid.hex(), sid, tenant)

    def __repr__(self):
        return (f"TraceContext({self.trace_id[:8]}…, span={self.span_id}"
                + (f", tenant={self.tenant!r})" if self.tenant else ")"))


def new_trace_id() -> str:
    return uuid.uuid4().hex


# ---------------------------------------------------------------------------
# producer side: bounded serve-span buffer behind /spans
# ---------------------------------------------------------------------------

class RemoteSpanStore:
    """Serve spans recorded on behalf of remote traces, keyed by
    trace_id, awaiting pull.

    Bounded the same way the tracer and the metrics registry are: at
    most ``max_traces`` distinct trace buckets (oldest evicted) and
    ``max_per_trace`` spans per bucket (new spans dropped); every loss
    is counted, never silent.  Span dicts are in THIS process's
    ``perf_counter_ns`` domain — the puller owns skew correction."""

    _instance: Optional["RemoteSpanStore"] = None
    _class_lock = threading.Lock()

    def __init__(self, max_traces: int = 64, max_per_trace: int = 512):
        self.max_traces = max_traces
        self.max_per_trace = max_per_trace
        self._lock = threading.Lock()
        self._by_trace: Dict[str, List[Dict[str, Any]]] = {}
        self._ids = iter(range(1, 1 << 62))
        self.dropped = 0
        self.evicted_traces = 0

    @classmethod
    def get(cls) -> "RemoteSpanStore":
        with cls._class_lock:
            if cls._instance is None:
                cls._instance = RemoteSpanStore()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._class_lock:
            cls._instance = None

    def configure(self, max_traces: int, max_per_trace: int) -> None:
        with self._lock:
            self.max_traces = max(1, int(max_traces))
            self.max_per_trace = max(1, int(max_per_trace))

    def next_span_id(self) -> int:
        with self._lock:
            return next(self._ids)

    def add(self, trace_id: str, span: Dict[str, Any]) -> None:
        from . import metrics as m
        with self._lock:
            bucket = self._by_trace.get(trace_id)
            if bucket is None:
                if len(self._by_trace) >= self.max_traces:
                    # evict the oldest trace: an abandoned consumer must
                    # not pin producer memory forever
                    oldest = next(iter(self._by_trace))
                    self._by_trace.pop(oldest)
                    self.evicted_traces += 1
                bucket = self._by_trace[trace_id] = []
            if len(bucket) >= self.max_per_trace:
                self.dropped += 1
                m.counter("tpu_trace_remote_spans_dropped_total",
                          "producer serve spans dropped past the "
                          "RemoteSpanStore bounds").inc()
                return
            bucket.append(span)

    def drain(self, trace_id: str) -> List[Dict[str, Any]]:
        """Pull semantics: handing the spans over removes them, so a
        repeated pull (retried fetch group) never double-merges."""
        with self._lock:
            return self._by_trace.pop(trace_id, [])

    def peek_all(self) -> Dict[str, List[Dict[str, Any]]]:
        with self._lock:
            return {k: list(v) for k, v in self._by_trace.items()}

    def span_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._by_trace.values())

    def to_json(self, trace_id: Optional[str] = None,
                drain: bool = False) -> str:
        if trace_id:
            spans = self.drain(trace_id) if drain \
                else self.peek_all().get(trace_id, [])
            return json.dumps({"traceId": trace_id, "spans": spans,
                               "dropped": self.dropped})
        return json.dumps({"traces": self.peek_all(),
                           "dropped": self.dropped,
                           "evictedTraces": self.evicted_traces})


class ServeSpanRecorder:
    """Producer-side span builder: one per served request that carried
    a TraceContext.  Records a root serve span parented (remotely)
    under the consumer's fetch span plus per-step children, all in this
    process's clock domain, then deposits them in the RemoteSpanStore
    at close."""

    def __init__(self, ctx: TraceContext, name: str, proc: str,
                 store: Optional[RemoteSpanStore] = None, **attrs):
        self.ctx = ctx
        self.store = store or RemoteSpanStore.get()
        self._spans: List[Dict[str, Any]] = []
        self._root_id = self.store.next_span_id()
        self._t0 = time.perf_counter_ns()
        self._root = {"spanId": self._root_id, "parentId": ctx.span_id,
                      "remoteParent": True, "name": name, "kind": "span",
                      "t0Ns": self._t0, "t1Ns": None, "status": "open",
                      "proc": proc, "attrs": dict(attrs)}
        if ctx.tenant:
            self._root["attrs"]["tenant"] = ctx.tenant
        self._spans.append(self._root)

    def step(self, name: str, t0_ns: int, t1_ns: int, **attrs) -> None:
        self._spans.append({
            "spanId": self.store.next_span_id(),
            "parentId": self._root_id, "remoteParent": False,
            "name": name, "kind": "span", "t0Ns": t0_ns, "t1Ns": t1_ns,
            "status": "ok", "proc": self._root["proc"],
            "attrs": dict(attrs)})

    def set_attrs(self, **attrs) -> None:
        self._root["attrs"].update(attrs)

    def close(self, status: str = "ok",
              error: Optional[str] = None) -> None:
        self._root["t1Ns"] = time.perf_counter_ns()
        self._root["status"] = status
        if error:
            self._root["error"] = error
        for sp in self._spans:
            self.store.add(self.ctx.trace_id, sp)


# ---------------------------------------------------------------------------
# clock alignment
# ---------------------------------------------------------------------------

class ClockSync:
    """Per-peer clock-offset registry fed by the transport hello
    handshake.  ``offset_ns(peer)`` is how far the peer's
    perf_counter_ns clock runs AHEAD of ours: a peer timestamp maps
    into our domain as ``t_local = t_peer - offset``."""

    _instance: Optional["ClockSync"] = None
    _class_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._offsets: Dict[str, int] = {}
        self._rtts: Dict[str, int] = {}

    @classmethod
    def get(cls) -> "ClockSync":
        with cls._class_lock:
            if cls._instance is None:
                cls._instance = ClockSync()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._class_lock:
            cls._instance = None

    @staticmethod
    def estimate(t0: int, t1: int, t2: int, t3: int) -> Tuple[int, int]:
        """NTP four-timestamp estimate over one request/response pair:
        t0 client send, t1 server receive, t2 server send, t3 client
        receive (t0/t3 client clock, t1/t2 server clock).  Returns
        (offset_ns, rtt_ns); the offset's error is bounded by rtt/2."""
        offset = ((t1 - t0) + (t2 - t3)) // 2
        rtt = (t3 - t0) - (t2 - t1)
        return offset, rtt

    def observe(self, peer: str, t0: int, t1: int, t2: int, t3: int
                ) -> int:
        offset, rtt = self.estimate(t0, t1, t2, t3)
        with self._lock:
            # keep the estimate with the smallest rtt: its offset error
            # bound (rtt/2) is the tightest we have seen for this peer
            best = self._rtts.get(peer)
            if best is None or rtt < best:
                self._offsets[peer] = offset
                self._rtts[peer] = rtt
            return self._offsets[peer]

    def offset_ns(self, peer: str) -> Optional[int]:
        with self._lock:
            return self._offsets.get(peer)

    def rtt_ns(self, peer: str) -> Optional[int]:
        with self._lock:
            return self._rtts.get(peer)


# ---------------------------------------------------------------------------
# tenant plumb-through (serving sets it; single-tenant leaves it empty)
# ---------------------------------------------------------------------------

_TENANT_TLS = threading.local()


def set_tenant(tenant: str) -> None:
    _TENANT_TLS.tenant = tenant


def current_tenant() -> str:
    return getattr(_TENANT_TLS, "tenant", "") or ""


# ---------------------------------------------------------------------------
# driver side: peer scraping + rollup + fleet verdict
# ---------------------------------------------------------------------------

#: peer families re-exposed on the driver as tpu_fleet_rollup{peer,name}.
#: A fixed allowlist keeps the rollup's cardinality at
#: len(ROLLUP_FAMILIES) x maxPeers no matter what a peer exposes.
ROLLUP_FAMILIES = (
    "tpu_shuffle_server_requests_total",
    "tpu_shuffle_fetch_blocks_total",
    "tpu_shuffle_fetch_bytes_total",
    "tpu_trace_spans_total",
    "tpu_queries_completed_total",
    "tpu_queries_failed_total",
)


def parse_prometheus_totals(text: str) -> Dict[str, float]:
    """Family -> summed value over every series, from Prometheus text
    exposition.  Histogram internals (_bucket/_sum/_count) fold into
    their family's _count so rollups stay order-of-magnitude readable."""
    totals: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            name_part, value_part = line.rsplit(None, 1)
            value = float(value_part)
        except ValueError:
            continue
        name = name_part.split("{", 1)[0]
        if name.endswith("_bucket") or name.endswith("_sum"):
            continue
        if name.endswith("_count"):
            name = name[:-len("_count")]
        totals[name] = totals.get(name, 0.0) + value
    return totals


def _http_get(host: str, port: int, path: str, timeout_s: float) -> str:
    import urllib.request
    url = f"http://{host}:{port}{path}"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode(errors="replace")


def pull_remote_spans(host: str, obs_port: int, trace_id: str,
                      timeout_s: float = 2.0) -> List[Dict[str, Any]]:
    """Drain one trace's serve spans from a peer's /spans endpoint.
    Raises on any transport/parse failure — the caller owns the
    spans_lost accounting."""
    body = _http_get(host, int(obs_port),
                     f"/spans?trace_id={trace_id}&drain=1", timeout_s)
    doc = json.loads(body)
    return list(doc.get("spans") or [])


class FleetAggregator:
    """Walks the heartbeat registry, scrapes each live peer, re-exposes
    the rollup on THIS process's registry, and keeps the fleet verdict.

    Peer label cardinality is bounded twice: ``max_peers`` caps how many
    peers are scraped per round (excess peers are counted, not labeled),
    and the registry's own series cap backstops the families."""

    def __init__(self, heartbeat, max_peers: int = 16,
                 timeout_s: float = 2.0):
        self.heartbeat = heartbeat
        self.max_peers = max(1, int(max_peers))
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._seen: Dict[str, Dict[str, Any]] = {}  # every peer ever live
        self._last: Dict[str, Dict[str, Any]] = {}

    # -- one scrape round ----------------------------------------------------
    def scrape(self) -> Dict[str, Any]:
        from . import metrics as m
        self.heartbeat.expire_dead()
        live = self.heartbeat.live_peers()
        up_g = m.gauge("tpu_fleet_peer_up",
                       "1 when the peer's /metrics endpoint answered "
                       "the last scrape, 0 when it did not", ("peer",))
        rollup_g = m.registry().gauge(
            "tpu_fleet_rollup",
            "per-peer rollup of allowlisted families scraped from "
            "each peer's /metrics", ("peer", "name"),
            max_series=self.max_peers * (len(ROLLUP_FAMILIES) + 1))
        scrapes_c = m.counter("tpu_fleet_scrapes_total",
                              "peer scrape attempts by outcome",
                              ("status",))
        peers: Dict[str, Dict[str, Any]] = {}
        skipped = 0
        for i, p in enumerate(live):
            if i >= self.max_peers:
                skipped += 1
                continue
            entry: Dict[str, Any] = {"host": p.host, "port": p.port,
                                     "obs_port": getattr(p, "obs_port",
                                                         0),
                                     "live": True, "scraped": False,
                                     "health": None}
            obs_port = entry["obs_port"]
            if obs_port:
                try:
                    text = _http_get(p.host, obs_port, "/metrics",
                                     self.timeout_s)
                    totals = parse_prometheus_totals(text)
                    for fam in ROLLUP_FAMILIES:
                        if fam in totals:
                            rollup_g.labels(peer=p.executor_id,
                                            name=fam).set(totals[fam])
                    health = json.loads(_http_get(
                        p.host, obs_port, "/healthz", self.timeout_s))
                    entry["health"] = health.get("status")
                    entry["scraped"] = True
                    scrapes_c.labels(status="ok").inc()
                except Exception as ex:
                    entry["error"] = repr(ex)
                    scrapes_c.labels(status="error").inc()
            up_g.labels(peer=p.executor_id).set(
                1 if entry["scraped"] else 0)
            peers[p.executor_id] = entry
        with self._lock:
            for pid, entry in peers.items():
                self._seen[pid] = entry
            # a peer seen alive before and absent from the live set now
            # is DEAD — it stays in the report (and the verdict) until
            # forget_peer()
            for pid in self._seen:
                if pid not in peers:
                    dead = dict(self._seen[pid])
                    dead["live"] = False
                    dead["scraped"] = False
                    self._seen[pid] = dead
                    peers[pid] = dead
                    up_g.labels(peer=pid).set(0)
            self._last = peers
        m.gauge("tpu_fleet_peers_live",
                "heartbeat-live peers at the last aggregator scrape") \
            .set(sum(1 for e in peers.values() if e["live"]))
        if skipped:
            m.counter("tpu_fleet_peers_skipped_total",
                      "live peers beyond fleet.scrape.maxPeers left "
                      "out of a scrape round").inc(skipped)
        return peers

    def forget_peer(self, executor_id: str) -> None:
        with self._lock:
            self._seen.pop(executor_id, None)
            self._last.pop(executor_id, None)

    # -- verdict -------------------------------------------------------------
    def verdict(self, scrape_first: bool = True) -> Dict[str, Any]:
        """Fleet health: ok only when every peer ever seen is still
        heartbeat-live, scrapeable, and self-reports ok."""
        peers = self.scrape() if scrape_first else dict(self._last)
        status = "ok"
        reasons: List[str] = []
        for pid, e in sorted(peers.items()):
            if not e.get("live"):
                status = "degraded"
                reasons.append(f"{pid}: dead (heartbeat expired)")
            elif e.get("obs_port") and not e.get("scraped"):
                status = "degraded"
                reasons.append(f"{pid}: unreachable "
                               f"({e.get('error', 'scrape failed')})")
            elif e.get("health") not in (None, "ok"):
                status = "degraded"
                reasons.append(f"{pid}: self-reports {e['health']}")
        return {"status": status, "peers": peers, "reasons": reasons}


# ---------------------------------------------------------------------------
# installation (what obs/health.py consults)
# ---------------------------------------------------------------------------

_AGGREGATOR: Optional[FleetAggregator] = None
_AGG_LOCK = threading.Lock()


def install_aggregator(agg: Optional[FleetAggregator]
                       ) -> Optional[FleetAggregator]:
    global _AGGREGATOR
    with _AGG_LOCK:
        _AGGREGATOR = agg
        return agg


def installed_aggregator() -> Optional[FleetAggregator]:
    with _AGG_LOCK:
        return _AGGREGATOR


def fleet_refresh() -> None:
    """Refresh the rollup series before an exposition read (no-op when
    no aggregator is installed; a scrape failure must never fail the
    endpoint serving it)."""
    agg = installed_aggregator()
    if agg is not None:
        try:
            agg.scrape()
        except Exception:
            pass
