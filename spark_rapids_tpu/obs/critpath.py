"""Critical-path extraction: decompose a query's wall time into an
exhaustive, non-overlapping latency-segment taxonomy.

The tracer (obs/tracer.py) records *where* time was spent as a span
tree; admission (memory/admission.py) records *that* queries queued;
``--serve`` reports aggregate p50/p99.  None of them can explain a
p99.  This module walks one query's **closed** span tree — the neutral
``span_dicts()`` schema, so it works identically on a live trace, a
replayed event log, or hand-built test fixtures — and partitions the
root span's wall-clock interval into named segments:

==================  =====================================================
segment             booked from
==================  =====================================================
``queue_wait``      ``admission.wait`` spans (byte-weighted admission)
``planning``        ``phase:plan`` / ``phase:planning`` /
                    ``phase:overrides`` / ``phase:subqueries`` /
                    ``phase:plan-retry`` / ``replan`` self-time
``compile``         synthetic intervals reconstructed from enriched
                    ``jit.build`` instant events (``total_s`` attr)
``prewarm``         same, when the build's ``cause`` is ``prewarm``
``host_assist``     ``phase:host_assist`` self-time (fetch crossings)
``compute:<Kind>``  operator-kind spans (``FilterExec`` etc.) self-time
``shuffle_write``   ``shuffle.map_write`` self-time
``fetch_wire``      ``shuffle.fetch`` self-time — time on the wire
                    after subtracting grafted producer-serve spans
``fetch_serve``     remote spans grafted by the fleet observatory
                    (``proc`` set): producer-side serve time
``oc_spill``        ``oc.sort_run`` / ``oc.merge`` /
                    ``oc.merge_partials`` — out-of-core spill + merge
``other``           root / ``phase:execute`` / bridge self-time
==================  =====================================================

**No double-booking.**  Concurrent children (per-partition execute
spans, parallel shuffle fetches) overlap in wall time; summing their
durations would book the same second twice.  The sweep instead
partitions every parent interval among its children: each elementary
slice is assigned to the covering child that *ends last* — the child
on the longest dependency chain to query completion, i.e. the
critical path — and only uncovered slices count as the parent's own
self-time.  The result is an exact partition of the root interval, so
segments sum to wall time by construction; the tolerance gate in
:func:`extract_critical_path` exists to catch algorithm bugs (an
unclipped child, a negative interval), not rounding.

The breakdown is triple-sunk by :func:`record_query_latency`: a
``critical_path`` annotation on the root span (rendered by Perfetto
via the chrome ``args``), ``tpu_latency_segment_seconds_total
{segment,tenant}`` counters (bounded cardinality: the family is
created with ``max_series=256`` so 4 tenants x ~40 segments does not
overflow into ``_overflow``), and a per-query record in the regress
HistoryDir's latency ledger via obs/slo.py.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

SEG_QUEUE_WAIT = "queue_wait"
SEG_PLANNING = "planning"
SEG_COMPILE = "compile"
SEG_PREWARM = "prewarm"
SEG_HOST_ASSIST = "host_assist"
SEG_SHUFFLE_WRITE = "shuffle_write"
SEG_FETCH_WIRE = "fetch_wire"
SEG_FETCH_SERVE = "fetch_serve"
SEG_OC_SPILL = "oc_spill"
SEG_OTHER = "other"
COMPUTE_PREFIX = "compute:"

#: reconciliation gate: |wall - sum(segments)| must stay under this
#: fraction of wall (plus an absolute floor for micro-queries).
RECONCILE_TOLERANCE = 0.05
RECONCILE_FLOOR_S = 0.001

_PLANNING_NAMES = frozenset((
    "phase:plan", "phase:planning", "phase:overrides",
    "phase:subqueries", "phase:plan-retry", "replan",
))

_OC_PREFIX = "oc."


def segment_of(span: dict) -> str:
    """Map one span dict to its latency segment.

    Grafted remote spans carry ``proc`` (the producing process) and
    classify as producer-serve time regardless of name — a remote
    operator span is the *producer's* compute, not ours; what we
    waited on is the serve."""
    if span.get("proc"):
        return SEG_FETCH_SERVE
    name = span.get("name", "")
    if name == "admission.wait":
        return SEG_QUEUE_WAIT
    if name in _PLANNING_NAMES:
        return SEG_PLANNING
    if name == "phase:host_assist":
        return SEG_HOST_ASSIST
    if name == "jit.build":  # synthetic compile interval (see below)
        attrs = span.get("attrs") or {}
        return SEG_PREWARM if attrs.get("cause") == "prewarm" else SEG_COMPILE
    if name == "shuffle.map_write":
        return SEG_SHUFFLE_WRITE
    if name == "shuffle.fetch":
        return SEG_FETCH_WIRE
    if name.startswith(_OC_PREFIX):
        return SEG_OC_SPILL
    if span.get("kind") == "operator":
        attrs = span.get("attrs") or {}
        op = attrs.get("op") or name.split(".", 1)[0]
        return COMPUTE_PREFIX + str(op)
    return SEG_OTHER


def _synthesize_compile_children(spans: Sequence[dict]) -> List[dict]:
    """jit compile time hides inside whatever span was open when the
    build ran: the compile observatory emits ``jit.build`` as an
    *instant* event carrying ``total_s``.  Reconstruct each build as a
    zero-API child interval ``[event_t0 - total_s, event_t0]`` of the
    event's parent so the sweep books it as ``compile`` (or
    ``prewarm``) instead of silently inflating operator self-time."""
    out = []
    for i, s in enumerate(spans):
        if s.get("name") != "jit.build":
            continue
        attrs = s.get("attrs") or {}
        total_s = attrs.get("total_s")
        if not total_s or total_s <= 0:
            continue
        total_ns = int(total_s * 1e9)
        t1 = int(s.get("startNs", 0))
        out.append({
            "spanId": -(i + 1),  # disjoint from real span ids (>= 1)
            "parentId": s.get("parentId"),
            "name": "jit.build",
            "kind": "span",
            "startNs": t1 - total_ns,
            "durNs": total_ns,
            "attrs": {"cause": attrs.get("cause")},
        })
    return out


def extract_critical_path(spans: Sequence[dict],
                          tolerance: float = RECONCILE_TOLERANCE
                          ) -> Dict[str, object]:
    """Partition the query root's wall interval into latency segments.

    ``spans`` is the ``QueryTrace.span_dicts()`` list (closed trace).
    Returns ``{"segments": {name: seconds}, "wall_s", "covered_s",
    "residual_s", "reconciled"}``.  Failed queries reconcile too: an
    error span mid-tree still has a closed interval (``finalize``
    closes open spans on the way out)."""
    root = None
    for s in spans:
        if s.get("kind") == "query":
            root = s
            break
    if root is None or not root.get("durNs"):
        return {"segments": {}, "wall_s": 0.0, "covered_s": 0.0,
                "residual_s": 0.0, "reconciled": True}

    work = list(spans) + _synthesize_compile_children(spans)
    by_id: Dict[object, dict] = {}
    children: Dict[object, List[dict]] = {}
    for s in work:
        if s.get("kind") == "event" or not s.get("durNs"):
            continue  # instants and zero-length spans own no wall time
        s = dict(s)
        s["_t0"] = int(s.get("startNs", 0))
        s["_t1"] = s["_t0"] + int(s.get("durNs", 0))
        by_id[s["spanId"]] = s
        children.setdefault(s.get("parentId"), []).append(s)

    root = by_id[root["spanId"]]
    seg_ns: Dict[str, int] = {}

    def attribute(span: dict, windows: List[List[int]]) -> None:
        kids = children.get(span["spanId"], ())
        kid_windows: Dict[object, List[List[int]]] = {}
        for lo, hi in windows:
            entries = []
            for k in kids:
                k0, k1 = max(k["_t0"], lo), min(k["_t1"], hi)
                if k1 > k0:
                    entries.append((k0, k1, k))
            if not entries:
                seg = segment_of(span)
                seg_ns[seg] = seg_ns.get(seg, 0) + (hi - lo)
                continue
            bounds = {lo, hi}
            for k0, k1, _ in entries:
                bounds.add(k0)
                bounds.add(k1)
            bounds = sorted(bounds)
            for a, b in zip(bounds, bounds[1:]):
                covering = [e for e in entries if e[0] <= a and e[1] >= b]
                if not covering:
                    seg = segment_of(span)
                    seg_ns[seg] = seg_ns.get(seg, 0) + (b - a)
                    continue
                # ends-last = the longest dependency chain to completion
                owner = max(covering, key=lambda e: (e[1], e[2]["spanId"]))
                wins = kid_windows.setdefault(owner[2]["spanId"], [])
                if wins and wins[-1][1] == a:
                    wins[-1][1] = b  # merge contiguous slices
                else:
                    wins.append([a, b])
        for kid_id, wins in kid_windows.items():
            attribute(by_id[kid_id], wins)

    attribute(root, [[root["_t0"], root["_t1"]]])

    segments = {k: v / 1e9 for k, v in sorted(seg_ns.items()) if v > 0}
    wall_s = root["durNs"] / 1e9
    covered_s = sum(segments.values())
    residual_s = wall_s - covered_s
    reconciled = abs(residual_s) <= max(tolerance * wall_s, RECONCILE_FLOOR_S)
    return {"segments": segments, "wall_s": wall_s, "covered_s": covered_s,
            "residual_s": residual_s, "reconciled": reconciled}


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------

SEGMENT_FAMILY = "tpu_latency_segment_seconds_total"
EXTRACT_FAMILY = "tpu_latency_extract_seconds_total"

#: 4 pool tenants x ~40 segments (compute:<Kind> fan-out) exceeds the
#: registry's 64-series default; a bigger explicit cap keeps every real
#: series out of ``_overflow`` while still bounding cardinality.
SEGMENT_MAX_SERIES = 256


def _segment_counter():
    from .metrics import MetricsRegistry
    return MetricsRegistry.get().counter(
        SEGMENT_FAMILY,
        "Critical-path wall seconds attributed to each latency segment, "
        "per tenant (obs/critpath.py).",
        ("segment", "tenant"), max_series=SEGMENT_MAX_SERIES)


def record_query_latency(tracer, tenant: str, error: Optional[BaseException]
                         = None, label: str = "") -> Optional[dict]:
    """Extract the critical path from a finalized trace and fan it out
    to all three sinks.  Called from the session's query-obs flush;
    advisory — never raises into the query path."""
    from .slo import LatencyObservatory
    t_start = time.perf_counter()
    res = extract_critical_path(tracer.span_dicts())
    if not res["segments"] and res["wall_s"] == 0.0:
        return None
    tenant = tenant or "default"
    # sink 1: root-span annotation -> chrome args -> Perfetto
    tracer.add_attrs(
        tracer.root_id,
        critical_path={k: round(v, 6) for k, v in res["segments"].items()},
        critical_path_reconciled=res["reconciled"],
        critical_path_residual_s=round(res["residual_s"], 6))
    # sink 2: bounded-cardinality counters
    fam = _segment_counter()
    for seg, sec in res["segments"].items():
        fam.labels(segment=seg, tenant=tenant).inc(sec)
    extract_s = time.perf_counter() - t_start
    from .metrics import MetricsRegistry
    MetricsRegistry.get().counter(
        EXTRACT_FAMILY,
        "Seconds spent extracting critical paths — the observatory's own "
        "overhead, guarded < 5% of query wall by the --slo gate.").inc(
            extract_s)
    # sink 3: the SLO observatory (burn window, tail reservoir, ledger).
    # Cancel/deadline accounting: a client cancel is excluded from the
    # burn window (the engine didn't miss), a blown deadline counts BAD
    from .progress import TpuQueryCancelled, TpuQueryDeadlineExceeded
    LatencyObservatory.get().record(
        tenant=tenant, wall_s=res["wall_s"], segments=res["segments"],
        failed=error is not None, label=label,
        reconciled=res["reconciled"], extract_s=extract_s,
        cancelled=(isinstance(error, TpuQueryCancelled)
                   and getattr(error, "cause", "client") == "client"),
        deadline=isinstance(error, TpuQueryDeadlineExceeded))
    return res
