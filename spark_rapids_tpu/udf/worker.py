"""Out-of-process Python UDF workers speaking Arrow IPC.

TPU-native analog of the reference's GPU-aware Python worker machinery
(ref: python/rapids/worker.py:22 + daemon.py — child processes that
initialize their own memory pools; GpuArrowEvalPythonExec.scala:58-260 —
Arrow batches streamed across the process boundary and paired back;
PythonWorkerSemaphore.scala — bounding concurrent python workers).

Redesign for this engine:

  * A `PythonWorker` is a subprocess running `worker_main()`.  Requests
    carry a cloudpickled task closure + N Arrow-IPC framed tables on the
    worker's stdin; responses return M Arrow-IPC tables (or a pickled
    scalar payload) on its stdout.  stderr passes through for user print
    debugging.
  * Workers are generic (no per-UDF state), pooled process-wide and
    reused across queries — the daemon-amortization idea without a fork
    server.  `PythonWorkerPool` bounds live workers with a semaphore
    (the PythonWorkerSemaphore analog).
  * Workers run with the TPU tunnel disabled (JAX_PLATFORMS=cpu): user
    python code must never contend for the device the engine owns —
    the exact concern the reference's worker RMM-pool bounds address.
  * Crash containment: a worker dying mid-request (OOM-kill, os._exit,
    segfault) surfaces as `PythonWorkerCrash` on that query; the pool
    discards the corpse and later queries get a fresh worker.
"""

from __future__ import annotations

import atexit
import io
import os
import struct
import subprocess
import sys
import threading
from typing import Callable, List, Optional, Sequence, Tuple

import pyarrow as pa

MAGIC = b"TPUW"
OP_TASK = 1
OP_SHUTDOWN = 2
OP_STREAM = 3
ST_OK = 0
ST_ERR = 1
TAG_BLOB = 1
TAG_END = 0


class PythonWorkerError(RuntimeError):
    """The user's UDF raised inside the worker (traceback attached)."""


class PythonWorkerCrash(RuntimeError):
    """The worker process died mid-request (crash/OOM-kill/exit)."""


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

def _write_blob(f, data: bytes) -> None:
    f.write(struct.pack("<Q", len(data)))
    f.write(data)


def _read_exact(f, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = f.read(n - len(buf))
        if not chunk:
            raise EOFError("worker stream closed")
        buf += chunk
    return buf


def _read_blob(f) -> bytes:
    (n,) = struct.unpack("<Q", _read_exact(f, 8))
    return _read_exact(f, n)


def _table_to_ipc(tbl: pa.Table) -> bytes:
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, tbl.schema) as w:
        w.write_table(tbl)
    return sink.getvalue()


def _ipc_to_table(data: bytes) -> pa.Table:
    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        return r.read_all()


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------

def worker_main(stdin=None, stdout=None) -> None:
    """Request loop; runs in the child process."""
    import cloudpickle
    fin = stdin or sys.stdin.buffer
    fout = stdout or sys.stdout.buffer
    if stdout is None:
        # the framing protocol owns the real stdout; user print() (and any
        # library chatter) must land on stderr or it would corrupt frames
        sys.stdout = sys.stderr
    while True:
        try:
            head = _read_exact(fin, 5)
        except EOFError:
            return
        magic, op = head[:4], head[4]
        if magic != MAGIC:
            return
        if op == OP_SHUTDOWN:
            return
        if op == OP_STREAM:
            _serve_stream(fin, fout)
            continue
        payload = _read_blob(fin)
        (n_in,) = struct.unpack("<I", _read_exact(fin, 4))
        tables = [_ipc_to_table(_read_blob(fin)) for _ in range(n_in)]
        try:
            task, aux = cloudpickle.loads(payload)
            out_tables, out_obj = task(tables, aux)
            fout.write(bytes([ST_OK]))
            fout.write(struct.pack("<I", len(out_tables)))
            for tb in out_tables:
                _write_blob(fout, _table_to_ipc(tb))
            _write_blob(fout, cloudpickle.dumps(out_obj))
        except Exception:  # noqa: BLE001 — everything must cross the pipe
            import traceback
            fout.write(bytes([ST_ERR]))
            _write_blob(fout, cloudpickle.dumps(traceback.format_exc()))
        fout.flush()


def _serve_stream(fin, fout) -> None:
    """Streaming request: input tables arrive tagged and are consumed
    lazily by the task generator; each output table is written as soon as
    the task yields it.  Peak memory stays one batch per side — the
    contract mapInPandas promises (ref RebatchingRoundoffIterator streams
    batch-by-batch through the reference's workers too)."""
    import cloudpickle
    payload = _read_blob(fin)

    def gen():
        while True:
            tag = _read_exact(fin, 1)[0]
            if tag == TAG_END:
                return
            yield _ipc_to_table(_read_blob(fin))

    inputs = gen()
    try:
        task_gen, aux = cloudpickle.loads(payload)
        for tb in task_gen(inputs, aux):
            fout.write(bytes([TAG_BLOB]))
            _write_blob(fout, _table_to_ipc(tb))
            fout.flush()
        # the task may return without draining its input; the parent's
        # writer thread stops at TAG_END either way — drain to stay in
        # protocol sync
        for _ in inputs:
            pass
        fout.write(bytes([TAG_END, ST_OK]))
    except Exception:  # noqa: BLE001
        import traceback
        for _ in inputs:
            pass
        fout.write(bytes([TAG_END, ST_ERR]))
        _write_blob(fout, cloudpickle.dumps(traceback.format_exc()))
    fout.flush()


# ---------------------------------------------------------------------------
# task bodies (module-level so cloudpickle ships them by reference; the
# user fn rides inside `aux`)
# ---------------------------------------------------------------------------

def _cast_result(pdf, schema: pa.Schema) -> pa.Table:
    tbl = pa.Table.from_pandas(pdf, preserve_index=False)
    return tbl.select(schema.names).cast(schema)


def _group_pandas(tbl: pa.Table, key_names: List[str]):
    import pandas as pd
    if tbl.num_rows == 0:
        return []
    pdf = tbl.to_pandas()
    out = []
    for key, sub in pdf.groupby(key_names, dropna=False, sort=True):
        if not isinstance(key, tuple):
            key = (key,)
        key = tuple(None if (isinstance(k, float) and k != k) or
                    k is pd.NaT else k for k in key)
        out.append((key, sub.reset_index(drop=True)))
    out.sort(key=lambda kv: tuple((k is None, k) for k in kv[0]))
    return out


def task_map_in_pandas(tables, aux):
    fn, schema = aux
    outs = [ _cast_result(pdf, schema)
             for pdf in fn(tb.to_pandas() for tb in tables) if len(pdf) ]
    return ([pa.concat_tables(outs)] if outs else []), None


def task_stream_map_in_pandas(tables_iter, aux):
    """Streaming mapInPandas: fn's input iterator pulls batches off the
    pipe one at a time; each produced frame ships back immediately."""
    fn, schema = aux
    for pdf in fn(tb.to_pandas() for tb in tables_iter):
        if len(pdf):
            yield _cast_result(pdf, schema)


def task_grouped_map(tables, aux):
    fn, schema, key_names = aux
    outs = []
    for _, pdf in _group_pandas(tables[0], key_names):
        res = fn(pdf)
        if len(res):
            outs.append(_cast_result(res, schema))
    return ([pa.concat_tables(outs)] if outs else []), None


def task_cogrouped_map(tables, aux):
    fn, schema, lkeys, rkeys = aux
    ltbl, rtbl = tables
    lgroups = dict(_group_pandas(ltbl, lkeys))
    rgroups = dict(_group_pandas(rtbl, rkeys))
    keys = sorted(set(lgroups) | set(rgroups),
                  key=lambda kv: tuple((k is None, k) for k in kv))
    outs = []
    for key in keys:
        lpdf = lgroups.get(key)
        rpdf = rgroups.get(key)
        if lpdf is None:
            lpdf = ltbl.schema.empty_table().to_pandas()
        if rpdf is None:
            rpdf = rtbl.schema.empty_table().to_pandas()
        res = fn(lpdf, rpdf)
        if len(res):
            outs.append(_cast_result(res, schema))
    return ([pa.concat_tables(outs)] if outs else []), None


def task_grouped_agg(tables, aux):
    """One output row per group: keys then one scalar per UDF.  Returns
    the row dict as the pickled payload (scalars may not be
    Arrow-encodable before the declared cast)."""
    udfs, key_names = aux  # udfs: [(out_name, fn, in_cols)]
    tbl = tables[0]
    rows = {n: [] for n in key_names}
    for n, _, _ in udfs:
        rows[n] = []
    groups = _group_pandas(tbl, key_names) if key_names else \
        [((), tbl.to_pandas())]
    for key, pdf in groups:
        for k_name, k_val in zip(key_names, key):
            rows[k_name].append(k_val)
        for out_name, fn, in_cols in udfs:
            rows[out_name].append(fn(*[pdf[c] for c in in_cols]))
    return [], rows


def task_stream_eval_bound(tables_iter, aux):
    """Streaming row-UDF evaluation: one output table per input table,
    in order.  The closure/expression payload ships ONCE per partition
    (not per batch) and the input carries only the columns the UDFs
    actually reference."""
    for tbl in tables_iter:
        out, _ = task_eval_bound([tbl], aux)
        yield out[0]


def task_eval_bound(tables, aux):
    """Evaluate bound engine expressions (python row UDFs) against the
    batch — the worker runs the same host evaluator the in-process path
    uses, so null/coercion semantics are identical.  Returns ONLY the
    UDF output columns; the parent pairs them with its local child
    columns (the BatchQueue pairing, ref GpuArrowEvalPythonExec:189)."""
    bound, child_names, child_types, udf_names, ansi = aux
    import numpy as np
    from ..columnar.device import batch_to_device, batch_to_arrow, DeviceBatch
    from ..columnar.interop import to_arrow_schema
    from ..expr.core import EvalContext, ScalarValue, scalar_to_column
    tbl = tables[0].combine_chunks()
    rbs = tbl.to_batches()
    rb = rbs[0] if rbs else to_arrow_schema(
        child_names, child_types).empty_table().to_batches()[0]
    b = batch_to_device(rb, xp=np)
    ectx = EvalContext(np, b, ansi=ansi)
    cols = []
    for u in bound:
        v = u.eval(ectx)
        if isinstance(v, ScalarValue):
            v = scalar_to_column(ectx, v)
        cols.append(v.col)
    out = DeviceBatch(cols, b.num_rows, udf_names)
    return [pa.Table.from_batches([batch_to_arrow(out)])], None


# ---------------------------------------------------------------------------
# parent side
# ---------------------------------------------------------------------------

class PythonWorker:
    def __init__(self):
        env = dict(os.environ)
        # user code must not contend for the engine's TPU (the worker
        # analog of the reference's per-worker RMM pool bounds)
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        # the worker must resolve by-reference pickles of user modules:
        # propagate the parent's import path (the role Spark's pyfiles
        # shipping plays for its python workers)
        env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p] +
            [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
        self.proc = subprocess.Popen(
            [sys.executable, "-c",
             "from spark_rapids_tpu.udf.worker import worker_main; "
             "worker_main()"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            env=env, cwd=os.getcwd())
        self.requests_served = 0

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def request(self, task: Callable, aux,
                tables: Sequence[pa.Table]
                ) -> Tuple[List[pa.Table], object]:
        import cloudpickle
        try:
            w = self.proc.stdin
            w.write(MAGIC + bytes([OP_TASK]))
            _write_blob(w, cloudpickle.dumps((task, aux)))
            w.write(struct.pack("<I", len(tables)))
            for tb in tables:
                _write_blob(w, _table_to_ipc(tb))
            w.flush()
            r = self.proc.stdout
            status = _read_exact(r, 1)[0]
            if status == ST_ERR:
                tb_str = cloudpickle.loads(_read_blob(r))
                raise PythonWorkerError(
                    f"python UDF raised in worker:\n{tb_str}")
            (n_out,) = struct.unpack("<I", _read_exact(r, 4))
            out_tables = [_ipc_to_table(_read_blob(r))
                          for _ in range(n_out)]
            out_obj = cloudpickle.loads(_read_blob(r))
            self.requests_served += 1
            return out_tables, out_obj
        except (EOFError, BrokenPipeError, OSError) as ex:
            rc = self.proc.poll()
            self.kill()
            raise PythonWorkerCrash(
                f"python worker died mid-request (rc={rc}): {ex}") from ex

    def request_stream(self, task_gen: Callable, aux, tables_iter):
        """Streaming request: a writer thread feeds input tables while
        this generator yields output tables as the worker produces them —
        one batch in flight per side, whatever the partition size."""
        import cloudpickle
        w = self.proc.stdin
        r = self.proc.stdout
        write_err: List[BaseException] = []

        def feed():
            try:
                for tb in tables_iter:
                    w.write(bytes([TAG_BLOB]))
                    _write_blob(w, _table_to_ipc(tb))
                    w.flush()
            except BaseException as ex:  # noqa: BLE001
                write_err.append(ex)
            # ALWAYS terminate the input stream — even when the upstream
            # iterator raised — or both sides would block forever waiting
            # for the next frame; the recorded error re-raises below
            try:
                w.write(bytes([TAG_END]))
                w.flush()
            except OSError as ex:
                if not write_err:
                    write_err.append(ex)

        try:
            w.write(MAGIC + bytes([OP_STREAM]))
            _write_blob(w, cloudpickle.dumps((task_gen, aux)))
            w.flush()
            feeder = threading.Thread(target=feed, daemon=True)
            # the feeder drives upstream execs on behalf of a borrow that
            # already holds a pool permit; mark it so nested borrows (a
            # stacked mapInPandas chain) skip the semaphore instead of
            # deadlocking against their own ancestor
            feeder._tpu_pool_nested = True
            feeder.start()
            while True:
                tag = _read_exact(r, 1)[0]
                if tag == TAG_END:
                    break
                yield _ipc_to_table(_read_blob(r))
            status = _read_exact(r, 1)[0]
            feeder.join(timeout=30)
            if status == ST_ERR:
                tb_str = cloudpickle.loads(_read_blob(r))
                raise PythonWorkerError(
                    f"python UDF raised in worker:\n{tb_str}")
            if write_err:
                raise write_err[0]
            self.requests_served += 1
        except (EOFError, BrokenPipeError, OSError) as ex:
            rc = self.proc.poll()
            self.kill()
            raise PythonWorkerCrash(
                f"python worker died mid-stream (rc={rc}): {ex}") from ex

    def kill(self):
        try:
            self.proc.kill()
            self.proc.wait(timeout=5)
        except Exception:
            pass


class PythonWorkerPool:
    """Reusable workers bounded by a semaphore
    (ref PythonWorkerSemaphore.scala; pooling plays daemon.py's
    fork-amortization role)."""

    _instance: Optional["PythonWorkerPool"] = None
    _lock = threading.Lock()

    def __init__(self, max_workers: int = 2):
        self.max_workers = max_workers
        self._sem = threading.BoundedSemaphore(max_workers)
        self._idle: List[PythonWorker] = []
        self._list_lock = threading.Lock()
        self._closed = False
        self.spawned = 0

    @classmethod
    def get(cls, max_workers: int = 2) -> "PythonWorkerPool":
        with cls._lock:
            if cls._instance is None or \
                    cls._instance.max_workers != max_workers:
                if cls._instance is not None:
                    cls._instance.shutdown()
                cls._instance = PythonWorkerPool(max_workers)
            return cls._instance

    def _checkout(self) -> PythonWorker:
        with self._list_lock:
            worker = self._idle.pop() if self._idle else None
        if worker is None or not worker.alive:
            worker = PythonWorker()
            self.spawned += 1
        return worker

    def _checkin(self, worker: PythonWorker):
        """Return a healthy worker; a closed pool reaps it instead (so a
        worker borrowed across a pool swap cannot leak as a zombie)."""
        with self._list_lock:
            if not self._closed and worker.alive:
                self._idle.append(worker)
                return
        worker.kill()

    def _acquire(self) -> bool:
        """Take a permit unless the current thread is a stream feeder
        already working on behalf of a held permit — a nested borrow
        blocking on its own ancestor would deadlock a single stacked
        query (permits bound CONCURRENT independent borrows; nesting
        depth is bounded by the plan height)."""
        if getattr(threading.current_thread(), "_tpu_pool_nested", False):
            return False
        self._sem.acquire()
        return True

    def run(self, task: Callable, aux, tables: Sequence[pa.Table]
            ) -> Tuple[List[pa.Table], object]:
        """Borrow a worker (blocking on the semaphore), run one request,
        return the worker to the pool if it survived.  A UDF exception
        (PythonWorkerError) leaves the worker in a clean protocol state —
        it is returned, not killed; only crashes cost a respawn."""
        held = self._acquire()
        worker = None
        try:
            worker = self._checkout()
            result = worker.request(task, aux, tables)
            self._checkin(worker)
            return result
        except PythonWorkerError:
            self._checkin(worker)
            raise
        except BaseException:
            if worker is not None and worker.alive:
                worker.kill()
            raise
        finally:
            if held:
                self._sem.release()

    def run_stream(self, task_gen: Callable, aux, tables_iter):
        """Streaming variant of run(); yields output tables lazily.  An
        abandoned generator (consumer stops early) kills the worker — the
        protocol is mid-stream and cannot be resynced."""
        held = self._acquire()
        worker = None
        try:
            worker = self._checkout()
            yield from worker.request_stream(task_gen, aux, tables_iter)
            self._checkin(worker)
        except PythonWorkerError:
            self._checkin(worker)
            raise
        except BaseException:
            if worker is not None and worker.alive:
                worker.kill()
            raise
        finally:
            if held:
                self._sem.release()

    def shutdown(self):
        with self._list_lock:
            self._closed = True
            workers, self._idle = self._idle, []
        for w in workers:
            try:
                w.proc.stdin.write(MAGIC + bytes([OP_SHUTDOWN]))
                w.proc.stdin.flush()
                w.proc.wait(timeout=2)
            except Exception:
                w.kill()


@atexit.register
def _shutdown_pool():
    if PythonWorkerPool._instance is not None:
        PythonWorkerPool._instance.shutdown()


def worker_path_usable(conf, *fns) -> bool:
    """Worker path is on and every fn survives cloudpickle (objects bound
    to unpicklable resources fall back in-process)."""
    from .. import config as cfg
    if not conf.get(cfg.PYTHON_WORKER_ENABLED):
        return False
    import cloudpickle
    try:
        for fn in fns:
            cloudpickle.dumps(fn)
        return True
    except Exception:
        return False


def pool_from_conf(conf) -> PythonWorkerPool:
    from .. import config as cfg
    return PythonWorkerPool.get(conf.get(cfg.CONCURRENT_PYTHON_WORKERS))


if __name__ == "__main__":
    worker_main()
